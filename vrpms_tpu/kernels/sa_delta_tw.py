"""Pallas TPU kernel: one FUSED delta-evaluated SA step for VRPTW.

VERDICT round-3 item 2: the flagship delta kernel (sa_delta.py) excluded
exactly the instance classes the contract most prizes — time windows
fell back to the full O(L * N-hat^2) one-hot evaluation per move. This
sibling kernel extends the same design to VRPTW:

  * every per-position NODE attribute the timed objective needs —
    demand, service, ready, due — rides as its own (L-hat, B) state
    array that transforms under moves exactly like the tour itself
    (the same masked sublane-roll machinery, no gathers);
  * the LEG durations ride as a fifth per-position array lg[k] =
    d[g[k], g[k+1]], transformed by the same rolls plus O(1) junction
    fixes read from the 12 pair lookups the untimed kernel already
    performs (reverse reuses interior legs under the symmetric-matrix
    gate; rotate/swap splice at most four junctions);
  * the candidate's FULL timeline is then recomputed in VMEM by a
    log-depth max-plus prefix scan over sublanes (the associative
    arrival map of core.cost._tw_eval: a' = max(a + t, r), with depot
    zeros resetting the clock to the shift start) — O(L log L) VPU work
    per move with NO N^2 term anywhere, which is the whole point:
    lateness is a global property of the tour, but the max-plus
    structure makes recomputing it as cheap as a prefix sum.

Because distance, capacity excess AND lateness are recomputed fresh
from the (exactly-moved) state arrays at every step, the committed cost
carries no accumulated drift at all — there is nothing to resync at
block boundaries (unlike the untimed kernel's running dist deltas); the
solver re-ranks the best pool in the exact one-hot basis once at the
end.

Rounding contract: leg durations are the bf16-rounded table (identical
to every hot path); service/ready/due are f32-exact (dp_init's
exact_f32 attribute init); demands ride gcd-scaled like the untimed
kernel (kernels.sa_eval.demand_scale). Note on in-kernel f32 matmuls
(exact_f32 attr init; flips are select-based since round 5 —
sa_delta._flip_sublanes): unlike XLA's einsum DEFAULT
precision — which bf16-truncates f32 operands on the MXU and silently
corrupted node ids > 256 outside kernels (core.cost.EXACT) — Mosaic's
in-kernel `jnp.dot` with f32 operands is measured EXACT on v5e: the
n=502 untimed bit-check pushed ids 257..501 through the identical flip
machinery bit-identically to interpret mode, and this kernel's R101-
shape hardware bit-check carried non-bf16-representable f32 window
values (synth horizon-1000 dues) with zero cost deviation. Gates (sa._delta_supported):
symmetric d, uniform fleet + scalable demands, uniform start times with
max(start, ready[0]) <= due[0] (so trailing pad legs contribute zero
lateness), n_nodes and tour length <= 256 (bf16-exact one-hot ids and
one lane-tile of table).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vrpms_tpu.kernels.sa_delta import (
    _flip_sublanes,
    _PALLAS_OK,
    _cap_excess_of,
    _roll_up_perlane,
    _roll_up_static,
    _value_at,
    _value_at_f,
)

if _PALLAS_OK:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e18
_BIG = 1e9  # matches core.instance.BIG (the depot-reset -BIG trick)


def _pair_lookup_stacked(d, u_rows, v_rows, nhat):
    """d[u_k, v_k] for K (1, T) node-row pairs -> list of (1, T), via ONE
    stacked (K*T, N-hat) one-hot matmul instead of K sequential small
    ones. The untimed kernel found stacking a wash at its shapes
    (sa_delta._pair_lookup's rationale); HERE the ablation showed the
    seven sequential lookups were the single largest step cost (41 of
    151 ms/block at tile 512), so the bigger/fewer-ops form wins."""
    k = len(u_rows)
    t = u_rows[0].shape[1]
    u_stack = jnp.concatenate([u.T for u in u_rows], axis=0)  # (K*T, 1)
    v_stack = jnp.concatenate([v.T for v in v_rows], axis=0)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (k * t, nhat), 1)
    u_oh = (u_stack == iota_n).astype(jnp.bfloat16)
    rows = jnp.dot(u_oh, d, preferred_element_type=jnp.float32)
    v_oh = (v_stack == iota_n).astype(jnp.float32)
    vals = jnp.sum(rows * v_oh, axis=1, keepdims=True)  # (K*T, 1)
    return [vals[j * t : (j + 1) * t].T for j in range(k)]


def _values_at_stacked(arr, pos_rows, iota_l):
    """arr values at K per-lane positions -> list of (1, T), as ONE
    compare/select/reduce over a K-wide lane concatenation (the eight
    separate _value_at reductions were ~8% of the step)."""
    k = len(pos_rows)
    t = arr.shape[1]
    big = jnp.concatenate([arr] * k, axis=1)
    pos = jnp.concatenate(pos_rows, axis=1)
    iota_big = jnp.concatenate([iota_l] * k, axis=1)
    vals = jnp.sum(
        jnp.where(iota_big == pos, big, 0), axis=0, keepdims=True
    )
    return [vals[:, j * t : (j + 1) * t] for j in range(k)]


def _shift_down(a, k, fill):
    rows = a.shape[0]
    pad = jnp.full((k, a.shape[1]), fill, a.dtype)
    return jnp.concatenate([pad, a[: rows - k]], axis=0)


def _maxplus_prefix(t, r, lhat):
    """Inclusive prefix of the max-plus affine maps down the sublanes:
    combine((t1, r1) earlier, (t2, r2) later) = (t1 + t2,
    max(r1 + t2, r2)) — associative, so log2(L-hat) doubling steps.
    Identity element: (t=0, r=-BIG)."""
    k = 1
    while k < lhat:
        t_p = _shift_down(t, k, 0.0)
        r_p = _shift_down(r, k, _NEG_BIG)
        r = jnp.maximum(r_p + t, r)
        t = t_p + t
        k *= 2
    return r  # arrive[k] = arrival time at position k+1


def tw_timeline_late(cand, lg_c, sv_c, rd_c, du_c, start0, lhat):
    """Total lateness of each lane's candidate tour from its
    per-position state arrays (semantics of core.cost._tw_eval /
    tw_components_batch, leg for leg).

    Leg k runs position k -> k+1. A depot origin (cand[k] == 0) resets
    the clock to the shift start; otherwise departure is arrival plus
    the origin's service. rd/du of the DESTINATION are the roll-up-by-1
    of the state arrays (the wrap at the last pad row reads position 0
    = the depot, whose window the gate guarantees open at start0, so
    pad legs contribute zero lateness).
    """
    rd_next = _roll_up_static(rd_c, 1)
    du_next = _roll_up_static(du_c, 1)
    z = cand == 0
    t = jnp.where(z, -_BIG, lg_c + sv_c)
    r = jnp.where(z, jnp.maximum(start0 + lg_c, rd_next), rd_next)
    arrive = _maxplus_prefix(t, r, lhat)
    return jnp.sum(jnp.maximum(arrive - du_next, 0.0), axis=0, keepdims=True)


def _tw_step_body(
    gt, at4, lg, cost, best, bestc,
    i_row, r_row, mt_row, m_row, u_row, temp,
    d, knn, cap0, wcap, wtw, start0, iota_l,
    *, length, lhat, t, nhat, has_knn,
):
    """One fused VRPTW delta step on VALUE arrays (shared by the
    single-step test kernel and the in-kernel block loop). Same
    proposal decode as sa_delta._step_body.

    `at4` is the lane-axis concatenation [demand | service | ready |
    due] of the four node-attribute arrays (one flip matmul + one roll
    chain transforms all four); `lg` is the per-position leg-duration
    array, transformed by the same machinery one window-row shorter
    plus O(1) junction fixes from the pair lookups."""
    # --- proposal decode: second endpoint (identical to the untimed kernel)
    if has_knn:
        a_for_knn = _value_at(gt, i_row, iota_l)
        iota_n = jax.lax.broadcasted_iota(jnp.int32, (t, nhat), 1)
        a_oh = (a_for_knn.T == iota_n).astype(jnp.bfloat16)
        rows = jnp.dot(a_oh, knn, preferred_element_type=jnp.float32)
        kw = knn.shape[1]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (t, kw), 1)
        r_oh = (r_row.T == iota_k).astype(jnp.float32)
        bnode = jnp.sum(rows * r_oh, axis=1, keepdims=True)
        bnode = bnode.astype(jnp.int32).T
        match = gt == bnode
        j_row = jnp.min(jnp.where(match, iota_l, lhat), axis=0, keepdims=True)
    else:
        j_row = r_row
    j_row = jnp.clip(j_row, 1, length - 2)

    lo = jnp.minimum(i_row, j_row)
    hi = jnp.maximum(i_row, j_row)
    span = hi - lo + 1
    mm = jnp.minimum(m_row, span - 1)
    mt = mt_row

    a_, b0, x2, b1, x_, y2, c_, e_ = _values_at_stacked(
        gt,
        [lo - 1, lo, lo + 1, lo + mm - 1, lo + mm, hi - 1, hi, hi + 1],
        iota_l,
    )

    (d_ac, d_be, d_ax, d_cb, d_b1e, d_cx2, d_y2b) = _pair_lookup_stacked(
        d,
        [a_, b0, a_, c_, b1, c_, y2],
        [c_, e_, x_, b0, e_, x2, b0],
        nhat,
    )

    in_win = (iota_l >= lo) & (iota_l <= hi)
    mask = lhat - 1

    def apply_move(arr, flipped, lo_, hi_, mm_, span_, in_win_, iota_):
        rho_rev = (lhat - 1 - (lo_ + hi_)) & mask
        rev = jnp.where(in_win_, _roll_up_perlane(flipped, rho_rev, lhat), arr)
        fwd = _roll_up_perlane(arr, mm_ & mask, lhat)
        wrap = _roll_up_perlane(arr, (mm_ - span_) & mask, lhat)
        rot = jnp.where(
            in_win_, jnp.where(iota_ + mm_ <= hi_, fwd, wrap), arr
        )
        return rev, rot

    def flip(arr):
        # exact sublane reversal (sa_delta._flip_sublanes): the MXU
        # antidiagonal flip truncates values > 256 at large lhat
        return _flip_sublanes(arr, lhat)

    def moved(arr, lo_, hi_, mm_, span_, mt_, in_win_, iota_, is_int=False):
        flipped = flip(arr)
        if is_int:
            flipped = flipped.astype(jnp.int32)
        rev, rot = apply_move(arr, flipped, lo_, hi_, mm_, span_, in_win_, iota_)
        at_lo = (
            _value_at(arr, lo_, iota_) if is_int else _value_at_f(arr, lo_, iota_)
        )
        at_hi = (
            _value_at(arr, hi_, iota_) if is_int else _value_at_f(arr, hi_, iota_)
        )
        swp = jnp.where(
            iota_ == lo_, at_hi, jnp.where(iota_ == hi_, at_lo, arr)
        )
        return jnp.where(mt_ == 0, rev, jnp.where(mt_ == 1, rot, swp))

    cand = moved(gt, lo, hi, mm, span, mt, in_win, iota_l, is_int=True)
    # The four node-attribute arrays transform under the SAME per-lane
    # move, so they ride ONE lane-axis concatenation: one flip matmul
    # and one masked-roll chain instead of four. (A 5-wide concat that
    # also carried the legs section was measured SLOWER — its re-concat
    # after the junction fixes and the 5-wide commit cost more than the
    # legs' own flip+rolls save, so legs stay separate.)
    rep4 = lambda x: jnp.concatenate([x] * 4, axis=1)  # noqa: E731
    lo4, hi4 = rep4(lo), rep4(hi)
    mm4, span4, mt4 = rep4(mm), rep4(span), rep4(mt)
    iota_l4 = rep4(iota_l)
    in_win4 = rep4(in_win)
    at4_c = moved(at4, lo4, hi4, mm4, span4, mt4, in_win4, iota_l4)
    dp_c = at4_c[:, :t]
    sv_c = at4_c[:, t : 2 * t]
    rd_c = at4_c[:, 2 * t : 3 * t]
    du_c = at4_c[:, 3 * t : 4 * t]

    # legs: same rolls with the window one row shorter (reverse's
    # reflection constant for legs is exactly L-lo-hi = L-1-(lo+(hi-1)),
    # so passing hi-1 yields both the window and the roll), then O(1)
    # junction fixes; rot fixes gate on validity span>=2 — where
    # invalid, hi == lo and d_ac/d_be degenerate to the unchanged
    # values, so the shared fixes stay no-ops.
    in_win_lg = (iota_l >= lo) & (iota_l <= hi - 1)
    lg_rev, lg_rot = apply_move(
        lg, flip(lg), lo, hi - 1, mm, span, in_win_lg, iota_l
    )
    lg_c = jnp.where(mt == 0, lg_rev, jnp.where(mt == 1, lg_rot, lg))
    rot_valid = (mt == 1) & (span >= 2) & (mm >= 1)
    fix_lo1 = jnp.where(rot_valid, d_ax, d_ac)
    fix_hi = jnp.where(rot_valid, d_b1e, d_be)
    lg_c = jnp.where(iota_l == lo - 1, fix_lo1, lg_c)
    lg_c = jnp.where(iota_l == hi, fix_hi, lg_c)
    lg_c = jnp.where(rot_valid & (iota_l == hi - mm), d_cb, lg_c)
    swap_gen = mt == 2
    lg_c = jnp.where(swap_gen & (iota_l == lo), d_cx2, lg_c)
    lg_c = jnp.where(swap_gen & (iota_l == hi - 1), d_y2b, lg_c)
    # adjacent swap IS the reverse: one junction leg d[c, b0] at lo
    lg_c = jnp.where(
        swap_gen & (hi == lo + 1) & (iota_l == lo), d_cb, lg_c
    )

    dist_c = jnp.sum(lg_c, axis=0, keepdims=True)
    cape_c = _cap_excess_of(cand, dp_c, cap0, lhat)
    late_c = tw_timeline_late(cand, lg_c, sv_c, rd_c, du_c, start0, lhat)
    cand_cost = dist_c + wcap * cape_c + wtw * late_c
    delta = cand_cost - cost
    accept = (delta < 0.0) | (u_row < jnp.exp(jnp.minimum(-delta / temp, 0.0)))

    gt_n = jnp.where(accept, cand, gt)
    at4_n = jnp.where(rep4(accept), at4_c, at4)
    lg_n = jnp.where(accept, lg_c, lg)
    cost_n = jnp.where(accept, cand_cost, cost)
    better = cost_n < bestc
    best_n = jnp.where(better, gt_n, best)
    bestc_n = jnp.where(better, cost_n, bestc)
    return gt_n, at4_n, lg_n, cost_n, best_n, bestc_n


def _tw_block_kernel(
    gt_ref, dp_ref, sv_ref, rd_ref, du_ref, lg_ref, cost_ref,
    best_ref, bestc_ref,
    i_ref, r_ref, mt_ref, m_ref, u_ref, temps_ref,
    d_ref, knn_ref, scal_ref,
    gt_o, dp_o, sv_o, rd_o, du_o, lg_o, cost_o, best_o, bestc_o,
    *, length, has_knn, n_steps,
):
    """n_steps fused TW delta steps, all state VMEM-resident for the
    whole block (one launch per block — the same dispatch-amortization
    as sa_delta._delta_block_kernel)."""
    lhat, t = gt_ref.shape
    nhat = d_ref.shape[0]
    d = d_ref[:]
    knn = knn_ref[:]
    cap0 = scal_ref[0, 0]
    wcap = scal_ref[0, 1]
    wtw = scal_ref[0, 2]
    start0 = scal_ref[0, 3]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (lhat, t), 0)

    def body(k, carry):
        gt, at4, lg, cost, best, bestc = carry
        return _tw_step_body(
            gt, at4, lg, cost, best, bestc,
            i_ref[pl.ds(k, 1), :], r_ref[pl.ds(k, 1), :],
            mt_ref[pl.ds(k, 1), :], m_ref[pl.ds(k, 1), :],
            u_ref[pl.ds(k, 1), :], temps_ref[0, k],
            d, knn, cap0, wcap, wtw, start0, iota_l,
            length=length, lhat=lhat, t=t, nhat=nhat, has_knn=has_knn,
        )

    # the four attribute arrays ride the loop as ONE lane-concat (see
    # _tw_step_body); split back into the interface refs at the end
    at4_0 = jnp.concatenate(
        [dp_ref[:], sv_ref[:], rd_ref[:], du_ref[:]], axis=1
    )
    carry = (
        gt_ref[:], at4_0, lg_ref[:], cost_ref[:], best_ref[:], bestc_ref[:]
    )
    gt, at4, lg, cost, best, bestc = jax.lax.fori_loop(0, n_steps, body, carry)
    gt_o[:] = gt
    dp_o[:] = at4[:, :t]
    sv_o[:] = at4[:, t : 2 * t]
    rd_o[:] = at4[:, 2 * t : 3 * t]
    du_o[:] = at4[:, 3 * t :]
    lg_o[:] = lg
    cost_o[:] = cost
    best_o[:] = best
    bestc_o[:] = bestc


@functools.partial(
    jax.jit, static_argnames=("length", "tile_b", "has_knn", "interpret")
)
def delta_tw_block(
    gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost, best_t, best_c,
    i, r, mt, m, u, temps, d_bf16, knn_f32, scal,
    *, length, tile_b, has_knn, interpret=False,
):
    """A whole block of fused VRPTW delta steps in one kernel launch.

    State: gt/dp/sv/rd/du/lg/best_t are (L-hat, B) [tour ids, scaled
    demand, service, ready, due, leg duration, best tour]; cost/best_c
    are (1, B). i/r/mt/m/u: (n_steps, B); temps: (1, n_steps) SMEM;
    scal: (1, 4) SMEM [cap0_scaled, wcap*g, wtw, start0].
    """
    lhat, b = gt_t.shape
    n_steps = i.shape[0]
    grid = b // tile_b
    kernel = functools.partial(
        _tw_block_kernel, length=length, has_knn=has_knn, n_steps=n_steps
    )
    tall = pl.BlockSpec((lhat, tile_b), lambda g: (0, g))
    row = pl.BlockSpec((1, tile_b), lambda g: (0, g))
    steps = pl.BlockSpec((n_steps, tile_b), lambda g: (0, g))
    tall_i32 = jax.ShapeDtypeStruct((lhat, b), jnp.int32)
    tall_f32 = jax.ShapeDtypeStruct((lhat, b), jnp.float32)
    row_f32 = jax.ShapeDtypeStruct((1, b), jnp.float32)
    # The TW step carries ~2x the untimed kernel's live state (seven
    # tall arrays) plus per-move roll temporaries, so the default 16 MB
    # SCOPED-vmem cap overflows at production shapes (measured: 43.5 MB
    # scoped at tile_b=256, n_steps=512 on v5e). v5e has 128 MiB of
    # physical VMEM; raise the cap to 100 MB. NOTE the budget scales
    # with BOTH tile_b and n_steps (the five presampled streams are
    # (n_steps, tile_b) VMEM blocks of this launch): the driver caps
    # launches at 512 steps and the measured-fastest tile is 512, which
    # lands ~85-90 MB — inside the cap, with no headroom for larger
    # launches (an unbounded n_steps would scale VMEM with the whole
    # iteration budget).
    params = None
    if not interpret:
        params = pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            tall, tall, tall, tall, tall, tall, row, tall, row,
            steps, steps, steps, steps, steps,
            pl.BlockSpec((1, n_steps), lambda g: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(d_bf16.shape, lambda g: (0, 0)),
            pl.BlockSpec(knn_f32.shape, lambda g: (0, 0)),
            pl.BlockSpec((1, 4), lambda g: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[tall, tall, tall, tall, tall, tall, row, tall, row],
        out_shape=[
            tall_i32, tall_f32, tall_f32, tall_f32, tall_f32, tall_f32,
            row_f32, tall_i32, row_f32,
        ],
        compiler_params=params,
        interpret=interpret,
    )(gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost, best_t, best_c,
      i, r, mt, m, u, temps, d_bf16, knn_f32, scal)


def tw_step(
    gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost, best_t, best_c,
    i, r, mt, m, u, temp, d_bf16, knn_f32, scal3,
    *, length, tile_b, has_knn, interpret=False,
):
    """Single-step convenience wrapper over delta_tw_block (tests and
    per-step host control)."""
    temps = jnp.asarray([[temp]], jnp.float32)
    return delta_tw_block(
        gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost, best_t, best_c,
        i[None], r[None], mt[None], m[None], u[None], temps,
        d_bf16, knn_f32, scal3,
        length=length, tile_b=tile_b, has_knn=has_knn, interpret=interpret,
    )
