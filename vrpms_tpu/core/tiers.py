"""Shape-tier canonicalization: collapse the XLA compile space.

Every distinct instance shape — node count N, vehicle count V, slice
count T — specializes a fresh XLA program in every solver, so a
realistic traffic mix pays a multi-second compile per size and the
micro-batcher's shape buckets almost never collide. This module pads
every incoming instance UP to a small ladder of canonical tiers with
**provably cost-neutral** phantom structure, so one compiled program
(persistent-cacheable, vrpms_tpu.utils.enable_compile_cache) serves
every size in its tier and same-tier jobs merge into one vmapped
launch (vrpms_tpu.sched.batch).

The padding recipe, axis by axis:

  N — phantom customers are DEPOT ALIASES: their duration rows and
      columns copy the depot's (every slice), demands/service are the
      depot's (zero), windows are [ready[0], BIG]. Combined with the
      separator semantics in core.encoding.separators (a phantom id in
      a giant tour splits routes exactly like a depot zero) this makes
      any padded tour price bit-identically to the real tour it
      decodes to: phantom legs contribute exact zeros, phantom
      "routes" are empty, and a phantom standing in for an interior
      separator reproduces the zero's capacity/TW accounting.
  V — phantom vehicles get capacity 0 and shift start ready[0]. The
      traced v_real clamp in core.split keeps the greedy/optimal
      splits from ever binding a customer to one, and solver moves
      never reach the tail (below), so phantom vehicles only ever hold
      empty routes (cost 0) or phantom customers (demand 0 — no
      excess against capacity 0).
  T — slice counts pad only to MULTIPLES on the ladder, by tiling the
      profile cyclically: (x % kT) % T == x % T, so the slice chosen
      for every departure time is unchanged and the time-dependent
      paths stay exact. A T with no ladder multiple stays as-is.

The real counts ride on the Instance as TRACED data (n_real/v_real),
and every solver confines its move/crossover/construction sampling to
the real prefix with dynamic masks — so sizes within a tier share one
trace instead of re-specializing jit.

Canonical padded layout (what constructive inits emit): positions
[0, L_real) hold the real giant tour exactly as the unpadded encoding
would (L_real = n_real + v_real), positions [L_real, L) hold the
phantom customers followed by the phantom vehicles' zeros. Masked
moves touch [1, L_real - 2] only, so the tail is invariant.

Env:
  VRPMS_TIERS  — "off" disables tiering; empty/unset uses the default
                 ladder; otherwise "n=8,16,...;v=1,2,...;t=1,8,..."
                 (an omitted axis keeps its default, an axis set to
                 nothing — e.g. "v=" — disables padding on that axis).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading

import jax.numpy as jnp
import numpy as np

from vrpms_tpu import config
from vrpms_tpu.core.instance import Instance

DEFAULT_N_TIERS = (8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512, 768, 1024)
DEFAULT_V_TIERS = (1, 2, 4, 8, 16, 32, 64)
DEFAULT_T_TIERS = (1, 8, 24, 48)


@dataclasses.dataclass(frozen=True)
class TierLadder:
    n: tuple  # node-count tiers (depot included)
    v: tuple  # vehicle-count tiers
    t: tuple  # slice-count tiers (pad only to MULTIPLES of the real T)


def parse_tiers(spec: str) -> TierLadder | None:
    """VRPMS_TIERS grammar -> TierLadder (None = tiering off).

    "off"/"0"/"none" disables; "" keeps defaults; otherwise semicolon-
    separated axis specs "n=8,16,24", "v=1,2,4", "t=1,8,24". An axis
    given with an empty value list disables padding on that axis only.
    """
    spec = (spec or "").strip()
    if spec.lower() in ("off", "0", "none", "false"):
        return None
    axes = {"n": DEFAULT_N_TIERS, "v": DEFAULT_V_TIERS, "t": DEFAULT_T_TIERS}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        key, _, vals = part.partition("=")
        key = key.strip().lower()
        if key not in axes:
            raise ValueError(f"unknown tier axis {key!r} in VRPMS_TIERS")
        axes[key] = tuple(
            sorted(int(x) for x in vals.split(",") if x.strip())
        )
    return TierLadder(n=axes["n"], v=axes["v"], t=axes["t"])


def ladder() -> TierLadder | None:
    """The process ladder from $VRPMS_TIERS (read per call: tests and
    embedders toggle the env var; parsing a short string is free)."""
    return parse_tiers(config.get("VRPMS_TIERS"))


def tier_up(value: int, tiers: tuple) -> int:
    """Smallest tier >= value, or value itself beyond the ladder."""
    for t in tiers:
        if t >= value:
            return t
    return value


def tier_up_multiple(value: int, tiers: tuple) -> int:
    """Smallest tier that is BOTH >= value and a multiple of it (the
    slice axis pads by cyclic tiling, which is exact only for
    multiples); value itself when no tier qualifies."""
    for t in tiers:
        if t >= value and t % value == 0:
            return t
    return value


# --- tier-cache observability ----------------------------------------------
# A "hit" means this padded shape signature was already seen by this
# process (its programs are in the jit caches, or at worst one disk-
# cache load away); a "miss" is the first sighting — the solve about to
# run may pay compiles. The observer seam keeps vrpms_tpu free of
# service imports; service.obs wires Prometheus counters in.

_seen_lock = threading.Lock()
_seen_tiers: set = set()
_observer = None


def set_tier_observer(fn) -> None:
    """fn(outcome: 'hit'|'miss', key: tuple) — called once per pad."""
    global _observer
    _observer = fn


def _record_tier(key: tuple) -> str:
    with _seen_lock:
        outcome = "hit" if key in _seen_tiers else "miss"
        _seen_tiers.add(key)
    if _observer is not None:
        try:
            _observer(outcome, key)
        except Exception:
            pass
    return outcome


def tier_key(inst: Instance) -> tuple:
    """The shape+metadata signature one compiled program serves."""
    return (
        tuple(inst.durations.shape),
        int(inst.n_vehicles),
        bool(inst.has_tw),
        bool(inst.het_fleet),
        int(inst.td_rank),
        float(inst.slice_minutes),
    )


def fingerprint(inst: Instance) -> str:
    """Content address of an instance: SHA-256 over every tensor's
    canonical float32 bytes (shape-tagged) plus the non-tensor metadata.

    Run on the PADDED instance this is the equal-instance detector the
    solution cache keys on: tier padding canonicalizes shape, so two
    requests for the same city/depot/customer set produce bit-identical
    padded tensors and therefore identical fingerprints, while any
    change to a duration, demand, window, fleet, or time profile changes
    the hash. Host-side (pulls the arrays off device once); the cost is
    one sha256 pass over the tier tensors — microseconds next to a
    solve, and comparable to the store read it gates.
    """
    h = hashlib.sha256()

    def _update(tag: str, arr) -> None:
        a = np.asarray(arr, dtype=np.float32)
        h.update(tag.encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())

    _update("durations", inst.durations)
    _update("demands", inst.demands)
    _update("capacities", inst.capacities)
    _update("ready", inst.ready)
    _update("due", inst.due)
    _update("service", inst.service)
    _update("start_times", inst.start_times)
    if inst.td_rank > 0:
        _update("td_factors", inst.td_factors)
        _update("td_basis", inst.td_basis)
    meta = (
        int(inst.n_vehicles),
        bool(inst.has_tw),
        bool(inst.het_fleet),
        int(inst.td_rank),
        float(inst.slice_minutes),
        -1 if inst.n_real is None else int(inst.n_real),
        -1 if inst.v_real is None else int(inst.v_real),
    )
    h.update(repr(meta).encode())
    return h.hexdigest()


def pad_instance(inst: Instance, lad: TierLadder | None = None) -> Instance:
    """Pad `inst` up to its (N, V, T) tier; host-side, returns a new
    Instance carrying traced n_real/v_real. Instances already at tier
    size are tagged too (every tiered instance shares one pytree
    structure, which is what lets same-tier jobs stack)."""
    lad = lad if lad is not None else ladder()
    if lad is None:
        return inst
    if inst.n_real is not None:
        return inst  # already padded
    n = inst.n_nodes
    v = inst.n_vehicles
    t = inst.n_slices
    nt = tier_up(n, lad.n) if lad.n else n
    vt = tier_up(v, lad.v) if lad.v else v
    tt = tier_up_multiple(t, lad.t) if lad.t else t

    f32 = np.float32
    d = np.asarray(inst.durations, dtype=f32)
    dp = np.zeros((tt, nt, nt), f32)
    for s in range(tt):
        dp[s, :n, :n] = d[s % t]
    # depot-alias phantoms: copy the depot column into phantom columns
    # first, then the (now full-width) depot row into phantom rows, so
    # phantom-to-phantom entries land on d[0, 0] == 0.
    dp[:, :n, n:] = dp[:, :n, :1]
    dp[:, n:, :] = dp[:, :1, :]

    def pad_vec(vec, fill):
        out = np.full(nt, fill, f32)
        out[:n] = np.asarray(vec, dtype=f32)
        return out

    demands = pad_vec(inst.demands, 0.0)
    service = pad_vec(inst.service, 0.0)
    ready0 = float(np.asarray(inst.ready)[0])
    ready = pad_vec(inst.ready, ready0)
    from vrpms_tpu.core.instance import BIG

    due = pad_vec(inst.due, BIG)
    capacities = np.zeros(vt, f32)
    capacities[:v] = np.asarray(inst.capacities, dtype=f32)
    # phantom shift starts = depot ready: an empty phantom route's
    # closing arrival is then exactly its start, so its elapsed time
    # (and hence durationSum/makespan) stays zero
    start_times = np.full(vt, ready0, f32)
    start_times[:v] = np.asarray(inst.start_times, dtype=f32)

    td_factors = td_basis = None
    if inst.td_rank > 0:
        fac = np.asarray(inst.td_factors, dtype=f32)  # [R, T]
        td_factors = fac[:, np.arange(tt) % t]
        bas = np.asarray(inst.td_basis, dtype=f32)  # [R, N, N]
        bp = np.zeros((bas.shape[0], nt, nt), f32)
        bp[:, :n, :n] = bas
        bp[:, :n, n:] = bp[:, :n, :1]
        bp[:, n:, :] = bp[:, :1, :]
        td_basis = bp

    out = Instance(
        durations=jnp.asarray(dp),
        demands=jnp.asarray(demands),
        capacities=jnp.asarray(capacities),
        ready=jnp.asarray(ready),
        due=jnp.asarray(due),
        service=jnp.asarray(service),
        start_times=jnp.asarray(start_times),
        has_tw=inst.has_tw,
        slice_minutes=inst.slice_minutes,
        # the REAL fleet's het flag: phantom zero-capacities are never
        # read by the (v_real-clamped) split or by any non-empty route
        het_fleet=inst.het_fleet,
        td_factors=None if td_factors is None else jnp.asarray(td_factors),
        td_basis=None if td_basis is None else jnp.asarray(td_basis),
        td_rank=inst.td_rank,
        n_real=jnp.int32(n),
        v_real=jnp.int32(v),
    )
    _record_tier(tier_key(out))
    return out


def maybe_pad(inst: Instance) -> Instance:
    """pad_instance under the env ladder; identity when tiering is off."""
    lad = ladder()
    return inst if lad is None else pad_instance(inst, lad)


def tier_label(inst: Instance, problem: str | None = None) -> str:
    """Human/metric label for an instance's padded shape:
    "<problem>:<N>x<V>x<T>" (the warmup-spec spelling). Unpadded
    instances label their real shape — the tier they effectively are."""
    shape = tuple(np.asarray(inst.durations).shape)
    t, n = (shape[0], shape[1]) if len(shape) == 3 else (1, shape[0])
    return f"{problem or 'vrp'}:{n}x{int(inst.n_vehicles)}x{t}"


def occupancy(inst: Instance, t_real: int | None = None) -> dict:
    """Padding occupancy of a (possibly tier-padded) instance: the real
    fraction of each padded axis plus `compute`, the fraction of the
    padded compute volume spent on real structure — 1 - compute is the
    cost burned on phantoms. The compute model is the solver inner
    loop's: work scales with the giant-tour length L = N + V (moves,
    pricing scans are linear in L; the slice axis only selects rows, so
    T contributes selection width, not volume — it rides along as its
    own axis ratio and stays out of `compute`).

    The padded Instance carries n_real/v_real as traced data; the slice
    axis keeps no t_real (cyclic tiling is exact), so callers that know
    the pre-pad T pass it — absent, the axis reports full occupancy."""
    shape = tuple(np.asarray(inst.durations).shape)
    t_pad, n_pad = (shape[0], shape[1]) if len(shape) == 3 else (1, shape[0])
    v_pad = int(inst.n_vehicles)
    n_real = n_pad if inst.n_real is None else int(inst.n_real)
    v_real = v_pad if inst.v_real is None else int(inst.v_real)
    t_r = t_pad if t_real is None else min(int(t_real), t_pad)
    l_real = n_real + v_real
    l_pad = n_pad + v_pad
    return {
        "n": round(n_real / max(1, n_pad), 4),
        "v": round(v_real / max(1, v_pad), 4),
        "t": round(t_r / max(1, t_pad), 4),
        "compute": round(l_real / max(1, l_pad), 4),
    }


def pad_perm(perm, inst: Instance):
    """Extend a REAL customer permutation (ids 1..n_real-1) with the
    phantom ids at its tail — the warm-start seed adapter (a padded
    solver's genome length is the tier's customer count)."""
    if inst.n_real is None:
        return perm
    nr = int(inst.n_real)
    phantoms = jnp.arange(nr, inst.n_nodes, dtype=jnp.int32)
    return jnp.concatenate([jnp.asarray(perm, jnp.int32), phantoms])


def canonical_giant(inst: Instance, real_giant) -> jnp.ndarray:
    """Embed a REAL giant tour into the padded layout: the real tour
    occupies [0, L_real) verbatim, phantoms then zeros fill the tail.
    Host-side helper (tests, warm starts)."""
    if inst.n_real is None:
        return jnp.asarray(real_giant, jnp.int32)
    nr, vr = int(inst.n_real), int(inst.v_real)
    length = inst.n_customers + inst.n_vehicles + 1
    g = np.zeros(length, np.int32)
    real = np.asarray(real_giant)
    if real.shape[0] != nr + vr:
        raise ValueError(
            f"real giant length {real.shape[0]} != L_real {nr + vr}"
        )
    g[: nr + vr] = real
    g[nr + vr : nr + vr + (inst.n_nodes - nr)] = np.arange(nr, inst.n_nodes)
    return jnp.asarray(g)
