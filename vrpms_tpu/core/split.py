"""Splitting a customer permutation into capacity-feasible routes.

GA and ACO evolve *permutation genomes* (a customer order with no depot
separators); turning an order into a CVRP solution is the classic "split"
step. Two TPU-friendly variants:

  * greedy split — walk the order, open a new route when the running load
    would exceed capacity. One O(n) `lax.scan` per genome, vmapped across
    the population; the default fitness path.
  * optimal split (Prins 2004 idea) — shortest path over the DAG whose
    edge (i, j) is the cost of serving order[i+1..j] as one route. Cast
    here as V rounds of min-plus matrix-vector products so each round is
    a dense [n+1, n+1] reduction (VPU-friendly, no inner scan), giving
    the bounded-fleet optimum min over r <= V of V_r[n].

Both assume a homogeneous capacity (capacities[0]); heterogeneous fleets
are handled by the giant-tour representation instead, where routes are
positionally bound to vehicles (vrpms_tpu.core.cost).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vrpms_tpu.core.encoding import giant_length
from vrpms_tpu.core.instance import BIG, Instance


def _greedy_fresh(perm: jax.Array, inst: Instance) -> jax.Array:
    """bool[n]: does position k open a fresh route under the greedy rule?

    The single source of truth for the greedy route-opening rule, shared
    by cost and reconstruction so they can never disagree. fresh[0] is
    only True when perm[0] alone exceeds capacity (and is not counted as
    an extra route by callers).
    """
    q = inst.capacities[0]
    dem = inst.demands[perm]

    def step(load, dk):
        fresh = load + dk > q
        return jnp.where(fresh, dk, load + dk), fresh

    _, fresh = jax.lax.scan(step, jnp.float32(0.0), dem)
    return fresh


def greedy_split_cost(perm: jax.Array, inst: Instance):
    """Distance of the greedy-split solution for one customer order.

    Returns (cost, n_routes). Feasible w.r.t. capacity by construction
    (unless a single customer exceeds capacity); callers penalise
    `n_routes > V` to respect the fleet bound.
    """
    d = inst.durations[0]
    fresh = _greedy_fresh(perm, inst)
    prev, cur = perm[:-1], perm[1:]
    via_depot = d[prev, 0] + d[0, cur]
    direct = d[prev, cur]
    legs = jnp.where(fresh[1:], via_depot, direct)
    cost = d[0, perm[0]] + legs.sum() + d[perm[-1], 0]
    n_routes = 1 + fresh[1:].sum()
    return cost, n_routes


def greedy_split_cost_batch(perms: jax.Array, inst: Instance):
    return jax.vmap(greedy_split_cost, in_axes=(0, None))(perms, inst)


def _route_cost_matrix(perm: jax.Array, inst: Instance) -> jax.Array:
    """C[i, j] = cost of serving perm[i..j-1] (0-based) as one route,
    BIG when empty/backward/capacity-infeasible. Shape [n+1, n+1] over
    split points 0..n."""
    d = inst.durations[0]
    n = perm.shape[0]
    dem = inst.demands[perm]
    cum_dem = jnp.concatenate([jnp.zeros(1), jnp.cumsum(dem)])
    inner = d[perm[:-1], perm[1:]]
    cum_len = jnp.concatenate([jnp.zeros(1), jnp.zeros(1), jnp.cumsum(inner)])
    # cum_len[j] = sum of direct legs among perm[0..j-1]; route (i, j]
    # interior length = cum_len[j] - cum_len[i+1].
    i = jnp.arange(n + 1)[:, None]
    j = jnp.arange(n + 1)[None, :]
    first = perm[jnp.minimum(i, n - 1)]
    last = perm[jnp.minimum(j - 1, n - 1)]
    cost = (
        d[0, first].reshape(-1, 1)
        + cum_len[j] - cum_len[jnp.minimum(i + 1, n)]
        + d[last, 0].reshape(1, -1)
    )
    load = cum_dem[j] - cum_dem[i]
    valid = (i < j) & (load <= inst.capacities[0])
    return jnp.where(valid, cost, BIG)


def optimal_split_cost(perm: jax.Array, inst: Instance) -> jax.Array:
    """Bounded-fleet optimal split distance via V min-plus matvec rounds."""
    n = perm.shape[0]
    v = inst.n_vehicles
    c = _route_cost_matrix(perm, inst)
    init = jnp.full(n + 1, BIG).at[0].set(0.0)

    def round_(vals, _):
        nxt = jnp.min(vals[:, None] + c, axis=0)
        # Allowing "stay" keeps vals[n] monotone in rounds: min over r<=V.
        nxt = jnp.minimum(nxt, vals)
        return nxt, None

    vals, _ = jax.lax.scan(round_, init, None, length=v)
    return vals[n]


def optimal_split_cost_batch(perms: jax.Array, inst: Instance) -> jax.Array:
    return jax.vmap(optimal_split_cost, in_axes=(0, None))(perms, inst)


def greedy_split_giant(perm: jax.Array, inst: Instance) -> jax.Array:
    """Giant tour (see core.encoding) from a permutation via greedy split.

    If greedy needs more than V routes, the surplus is crammed into the
    last vehicle (capacity penalty then reflects the violation), keeping
    the output shape-valid.
    """
    n = perm.shape[0]
    v = inst.n_vehicles
    fresh = _greedy_fresh(perm, inst)
    rid = jnp.minimum(jnp.cumsum(fresh.astype(jnp.int32)) - fresh[0], v - 1)
    pos = 1 + jnp.arange(n) + rid
    giant = jnp.zeros(giant_length(n, v), dtype=jnp.int32)
    return giant.at[pos].set(perm.astype(jnp.int32))


def optimal_split_routes(perm, inst: Instance) -> list[list[int]]:
    """Host-side optimal split with route reconstruction (numpy).

    Used for final-answer reporting; `optimal_split_cost` is the jitted
    fitness twin. Tested to agree with it exactly.
    """
    p = np.asarray(perm)
    n = p.shape[0]
    v = int(inst.n_vehicles)
    c = np.asarray(_route_cost_matrix(jnp.asarray(p), inst))
    vals = np.full(n + 1, np.inf)
    vals[0] = 0.0
    pred = np.zeros((v, n + 1), dtype=np.int64)
    for r in range(v):
        cand = vals[:, None] + c
        nxt = cand.min(axis=0)
        pred[r] = cand.argmin(axis=0)
        keep = vals <= nxt
        nxt = np.where(keep, vals, nxt)
        pred[r] = np.where(keep, -1, pred[r])  # -1: value inherited, no new route
        vals = nxt
    if vals[n] >= BIG / 2:
        raise ValueError(
            "no capacity-feasible split of this order within the fleet bound"
        )
    routes: list[list[int]] = []
    j, r = n, v - 1
    while j > 0 and r >= 0:
        if pred[r, j] == -1:
            r -= 1
            continue
        i = int(pred[r, j])
        routes.append([int(x) for x in p[i:j]])
        j, r = i, r - 1
    routes.reverse()
    return routes
