"""Splitting a customer permutation into capacity-feasible routes.

GA and ACO evolve *permutation genomes* (a customer order with no depot
separators); turning an order into a CVRP solution is the classic "split"
step. Two TPU-friendly variants:

  * greedy split — walk the order, open a new route when the running load
    would exceed capacity. One O(n) `lax.scan` per genome, vmapped across
    the population; the default fitness path.
  * optimal split (Prins 2004 idea) — shortest path over the DAG whose
    edge (i, j) is the cost of serving order[i+1..j] as one route. Cast
    here as V rounds of min-plus matrix-vector products so each round is
    a dense [n+1, n+1] reduction (VPU-friendly, no inner scan), giving
    the bounded-fleet optimum min over r <= V of V_r[n].

Heterogeneous fleets: the greedy rule and the optimal-split DP both
apply PER-VEHICLE capacities in vehicle-index order (routes bind to
vehicles positionally, exactly like the giant-tour pricing in
vrpms_tpu.core.cost). Only the gather-free pointer-doubling fitness
shortcut (greedy_split_cost_hot_batch) requires a homogeneous fleet —
het-fleet fitness goes through exact giant evaluation instead
(solvers.common.perm_fitness_fn dispatches on Instance.het_fleet).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vrpms_tpu.core.encoding import giant_length
from vrpms_tpu.core.instance import BIG, Instance


def _greedy_fresh(perm: jax.Array, inst: Instance) -> jax.Array:
    """bool[n]: does position k open a fresh route under the greedy rule?

    The single source of truth for the greedy route-opening rule, shared
    by cost and reconstruction so they can never disagree. fresh[0] is
    only True when perm[0] alone exceeds capacity (and is not counted as
    an extra route by callers).

    Heterogeneous fleets are priced exactly: route r checks against
    capacities[r] in vehicle order (routes bind to vehicles positionally
    in the giant encoding); routes past the fleet bound reuse the last
    vehicle's capacity, matching greedy_split_giant's cramming rule.

    Tier-padded instances (core.tiers): the vehicle clamp uses the
    TRACED real fleet bound, so phantom zero-capacity vehicles are
    never consulted, and phantom customers (depot aliases, demand 0)
    never open a route — they ride the incumbent route with zero-cost
    legs, exactly like the trailing layout the padding promises.
    """
    caps = inst.capacities
    v = caps.shape[0]
    dem = inst.demands[perm]
    n = perm.shape[0]
    v_last = (v - 1) if inst.v_real is None else (inst.v_real - 1)
    nr = inst.n_real

    def step(carry, x):
        load, r = carry
        dk, node, k = x
        fresh = load + dk > caps[jnp.minimum(r, v_last)]
        if nr is not None:
            fresh = fresh & (node < nr)
        # position 0 is route 0 even when oversized (callers don't count
        # fresh[0] as an extra route)
        r = r + (fresh & (k > 0)).astype(jnp.int32)
        load = jnp.where(fresh, dk, load + dk)
        return (load, r), fresh

    _, fresh = jax.lax.scan(
        step, (jnp.float32(0.0), jnp.int32(0)), (dem, perm, jnp.arange(n))
    )
    return fresh


def greedy_split_cost(perm: jax.Array, inst: Instance):
    """Distance of the greedy-split solution for one customer order.

    Returns (cost, n_routes). Feasible w.r.t. capacity by construction
    (unless a single customer exceeds capacity); callers penalise
    `n_routes > V` to respect the fleet bound.
    """
    d = inst.durations[0]
    fresh = _greedy_fresh(perm, inst)
    prev, cur = perm[:-1], perm[1:]
    via_depot = d[prev, 0] + d[0, cur]
    direct = d[prev, cur]
    legs = jnp.where(fresh[1:], via_depot, direct)
    cost = d[0, perm[0]] + legs.sum() + d[perm[-1], 0]
    n_routes = 1 + fresh[1:].sum()
    return cost, n_routes


def greedy_split_cost_batch(perms: jax.Array, inst: Instance):
    return jax.vmap(greedy_split_cost, in_axes=(0, None))(perms, inst)


def greedy_split_cost_hot_batch(perms: jax.Array, inst: Instance):
    """Gather-free batched greedy-split cost (the TPU GA/ACO fitness).

    Same semantics as greedy_split_cost (to bf16 rounding of the
    durations matrix), reformulated for hardware where data-dependent
    gathers lower to a scalar loop:

      * per-leg demands / direct legs / depot detours are one-hot
        contractions (exact selections of a bf16-rounded table);
      * the greedy route boundaries are the orbit of 0 under the jump
        function f(s) = first position j > s whose cumulative demand
        exceeds capacity from a route starting at s — computable without
        a sequential position walk because cumulative demand is
        nondecreasing, so each route is a contiguous prefix run;
      * the orbit is found by pointer doubling: encode f as a one-hot
        transition matrix (plus an absorbing end state) and square it
        log2(n) times, unioning reach sets — all small bf16 MXU matmuls
        with 0/1 entries (clamped after each product), no gathers.

    Requires nonnegative demands and a homogeneous fleet (capacities[0])
    like the scan version it mirrors. Returns (cost, n_routes).
    """
    d = inst.durations[0].astype(jnp.bfloat16)
    q = inst.capacities[0]
    b, n = perms.shape
    n_nodes = inst.n_nodes
    from vrpms_tpu.core.cost import _onehot, onehot_dtype

    dt = onehot_dtype(max(n_nodes, n + 1))
    oh = _onehot(perms, n_nodes, dt)  # (B, n, N)
    from vrpms_tpu.core.cost import EXACT

    # demands are VALUES (exact f32 accumulation: TPU's default dot
    # precision would bf16-truncate them above 256 — core.cost.EXACT)
    dem = jnp.einsum(
        "bkn,n->bk", oh, inst.demands,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    # direct[k] = d[p_k, p_k+1]; depot detour legs from the 0-row/column.
    x = jnp.einsum(
        "bkn,nm->bkm", oh[:, :-1], d, preferred_element_type=dt
    )
    direct = jnp.einsum(
        "bkm,bkm->bk", x, oh[:, 1:], preferred_element_type=jnp.float32
    )
    to_depot = jnp.einsum(
        "bkn,n->bk", oh[:, :-1], d[:, 0].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    from_depot = jnp.einsum(
        "bkn,n->bk", oh[:, 1:], d[0].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    first_leg = jnp.einsum(
        "bn,n->b", oh[:, 0], d[0].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    last_leg = jnp.einsum(
        "bn,n->b", oh[:, -1], d[:, 0].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )

    # Jump function on route-start positions 0..n-1 plus absorbing n:
    # a route from s spans the longest prefix with cumdem <= cumdem[s-1]
    # + Q, but always at least one customer.
    cum = jnp.cumsum(dem, axis=1)  # (B, n), inclusive
    cum_excl = jnp.concatenate([jnp.zeros((b, 1)), cum[:, :-1]], axis=1)
    limit = cum_excl + q  # (B, n) per start s
    jpos = jnp.arange(n)
    fits = (jpos[None, None, :] >= jnp.arange(n)[None, :, None]) & (
        cum[:, None, :] <= limit[:, :, None]
    )  # (B, s, j): j continues the route started at s
    f = jnp.arange(n)[None, :] + fits.sum(-1)  # first position NOT fitting
    f = jnp.clip(jnp.maximum(f, jnp.arange(n)[None, :] + 1), 0, n)

    # Orbit of 0 under f via reach-set doubling on one-hot matrices.
    m = _onehot(f, n + 1, dt)  # (B, n, n+1) rows for states 0..n-1
    absorb = jnp.zeros((b, 1, n + 1), dt).at[:, 0, n].set(1)
    m = jnp.concatenate([m, absorb], axis=1)  # (B, n+1, n+1)
    reach = jnp.zeros((b, 1, n + 1), dt).at[:, 0, 0].set(1)
    steps = max(1, (n).bit_length())
    for s in range(steps):
        reach = jnp.minimum(
            reach
            + jnp.einsum("bij,bjk->bik", reach, m, preferred_element_type=dt),
            1,
        )
        if s < steps - 1:  # the final squaring's result is never read
            m = jnp.minimum(
                jnp.einsum("bij,bjk->bik", m, m, preferred_element_type=dt), 1
            )
    starts = reach[:, 0, :n].astype(jnp.float32)  # route-start indicator

    # Legs k (p_k -> p_k+1) become depot detours when k+1 starts a route.
    fresh = starts[:, 1:]
    legs = direct + fresh * (to_depot + from_depot - direct)
    cost = first_leg + legs.sum(axis=1) + last_leg
    n_routes = 1.0 + fresh.sum(axis=1)
    return cost, n_routes


def _route_cost_load(perm: jax.Array, inst: Instance):
    """(cost[i, j], load[i, j]) of serving perm[i..j-1] (0-based) as one
    route; cost is BIG for empty/backward spans, load is the span's
    total demand. Shape [n+1, n+1] over split points 0..n. Capacity is
    NOT applied here — the DP rounds apply each vehicle's own bound."""
    d = inst.durations[0]
    n = perm.shape[0]
    dem = inst.demands[perm]
    cum_dem = jnp.concatenate([jnp.zeros(1), jnp.cumsum(dem)])
    inner = d[perm[:-1], perm[1:]]
    cum_len = jnp.concatenate([jnp.zeros(1), jnp.zeros(1), jnp.cumsum(inner)])
    # cum_len[j] = sum of direct legs among perm[0..j-1]; route (i, j]
    # interior length = cum_len[j] - cum_len[i+1].
    i = jnp.arange(n + 1)[:, None]
    j = jnp.arange(n + 1)[None, :]
    first = perm[jnp.minimum(i, n - 1)]
    last = perm[jnp.minimum(j - 1, n - 1)]
    cost = (
        d[0, first].reshape(-1, 1)
        + cum_len[j] - cum_len[jnp.minimum(i + 1, n)]
        + d[last, 0].reshape(1, -1)
    )
    load = cum_dem[j] - cum_dem[i]
    return jnp.where(i < j, cost, BIG), load


def optimal_split_cost(perm: jax.Array, inst: Instance) -> jax.Array:
    """Bounded-fleet optimal split distance via V min-plus matvec rounds.

    Heterogeneous fleets are exact: round r masks route spans against
    capacities[r], i.e. routes are assigned to vehicles in index order —
    the same positional binding the giant encoding uses. (Order-dependent
    fleet assignment is inherent to that binding; the DP finds the best
    split GIVEN it.) The "stay" transition lets any vehicle go unused.
    """
    n = perm.shape[0]
    v = inst.n_vehicles
    cost, load = _route_cost_load(perm, inst)
    init = jnp.full(n + 1, BIG).at[0].set(0.0)

    def round_(vals, cap_r):
        c = jnp.where(load <= cap_r, cost, BIG)
        nxt = jnp.min(vals[:, None] + c, axis=0)
        # Allowing "stay" keeps vals[n] monotone in rounds: min over r<=V.
        nxt = jnp.minimum(nxt, vals)
        return nxt, None

    vals, _ = jax.lax.scan(round_, init, inst.capacities)
    return vals[n]


def optimal_split_cost_batch(perms: jax.Array, inst: Instance) -> jax.Array:
    return jax.vmap(optimal_split_cost, in_axes=(0, None))(perms, inst)


def greedy_split_giant(perm: jax.Array, inst: Instance) -> jax.Array:
    """Giant tour (see core.encoding) from a permutation via greedy split.

    If greedy needs more than V routes, the surplus is crammed into the
    last vehicle (capacity penalty then reflects the violation), keeping
    the output shape-valid. Tier-padded instances clamp to the TRACED
    real fleet, so real customers never land in a phantom vehicle's
    slots.
    """
    n = perm.shape[0]
    v = inst.n_vehicles
    v_last = (v - 1) if inst.v_real is None else (inst.v_real - 1)
    fresh = _greedy_fresh(perm, inst)
    rid = jnp.minimum(jnp.cumsum(fresh.astype(jnp.int32)) - fresh[0], v_last)
    pos = 1 + jnp.arange(n) + rid
    giant = jnp.zeros(giant_length(n, v), dtype=jnp.int32)
    return giant.at[pos].set(perm.astype(jnp.int32))


def optimal_split_routes(perm, inst: Instance) -> list[list[int]]:
    """Host-side optimal split with route reconstruction (numpy).

    Used for final-answer reporting; `optimal_split_cost` is the jitted
    fitness twin. Tested to agree with it exactly. Returns ONE list per
    vehicle, vehicle-aligned (unused vehicles get []) — a heterogeneous
    fleet's spans must land on the vehicle whose capacity bound the DP
    actually applied, or positional giant pricing would disagree.
    """
    p = np.asarray(perm)
    n = p.shape[0]
    v = int(inst.n_vehicles)
    cost, load = _route_cost_load(jnp.asarray(p), inst)
    cost, load = np.asarray(cost), np.asarray(load)
    caps = np.asarray(inst.capacities)
    vals = np.full(n + 1, np.inf)
    vals[0] = 0.0
    pred = np.zeros((v, n + 1), dtype=np.int64)
    for r in range(v):
        # vehicle r's own capacity bound (het-fleet exactness; mirrors
        # optimal_split_cost's per-round mask)
        c = np.where(load <= caps[r], cost, BIG)
        cand = vals[:, None] + c
        nxt = cand.min(axis=0)
        pred[r] = cand.argmin(axis=0)
        keep = vals <= nxt
        nxt = np.where(keep, vals, nxt)
        pred[r] = np.where(keep, -1, pred[r])  # -1: value inherited, no new route
        vals = nxt
    if vals[n] >= BIG / 2:
        raise ValueError(
            "no capacity-feasible split of this order within the fleet bound"
        )
    routes: list[list[int]] = [[] for _ in range(v)]
    j, r = n, v - 1
    while j > 0 and r >= 0:
        if pred[r, j] == -1:
            r -= 1
            continue
        i = int(pred[r, j])
        routes[r] = [int(x) for x in p[i:j]]
        j, r = i, r - 1
    return routes
