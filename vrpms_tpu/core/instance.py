"""Problem instance representation — one device-resident bundle of arrays.

The reference keeps the travel-duration structure in two places: a random
per-pair stub (reference src/solver.py:7-15, `calculate_duration(source,
target, time_of_day=0)`) and a per-request `durations` matrix fetched from
its database (reference api/database.py:38-48, `row['matrix']`). Here the
two are unified into a single time-sliced tensor `durations[T, N, N]`
placed on device once per solve, per SURVEY.md §3.5.

Everything is fixed-shape and functional so solvers can be jit-compiled:
node 0 is always the depot, customers are 1..n, and the number of vehicles
V is derivable from `capacities.shape`. Static facts that change trace
behavior (whether time windows exist) live in metadata fields so jit
re-specializes only when they change.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

# A number treated as "infinite" time/capacity while staying well inside
# float32 range even after a few additions.
BIG = 1e9


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "durations",
        "demands",
        "capacities",
        "ready",
        "due",
        "service",
        "start_times",
        "td_factors",
        "td_basis",
        "n_real",
        "v_real",
    ],
    meta_fields=["has_tw", "slice_minutes", "het_fleet", "td_rank"],
)
@dataclasses.dataclass(frozen=True)
class Instance:
    """A VRP/TSP instance as a JAX pytree.

    durations:    f32[T, N, N] travel durations; slice t applies to legs
                  departing within time-of-day slice t (cyclic). T == 1
                  means time-independent.
    demands:      f32[N], demands[0] == 0 (depot).
    capacities:   f32[V] per-vehicle capacities (BIG => uncapacitated).
                  V == 1 with capacity BIG models plain TSP.
    ready/due:    f32[N] time-window bounds (0 / BIG when absent).
    service:      f32[N] service durations (0 when absent).
    start_times:  f32[V] vehicle shift start times.
    has_tw:       static bool — whether the TW propagation path is traced.
    slice_minutes:static float — wall-minutes per time-of-day slice.
    het_fleet:    static bool — capacities are non-uniform; split-based
                  fitness shortcuts (which assume one capacity) must
                  give way to exact per-vehicle giant-tour pricing.
    n_real/v_real: TRACED real node / vehicle counts of a tier-padded
                  instance (core.tiers), or None when unpadded. Node
                  ids >= n_real are depot-alias phantoms: their
                  duration rows/columns copy the depot's, demands and
                  service are zero, windows are [ready[0], BIG] — so
                  in the giant encoding a phantom behaves EXACTLY like
                  a depot-zero route separator (core.encoding.
                  separators). Carrying the counts as data (not
                  metadata) is the whole point: every instance in a
                  tier shares one compiled program, and the masks that
                  confine search to the real prefix are dynamic.
    td_rank/td_factors/td_basis: the time-profile factorization
                  durations[t] == sum_r td_factors[r, t] * td_basis[r]
                  (exact to f32 noise), detected at build time for
                  time-dependent instances. Real time-of-day matrices
                  are low-rank in time (a base matrix modulated by a
                  daily profile), and the factorized form is what lets
                  the TD hot path pay R ~ 1 leg-contraction instead of
                  T = 24 (core.cost._td_hot_batch). td_rank == 0 means
                  no exact low-rank form was found; the hot path then
                  falls back to the flat-gather scan.
    """

    durations: jax.Array
    demands: jax.Array
    capacities: jax.Array
    ready: jax.Array
    due: jax.Array
    service: jax.Array
    start_times: jax.Array
    has_tw: bool
    slice_minutes: float
    het_fleet: bool = False
    td_factors: jax.Array | None = None  # [R, T]
    td_basis: jax.Array | None = None  # [R, N, N]
    td_rank: int = 0
    n_real: jax.Array | None = None  # i32 scalar: real node count (tiers)
    v_real: jax.Array | None = None  # i32 scalar: real vehicle count

    @property
    def n_nodes(self) -> int:
        return self.durations.shape[-1]

    @property
    def n_customers(self) -> int:
        return self.n_nodes - 1

    @property
    def n_vehicles(self) -> int:
        return self.capacities.shape[0]

    @property
    def n_slices(self) -> int:
        return self.durations.shape[0]

    @property
    def time_dependent(self) -> bool:
        return self.n_slices > 1

    @property
    def padded(self) -> bool:
        """Whether this instance carries tier padding (core.tiers).
        None-ness of n_real is pytree STRUCTURE, so branching on it
        inside jit stays static."""
        return self.n_real is not None

    @property
    def real_nodes(self):
        """Real node count: traced i32 when padded, python int otherwise."""
        return self.n_nodes if self.n_real is None else self.n_real

    @property
    def real_vehicles(self):
        return self.n_vehicles if self.v_real is None else self.v_real

    @property
    def perm_limit(self):
        """Traced real CUSTOMER count on tier-padded instances — the
        mask bound for permutation-genome operators (crossover cuts,
        mutation windows, ruin seeds); None when unpadded (operators
        then use their static full range)."""
        return None if self.n_real is None else self.n_real - 1

    @property
    def move_limit(self):
        """Effective giant-tour length L_real = n_real + v_real (the
        real prefix [0, L_real) of a padded giant; the closing depot
        zero sits at L_real - 1 and moves touch [1, L_real - 2]).
        None when unpadded — callers then use the static length."""
        if self.n_real is None:
            return None
        return self.n_real + self.v_real


def mean_duration(inst: Instance) -> jax.Array:
    """Mean of the slice-0 durations over REAL nodes only (jittable).

    Tier-padded instances carry depot-alias values in phantom rows and
    columns, so a plain matrix mean would skew with the tier size; the
    masked mean keeps temperature scales and pheromone inits a function
    of the real problem alone.
    """
    d = inst.durations[0]
    if inst.n_real is None:
        return jnp.mean(d)
    nr = inst.n_real
    m = (jnp.arange(d.shape[0]) < nr).astype(d.dtype)
    return jnp.sum(d * m[:, None] * m[None, :]) / (
        nr.astype(d.dtype) ** 2
    )


def travel_duration(
    inst: Instance, source, target, depart_time: float = 0.0
) -> jax.Array:
    """Point-to-point travel duration, time-of-day slicing honored.

    The real implementation of the reference's duration-query stub
    (reference src/solver.py:7-15, `calculate_duration(source, target,
    time_of_day=0)` returning a random 3-320 placeholder): the slice is
    chosen cyclically from the departure time exactly as the
    time-dependent cost path does (core.cost._td_eval), so a query and a
    solve can never disagree. Jittable; indices may be traced.
    """
    s = jnp.asarray(source, jnp.int32)
    t = jnp.asarray(target, jnp.int32)
    slice_idx = (
        jnp.asarray(depart_time, jnp.float32) // inst.slice_minutes
    ).astype(jnp.int32) % inst.n_slices
    return inst.durations[slice_idx, s, t]


def make_instance(
    durations,
    demands=None,
    capacities=None,
    n_vehicles: int | None = None,
    ready=None,
    due=None,
    service=None,
    start_times=None,
    slice_minutes: float = 60.0,
    slice_axis: str = "auto",
    dtype=jnp.float32,
) -> Instance:
    """Build an Instance from loosely-typed host data.

    `durations` may be [N,N] or [T,N,N] (nested lists or arrays). The
    service layer feeds the database matrix (reference api/database.py:45
    `row['matrix']`) straight in; time-sliced matrices arrive as a list of
    per-slice rows or an [N,N,T] nesting, both normalised here.

    `slice_axis` pins where the time axis sits for 3-D input: "first"
    ([T,N,N]), "last" ([N,N,T]), or "auto" to infer from the square pair
    of axes. "auto" is ambiguous when T == N, so explicit callers (the
    service layer knows its JSON nesting) should pass "last"/"first".

    All normalization runs in HOST numpy; device arrays are created only
    by the final per-field transfers. The previous eager-jnp version
    issued ~10 tiny device programs per build, each costing a compile/
    load round trip through a tunneled TPU — seconds of latency before
    a solve could even start.
    """
    import numpy as np

    np_dtype = np.dtype(dtype)
    d = np.array(durations, dtype=np_dtype)
    if d.ndim == 2:
        d = d[None]
    elif d.ndim == 3:
        if slice_axis == "last":
            d = np.moveaxis(d, -1, 0)
        elif slice_axis == "auto":
            # [N, N, T] (per-pair list of slice durations, the natural
            # JSON nesting for matrix[i][j] == [t0, t1, ...]) -> T first.
            if d.shape[0] == d.shape[1] and d.shape[1] != d.shape[2]:
                d = np.moveaxis(d, -1, 0)
            elif d.shape[0] == d.shape[1] == d.shape[2]:
                raise ValueError(
                    "ambiguous cubic durations (T == N); pass "
                    "slice_axis='first' or 'last'"
                )
        elif slice_axis != "first":
            raise ValueError(f"slice_axis must be auto/first/last, got {slice_axis!r}")
    else:
        raise ValueError(f"durations must be [N,N] or time-sliced 3-D, got {d.shape}")
    n = d.shape[-1]
    if d.shape[-2] != n:
        raise ValueError(f"durations must be square, got {d.shape}")
    # Depot self-loop must be free: adjacent separator zeros in the giant
    # tour encode an unused vehicle, whose legs are (0, 0).
    d[:, 0, 0] = 0.0

    demands = (
        np.zeros(n, np_dtype)
        if demands is None
        else np.array(demands, dtype=np_dtype)
    )
    if demands.shape == (n,):
        demands[0] = 0.0
    if capacities is None:
        v = n_vehicles or 1
        capacities = np.full((v,), BIG, np_dtype)
    else:
        capacities = np.asarray(capacities, dtype=np_dtype).reshape(-1)
    v = capacities.shape[0]

    # Ready times alone also require the timed path (arrival waiting).
    has_tw = due is not None or ready is not None
    ready = np.zeros(n, np_dtype) if ready is None else np.asarray(ready, np_dtype)
    due = np.full(n, BIG, np_dtype) if due is None else np.asarray(due, np_dtype)
    service = (
        np.zeros(n, np_dtype)
        if service is None
        else np.array(service, dtype=np_dtype)
    )
    if service.shape == (n,):
        service[0] = 0.0  # no service at the depot
    start_times = (
        np.zeros(v, np_dtype)
        if start_times is None
        else np.asarray(start_times, np_dtype).reshape(-1)
    )
    if start_times.shape[0] != v:
        raise ValueError(
            f"start_times has {start_times.shape[0]} entries for {v} vehicles"
        )
    for name, arr in (
        ("demands", demands),
        ("ready", ready),
        ("due", due),
        ("service", service),
    ):
        if arr.shape != (n,):
            # JAX clamps out-of-range gathers silently, so a wrong-length
            # array would corrupt costs instead of erroring — reject here.
            raise ValueError(f"{name} must have shape ({n},), got {arr.shape}")

    td_factors = td_basis = None
    td_rank = 0
    if d.shape[0] > 1:
        td_rank, td_factors, td_basis = _td_factorize(d)

    return Instance(
        durations=jnp.asarray(d),
        demands=jnp.asarray(demands),
        capacities=jnp.asarray(capacities),
        ready=jnp.asarray(ready),
        due=jnp.asarray(due),
        service=jnp.asarray(service),
        start_times=jnp.asarray(start_times),
        has_tw=bool(has_tw),
        slice_minutes=float(slice_minutes),
        het_fleet=bool(np.unique(capacities).size > 1),
        td_factors=None if td_factors is None else jnp.asarray(td_factors),
        td_basis=None if td_basis is None else jnp.asarray(td_basis),
        td_rank=td_rank,
    )


def _td_factorize(d, max_rank: int = 4):
    """Exact low-rank time-profile factorization of [T, N, N] durations.

    Host-side SVD of the [T, N*N] unfolding; accepted at the smallest
    rank R <= max_rank whose reconstruction is exact to f32 noise
    (max abs error <= 1e-5 * scale — below the bf16 table rounding the
    one-hot hot paths already live with). Typical time-of-day data IS
    low-rank: a base matrix times a rush-hour profile is rank 1; a few
    independent zone profiles rank 2-4. Returns (0, None, None) when no
    exact form exists.
    """
    import numpy as np

    t = d.shape[0]
    flat = d.reshape(t, -1).astype(np.float64)
    try:
        u, s, vt = np.linalg.svd(flat, full_matrices=False)
    except np.linalg.LinAlgError:  # pragma: no cover - degenerate input
        return 0, None, None
    scale = float(np.abs(flat).max()) or 1.0
    for r in range(1, min(max_rank, len(s)) + 1):
        approx = (u[:, :r] * s[:r]) @ vt[:r]
        if float(np.abs(approx - flat).max()) <= 1e-5 * scale:
            factors = np.ascontiguousarray((u[:, :r] * s[:r]).T, dtype=np.float32)
            basis = np.ascontiguousarray(
                vt[:r].reshape(r, d.shape[1], d.shape[2]), dtype=np.float32
            )
            return r, factors, basis
    return 0, None, None
