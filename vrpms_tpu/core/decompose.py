"""Giant-instance decomposition: cluster -> batched tier solves -> stitch.

The tier ladder (core.tiers) tops out around n=1024 nodes; above it
there is no canonical shape, the TD delta kernel is gated, and a
monolithic SA state at n=10k would specialize a one-off multi-GB
program no other request ever shares. This module converts those
instances into exactly the workload the rest of the system was built
to exploit:

  1. **cluster** — customers are spatially partitioned (medoid
     farthest-point over the duration matrix, or k-means over
     coordinates when the matrix was never materialized — the streamed
     CVRPLIB path) into K shards, every shard sized to fit ONE
     canonical node tier. Same tier by construction means the shard
     instances share one padded shape, one compiled program, and one
     micro-batch bucket.
  2. **solve** — the K shard instances dispatch through the SAME
     batched kernel the micro-batcher uses (sched.batch.solve_sa_batch)
     in chunks of max_batch: ceil(K / max_batch) vmapped launches
     instead of K solo solves. Per-shard incumbents roll up through a
     ProgressFanout-style aggregator (ShardRollup) into one monotone
     incumbent/gap stream on the job's progress sink.
  3. **stitch** — shard routes merge onto their assigned slice of the
     global fleet (slices proportional to shard demand), then the
     cross-shard frontier is repaired: the band of customers nearest a
     neighboring shard's center is STRIPPED from the merged routes
     (their relative visit order preserved — core.delta's strip
     semantics) and re-optimized as one small warm-seeded same-tier
     instance on a reserved fleet slice (SA continuation from the
     stripped order); bands too small to warrant a solve, or customers
     that do not fit the reserved capacity, fall back to the
     capacity-aware cheapest-insertion repair.

Everything here is host-side numpy except the shard solves themselves;
solver/scheduler imports are function-level (the same layering rule
sched.batch follows). The service wires this in behind VRPMS_DECOMP
(service.solve._solve_decomposed); tests and benchmarks drive it
directly.

Env:
  VRPMS_DECOMP          — off | auto (default) | on; auto/on engage the
                          path for VRP SA requests above the ladder top.
  VRPMS_DECOMP_TIER     — target shard NODE tier (0 = auto: the largest
                          ladder tier <= 256).
  VRPMS_DECOMP_BOUNDARY — frontier ratio: a customer joins the boundary
                          band when its distance to the nearest OTHER
                          shard center is within this factor of the
                          distance to its own.
"""

from __future__ import annotations

import dataclasses
import math
import time

import numpy as np

from vrpms_tpu import config
from vrpms_tpu.core import tiers

#: auto shard node tier: the largest ladder tier at or below this —
#: big enough to amortize per-shard fixed costs, small enough that a
#: 10k-customer instance still yields a few dozen batchable shards
DEFAULT_SHARD_TARGET = 256

#: bands smaller than this greedy-insert instead of paying a solve
REOPT_MIN = 6

#: default SA budget of the boundary re-opt pass (a CONTINUATION from
#: the stripped order — the band re-enters the anneal warm, so a small
#: budget refines instead of re-melting)
REOPT_ITERS = 2000


# ---------------------------------------------------------------------------
# Engagement: when does a request take the decomposed path?
# ---------------------------------------------------------------------------


def mode() -> str:
    """VRPMS_DECOMP normalized to off|auto|on (junk falls back to auto,
    the registry's forgiving-parse policy)."""
    raw = str(config.get("VRPMS_DECOMP") or "auto").strip().lower()
    if raw in ("off", "0", "false", "no", "none"):
        return "off"
    return raw if raw in ("auto", "on") else "auto"


def ceiling(lad=None) -> int | None:
    """The ladder-top NODE tier — the largest instance the monolithic
    tier path canonicalizes. None when tiering is off (no ceiling
    notion, so decomposition never engages)."""
    lad = lad if lad is not None else tiers.ladder()
    if lad is None or not lad.n:
        return None
    return lad.n[-1]


def engaged(problem: str, algorithm: str, n_nodes: int, opts: dict) -> bool:
    """Whether this request takes the decompose-solve-stitch path.

    Engages only for VRP SA requests strictly ABOVE the ladder top —
    any instance that fits one tier keeps the exact monolithic path, so
    VRPMS_DECOMP on/auto is byte-identical to off below the ceiling.
    Options the decomposed path does not model (islands, ILS, polish,
    warm starts, makespan pricing) keep the monolithic path too: a
    requested feature must never be silently dropped.
    """
    if mode() == "off":
        return False
    if problem != "vrp" or algorithm != "sa":
        return False
    top = ceiling()
    if top is None or n_nodes <= top:
        return False
    unsupported = (
        "islands", "ils_rounds", "warm_start", "local_search",
        "local_search_pool", "makespan_weight", "profile",
    )
    return not any(opts.get(k) for k in unsupported)


def shard_node_tier(lad=None) -> int:
    """The common NODE tier every shard pads to: VRPMS_DECOMP_TIER, or
    the largest ladder tier <= DEFAULT_SHARD_TARGET (never above the
    ladder top — shards must fit one tier by construction)."""
    lad = lad if lad is not None else tiers.ladder()
    n_tiers = lad.n if (lad is not None and lad.n) else (DEFAULT_SHARD_TARGET,)
    target = int(config.get("VRPMS_DECOMP_TIER") or 0)
    if target <= 0:
        target = DEFAULT_SHARD_TARGET
    target = min(target, n_tiers[-1])
    at_or_below = [t for t in n_tiers if t <= target]
    return at_or_below[-1] if at_or_below else n_tiers[0]


# ---------------------------------------------------------------------------
# Partitioning: customers -> K tier-sized shards (+ the boundary band)
# ---------------------------------------------------------------------------


def _balanced_assign(dist: np.ndarray, cap: int) -> np.ndarray:
    """Assign each of n customers (rows of `dist`: distance to each of
    the k centers) to its nearest center with space, capped at `cap`
    members per center. Customers with the most to lose (largest
    best-vs-second-best regret) choose first — the classic regret
    heuristic, deterministic. Returns labels [n]."""
    n, k = dist.shape
    if k == 1:
        return np.zeros(n, dtype=np.int64)
    part = np.partition(dist, 1, axis=1)
    regret = part[:, 1] - part[:, 0]
    order = np.argsort(-regret, kind="stable")
    counts = np.zeros(k, dtype=np.int64)
    labels = np.full(n, -1, dtype=np.int64)
    for c in order:
        for center in np.argsort(dist[c], kind="stable"):
            if counts[center] < cap:
                labels[c] = center
                counts[center] += 1
                break
        else:  # every center full (k*cap < n) — least-filled fallback
            center = int(np.argmin(counts))
            labels[c] = center
            counts[center] += 1
    return labels


def partition_matrix(d: np.ndarray, k: int, cap: int):
    """Medoid partition straight off the duration matrix (the service
    path: requests carry a matrix, never coordinates). Farthest-point
    medoid seeding from the depot, then regret-ordered balanced
    nearest-medoid assignment. Returns (labels [n-1], dist [n-1, k]) in
    CUSTOMER indexing (customer i is node position i+1). The clustering
    metric is the symmetrized duration, computed COLUMN-WISE per medoid
    (O(n*k)) — a full np.minimum(d, d.T) copy would double the one
    giant allocation this path carries."""

    def sym_col(j):  # min(d[c, j], d[j, c]) over customers c
        return np.minimum(d[1:, j], d[j, 1:])

    medoids = [1 + int(np.argmax(np.minimum(d[0, 1:], d[1:, 0])))]
    cols = [sym_col(medoids[0])]
    while len(medoids) < k:
        to_set = np.min(np.stack(cols, axis=1), axis=1)
        far = 1 + int(np.argmax(to_set))
        if far in medoids:  # degenerate (duplicate points)
            far = 1 + int(np.argmin(np.isin(
                np.arange(1, d.shape[0]), medoids)))
        medoids.append(far)
        cols.append(sym_col(far))
    dist = np.stack(cols, axis=1)
    return _balanced_assign(dist, cap), dist


def partition_coords(coords: np.ndarray, k: int, cap: int, seed: int = 0,
                     iters: int = 15):
    """k-means partition over customer COORDINATES (the streamed
    CVRPLIB / generator path, where the O(n^2) matrix was deliberately
    never built). Seeded k-means++ init, a few Lloyd iterations, then
    the same balanced assignment as partition_matrix. `coords` includes
    the depot at row 0; returns (labels [n-1], dist [n-1, k])."""
    pts = np.asarray(coords, dtype=np.float64)[1:]
    n = pts.shape[0]
    rng = np.random.default_rng(seed)
    centers = [pts[int(rng.integers(n))]]
    for _ in range(1, k):
        d2 = np.min(
            ((pts[:, None] - np.asarray(centers)[None]) ** 2).sum(-1), axis=1
        )
        total = float(d2.sum())
        if total <= 0:
            centers.append(pts[int(rng.integers(n))])
            continue
        centers.append(pts[int(rng.choice(n, p=d2 / total))])
    centers = np.asarray(centers)
    for _ in range(iters):
        dist = np.linalg.norm(pts[:, None] - centers[None], axis=-1)
        labels = np.argmin(dist, axis=1)
        for j in range(k):
            sel = pts[labels == j]
            if len(sel):
                centers[j] = sel.mean(axis=0)
    dist = np.linalg.norm(pts[:, None] - centers[None], axis=-1)
    return _balanced_assign(dist, cap), dist


def boundary_band(labels: np.ndarray, dist: np.ndarray, ratio: float,
                  cap: int) -> np.ndarray:
    """The boundary band: customers whose distance to the nearest OTHER
    shard center is within `ratio` of the distance to their own —
    exactly the customers a shard-respecting solution most plausibly
    misplaces. Nearest-frontier-first, capped at `cap` so the band
    itself fits one tier. Returns NODE positions (customer index + 1),
    sorted ascending."""
    n, k = dist.shape
    if k < 2 or cap <= 0:
        return np.zeros(0, dtype=np.int64)
    own = dist[np.arange(n), labels]
    masked = dist.copy()
    masked[np.arange(n), labels] = np.inf
    other = masked.min(axis=1)
    with np.errstate(divide="ignore", invalid="ignore"):
        r = other / np.maximum(own, 1e-12)
    band = np.flatnonzero(r <= ratio)
    if band.size > cap:
        band = band[np.argsort(r[band], kind="stable")[:cap]]
    return np.sort(band) + 1


def boundary_ratio() -> float:
    val = float(config.get("VRPMS_DECOMP_BOUNDARY"))
    return val if val > 0 else 1.25


# ---------------------------------------------------------------------------
# The plan: shards, fleet slices, boundary band, shard-sum lower bound
# ---------------------------------------------------------------------------


class _Dist:
    """Distance accessor over either the dense duration matrix or raw
    coordinates (the streamed giant-file path, where the O(n^2) matrix
    deliberately never exists): `sub` builds one shard's submatrix on
    demand, `point` computes a single leg. Coordinate mode mirrors the
    CVRPLIB nint rounding convention so a shard of a streamed load
    prices identically to the same slice of a dense load."""

    def __init__(self, arrays: dict):
        self._d = arrays.get("durations")
        self._coords = arrays.get("coords")
        self._nint = bool(arrays.get("round_nint", False))

    def sub(self, idx) -> np.ndarray:
        if self._d is not None:
            idx = np.asarray(idx, dtype=np.int64)
            return self._d[np.ix_(idx, idx)]
        from vrpms_tpu.io.cvrplib import shard_matrix

        return shard_matrix(self._coords, idx, self._nint).astype(
            np.float32
        )

    def point(self, a, b) -> float:
        if self._d is not None:
            return float(self._d[a, b])
        # one leg of io.cvrplib._euc2d's convention, inlined: building
        # a 2x2 shard_matrix per call would triple the host repair
        # loops' cost (tests pin this equal to a shard_matrix entry)
        d = float(np.linalg.norm(self._coords[a] - self._coords[b]))
        return float(np.floor(d + 0.5)) if self._nint else d


@dataclasses.dataclass
class DecompPlan:
    """One giant request, decomposed. Node positions are ACTIVE
    positions (depot 0, customers 1..n-1) of the request's active set;
    vehicle ids are global fleet indices."""

    members: list          # per-shard np arrays of node positions
    boundary: np.ndarray   # node positions of the frontier band
    vehicles: list         # per-shard np arrays of global vehicle ids
    boundary_vehicles: np.ndarray  # reserved fleet slice for the band
    tier_n: int            # common node tier every shard pads to
    tier_v: int            # common vehicle tier
    lower_bound: float | None  # shard-sum quick lower bound
    arrays: dict           # host inputs: durations OR coords, demands,
                           # service, capacities, start_times, ...

    @property
    def n_shards(self) -> int:
        return len(self.members)

    @property
    def dist(self) -> _Dist:
        return _Dist(self.arrays)


def assign_fleet(capacities: np.ndarray, weights: list) -> list:
    """Split the global fleet into len(weights) slices sized to the
    demand weights: one vehicle per positive-weight group first, then
    each spare vehicle goes to the group with the largest CAPACITY
    DEFICIT (demand minus the capacity already assigned) — directly
    minimizing the excess the shard solves would otherwise have to
    penalize, where a plain proportional split leaves half the shards
    one vehicle short. Returns per-group arrays of vehicle ids,
    contiguous in id order — capacities are typically uniform, and
    contiguity keeps the stitched vehicle numbering readable."""
    caps = np.asarray(capacities, dtype=np.float64)
    v = len(caps)
    g = len(weights)
    w = np.maximum(np.asarray(weights, dtype=np.float64), 0.0)
    if w.sum() <= 0:
        w = np.ones(g)
    counts = np.zeros(g, dtype=np.int64)
    active = w > 0
    counts[active] = 1
    spare = v - int(counts.sum())
    if spare < 0:
        raise ValueError(
            f"{v} vehicles cannot cover {int(active.sum())} shard groups"
        )
    mean_cap = float(caps.mean())
    assigned = counts * mean_cap
    for _ in range(spare):
        deficit = np.where(active, w - assigned, -np.inf)
        i = int(np.argmax(deficit))
        if deficit[i] <= 0:
            # everyone covered: spread the rest proportionally
            i = int(np.argmax(np.where(active, w / np.maximum(
                counts, 1), -np.inf)))
        counts[i] += 1
        assigned[i] += mean_cap
    out, at = [], 0
    for c in counts:
        out.append(np.arange(at, at + int(c), dtype=np.int64))
        at += int(c)
    return out


def shard_sum_lower_bound(dist: _Dist, members: list) -> float | None:
    """Sum of per-shard MST bounds over (depot + shard members) — the
    ms-scale gap reference for decomposed solves (the quadratic-in-n
    monolithic quick bound would dominate a 10k submit). Valid for any
    shard-respecting route set: each shard's routes plus the depot form
    a connected spanning subgraph of its node set, so the shard MST is
    a floor; sums stay a floor of the decomposed objective. Submatrices
    are built (and symmetrized) per shard, O(shard^2) each — never a
    full-matrix copy. Returns None when vacuous."""
    from vrpms_tpu.io.bounds import _mst_weight

    total = 0.0
    for m in members:
        nodes = np.concatenate([[0], np.asarray(m, dtype=np.int64)])
        sm = np.asarray(dist.sub(nodes), dtype=np.float64)
        total += float(_mst_weight(np.minimum(sm, sm.T)))
    return total if total > 0 else None


def build_plan(
    durations,
    demands,
    service,
    capacities,
    start_times,
    slice_minutes: float = 60.0,
    seed: int = 0,
    coords=None,
    round_nint: bool = False,
) -> DecompPlan:
    """Cluster a giant untimed CVRP into a DecompPlan.

    Exactly one distance source: `durations` — the dense [N, N] matrix
    (float32 host copy is taken; the service path, where requests carry
    a matrix) — or `coords` [N, 2] (the STREAMED path: cvrplib
    parse_cvrplib(max_dense_n=...) meta, synth_clustered_coords), which
    partitions by k-means and builds every submatrix on demand so
    nothing O(n^2) ever materializes (`round_nint` mirrors the CVRPLIB
    rounding convention). Raises ValueError when the fleet cannot cover
    the shard count — the service maps that to a Data error.
    """
    if (durations is None) == (coords is None):
        raise ValueError(
            "decomposition needs exactly one of durations (dense) or "
            "coords (streamed)"
        )
    arrays: dict = {}
    if durations is not None:
        d = np.asarray(durations, dtype=np.float32)
        if d.ndim != 2:
            raise ValueError(
                "decomposition requires an untimed [N, N] matrix"
            )
        n = d.shape[0]
        arrays["durations"] = d
    else:
        pts = np.asarray(coords, dtype=np.float64)
        n = pts.shape[0]
        arrays["coords"] = pts
        arrays["round_nint"] = bool(round_nint)
    demands = np.asarray(demands, dtype=np.float64)
    service = np.asarray(service, dtype=np.float64)
    capacities = np.asarray(capacities, dtype=np.float64)
    start_times = np.asarray(start_times, dtype=np.float64)

    lad = tiers.ladder()
    tier_n = shard_node_tier(lad)
    cap = tier_n - 1  # customers per shard
    k = max(1, math.ceil((n - 1) / cap))
    if k > len(capacities):
        raise ValueError(
            f"decomposition needs at least {k} vehicles for {n - 1} "
            f"customers at shard tier {tier_n}, got {len(capacities)}"
        )
    if durations is not None:
        labels, dist = partition_matrix(d, k, cap)
    else:
        labels, dist = partition_coords(pts, k, cap, seed=seed)
    members = [
        np.flatnonzero(labels == j).astype(np.int64) + 1 for j in range(k)
    ]
    members = [m for m in members if m.size]
    band = boundary_band(labels, dist, boundary_ratio(), cap)

    band_demand = float(demands[band].sum()) if band.size else 0.0
    reserve_band = band.size >= REOPT_MIN and len(capacities) > len(members)
    if reserve_band:
        # the band ends up stripped onto the reserved slice, so shard
        # slices are sized for what each shard KEEPS — counting band
        # demand twice would starve the shards of vehicles
        band_set = set(int(c) for c in band)
        weights = [
            float(sum(demands[c] for c in m if int(c) not in band_set))
            for m in members
        ]
        weights.append(band_demand)
    else:
        weights = [float(demands[m].sum()) for m in members]
    slices = assign_fleet(capacities, weights)
    vehicles = slices[: len(members)]
    boundary_vehicles = (
        slices[len(members)] if reserve_band else np.zeros(0, dtype=np.int64)
    )

    group_sizes = [len(s) for s in slices]
    v_tiers = lad.v if (lad is not None and lad.v) else ()
    tier_v = tiers.tier_up(max(group_sizes), v_tiers) if v_tiers else max(group_sizes)

    arrays.update(
        demands=demands,
        service=service,
        capacities=capacities,
        start_times=start_times,
        slice_minutes=float(slice_minutes),
    )
    lb = shard_sum_lower_bound(_Dist(arrays), members)

    return DecompPlan(
        members=members,
        boundary=band,
        vehicles=vehicles,
        boundary_vehicles=boundary_vehicles,
        tier_n=tier_n,
        tier_v=tier_v,
        lower_bound=lb,
        arrays=arrays,
    )


# ---------------------------------------------------------------------------
# Shard instances: every shard pads to ONE (tier_n, tier_v) shape
# ---------------------------------------------------------------------------


def _sub_instance(plan: DecompPlan, nodes: np.ndarray, veh: np.ndarray,
                  lad1: "tiers.TierLadder"):
    from vrpms_tpu.core.instance import make_instance

    a = plan.arrays
    idx = np.concatenate([[0], nodes]).astype(np.int64)
    inst = make_instance(
        plan.dist.sub(idx),
        demands=a["demands"][idx],
        capacities=a["capacities"][veh],
        service=a["service"][idx],
        start_times=a["start_times"][veh],
        slice_minutes=a["slice_minutes"],
    )
    return tiers.pad_instance(inst, lad1)


def _shard_ladder(plan: DecompPlan) -> "tiers.TierLadder":
    return tiers.TierLadder(n=(plan.tier_n,), v=(plan.tier_v,), t=(1,))


def shard_instances(plan: DecompPlan) -> list:
    """Build + tier-pad every shard's Instance. All shards share one
    padded shape AND one pytree metadata set (the stacking contract):
    het_fleet is forced uniform across shards — a slice that happens to
    be uniform-capacity must not split the batch."""
    import dataclasses as _dc

    lad1 = _shard_ladder(plan)
    insts = [
        _sub_instance(plan, m, v, lad1)
        for m, v in zip(plan.members, plan.vehicles)
    ]
    if len({i.het_fleet for i in insts}) > 1:
        insts = [
            i if i.het_fleet else _dc.replace(i, het_fleet=True)
            for i in insts
        ]
    return insts


# ---------------------------------------------------------------------------
# Progress: K shard incumbent streams -> one monotone rollup
# ---------------------------------------------------------------------------


class CompletedShard:
    """A shard restored from a durable checkpoint instead of solved:
    `routes` are shard-LOCAL (node positions 1..m in the shard's
    sub-instance), `cost` the checkpointed penalized objective. `evals`
    is 0 by construction — a resumed attempt did not re-evaluate this
    shard, which is exactly what the recovery benchmark measures."""

    __slots__ = ("routes", "cost", "evals")

    def __init__(self, routes: list, cost: float):
        self.routes = [list(map(int, r)) for r in routes]
        self.cost = float(cost)
        self.evals = 0


def completed_from_state(plan: DecompPlan, shards_state) -> dict:
    """Validate a checkpoint's per-shard routes against THIS plan and
    return {shard index: CompletedShard} for the shards that can be
    skipped. Plans are deterministic for an unchanged request (seeded
    medoid/k-means over the same active set), so stored local routes
    normally match; any shard that does not validate — index out of
    range, wrong customer set — simply re-solves. Never raises."""
    out: dict = {}
    if not isinstance(shards_state, dict):
        return out
    for key, doc in shards_state.items():
        try:
            si = int(key)
            if not 0 <= si < plan.n_shards:
                continue
            routes = (doc or {}).get("routes")
            cost = float((doc or {}).get("cost"))
            m = int(plan.members[si].size)
            visited = sorted(c for r in routes for c in r)
            if visited != list(range(1, m + 1)):
                continue
            out[si] = CompletedShard(routes, cost)
        except (TypeError, ValueError, KeyError):
            continue
    return out


def _local_routes(res, n_real: int) -> list:
    """Per-vehicle shard-LOCAL routes out of either a SolveResult (its
    giant decodes) or a CompletedShard (already routes)."""
    routes = getattr(res, "routes", None)
    if routes is not None:
        return routes
    from vrpms_tpu.core.encoding import routes_from_giant

    return routes_from_giant(res.giant, n_real)


class ShardRollup:
    """ProgressFanout-style aggregator for the decomposed solve: the
    batched launch syncs a [K, B] per-shard best array; the rollup
    tracks each shard's best-so-far and publishes the SUM to the job's
    single sink — one monotone incumbent/gap stream for the whole
    decomposition. Chunked dispatch publishes only once every shard has
    reported (a partial sum would jump upward when the next chunk
    starts); eval accounting flows through either way. Cancellation
    passes straight through, so a job DELETE stops shard chunks at
    their next block boundary."""

    def __init__(self, sink, n_shards: int):
        self._sink = sink
        self._best = [None] * n_shards
        self._chunk: list = []

    def seed(self, shard: int, cost: float) -> None:
        """Pre-fill a resumed (checkpoint-restored) shard's best so the
        rolled-up incumbent stream prices the WHOLE instance once the
        remaining shards report — a resumed decomposition's stream is
        indistinguishable from a fresh one's."""
        self._best[int(shard)] = float(cost)

    def begin(self, shard_indices) -> None:
        self._chunk = list(shard_indices)

    def record(self, best, iters: int, evals_per_iter) -> None:
        try:
            rows = np.asarray(best)
            per = rows.reshape(rows.shape[0], -1).min(axis=1)
        except Exception:
            return
        for i, si in enumerate(self._chunk):
            if i >= per.shape[0]:
                break
            b = float(per[i])
            if self._best[si] is None or b < self._best[si]:
                self._best[si] = b
        if self._sink is None:
            return
        if any(b is None for b in self._best):
            # not every shard has an incumbent yet: forward the eval
            # accounting but no cost (an unreadable best is the sink's
            # documented "count evals, skip the snapshot" path)
            self._sink.record(None, iters, evals_per_iter)
            return
        self._sink.record(
            np.asarray([sum(self._best)], dtype=np.float64),
            iters,
            evals_per_iter,
        )

    def publish_total(self, total: float) -> None:
        """Post-stitch final total (boundary repair included)."""
        if self._sink is not None:
            self._sink.record(np.asarray([float(total)]), 0, None)

    @property
    def cancelled(self) -> bool:
        return self._sink is not None and self._sink.cancelled

    def note_cancel_seen(self) -> None:
        if self._sink is not None:
            self._sink.note_cancel_seen()


# ---------------------------------------------------------------------------
# Batched shard dispatch: ceil(K / max_batch) vmapped launches
# ---------------------------------------------------------------------------


def solve_shards(
    insts: list,
    seeds: list,
    params,
    weights=None,
    deadline_s: float | None = None,
    max_batch: int = 16,
    rollup: ShardRollup | None = None,
    on_launch=None,
    completed: dict | None = None,
    on_shard=None,
):
    """Solve every shard on the batched SA kernel in chunks of
    `max_batch` — the decomposition rides the micro-batcher's vmapped
    launch (sched.batch.solve_sa_batch) instead of a Python loop of
    solo solves. Returns (results, launches). The deadline splits
    evenly across the remaining chunks; a cancelled rollup collapses
    the remaining chunks to a zero budget so they return their
    constructive incumbents at one block's cost. `on_launch(chunk_index,
    shard_lo, size, wall_s)` fires after each vmapped launch — the
    service hangs per-launch trace events off it so the n=5000
    waterfall shows where the launches spent their time.

    `completed` ({shard index: CompletedShard}, from
    completed_from_state) restores checkpoint-solved shards WITHOUT
    re-solving them: only the remaining shards dispatch (fewer chunks,
    the deadline splits across what is actually left), their bests seed
    the rollup, and the results list carries the restored entries in
    place. `on_shard(shard_index, result)` fires once per NEWLY solved
    shard as its chunk completes — the durable checkpointer persists
    each shard's routes there, so a crash mid-decomposition loses at
    most the in-flight chunk."""
    from vrpms_tpu.obs import progress
    from vrpms_tpu.sched.batch import solve_sa_batch

    max_batch = max(1, int(max_batch))
    k = len(insts)
    results: list = [None] * k
    for si, cs in (completed or {}).items():
        results[si] = cs
        if rollup is not None:
            rollup.seed(si, cs.cost)
    remaining = [i for i in range(k) if results[i] is None]
    n_chunks = math.ceil(len(remaining) / max_batch)
    launches = 0
    t0 = time.monotonic()
    for ci in range(n_chunks):
        ids = remaining[ci * max_batch : (ci + 1) * max_batch]
        chunk = [insts[i] for i in ids]
        chunk_deadline = None
        if deadline_s is not None:
            left = max(0.0, deadline_s - (time.monotonic() - t0))
            chunk_deadline = left / (n_chunks - ci)
        if rollup is not None:
            if rollup.cancelled:
                chunk_deadline = 0.0
            rollup.begin(ids)
        launch_t0 = time.monotonic()
        with progress.attach(rollup):
            solved = solve_sa_batch(
                chunk,
                [seeds[i] for i in ids],
                params=params,
                weights=weights,
                deadline_s=chunk_deadline,
            )
        launches += 1
        for si, res in zip(ids, solved):
            results[si] = res
            if on_shard is not None:
                try:
                    on_shard(si, res)
                except Exception:
                    pass  # checkpoint bookkeeping must never fail a solve
        if on_launch is not None:
            try:
                on_launch(
                    ci, ids[0] if ids else 0, len(chunk),
                    time.monotonic() - launch_t0,
                )
            except Exception:
                pass  # trace bookkeeping must never fail a solve
    return results, launches


# ---------------------------------------------------------------------------
# Stitch: shard routes -> global fleet, then boundary repair
# ---------------------------------------------------------------------------


def stitch(plan: DecompPlan, results: list) -> list:
    """Merge shard SolveResults into per-global-vehicle routes of node
    positions. Shard route r rides global vehicle plan.vehicles[s][r];
    routes the solver parked on a shard's phantom vehicles (possible
    only on pathological penalized solutions) are collected and
    re-inserted by the capacity-aware repair."""
    v_total = len(plan.arrays["capacities"])
    routes: list = [[] for _ in range(v_total)]
    leftovers: list = []
    for members, veh, res in zip(plan.members, plan.vehicles, results):
        n_real = members.size + 1
        for r, route in enumerate(_local_routes(res, n_real)):
            mapped = [int(members[c - 1]) for c in route]
            if not mapped:
                continue
            if r < len(veh):
                routes[int(veh[r])].extend(mapped)
            else:
                leftovers.extend(mapped)
    if leftovers:
        _insert_capacitated(plan, routes, leftovers)
    return routes


def strip_band(routes: list, band: np.ndarray) -> list:
    """Remove the boundary band from merged routes IN PLACE, returning
    the stripped customers in their merged visit order (vehicle id
    order, then position) — the warm seed of the band re-opt, exactly
    core.delta's strip semantics over positions."""
    band_set = set(int(c) for c in band)
    order: list = []
    for v, route in enumerate(routes):
        kept = []
        for c in route:
            if c in band_set and c not in order:
                order.append(c)
            elif c not in band_set:
                kept.append(c)
        routes[v] = kept
    for c in band_set - set(order):  # defensive: band member never routed
        order.append(c)
    return order


def _insert_capacitated(plan: DecompPlan, routes: list, custs: list) -> None:
    """Capacity-aware cheapest insertion (the greedy-insert repair of
    core.delta, made load-feasible): each customer lands at the
    cheapest position whose route still has capacity headroom; with no
    feasible slot anywhere it takes the globally cheapest slot — the
    same penalized-best-effort semantics the SA objective prices."""
    d = plan.dist.point
    demands = plan.arrays["demands"]
    caps = plan.arrays["capacities"]
    loads = [float(demands[r].sum()) if r else 0.0 for r in routes]
    for c in custs:
        best = best_any = None  # (delta, v, pos)
        for v, route in enumerate(routes):
            seq = [0] + route + [0]
            feasible = loads[v] + demands[c] <= caps[v] + 1e-9
            for pos in range(1, len(seq)):
                a, b = seq[pos - 1], seq[pos]
                delta = d(a, c) + d(c, b) - d(a, b)
                cand = (delta, v, pos - 1)
                if best_any is None or cand < best_any:
                    best_any = cand
                if feasible and (best is None or cand < best):
                    best = cand
        _, v, pos = best if best is not None else best_any
        routes[v].insert(pos, int(c))
        loads[v] += float(demands[c])


def band_instance(plan: DecompPlan):
    """The boundary band as its own SAME-TIER instance on the reserved
    fleet slice (None when the band is too small or has no slice)."""
    if plan.boundary.size < REOPT_MIN or plan.boundary_vehicles.size == 0:
        return None
    return _sub_instance(
        plan, plan.boundary, plan.boundary_vehicles, _shard_ladder(plan)
    )


def repair_boundary(
    plan: DecompPlan,
    routes: list,
    seed: int = 0,
    weights=None,
    deadline_s: float | None = None,
    n_chains: int = 32,
    n_iters: int = REOPT_ITERS,
) -> dict:
    """The stitch pass's frontier repair: strip the boundary band from
    the merged routes, then re-optimize it as ONE small warm-seeded
    instance (SA continuation from the stripped visit order) on the
    reserved fleet slice; bands below REOPT_MIN — or customers the
    reserved capacity cannot hold — fall back to capacity-aware
    cheapest insertion. Returns a report dict for the response's
    `decomposition` block."""
    band = plan.boundary
    if band.size == 0:
        return {"boundary": 0, "reoptimized": False}
    order = strip_band(routes, band)
    inst = band_instance(plan)
    if inst is None:
        _insert_capacitated(plan, routes, order)
        return {"boundary": int(band.size), "reoptimized": False}

    import jax

    from vrpms_tpu.core.cost import resolve_eval_mode
    from vrpms_tpu.core.encoding import routes_from_giant
    from vrpms_tpu.core.split import greedy_split_giant
    from vrpms_tpu.solvers import SAParams
    from vrpms_tpu.solvers.sa import (
        continuation_params,
        perturbed_clones,
        solve_sa,
    )

    pos_of = {int(c): i + 1 for i, c in enumerate(band)}
    warm = tiers.pad_perm(
        np.asarray([pos_of[c] for c in order], dtype=np.int32), inst
    )
    params = SAParams(n_chains=n_chains, n_iters=n_iters)
    seed_giant = greedy_split_giant(warm, inst)
    params = continuation_params(inst, params, seed_giant, weights)
    init = perturbed_clones(
        jax.random.key(seed + 1),
        params.n_chains,
        seed_giant,
        resolve_eval_mode("auto"),
        length_real=inst.move_limit,
    )
    from vrpms_tpu.obs import progress

    with progress.masked():
        # the band instance's costs are a fraction of the full
        # instance's: left unmasked they would publish as the job's
        # incumbent and the improves-only filter would then discard
        # every honest full-instance total that follows
        res = solve_sa(
            inst,
            key=seed,
            params=params,
            weights=weights,
            init_giants=init,
            deadline_s=deadline_s,
        )
    n_real = band.size + 1
    overflow: list = []
    for r, route in enumerate(routes_from_giant(res.giant, n_real)):
        mapped = [int(band[c - 1]) for c in route]
        if not mapped:
            continue
        if r < plan.boundary_vehicles.size:
            routes[int(plan.boundary_vehicles[r])].extend(mapped)
        else:
            overflow.extend(mapped)
    if overflow:
        _insert_capacitated(plan, routes, overflow)
    return {
        "boundary": int(band.size),
        "reoptimized": True,
        "reoptEvals": int(res.evals),
    }


def rebalance_capacity(plan: DecompPlan, routes: list) -> int:
    """Post-stitch feasibility sweep: while a vehicle carries more than
    its capacity, relocate the overloaded route's cheapest-to-move
    customer to the cheapest position on a route with headroom. The
    shard solves are independently capacity-feasible almost always, but
    the penalized SA objective CAN return a slightly overloaded route
    (and the band re-opt's slice is sized by estimate) — this sweep
    restores feasibility whenever fleet headroom exists at all, the
    same guarantee the monolithic exact path's packing gives. Bounded
    at one relocation per customer; returns relocations performed."""
    d = plan.dist.point
    demands = plan.arrays["demands"]
    caps = plan.arrays["capacities"]
    loads = [float(demands[r].sum()) if r else 0.0 for r in routes]
    budget = sum(len(r) for r in routes)
    moves = 0
    progressed = True
    while progressed and moves < budget:
        progressed = False
        for v, route in enumerate(routes):
            while loads[v] > caps[v] + 1e-9 and moves < budget:
                best = None  # (net_delta, ci, tv, tpos)
                seq = [0] + route + [0]
                for ci, c in enumerate(route):
                    gain = (
                        d(seq[ci], c) + d(c, seq[ci + 2])
                        - d(seq[ci], seq[ci + 2])
                    )
                    for tv, target in enumerate(routes):
                        if tv == v or (
                            loads[tv] + demands[c] > caps[tv] + 1e-9
                        ):
                            continue
                        tseq = [0] + target + [0]
                        for pos in range(1, len(tseq)):
                            a, b = tseq[pos - 1], tseq[pos]
                            delta = d(a, c) + d(c, b) - d(a, b)
                            cand = (delta - gain, ci, tv, pos - 1)
                            if best is None or cand < best:
                                best = cand
                if best is None:
                    break  # no headroom anywhere: leave penalized
                _, ci, tv, pos = best
                c = route.pop(ci)
                routes[tv].insert(pos, c)
                loads[v] -= float(demands[c])
                loads[tv] += float(demands[c])
                moves += 1
                progressed = True
    return moves


# ---------------------------------------------------------------------------
# Host pricing of the stitched solution (untimed CVRP only — the
# decomposed path's engagement gate)
# ---------------------------------------------------------------------------


def evaluate_routes(plan: DecompPlan, routes: list) -> dict:
    """Price the final global routes exactly as core.cost's untimed
    path would: route duration = legs + service of visited customers,
    distance = legs only, capacity excess per route against its own
    vehicle. Host numpy — O(n), never builds the giant tensor."""
    d = plan.dist.point
    demands = plan.arrays["demands"]
    service = plan.arrays["service"]
    caps = plan.arrays["capacities"]
    route_durations, loads = [], []
    distance = excess = 0.0
    for v, route in enumerate(routes):
        if not route:
            route_durations.append(0.0)
            loads.append(0.0)
            continue
        path = [0] + route + [0]
        legs = float(sum(d(a, b) for a, b in zip(path[:-1], path[1:])))
        srv = float(sum(service[c] for c in route))
        load = float(sum(demands[c] for c in route))
        distance += legs
        route_durations.append(legs + srv)
        loads.append(load)
        excess += max(0.0, load - float(caps[v]))
    return {
        "distance": distance,
        "duration_sum": float(sum(route_durations)),
        "duration_max": float(max(route_durations) if route_durations else 0.0),
        "route_durations": route_durations,
        "route_loads": loads,
        "cap_excess": excess,
    }
