"""Route-cost kernels: the hot path of every solver.

The reference specified this slot but left it empty — its cost stub
returns random durations (reference src/solver.py:7-15) beneath a `# TODO:
Run algorithm` hole in every endpoint (e.g. reference api/vrp/ga/
index.py:48). Here it is a fixed-shape, gather+segment-reduce kernel that
vmaps over thousands of candidate giant tours at once.

Three compile-time paths, selected by static instance metadata:

  1. time-independent, no time windows — pure gathers + segment sums,
     O(L) with no sequential dependency at all (the SA/GA inner loop);
  2. time windows, time-independent durations — arrival propagation
     `a' = max(a + t, ready)` is a max-plus affine map, so the whole
     route timeline is a `jax.lax.associative_scan` (log-depth, stays
     vectorised on the VPU);
  3. time-dependent durations (durations[T, N, N]) — travel time depends
     on departure time, which breaks associativity, so a `lax.scan` walks
     the tour; still batched across candidates by vmap.

All three return the same CostBreakdown so solvers are path-agnostic.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from vrpms_tpu.core.encoding import route_ids
from vrpms_tpu.core.instance import BIG, Instance


class CostBreakdown(NamedTuple):
    """Per-candidate cost components (all f32 scalars except route_durations)."""

    distance: jax.Array        # sum of travel durations over all legs
    route_durations: jax.Array # f32[V]: per-route elapsed time (travel +
                               # service + TW waiting when applicable)
    cap_excess: jax.Array      # sum of per-route demand overflow
    tw_lateness: jax.Array     # sum of per-visit lateness past `due`

    @property
    def duration_max(self) -> jax.Array:
        # axis=-1 keeps per-candidate values on batched breakdowns
        return self.route_durations.max(axis=-1)

    @property
    def duration_sum(self) -> jax.Array:
        return self.route_durations.sum(axis=-1)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["cap", "tw"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class CostWeights:
    """Penalty weights combining CostBreakdown into one scalar objective."""

    cap: jax.Array
    tw: jax.Array

    @staticmethod
    def make(cap: float = 1_000.0, tw: float = 100.0) -> "CostWeights":
        return CostWeights(jnp.float32(cap), jnp.float32(tw))


def total_cost(c: CostBreakdown, w: CostWeights) -> jax.Array:
    return c.distance + w.cap * c.cap_excess + w.tw * c.tw_lateness


def _cap_excess(giant, rid, inst: Instance) -> jax.Array:
    v = inst.n_vehicles
    node_demand = inst.demands[giant[:-1]]
    load = jax.ops.segment_sum(node_demand, rid[:-1], num_segments=v)
    return jnp.maximum(load - inst.capacities, 0.0).sum()


def _fast_eval(giant, inst: Instance) -> CostBreakdown:
    """Path 1: gathers + segment sums only."""
    v = inst.n_vehicles
    d = inst.durations[0]
    rid = route_ids(giant)
    legs = d[giant[:-1], giant[1:]]
    elapsed = legs + inst.service[giant[:-1]]
    route_dur = jax.ops.segment_sum(elapsed, rid[:-1], num_segments=v)
    return CostBreakdown(
        distance=legs.sum(),
        route_durations=route_dur,
        cap_excess=_cap_excess(giant, rid, inst),
        tw_lateness=jnp.float32(0.0),
    )


def _tw_eval(giant, inst: Instance) -> CostBreakdown:
    """Path 2: associative-scan arrival propagation.

    Each leg k-1 -> k is the max-plus affine map  a -> max(a + t_k, r_k).
    Departing a depot-zero resets the clock to that route's shift start
    (vehicles run in parallel, so route r+1 does not wait for route r):
    encoded as t = -BIG so the reset's `r` term always wins. Maps compose
    as (t1,r1) then (t2,r2) = (t1+t2, max(r1+t2, r2)) — associative, so
    the full timeline is one log-depth scan.
    """
    v = inst.n_vehicles
    d = inst.durations[0]
    rid = route_ids(giant)
    prev, cur = giant[:-1], giant[1:]
    legs = d[prev, cur]
    from_depot = prev == 0
    route_of_leg = jnp.minimum(rid[:-1], v - 1)
    start = inst.start_times[route_of_leg]

    t = jnp.where(from_depot, -BIG, legs + inst.service[prev])
    r = jnp.where(
        from_depot,
        jnp.maximum(start + legs, inst.ready[cur]),
        inst.ready[cur],
    )

    def combine(x, y):
        t1, r1 = x
        t2, r2 = y
        return t1 + t2, jnp.maximum(r1 + t2, r2)

    _, arrive = jax.lax.associative_scan(combine, (t, r))
    # arrive[k-1] is the arrival time at position k (k = 1..L-1); the
    # first leg departs a depot so the reset makes the initial value moot.
    lateness = jnp.maximum(arrive - inst.due[cur], 0.0).sum()

    # Route r's elapsed time = arrival at its closing zero - shift start.
    closes = cur == 0  # position k closes route rid[k]-1 == rid[k-1 at prev]
    route_end = jax.ops.segment_sum(
        jnp.where(closes, arrive, 0.0), route_of_leg, num_segments=v
    )
    route_dur = jnp.maximum(route_end - inst.start_times, 0.0)

    return CostBreakdown(
        distance=legs.sum(),
        route_durations=route_dur,
        cap_excess=_cap_excess(giant, rid, inst),
        tw_lateness=lateness,
    )


def _td_eval(giant, inst: Instance) -> CostBreakdown:
    """Path 3: sequential walk for time-of-day-dependent durations.

    Realises the `time_of_day` axis the reference declared but never used
    (reference src/solver.py:7): the duration slice is chosen by the
    departure time, cyclically over the T slices of `slice_minutes` each.
    """
    v = inst.n_vehicles
    t_slices = inst.n_slices
    rid = route_ids(giant)
    prev, cur = giant[:-1], giant[1:]
    from_depot = prev == 0
    route_of_leg = jnp.minimum(rid[:-1], v - 1)
    start = inst.start_times[route_of_leg]

    def step(clock, leg):
        p, c, dep_reset, shift_start = leg
        depart = jnp.where(dep_reset, shift_start, clock + inst.service[p])
        slice_idx = (depart // inst.slice_minutes).astype(jnp.int32) % t_slices
        travel = inst.durations[slice_idx, p, c]
        arrive = jnp.maximum(depart + travel, inst.ready[c])
        return arrive, (travel, arrive)

    _, (legs, arrive) = jax.lax.scan(
        step, jnp.float32(0.0), (prev, cur, from_depot, start)
    )
    lateness = jnp.maximum(arrive - inst.due[cur], 0.0).sum()
    closes = cur == 0
    route_end = jax.ops.segment_sum(
        jnp.where(closes, arrive, 0.0), route_of_leg, num_segments=v
    )
    route_dur = jnp.maximum(route_end - inst.start_times, 0.0)
    return CostBreakdown(
        distance=legs.sum(),
        route_durations=route_dur,
        cap_excess=_cap_excess(giant, rid, inst),
        tw_lateness=lateness,
    )


def evaluate_giant(giant: jax.Array, inst: Instance) -> CostBreakdown:
    """Evaluate one giant tour; dispatches on static instance metadata."""
    if inst.time_dependent:
        return _td_eval(giant, inst)
    if inst.has_tw:
        return _tw_eval(giant, inst)
    return _fast_eval(giant, inst)


def evaluate_batch(giants: jax.Array, inst: Instance) -> CostBreakdown:
    """vmapped evaluation over a [B, L] batch of candidates."""
    return jax.vmap(evaluate_giant, in_axes=(0, None))(giants, inst)


def objective(giant: jax.Array, inst: Instance, w: CostWeights) -> jax.Array:
    return total_cost(evaluate_giant(giant, inst), w)


def objective_batch(giants: jax.Array, inst: Instance, w: CostWeights) -> jax.Array:
    return jax.vmap(objective, in_axes=(0, None, None))(giants, inst, w)
