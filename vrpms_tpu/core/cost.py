"""Route-cost kernels: the hot path of every solver.

The reference specified this slot but left it empty — its cost stub
returns random durations (reference src/solver.py:7-15) beneath a `# TODO:
Run algorithm` hole in every endpoint (e.g. reference api/vrp/ga/
index.py:48). Here it is a fixed-shape, gather+segment-reduce kernel that
vmaps over thousands of candidate giant tours at once.

Three compile-time paths, selected by static instance metadata:

  1. time-independent, no time windows — pure gathers + segment sums,
     O(L) with no sequential dependency at all (the SA/GA inner loop);
  2. time windows, time-independent durations — arrival propagation
     `a' = max(a + t, ready)` is a max-plus affine map, so the whole
     route timeline is a `jax.lax.associative_scan` (log-depth, stays
     vectorised on the VPU);
  3. time-dependent durations (durations[T, N, N]) — travel time depends
     on departure time, which breaks associativity, so a `lax.scan` walks
     the tour; still batched across candidates by vmap.

All three return the same CostBreakdown so solvers are path-agnostic.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from vrpms_tpu.core.encoding import route_ids, separators
from vrpms_tpu.core.instance import BIG, Instance


class CostBreakdown(NamedTuple):
    """Per-candidate cost components (all f32 scalars except route_durations)."""

    distance: jax.Array        # sum of travel durations over all legs
    route_durations: jax.Array # f32[V]: per-route elapsed time (travel +
                               # service + TW waiting when applicable)
    cap_excess: jax.Array      # sum of per-route demand overflow
    tw_lateness: jax.Array     # sum of per-visit lateness past `due`

    @property
    def duration_max(self) -> jax.Array:
        # axis=-1 keeps per-candidate values on batched breakdowns
        return self.route_durations.max(axis=-1)

    @property
    def duration_sum(self) -> jax.Array:
        return self.route_durations.sum(axis=-1)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["cap", "tw", "makespan"],
    meta_fields=["use_makespan"],
)
@dataclasses.dataclass(frozen=True)
class CostWeights:
    """Penalty weights combining CostBreakdown into one scalar objective.

    `makespan` prices the LONGEST route's elapsed time into the
    objective — the durationMax the contract reports (and the reference
    parses but never optimizes). `use_makespan` is static metadata so
    the cheaper no-makespan traces (which skip per-route duration
    bookkeeping entirely) stay specialized.
    """

    cap: jax.Array
    tw: jax.Array
    makespan: jax.Array
    use_makespan: bool

    @staticmethod
    def make(
        cap: float = 1_000.0, tw: float = 100.0, makespan: float = 0.0
    ) -> "CostWeights":
        return CostWeights(
            jnp.float32(cap),
            jnp.float32(tw),
            jnp.float32(makespan),
            bool(makespan != 0.0),
        )


def total_cost(c: CostBreakdown, w: CostWeights) -> jax.Array:
    cost = c.distance + w.cap * c.cap_excess + w.tw * c.tw_lateness
    if w.use_makespan:
        cost = cost + w.makespan * c.duration_max
    return cost


def _cap_excess(giant, rid, inst: Instance) -> jax.Array:
    v = inst.n_vehicles
    node_demand = inst.demands[giant[:-1]]
    load = jax.ops.segment_sum(node_demand, rid[:-1], num_segments=v)
    return jnp.maximum(load - inst.capacities, 0.0).sum()


def _fast_eval(giant, inst: Instance) -> CostBreakdown:
    """Path 1: gathers + segment sums only."""
    v = inst.n_vehicles
    d = inst.durations[0]
    rid = route_ids(giant, inst.n_real)
    legs = d[giant[:-1], giant[1:]]
    elapsed = legs + inst.service[giant[:-1]]
    route_dur = jax.ops.segment_sum(elapsed, rid[:-1], num_segments=v)
    return CostBreakdown(
        distance=legs.sum(),
        route_durations=route_dur,
        cap_excess=_cap_excess(giant, rid, inst),
        tw_lateness=jnp.float32(0.0),
    )


def _tw_eval(giant, inst: Instance) -> CostBreakdown:
    """Path 2: associative-scan arrival propagation.

    Each leg k-1 -> k is the max-plus affine map  a -> max(a + t_k, r_k).
    Departing a depot-zero resets the clock to that route's shift start
    (vehicles run in parallel, so route r+1 does not wait for route r):
    encoded as t = -BIG so the reset's `r` term always wins. Maps compose
    as (t1,r1) then (t2,r2) = (t1+t2, max(r1+t2, r2)) — associative, so
    the full timeline is one log-depth scan.
    """
    v = inst.n_vehicles
    d = inst.durations[0]
    rid = route_ids(giant, inst.n_real)
    prev, cur = giant[:-1], giant[1:]
    legs = d[prev, cur]
    from_depot = separators(prev, inst.n_real)
    route_of_leg = jnp.minimum(rid[:-1], v - 1)
    start = inst.start_times[route_of_leg]

    t = jnp.where(from_depot, -BIG, legs + inst.service[prev])
    r = jnp.where(
        from_depot,
        jnp.maximum(start + legs, inst.ready[cur]),
        inst.ready[cur],
    )

    def combine(x, y):
        t1, r1 = x
        t2, r2 = y
        return t1 + t2, jnp.maximum(r1 + t2, r2)

    _, arrive = jax.lax.associative_scan(combine, (t, r))
    # arrive[k-1] is the arrival time at position k (k = 1..L-1); the
    # first leg departs a depot so the reset makes the initial value moot.
    lateness = jnp.maximum(arrive - inst.due[cur], 0.0).sum()

    # Route r's elapsed time = arrival at its closing separator - start.
    # Summed over the UNCLAMPED rid: a padded tail's surplus separators
    # carry rid >= v, which segment_sum drops (matching the batched
    # _per_route_sums) — the v-1 clamp (needed only for the start-time
    # gather above) would collapse them all into the last real route
    # and inflate its duration whenever ready[0]/starts are nonzero.
    closes = separators(cur, inst.n_real)  # position k closes route rid[k]-1
    route_end = jax.ops.segment_sum(
        jnp.where(closes, arrive, 0.0), rid[:-1], num_segments=v
    )
    route_dur = jnp.maximum(route_end - inst.start_times, 0.0)

    return CostBreakdown(
        distance=legs.sum(),
        route_durations=route_dur,
        cap_excess=_cap_excess(giant, rid, inst),
        tw_lateness=lateness,
    )


def _td_eval(giant, inst: Instance) -> CostBreakdown:
    """Path 3: sequential walk for time-of-day-dependent durations.

    Realises the `time_of_day` axis the reference declared but never used
    (reference src/solver.py:7): the duration slice is chosen by the
    departure time, cyclically over the T slices of `slice_minutes` each.
    """
    v = inst.n_vehicles
    t_slices = inst.n_slices
    rid = route_ids(giant, inst.n_real)
    prev, cur = giant[:-1], giant[1:]
    from_depot = separators(prev, inst.n_real)
    route_of_leg = jnp.minimum(rid[:-1], v - 1)
    start = inst.start_times[route_of_leg]

    def step(clock, leg):
        p, c, dep_reset, shift_start = leg
        depart = jnp.where(dep_reset, shift_start, clock + inst.service[p])
        slice_idx = (depart // inst.slice_minutes).astype(jnp.int32) % t_slices
        travel = inst.durations[slice_idx, p, c]
        arrive = jnp.maximum(depart + travel, inst.ready[c])
        return arrive, (travel, arrive)

    _, (legs, arrive) = jax.lax.scan(
        step, jnp.float32(0.0), (prev, cur, from_depot, start)
    )
    lateness = jnp.maximum(arrive - inst.due[cur], 0.0).sum()
    closes = separators(cur, inst.n_real)
    # unclamped rid: padded-tail closes (rid >= v) must DROP, not pile
    # into route v-1 (see _tw_eval)
    route_end = jax.ops.segment_sum(
        jnp.where(closes, arrive, 0.0), rid[:-1], num_segments=v
    )
    route_dur = jnp.maximum(route_end - inst.start_times, 0.0)
    return CostBreakdown(
        distance=legs.sum(),
        route_durations=route_dur,
        cap_excess=_cap_excess(giant, rid, inst),
        tw_lateness=lateness,
    )


# --- One-hot (MXU) evaluation path -----------------------------------------
#
# TPU profiling (see bench.py history) shows XLA lowers elementwise gathers
# with ~1M data-dependent indices — `d[prev, next]`, `demands[giant]`, and
# batched `giant[src]` — to a scalar loop at ~140M elem/s, making the
# gather-based sweep ~25 ms at B=4096 while every other op is microseconds.
# The one-hot path reformulates those gathers as one-hot contractions that
# run on the MXU: selecting via `onehot(idx) @ table` is exact (each output
# sums exactly one table element), so the only approximation is that the
# durations matrix itself is rounded to bfloat16 (~1e-3 relative). Penalty
# terms stay exact: route-membership counts are integers <= L (exact in
# bf16 when L <= 256; larger instances auto-switch to f32 one-hots).


def onehot_dtype(bound: int):
    """Widest-exact one-hot dtype: integers <= 256 are exact in bf16."""
    return jnp.bfloat16 if bound <= 256 else jnp.float32


# XLA:TPU's DEFAULT dot/einsum precision TRUNCATES f32 operands to bf16
# on the MXU, so a one-hot contraction against a VALUE-carrying f32
# operand silently rounds values above 256 even when every dtype in the
# program says float32 (measured on v5e at n=502: node id 315 came out
# 316 through the one-hot move apply; CPU is exact, which is why CI
# never saw it). Every einsum whose VALUES are semantic — node ids,
# demands, ready/due windows, service/start times — must pass this
# precision. Pure 0/1 contractions and the d-table leg selections keep
# the fast default (the table's bf16 rounding is disclosed everywhere).
EXACT = jax.lax.Precision.HIGHEST


def _onehot(x: jax.Array, n: int, dtype) -> jax.Array:
    return (x[..., None] == jnp.arange(n, dtype=x.dtype)).astype(dtype)


def _tpu_backend() -> bool:
    """True when the default backend's devices are TPU chips (covers
    plugin aliases like 'axon' whose device platform is still 'tpu')."""
    try:
        dev = jax.devices()[0]
    except RuntimeError:
        return False
    return jax.default_backend() == "tpu" or dev.platform == "tpu"


def resolve_eval_mode(mode: str = "auto") -> str:
    """'pallas' (fused kernel) on TPU backends, 'gather' elsewhere;
    explicit modes pass through. The split exists because each hot-path
    formulation is catastrophic off its platform (scalar-loop gathers on
    TPU; dense 80-GFLOP one-hot contractions on CPU). 'pallas' degrades
    to 'onehot' per call when the kernel doesn't apply (timed instances,
    batch not a lane-tile multiple, pallas unavailable)."""
    if mode == "auto":
        backend = jax.default_backend()
        if backend == "cpu":
            return "gather"
        # The fused kernel is Mosaic/TPU-only; the TPU plugin registers
        # under an alias in some environments (e.g. 'axon'). Other
        # accelerators (GPU) get the XLA one-hot formulation.
        return "pallas" if _tpu_backend() else "onehot"
    if mode not in ("pallas", "onehot", "gather"):
        raise ValueError(
            f"eval mode must be auto/pallas/onehot/gather, got {mode!r}"
        )
    return mode


def _rid_batch(giants, n_real=None) -> jax.Array:
    """Batched route ids (the vectorized twin of encoding.route_ids);
    phantom ids >= n_real count as separators on padded instances."""
    return jnp.cumsum(
        separators(giants, n_real).astype(jnp.int32), axis=1
    ) - 1


def _per_route_sums(vals: jax.Array, rid: jax.Array, v: int) -> jax.Array:
    """Scatter-free per-route totals: vals[b, k] summed into the route
    owning leg k. cum-through-route-v is one einsum against the
    rid <= v mask; a diff recovers the per-route values. (For valid
    giant tours rid of every leg position is already in [0, v-1].)"""
    b = vals.shape[0]
    le = (rid[:, :-1, None] <= jnp.arange(v)[None, None, :]).astype(
        jnp.float32
    )
    cum = jnp.einsum(
        "bkv,bk->bv", le, vals,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    return jnp.diff(cum, axis=1, prepend=jnp.zeros((b, 1), cum.dtype))


def _cap_excess_hot(prev_oh, rid, inst: Instance) -> jax.Array:
    """Batched capacity excess without scatter: per-route loads from the
    one-hot-selected per-leg demands."""
    dem_prev = jnp.einsum(
        "bkn,n->bk", prev_oh, inst.demands,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    load = _per_route_sums(dem_prev, rid, inst.n_vehicles)
    return jnp.maximum(load - inst.capacities, 0.0).sum(-1)


def _legs_hot(giants: jax.Array, inst: Instance):
    """One-hot leg selection shared by the hot paths: returns (prev_oh,
    next_oh, legs, dt) with legs[b, k] = durations[0][g_k, g_k+1]
    selected exactly from the dt-rounded matrix; dt is the widest-exact
    one-hot dtype for this instance, owned here so both hot paths stay
    in precision lockstep."""
    n = inst.n_nodes
    dt = onehot_dtype(max(giants.shape[1], n))
    prev_oh = _onehot(giants[:, :-1], n, dt)  # (B, K, N), K = L-1
    next_oh = _onehot(giants[:, 1:], n, dt)
    d = inst.durations[0].astype(dt)
    # X[b,k,m] = durations[prev[b,k], m] — exact row selection of the
    # dt-rounded matrix; legs contract it against the next-node one-hot.
    x = jnp.einsum("bkn,nm->bkm", prev_oh, d, preferred_element_type=dt)
    legs = jnp.einsum(
        "bkm,bkm->bk", x, next_oh, preferred_element_type=jnp.float32
    )
    return prev_oh, next_oh, legs, dt


def tw_components_batch(giants: jax.Array, inst: Instance):
    """(distance, cap_excess, lateness, arrive, rid) of the one-hot TW
    path — the components _tw_hot_batch combines, shared so the TW
    delta solver can re-rank its pools in the exact same basis."""
    v = inst.n_vehicles
    prev_oh, next_oh, legs, dt = _legs_hot(giants, inst)
    dist = legs.sum(axis=1)

    service_prev = jnp.einsum(
        "bkn,n->bk", prev_oh, inst.service,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    ready_cur = jnp.einsum(
        "bkn,n->bk", next_oh, inst.ready,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    due_cur = jnp.einsum(
        "bkn,n->bk", next_oh, inst.due,
        preferred_element_type=jnp.float32, precision=EXACT,
    )

    from_depot = separators(giants[:, :-1], inst.n_real)
    rid = _rid_batch(giants, inst.n_real)
    route_of_leg = jnp.minimum(rid[:, :-1], v - 1)
    start_oh = (route_of_leg[..., None] == jnp.arange(v)).astype(jnp.float32)
    start = jnp.einsum(
        "bkv,v->bk", start_oh, inst.start_times,
        preferred_element_type=jnp.float32, precision=EXACT,
    )

    t = jnp.where(from_depot, -BIG, legs + service_prev)
    r = jnp.where(from_depot, jnp.maximum(start + legs, ready_cur), ready_cur)

    def combine(a, b):
        t1, r1 = a
        t2, r2 = b
        return t1 + t2, jnp.maximum(r1 + t2, r2)

    _, arrive = jax.lax.associative_scan(combine, (t, r), axis=1)
    lateness = jnp.maximum(arrive - due_cur, 0.0).sum(axis=1)
    cap_excess = _cap_excess_hot(prev_oh, rid, inst)
    return dist, cap_excess, lateness, arrive, rid


def _tw_hot_batch(giants: jax.Array, inst: Instance, w: CostWeights) -> jax.Array:
    """Gather-free batched objective for time-windowed instances.

    The same max-plus associative-scan arrival propagation as _tw_eval
    (see its derivation), but every per-leg quantity — leg duration,
    service at the origin, ready/due at the destination, the route's
    shift start — is a one-hot contraction instead of a gather, so the
    whole evaluation vectorizes on TPU (gathers there lower to a scalar
    loop ~50x slower). The scan itself runs batched over axis 1.
    """
    v = inst.n_vehicles
    dist, cap_excess, lateness, arrive, rid = tw_components_batch(giants, inst)
    cost = dist + w.cap * cap_excess + w.tw * lateness
    if w.use_makespan:
        # Route elapsed time = arrival at its closing separator minus
        # its shift start (the batched twin of _tw_eval's route_dur).
        closes = separators(giants[:, 1:], inst.n_real)
        route_end = _per_route_sums(jnp.where(closes, arrive, 0.0), rid, v)
        route_dur = jnp.maximum(route_end - inst.start_times[None, :], 0.0)
        cost = cost + w.makespan * route_dur.max(axis=-1)
    return cost


def _td_hot_batch(giants: jax.Array, inst: Instance, w: CostWeights) -> jax.Array:
    """Batched objective for time-DEPENDENT durations — the lean-scan
    hot path.

    The duration slice of each leg is chosen by its departure time
    (reference src/solver.py:7 `time_of_day`), a true sequential
    dependency with no associative reformulation — so a scan over the
    leg positions is irreducible. What IS reducible is everything
    around it: all per-leg aux quantities precompute over the whole
    (B, K) leg grid as one-hot contractions (MXU) before the scan, and
    when the instance carries an exact time-profile factorization
    (Instance.td_rank — the common case for real time-of-day data) the
    travel times do too: R basis-leg tables replace the per-step
    gather, and the scan body is pure VPU math. Semantics match
    _td_eval leg for leg (same clock propagation, same `% n_slices`
    cyclic slicing); the factorized path's travel times carry the same
    bf16 table rounding as every other one-hot hot path (the fallback
    flat-gather path, used when no exact factorization exists, stays
    f32-exact).
    """
    v = inst.n_vehicles
    t_slices = inst.n_slices
    n = inst.n_nodes
    b = giants.shape[0]
    dt = onehot_dtype(max(giants.shape[1], n))
    prev, cur = giants[:, :-1], giants[:, 1:]
    prev_oh = _onehot(prev, n, dt)
    next_oh = _onehot(cur, n, dt)
    service_prev = jnp.einsum(
        "bkn,n->bk", prev_oh, inst.service,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    ready_cur = jnp.einsum(
        "bkn,n->bk", next_oh, inst.ready,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    due_cur = jnp.einsum(
        "bkn,n->bk", next_oh, inst.due,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    rid = _rid_batch(giants, inst.n_real)
    route_of_leg = jnp.minimum(rid[:, :-1], v - 1)
    start_oh = (route_of_leg[..., None] == jnp.arange(v)).astype(jnp.float32)
    start = jnp.einsum(
        "bkv,v->bk", start_oh, inst.start_times,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    from_depot = separators(prev, inst.n_real)

    # Factorized fast path (VERDICT round-2 item 5): with the exact
    # time-profile factorization durations[t] = sum_r factors[r, t] *
    # basis[r] (Instance.td_rank, detected at build), the per-leg travel
    # for EVERY slice is available from R basis-leg contractions —
    # R ~ 1-4 times the cost of an untimed evaluation instead of T = 24
    # (a naive legs-by-slice einsum is T-times the untimed cost: 1.5
    # TFLOP per step at B=2048/n=200 — slower than the gather it would
    # replace). The scan body then reads factors at the departure slice
    # (a T-wide one-hot over a [R, T] table — VPU elementwise) and dots
    # them with the basis legs: no gather anywhere.
    if inst.td_rank > 0:
        # basis legs, one [B,K,N] intermediate at a time (R of them)
        rows = jnp.einsum(
            "bkn,rnm->rbkm",
            prev_oh,
            inst.td_basis.astype(dt),
            preferred_element_type=dt,
        )
        basis_legs = jnp.einsum(
            "rbkm,bkm->rbk", rows, next_oh, preferred_element_type=jnp.float32
        )  # [R, B, K]
        slice_ids = jnp.arange(t_slices, dtype=jnp.int32)
        factors = inst.td_factors  # [R, T]

        def step(clock, x):
            blegs_k, reset_k, start_k, svc_k, rdy_k = x  # blegs_k: [R, B]
            depart = jnp.where(reset_k, start_k, clock + svc_k)
            sidx = (depart // inst.slice_minutes).astype(jnp.int32) % t_slices
            sel = (slice_ids[None, :] == sidx[:, None]).astype(jnp.float32)
            fac = sel @ factors.T  # [B, R]: factors at each chain's slice
            travel = (fac.T * blegs_k).sum(axis=0)
            arrive = jnp.maximum(depart + travel, rdy_k)
            return arrive, (travel, arrive)

        xs = (
            jnp.moveaxis(basis_legs, 2, 0),  # [K, R, B]
            from_depot.T,
            start.T,
            service_prev.T,
            ready_cur.T,
        )
    else:
        # flat travel lookup: index = slice*N*N + prev*N + cur; the
        # (prev, cur) part is departure-independent, precomputed per leg.
        # T*N*N beyond int32 would gather garbage silently — and the
        # obvious jnp.int64 fix is a no-op here because x64 is never
        # enabled (int64 canonicalizes to int32; ADVICE round 3), so the
        # shape is rejected loudly at trace time instead. A [T, N, N]
        # table that big (~17 GB f32) exceeds HBM anyway.
        nn = n * n
        if t_slices * nn > 2**31 - 1:
            raise ValueError(
                f"full-rank time-dependent durations with T*N*N = "
                f"{t_slices * nn} exceed int32 flat indexing; reduce the "
                "slice count or supply factorizable (low-rank) profiles"
            )
        idt = jnp.int32
        pn = prev.astype(idt) * n + cur.astype(idt)
        d_flat = inst.durations.reshape(t_slices * nn)

        def step(clock, x):
            pn_k, reset_k, start_k, svc_k, rdy_k = x
            depart = jnp.where(reset_k, start_k, clock + svc_k)
            sidx = (depart // inst.slice_minutes).astype(idt) % t_slices
            travel = d_flat[sidx * nn + pn_k]
            arrive = jnp.maximum(depart + travel, rdy_k)
            return arrive, (travel, arrive)

        xs = (pn.T, from_depot.T, start.T, service_prev.T, ready_cur.T)

    _, (legs, arrive) = jax.lax.scan(step, jnp.zeros((b,), jnp.float32), xs)
    legs, arrive = legs.T, arrive.T  # back to (B, K)
    dist = legs.sum(axis=1)
    lateness = jnp.maximum(arrive - due_cur, 0.0).sum(axis=1)
    cap_excess = _cap_excess_hot(prev_oh, rid, inst)
    cost = dist + w.cap * cap_excess + w.tw * lateness
    if w.use_makespan:
        closes = separators(cur, inst.n_real)
        route_end = _per_route_sums(jnp.where(closes, arrive, 0.0), rid, v)
        route_dur = jnp.maximum(route_end - inst.start_times[None, :], 0.0)
        cost = cost + w.makespan * route_dur.max(axis=-1)
    return cost


def objective_hot_batch(
    giants: jax.Array, inst: Instance, w: CostWeights
) -> jax.Array:
    """Gather-free batched objective (XLA one-hot formulation).

    distance: bf16-rounded durations (exact one-hot selection of a
    rounded table); capacity excess: exact. Time-windowed instances take
    the one-hot max-plus-scan path above; time-DEPENDENT durations take
    the lean-scan path (_td_hot_batch): one-hot precomputation around an
    irreducible departure-time scan.
    """
    if inst.time_dependent:
        return _td_hot_batch(giants, inst, w)
    if inst.has_tw:
        return _tw_hot_batch(giants, inst, w)
    prev_oh, _, legs, dt = _legs_hot(giants, inst)
    dist = legs.sum(axis=1)
    rid = _rid_batch(giants, inst.n_real)
    cap_excess = _cap_excess_hot(prev_oh, rid, inst)
    cost = dist + w.cap * cap_excess
    if w.use_makespan:
        service_prev = jnp.einsum(
            "bkn,n->bk", prev_oh, inst.service,
            preferred_element_type=jnp.float32, precision=EXACT,
        )
        route_dur = _per_route_sums(legs + service_prev, rid, inst.n_vehicles)
        cost = cost + w.makespan * route_dur.max(axis=-1)
    return cost


def objective_batch_mode(
    giants: jax.Array, inst: Instance, w: CostWeights, mode: str = "auto"
) -> jax.Array:
    """Batched objective in the given eval mode.

    'pallas' requires an untimed instance and a lane-tile-multiple batch;
    anything else quietly uses the XLA one-hot path so solvers can pass
    one mode for every instance shape.
    """
    mode = resolve_eval_mode(mode)
    if mode == "pallas":
        from vrpms_tpu.kernels.sa_eval import pallas_objective_batch, pallas_supported

        # pallas_supported mirrors every kernel precondition including
        # the VMEM fit, so oversized instances degrade instead of
        # failing at Mosaic compile time. The kernel computes distance +
        # capacity only, so makespan-priced objectives use the XLA path.
        # tier-padded instances stay on the XLA paths: the fused
        # kernel's internal route logic keys on literal zeros and does
        # not model phantom separators
        if (
            _tpu_backend()
            and not w.use_makespan
            and inst.n_real is None
            and pallas_supported(inst, giants.shape[0])
        ):
            return pallas_objective_batch(giants, inst, w)
        mode = "onehot"
    if mode == "onehot":
        return objective_hot_batch(giants, inst, w)
    return objective_batch(giants, inst, w)


def evaluate_giant(giant: jax.Array, inst: Instance) -> CostBreakdown:
    """Evaluate one giant tour; dispatches on static instance metadata."""
    if inst.time_dependent:
        return _td_eval(giant, inst)
    if inst.has_tw:
        return _tw_eval(giant, inst)
    return _fast_eval(giant, inst)


@functools.lru_cache(maxsize=4)
def _exact_eval_fn():
    """Jitted (breakdown, total_cost) of one tour — the ONE compiled
    exact-evaluation program every solver's final/championship check
    uses. Eagerly, evaluate_giant + total_cost issue ~10 small device
    programs; through a tunneled TPU that is seconds of dispatch latency
    per call, paid once per solve and once per ILS round — as one jitted
    (and persistently cached) program it is one dispatch."""

    @jax.jit
    def fn(giant, inst, w):
        bd = evaluate_giant(giant, inst)
        return bd, total_cost(bd, w)

    return fn


def exact_cost(giant: jax.Array, inst: Instance, w: CostWeights):
    """(CostBreakdown, penalized cost) via the shared jitted program."""
    return _exact_eval_fn()(giant, inst, w)


@functools.lru_cache(maxsize=4)
def _exact_eval_batch_fn():
    """Jitted exact penalized costs of a [B, L] giant batch (the
    batched twin of _exact_eval_fn; used to re-rank small elite pools
    by the TRUE objective before results cross the solver boundary)."""

    @jax.jit
    def fn(giants, inst, w):
        bd = jax.vmap(evaluate_giant, in_axes=(0, None))(giants, inst)
        return total_cost(bd, w)

    return fn


def exact_cost_batch(giants: jax.Array, inst: Instance, w: CostWeights):
    """f32[B] exact penalized costs via the shared jitted program."""
    return _exact_eval_batch_fn()(giants, inst, w)


def evaluate_batch(giants: jax.Array, inst: Instance) -> CostBreakdown:
    """vmapped evaluation over a [B, L] batch of candidates."""
    return jax.vmap(evaluate_giant, in_axes=(0, None))(giants, inst)


def objective(giant: jax.Array, inst: Instance, w: CostWeights) -> jax.Array:
    return total_cost(evaluate_giant(giant, inst), w)


def objective_batch(giants: jax.Array, inst: Instance, w: CostWeights) -> jax.Array:
    return jax.vmap(objective, in_axes=(0, None, None))(giants, inst, w)


def best_feasible_pool(pool, inst) -> float | None:
    """Min DISTANCE over the zero-lateness zero-excess members of an
    elite pool ([K, L] giants), or None when no member is feasible.

    Gap-to-BKS lines must price a FEASIBLE tour; the cost-optimal
    champion of a penalized search may carry epsilon lateness while a
    slightly longer feasible elite sits in the pool (round 5)."""
    if pool is None:
        return None
    import numpy as np

    dist, cape, late, _, _ = tw_components_batch(pool, inst)
    dist, cape, late = map(np.asarray, (dist, cape, late))
    feas = (cape == 0.0) & (late == 0.0)
    if not feas.any():
        return None
    return float(dist[feas].min())
