"""Instance deltas + tour repair over the separator encoding — the
dynamic re-solve core.

Real fleets re-solve a rolling horizon: customers are added and
dropped, demands and time windows change, and every such request used
to pay a full cold metaheuristic solve. This module holds the two pure
pieces that make a re-solve cheap, shared by every consumer (the
solution cache's near-hit seeding, the explicit `warmStart` spec, and
the `POST /api/jobs/{id}/resolve` cancel-and-resolve path):

  * **tour repair** (`strip_order` / `repair_order` / `repair_perm`) —
    a prior solution's routes (ORIGINAL location ids) are repaired onto
    the CURRENT active customer set over the separator encoding: ids no
    longer active are stripped (surviving customers keep their relative
    visit order), customers the prior tour never saw are greedy-
    inserted at their cheapest position by slice-0 durations. The
    result is an int32 permutation of the active positions 1..n-1 —
    exactly the shape the warm-start machinery consumes — and the
    greedy split re-tiers it into V routes with the encoding's V+1
    separators intact.

  * **request deltas** (`apply_request_delta`) — a request may carry a
    `delta` relative to its stored dataset instead of re-spelling the
    whole instance: customers added back / dropped (rolling-horizon
    arrivals and completions, riding the reference's ignored/completed
    dynamic inputs) and per-location demand / time-window changes.
    Applied at the HTTP intake (handler_base / jobs submit), BEFORE the
    instance is built, so the fingerprint, the tier padding, and the
    cache keys all see the post-delta instance. Validation errors
    accumulate as the contract's Data-error envelope entries (a
    duplicate add or an unknown id is a 400, never a silent no-op).

Host-side and jax-light by design (one jnp.asarray at the very end):
repair is O(n^2) python over lists, which is microseconds at service
sizes and never touches the device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Tour repair: prior routes -> warm permutation for the current active set
# ---------------------------------------------------------------------------


def strip_order(routes, active_ids: list) -> tuple[list, set]:
    """The shared strip step of every cached-tour repair: surviving
    customers of `routes` (ORIGINAL location ids) as positions in the
    CURRENT active indexing, relative visit order preserved; also the
    set of positions covered. Used by the legacy checkpoint re-seed
    (service.solve._warm_perm), the cache's near-hit repair, and the
    explicit warm-start spec resolution."""
    index_of = {cid: i for i, cid in enumerate(active_ids)}
    seen: set = set()
    order: list = []
    for route in routes:
        for cid in route:
            pos = index_of.get(cid)
            if pos is not None and pos > 0 and pos not in seen:
                order.append(pos)
                seen.add(pos)
    return order, seen


def greedy_insert_positions(order: list, new: list, durations) -> list:
    """Insert each position in `new` into the depot-anchored sequence
    implied by `order` at its cheapest position (classic cheapest-
    insertion deltas over the slice-0 duration matrix, active
    indexing). Returns the extended order."""
    d = np.asarray(durations)
    seq = [0] + list(order) + [0]
    for c in new:
        best_delta, best_at = None, 1
        for k in range(1, len(seq)):
            a, b = seq[k - 1], seq[k]
            delta = float(d[a, c] + d[c, b] - d[a, b])
            if best_delta is None or delta < best_delta:
                best_delta, best_at = delta, k
        seq.insert(best_at, c)
    return seq[1:-1]


def repair_order(routes, active_ids: list, durations) -> list | None:
    """Strip-and-insert repair: prior `routes` (original ids) -> visit
    order over the CURRENT active positions 1..len(active_ids)-1, every
    active customer exactly once. `durations` is the active-indexed
    slice-0 matrix the insertions price against. Returns None when no
    prior customer survives — appending alone would be an arbitrary-
    order seed, no better than construction, so callers decline to
    seed."""
    order, seen = strip_order(routes, active_ids)
    if not order:
        return None
    new = [i for i in range(1, len(active_ids)) if i not in seen]
    if new:
        order = greedy_insert_positions(order, new, durations)
    return order


def repair_perm(routes, active_ids: list, durations):
    """repair_order as the int32 device array the warm-start machinery
    consumes (service.solve passes it through tiers.pad_perm on padded
    instances), or None when nothing survives to seed from."""
    order = repair_order(routes, active_ids, durations)
    if order is None:
        return None
    return jnp.asarray(order, dtype=jnp.int32)


# ---------------------------------------------------------------------------
# Request deltas: {add, drop, demands, timeWindows} against the dataset
# ---------------------------------------------------------------------------

_DELTA_KEYS = ("add", "drop", "demands", "timeWindows")


def _err(errors, reason: str) -> None:
    errors += [{"what": "Data error", "reason": reason}]


def _id_list(delta: dict, key: str, errors) -> list | None:
    val = delta.get(key)
    if val is None:
        return []
    if not isinstance(val, list):
        _err(errors, f"delta.{key} must be a list of location ids")
        return None
    if len(set(map(repr, val))) != len(val):
        _err(errors, f"delta.{key} contains duplicate ids")
        return None
    return val


def _attr_map(delta: dict, key: str, errors) -> dict | None:
    """A per-id attribute-change map. JSON object keys are strings, so
    ids are matched by their string form (str(3) == "3"); a list of
    [id, value] pairs is accepted too and keeps exotic id types exact."""
    val = delta.get(key)
    if val is None:
        return {}
    if isinstance(val, dict):
        return {str(k): v for k, v in val.items()}
    if isinstance(val, list) and all(
        isinstance(p, (list, tuple)) and len(p) == 2 for p in val
    ):
        return {str(k): v for k, v in val}
    _err(
        errors,
        f"delta.{key} must be an object of id -> value (or a list of "
        "[id, value] pairs)",
    )
    return None


def apply_request_delta(
    problem: str, params: dict, locations: list, delta, errors
) -> list | None:
    """Apply a request `delta` to its dataset view before the instance
    is built. Mutates the ACTIVE-SET parameters in place (VRP:
    ignored/completed lists; TSP: the customers list) so every later
    consumer — instance build, cache keys, the save-path location
    filter — sees the post-delta world, and returns a locations list
    with demand/time-window changes applied (changed dicts are copies;
    the stored dataset rows are never mutated). On any contract
    violation appends Data-error envelope entries and returns None.
    """
    if not isinstance(delta, dict):
        _err(errors, "'delta' must be an object")
        return None
    unknown = [k for k in delta if k not in _DELTA_KEYS]
    if unknown:
        _err(
            errors,
            f"unknown delta key(s) {unknown}; expected one of "
            f"{list(_DELTA_KEYS)}",
        )
        return None
    add = _id_list(delta, "add", errors)
    drop = _id_list(delta, "drop", errors)
    demands = _attr_map(delta, "demands", errors)
    windows = _attr_map(delta, "timeWindows", errors)
    if add is None or drop is None or demands is None or windows is None:
        return None
    both = [c for c in add if c in drop]
    if both:
        _err(errors, f"delta adds and drops the same id(s) {both}")
        return None

    ids = [loc.get("id") for loc in locations]
    id_set = set(map(repr, ids))
    for cid in add + drop:
        if repr(cid) not in id_set:
            _err(errors, f"delta id {cid!r} is not in the locations dataset")
            return None

    if problem == "vrp":
        depot_id = locations[ids.index(0) if 0 in ids else 0].get("id")
        ignored = list(params.get("ignored_customers") or [])
        completed = list(params.get("completed_customers") or [])
        excluded = set(map(repr, ignored + completed))
        for cid in add:
            if repr(cid) == repr(depot_id):
                _err(errors, "delta cannot add the depot")
                return None
            if repr(cid) not in excluded:
                _err(
                    errors,
                    f"duplicate add: customer {cid!r} is already active",
                )
                return None
        for cid in drop:
            if repr(cid) == repr(depot_id):
                _err(errors, "delta cannot drop the depot")
                return None
            if repr(cid) in excluded:
                _err(errors, f"cannot drop customer {cid!r}: not active")
                return None
        add_set = set(map(repr, add))
        params["ignored_customers"] = [
            c for c in ignored if repr(c) not in add_set
        ] + list(drop)
        params["completed_customers"] = [
            c for c in completed if repr(c) not in add_set
        ]
    else:
        customers = list(params.get("customers") or [])
        active = set(map(repr, customers + [params.get("start_node")]))
        for cid in add:
            if repr(cid) in active:
                _err(
                    errors,
                    f"duplicate add: customer {cid!r} is already active",
                )
                return None
        drop_set = set(map(repr, drop))
        for cid in drop:
            if repr(cid) not in set(map(repr, customers)):
                _err(errors, f"cannot drop customer {cid!r}: not active")
                return None
        params["customers"] = [
            c for c in customers if repr(c) not in drop_set
        ] + list(add)
        if demands:
            # TSP instances carry no demands (make_instance demands=None)
            _err(errors, "delta.demands applies to VRP requests only")
            return None

    id_strs = {str(i) for i in ids}
    for key in list(demands) + list(windows):
        if key not in id_strs:
            _err(errors, f"delta id {key!r} is not in the locations dataset")
            return None
    out = []
    for loc in locations:
        key = str(loc.get("id"))
        if key not in demands and key not in windows:
            out.append(loc)
            continue
        loc = dict(loc)
        if key in demands:
            try:
                loc["demand"] = float(demands[key])
            except (TypeError, ValueError):
                _err(errors, f"delta demand for id {key} must be a number")
                return None
        if key in windows:
            tw = windows[key]
            if tw is None:
                loc.pop("timeWindow", None)  # null clears the window
            elif not isinstance(tw, (list, tuple)) or len(tw) != 2:
                _err(
                    errors,
                    f"delta time window for id {key} must be "
                    "[ready, due] or null",
                )
                return None
            else:
                try:
                    ready, due = float(tw[0]), float(tw[1])
                except (TypeError, ValueError):
                    _err(
                        errors,
                        f"delta time window for id {key} must be numeric",
                    )
                    return None
                if ready > due:
                    _err(
                        errors,
                        f"delta time window for id {key}: ready > due",
                    )
                    return None
                loc["timeWindow"] = [ready, due]
        out.append(loc)
    return out
