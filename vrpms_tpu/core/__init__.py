from vrpms_tpu.core.instance import Instance, make_instance, travel_duration
from vrpms_tpu.core.encoding import (
    giant_length,
    random_giant,
    routes_from_giant,
    giant_from_routes,
)
from vrpms_tpu.core.cost import evaluate_giant, CostWeights, total_cost
