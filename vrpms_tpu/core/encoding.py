"""Giant-tour encoding with depot separators — the fixed-shape route tensor.

A solution is one int32 vector `giant[L]`, `L = n + V + 1`:

    [0, c, c, 0, c, c, c, 0, ..., 0]

Position 0 and L-1 are pinned to the depot (node 0); the V-1 interior
zeros are route separators, so the array always contains every customer
exactly once and exactly V+1 zeros delimiting exactly V (possibly empty)
routes. TSP is the V == 1 special case `[0, c, ..., c, 0]`.

Why this shape: XLA requires static shapes, and this single flat vector
makes every neighborhood move (reverse / rotate / swap — see
vrpms_tpu.moves) a pure index transform, every cost term a gather plus a
segment reduction, and batching a trivial leading axis for vmap. It is the
TPU-native answer to the `[0] + tour + [0]` list the reference's stub
emits (reference src/solver.py:22-24) — same concept, tensorised.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def giant_length(n_customers: int, n_vehicles: int) -> int:
    return n_customers + n_vehicles + 1


def random_giant(key: jax.Array, n_customers: int, n_vehicles: int) -> jax.Array:
    """Uniformly random giant tour: shuffled customers + separators."""
    interior = jnp.concatenate(
        [
            jnp.arange(1, n_customers + 1, dtype=jnp.int32),
            jnp.zeros(n_vehicles - 1, dtype=jnp.int32),
        ]
    )
    interior = jax.random.permutation(key, interior)
    zero = jnp.zeros(1, dtype=jnp.int32)
    return jnp.concatenate([zero, interior, zero])


def random_giant_batch(key: jax.Array, batch: int, n_customers: int, n_vehicles: int):
    keys = jax.random.split(key, batch)
    return jax.vmap(lambda k: random_giant(k, n_customers, n_vehicles))(keys)


def separators(giant: jax.Array, n_real=None) -> jax.Array:
    """bool mask of route separators: depot zeros, plus — on tier-padded
    instances (core.tiers) — phantom nodes (ids >= n_real). Phantoms
    carry depot-alias durations/attributes, so treating them as
    separators makes every padded tour price EXACTLY like the real tour
    it decodes to. `n_real` may be traced (Instance.n_real)."""
    s = giant == 0
    if n_real is not None:
        s = s | (giant >= n_real)
    return s


def route_ids(giant: jax.Array, n_real=None) -> jax.Array:
    """Route index for every position; the leg leaving position k belongs
    to route `route_ids(giant)[k]`. A route's closing separator carries
    the next route's id (it is position-of-departure for that route)."""
    return jnp.cumsum(separators(giant, n_real).astype(jnp.int32)) - 1

def routes_from_giant(giant, n_real: int | None = None) -> list[list[int]]:
    """Host-side decode: split on separators into customer lists.

    With `n_real` (tier-padded instances), phantom ids >= n_real are
    separators like zeros — the decoded routes contain only real
    customers and stay index-aligned with the cost kernels' route ids.
    """
    g = np.asarray(giant).tolist()
    routes: list[list[int]] = []
    cur: list[int] = []
    for node in g[1:]:
        if node == 0 or (n_real is not None and node >= n_real):
            routes.append(cur)
            cur = []
        else:
            cur.append(int(node))
    return routes


def giant_from_routes(
    routes: list[list[int]], n_customers: int, n_vehicles: int
) -> jax.Array:
    """Host-side encode: customer lists -> padded giant tour."""
    if len(routes) > n_vehicles:
        raise ValueError(f"{len(routes)} routes > {n_vehicles} vehicles")
    flat: list[int] = [0]
    for r in routes:
        flat.extend(int(c) for c in r)
        flat.append(0)
    flat.extend([0] * (n_vehicles - len(routes)))
    expect = giant_length(n_customers, n_vehicles)
    if len(flat) != expect:
        raise ValueError(f"routes encode to length {len(flat)}, expected {expect}")
    return jnp.asarray(flat, dtype=jnp.int32)


def perm_from_giant(giant, n_real: int | None = None) -> np.ndarray:
    """Host-side: customer visit order with separators stripped (zeros,
    plus phantom ids >= n_real on tier-padded instances)."""
    g = np.asarray(giant)
    keep = g != 0
    if n_real is not None:
        keep &= g < n_real
    return g[keep]


def is_valid_giant(giant, n_customers: int, n_vehicles: int) -> bool:
    """Host-side structural check: every customer once, V+1 zeros, pinned ends."""
    g = np.asarray(giant)
    if g.shape != (giant_length(n_customers, n_vehicles),):
        return False
    if g[0] != 0 or g[-1] != 0:
        return False
    counts = np.bincount(g, minlength=n_customers + 1)
    if counts[0] != n_vehicles + 1:
        return False
    return bool(np.all(counts[1:] == 1)) and g.max() <= n_customers
