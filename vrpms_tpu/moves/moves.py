"""Neighborhood moves as pure index transforms on the giant tour.

Classic VRP local-search moves (2-opt, or-opt, swap — the set SURVEY.md
§2.2 requires for SA) reshaped for XLA: no dynamic slices, no in-place
surgery — each move builds a static-shape source-index map with
`jnp.where` arithmetic and performs one gather. That keeps every move
jit-compatible, O(L), and trivially vmappable across thousands of chains.

Because the giant tour interleaves customers and depot separators
(core.encoding), the same three transforms cover both intra-route moves
and inter-route moves (a reversal or rotation spanning a separator
reassigns customers between vehicles) — no special cross-route cases.

Positions 0 and L-1 are pinned (depot anchors); moves touch [1, L-2].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

N_MOVE_TYPES = 3  # reverse (2-opt), rotate (or-opt relocation), swap


def reverse_segment(giant: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    """2-opt: reverse positions i..j (inclusive). Identity when i >= j."""
    k = jnp.arange(giant.shape[0])
    inside = (k >= i) & (k <= j)
    src = jnp.where(inside, i + j - k, k)
    return giant[src]


def rotate_segment(
    giant: jax.Array, i: jax.Array, j: jax.Array, m: jax.Array
) -> jax.Array:
    """Or-opt: left-rotate the subarray [i..j] by m — relocates the m-long
    block at the front of the window to its back, i.e. moves a segment
    elsewhere in the tour without reversing it."""
    k = jnp.arange(giant.shape[0])
    span = jnp.maximum(j - i + 1, 1)
    inside = (k >= i) & (k <= j)
    src = jnp.where(inside, i + (k - i + m) % span, k)
    return giant[src]


def swap_positions(giant: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    k = jnp.arange(giant.shape[0])
    src = jnp.where(k == i, j, jnp.where(k == j, i, k))
    return giant[src]


def random_move(key: jax.Array, giant: jax.Array) -> jax.Array:
    """Sample and apply one uniformly-chosen move; used as the SA proposal.

    vmap this over (keys, giants) for batched chains.
    """
    length = giant.shape[0]
    k_pos, k_type, k_rot = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (2,), 1, length - 1)
    i = jnp.minimum(ij[0], ij[1])
    j = jnp.maximum(ij[0], ij[1])
    m = jax.random.randint(k_rot, (), 1, 4)
    move_type = jax.random.randint(k_type, (), 0, N_MOVE_TYPES)
    return jax.lax.switch(
        move_type,
        [
            lambda g: reverse_segment(g, i, j),
            lambda g: rotate_segment(g, i, j, m),
            lambda g: swap_positions(g, i, j),
        ],
        giant,
    )
