"""Neighborhood moves as pure index transforms on the giant tour.

Classic VRP local-search moves (2-opt, or-opt, swap — the set SURVEY.md
§2.2 requires for SA) reshaped for XLA: no dynamic slices, no in-place
surgery — each move builds a static-shape source-index map with
`jnp.where` arithmetic and performs one gather. That keeps every move
jit-compatible, O(L), and trivially vmappable across thousands of chains.

Because the giant tour interleaves customers and depot separators
(core.encoding), the same three transforms cover both intra-route moves
and inter-route moves (a reversal or rotation spanning a separator
reassigns customers between vehicles) — no special cross-route cases.

Positions 0 and L-1 are pinned (depot anchors); moves touch [1, L-2].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

N_MOVE_TYPES = 3  # reverse (2-opt), rotate (or-opt relocation), swap


def reverse_segment(giant: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    """2-opt: reverse positions i..j (inclusive). Identity when i >= j."""
    k = jnp.arange(giant.shape[0])
    inside = (k >= i) & (k <= j)
    src = jnp.where(inside, i + j - k, k)
    return giant[src]


def rotate_segment(
    giant: jax.Array, i: jax.Array, j: jax.Array, m: jax.Array
) -> jax.Array:
    """Or-opt: left-rotate the subarray [i..j] by m — relocates the m-long
    block at the front of the window to its back, i.e. moves a segment
    elsewhere in the tour without reversing it."""
    k = jnp.arange(giant.shape[0])
    span = jnp.maximum(j - i + 1, 1)
    inside = (k >= i) & (k <= j)
    src = jnp.where(inside, i + (k - i + m) % span, k)
    return giant[src]


def swap_positions(giant: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    k = jnp.arange(giant.shape[0])
    src = jnp.where(k == i, j, jnp.where(k == j, i, k))
    return giant[src]


def random_src_map(key: jax.Array, batch: int, length: int) -> jax.Array:
    """Batched proposal: one (B, L) source-index map encoding a random
    reverse/rotate/swap per chain, built entirely from `jnp.where`
    arithmetic (no integer modulo — TPUs have no hardware integer divide,
    so `% span` with a runtime divisor expands into a long scalar
    sequence; the rotate wrap is a compare-subtract instead)."""
    k_pos, k_type, k_rot = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (batch, 2), 1, length - 1)
    i = jnp.minimum(ij[:, 0], ij[:, 1])[:, None]
    j = jnp.maximum(ij[:, 0], ij[:, 1])[:, None]
    m = jax.random.randint(k_rot, (batch, 1), 1, 4)
    mt = jax.random.randint(k_type, (batch, 1), 0, N_MOVE_TYPES)
    k = jnp.arange(length, dtype=jnp.int32)[None, :]
    inside = (k >= i) & (k <= j)
    span = j - i + 1
    mm = jnp.minimum(m, span - 1)  # left-rotate by mm < span
    shifted = k + mm
    wrapped = jnp.where(shifted > j, shifted - span, shifted)
    src_rev = jnp.where(inside, i + j - k, k)
    src_rot = jnp.where(inside, wrapped, k)
    src_swp = jnp.where(k == i, j, jnp.where(k == j, i, k))
    return jnp.where(mt == 0, src_rev, jnp.where(mt == 1, src_rot, src_swp))


def apply_src_map(giants: jax.Array, src: jax.Array, mode: str = "gather") -> jax.Array:
    """out[b, k] = giants[b, src[b, k]] for a (B, L) batch.

    mode 'gather': one flat gather — fast on CPU, scalar-loop slow on TPU.
    mode 'onehot': exact one-hot matmul on the MXU (node ids and integer
    one-hot sums stay exact in bf16 up to 256, f32 above).
    """
    b, length = giants.shape
    if mode == "pallas":  # pallas covers the objective; apply stays XLA
        mode = "onehot"
    if mode == "onehot":
        from vrpms_tpu.core.cost import _onehot, onehot_dtype

        # node ids < L and src < L, so L bounds every integer involved
        dt = onehot_dtype(length)
        oh = _onehot(src, length, dt)
        out = jnp.einsum(
            "bkl,bl->bk",
            oh,
            giants.astype(dt),
            preferred_element_type=jnp.float32,
        )
        return jnp.round(out).astype(giants.dtype)
    idx = jnp.arange(b, dtype=jnp.int32)[:, None] * length + src
    return giants.reshape(-1)[idx]


def random_move_batch(
    key: jax.Array, giants: jax.Array, mode: str = "gather"
) -> jax.Array:
    """Sample and apply one random move per chain; the SA batch proposal."""
    src = random_src_map(key, giants.shape[0], giants.shape[1])
    return apply_src_map(giants, src, mode=mode)


def random_move(key: jax.Array, giant: jax.Array) -> jax.Array:
    """Sample and apply one uniformly-chosen move; used as the SA proposal.

    vmap this over (keys, giants) for batched chains.
    """
    length = giant.shape[0]
    k_pos, k_type, k_rot = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (2,), 1, length - 1)
    i = jnp.minimum(ij[0], ij[1])
    j = jnp.maximum(ij[0], ij[1])
    m = jax.random.randint(k_rot, (), 1, 4)
    move_type = jax.random.randint(k_type, (), 0, N_MOVE_TYPES)
    return jax.lax.switch(
        move_type,
        [
            lambda g: reverse_segment(g, i, j),
            lambda g: rotate_segment(g, i, j, m),
            lambda g: swap_positions(g, i, j),
        ],
        giant,
    )
