"""Neighborhood moves as pure index transforms on the giant tour.

Classic VRP local-search moves (2-opt, or-opt, swap — the set SURVEY.md
§2.2 requires for SA) reshaped for XLA: no dynamic slices, no in-place
surgery — each move builds a static-shape source-index map with
`jnp.where` arithmetic and performs one gather. That keeps every move
jit-compatible, O(L), and trivially vmappable across thousands of chains.

Because the giant tour interleaves customers and depot separators
(core.encoding), the same three transforms cover both intra-route moves
and inter-route moves (a reversal or rotation spanning a separator
reassigns customers between vehicles) — no special cross-route cases.

Positions 0 and L-1 are pinned (depot anchors); moves touch [1, L-2].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

N_MOVE_TYPES = 3  # reverse (2-opt), rotate (or-opt relocation), swap

# id-valued one-hot contractions need exact f32 accumulation on TPU
# (XLA's DEFAULT dot precision truncates f32 operands to bf16 on the
# MXU: node ids above 256 silently round — see core.cost.EXACT)
from vrpms_tpu.core.cost import EXACT  # noqa: E402


def reverse_segment(giant: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    """2-opt: reverse positions i..j (inclusive). Identity when i >= j."""
    k = jnp.arange(giant.shape[0])
    inside = (k >= i) & (k <= j)
    src = jnp.where(inside, i + j - k, k)
    return giant[src]


def rotate_segment(
    giant: jax.Array, i: jax.Array, j: jax.Array, m: jax.Array
) -> jax.Array:
    """Or-opt: left-rotate the subarray [i..j] by m — relocates the m-long
    block at the front of the window to its back, i.e. moves a segment
    elsewhere in the tour without reversing it."""
    k = jnp.arange(giant.shape[0])
    span = jnp.maximum(j - i + 1, 1)
    inside = (k >= i) & (k <= j)
    src = jnp.where(inside, i + (k - i + m) % span, k)
    return giant[src]


def swap_positions(giant: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    k = jnp.arange(giant.shape[0])
    src = jnp.where(k == i, j, jnp.where(k == j, i, k))
    return giant[src]


def _segment_src_map(lo, hi, mt, m, length: int) -> jax.Array:
    """(B, L) source-index map for a reverse/rotate/swap over [lo, hi].

    Shared move encoding for every batched proposal, built entirely from
    `jnp.where` arithmetic (no integer modulo — TPUs have no hardware
    integer divide, so `% span` with a runtime divisor expands into a
    long scalar sequence; the rotate wrap is a compare-subtract instead).
    lo/hi/mt/m are (B, 1) columns.
    """
    k = jnp.arange(length, dtype=jnp.int32)[None, :]
    inside = (k >= lo) & (k <= hi)
    span = hi - lo + 1
    mm = jnp.minimum(m, span - 1)  # left-rotate by mm < span
    shifted = k + mm
    wrapped = jnp.where(shifted > hi, shifted - span, shifted)
    src_rev = jnp.where(inside, lo + hi - k, k)
    src_rot = jnp.where(inside, wrapped, k)
    src_swp = jnp.where(k == lo, hi, jnp.where(k == hi, lo, k))
    return jnp.where(mt == 0, src_rev, jnp.where(mt == 1, src_rot, src_swp))


def random_src_map(
    key: jax.Array, batch: int, length: int, length_real=None
) -> jax.Array:
    """Batched proposal: a uniform random reverse/rotate/swap per chain.

    `length_real` (traced; Instance.move_limit) confines the window to
    the real prefix of a tier-padded tour: positions are drawn from
    [1, length_real - 2], exactly the range an unpadded tour of that
    size would use — so a padded chain replays the unpadded chain's
    draws bit for bit from the same key.
    """
    eff = length if length_real is None else length_real
    k_pos, k_type, k_rot = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (batch, 2), 1, eff - 1)
    i = jnp.minimum(ij[:, 0], ij[:, 1])[:, None]
    j = jnp.maximum(ij[:, 0], ij[:, 1])[:, None]
    m = jax.random.randint(k_rot, (batch, 1), 1, 4)
    mt = jax.random.randint(k_type, (batch, 1), 0, N_MOVE_TYPES)
    return _segment_src_map(i, j, mt, m, length)


def apply_src_map(giants: jax.Array, src: jax.Array, mode: str = "gather") -> jax.Array:
    """out[b, k] = giants[b, src[b, k]] for a (B, L) batch.

    mode 'gather': one flat gather — fast on CPU, scalar-loop slow on TPU.
    mode 'onehot': exact one-hot matmul on the MXU (node ids and integer
    one-hot sums stay exact in bf16 up to 256, f32 above).
    """
    b, length = giants.shape
    if mode == "pallas":  # pallas covers the objective; apply stays XLA
        mode = "onehot"
    if mode == "onehot":
        from vrpms_tpu.core.cost import _onehot, onehot_dtype

        # node ids < L and src < L, so L bounds every integer involved
        dt = onehot_dtype(length)
        oh = _onehot(src, length, dt)
        out = jnp.einsum(
            "bkl,bl->bk",
            oh,
            giants.astype(dt),
            preferred_element_type=jnp.float32,
            precision=EXACT,
        )
        return jnp.round(out).astype(giants.dtype)
    idx = jnp.arange(b, dtype=jnp.int32)[:, None] * length + src
    return giants.reshape(-1)[idx]


def random_move_batch(
    key: jax.Array, giants: jax.Array, mode: str = "gather", length_real=None
) -> jax.Array:
    """Sample and apply one random move per chain; the SA batch proposal."""
    src = random_src_map(key, giants.shape[0], giants.shape[1], length_real)
    return apply_src_map(giants, src, mode=mode)


def presample_move_params(
    key: jax.Array, batch: int, length: int, n_steps: int, knn_width: int,
    length_real=None,
):
    """Draw EVERY random number an n_steps anneal block needs, in one
    shot: (i, r_or_j, mt, m, u) each [n_steps, batch].

    Rationale (measured, v5e, B=4096, n=200): the per-step threefry
    chain — fold_in + split + four small randints — costs ~0.76 ms,
    MORE than the move apply and the one-hot objective combined. Drawn
    as whole-block tensors the same bits cost ~nothing per step, and the
    scan consumes one [batch] slice per iteration. With knn_width > 0
    the second stream holds candidate-list ranks in [0, knn_width);
    otherwise it holds a second uniform position and the proposal is the
    uniform-window one (random_src_map semantics).
    """
    k_i, k_r, k_t, k_m, k_u = jax.random.split(key, 5)
    shape = (n_steps, batch)
    # tier-padded tours draw positions from the TRACED real prefix
    # (same draws as an unpadded tour of the real size — the bound is a
    # value, not a shape, so one compiled program serves every size)
    eff = length if length_real is None else length_real
    i = jax.random.randint(k_i, shape, 1, eff - 1, dtype=jnp.int32)
    if knn_width > 0:
        r = jax.random.randint(k_r, shape, 0, knn_width, dtype=jnp.int32)
    else:
        r = jax.random.randint(k_r, shape, 1, eff - 1, dtype=jnp.int32)
    mt = jax.random.randint(k_t, shape, 0, N_MOVE_TYPES, dtype=jnp.int32)
    m = jax.random.randint(k_m, shape, 1, 4, dtype=jnp.int32)
    u = jax.random.uniform(k_u, shape)
    return i, r, mt, m, u


def window_from_params(i, r, mt, m, giants, knn, mode: str, length_real=None):
    """(lo, hi, mt, m) columns for one presampled step.

    knn None: (i, r) are two uniform positions (random_src_map). Else r
    ranks into the candidate list of the node at position i and the
    window closes at that neighbor's current position (knn_src_map).
    `length_real` clips the knn-endpoint position into the real prefix
    of tier-padded tours."""
    if knn is None:
        j = r[:, None]
        i = i[:, None]
        return jnp.minimum(i, j), jnp.maximum(i, j), mt[:, None], m[:, None]
    b, length = giants.shape
    n_nodes, k_width = knn.shape
    if mode != "gather":  # onehot/pallas: no elementwise gathers on TPU
        from vrpms_tpu.core.cost import _onehot, onehot_dtype

        dt_l = onehot_dtype(length)
        oh_i = _onehot(i, length, dt_l)
        a = jnp.round(
            jnp.einsum(
                "bl,bl->b", oh_i, giants.astype(dt_l), precision=EXACT
            )
        ).astype(jnp.int32)
        dt_n = onehot_dtype(max(n_nodes, length))
        oh_a = _onehot(a, n_nodes, dt_n)
        rows = jnp.einsum(
            "bn,nk->bk", oh_a, knn.astype(dt_n), precision=EXACT
        )
        oh_r = _onehot(r, k_width, jnp.float32)
        bnode = jnp.round(
            jnp.einsum(
                "bk,bk->b", rows.astype(jnp.float32), oh_r, precision=EXACT
            )
        ).astype(jnp.int32)
    else:
        a = jnp.take_along_axis(giants, i[:, None], axis=1)[:, 0]
        bnode = knn[a, r]
    eff = length if length_real is None else length_real
    j = jnp.argmax(giants == bnode[:, None], axis=1).astype(jnp.int32)
    j = jnp.clip(j, 1, eff - 2)[:, None]
    i = i[:, None]
    return jnp.minimum(i, j), jnp.maximum(i, j), mt[:, None], m[:, None]


def move_batch_from_params(
    i, r, mt, m, giants, knn, mode: str, length_real=None
) -> jax.Array:
    """Apply one presampled move per chain (the block-RNG twin of
    random_move_batch / knn_move_batch)."""
    lo, hi, mtc, mc = window_from_params(
        i, r, mt, m, giants, knn, mode, length_real
    )
    src = _segment_src_map(lo, hi, mtc, mc, giants.shape[1])
    return apply_src_map(giants, src, mode=mode)


def proposal_knn(inst, k: int):
    """The production candidate-list builder: knn_table over a
    PROPOSAL metric, not raw distance.

    For time-windowed instances the metric is
        d[i, j] + 0.5 * |ready_i - ready_j|
    — nodes are good 2-opt/or-opt partners only when they are close in
    BOTH space and schedule. On the real Solomon R101 (10-wide windows)
    this took the 10 s B=16k delta anneal from lateness 3319 to 0.2 at
    LOWER distance (1817 vs 1827); alpha grid {0.5, 1, 2} measured 0.5
    best (round 5). Untimed instances keep the plain distance metric.
    """
    import numpy as np

    d = np.asarray(inst.durations[0])
    if inst.has_tw:
        ready = np.asarray(inst.ready)
        d = d + 0.5 * np.abs(ready[:, None] - ready[None, :])
    if inst.n_real is None:
        return knn_table(d, k)
    # Tier-padded instance: candidate lists are built over the REAL
    # subgraph only (phantom columns masked out — their depot-alias
    # distances would otherwise flood every list), with width bounded
    # by the real size so a padded solve draws the same ranks an
    # unpadded one would. Phantom ROWS alias the depot's row: a phantom
    # standing in for a route separator then proposes exactly what a
    # depot zero at that position proposes.
    nr = int(inst.n_real)
    tbl = np.asarray(knn_table(d[:nr, :nr], min(k, nr - 1)))
    # tier-constant WIDTH (table shape feeds the traces): a real size
    # too small for k candidates repeats its last column — a duplicated
    # candidate skews sampling slightly, never validity — so every size
    # in the tier shares one compiled program
    width = min(k, inst.n_nodes - 1)
    if tbl.shape[1] < width:
        tbl = np.concatenate(
            [tbl] + [tbl[:, -1:]] * (width - tbl.shape[1]), axis=1
        )
    full = np.zeros((inst.n_nodes, tbl.shape[1]), tbl.dtype)
    full[:nr] = tbl
    full[nr:] = tbl[0]
    return jnp.asarray(full)


def knn_table(durations: jax.Array, k: int):
    """Host-side K-nearest-neighbor list from a durations matrix.

    knn[a] = the k nearest nodes to a (self excluded), by outgoing
    duration. The SA proposal below uses it as a candidate list — the
    classic local-search speedup: most improving 2-opt/or-opt moves
    connect geometrically close nodes, so sampling the second endpoint
    from knn[first] instead of uniformly raises the useful-proposal rate
    enormously (measured on synth X-n200: 19% lower best cost after 10k
    sweeps at identical routes/s).
    """
    import numpy as np

    d = np.asarray(durations)
    n = d.shape[0]
    k = min(k, n - 1)
    order = np.argsort(d + np.eye(n) * 1e18, axis=1)[:, :k]
    return jnp.asarray(order.astype(np.int32))


def knn_src_map(
    key: jax.Array, giants: jax.Array, knn: jax.Array, mode: str,
    length_real=None,
):
    """Candidate-list proposal: position i uniform, position j = where the
    tour currently visits a random K-nearest-neighbor of the node at i;
    then a uniform reverse/rotate/swap over [i, j]. Node lookups run as
    one-hot contractions in 'onehot'/'pallas' mode (TPU — elementwise
    gathers lower to a scalar loop there) and as plain gathers on CPU.
    """
    b, length = giants.shape
    n_nodes, k_width = knn.shape
    eff = length if length_real is None else length_real
    k_i, k_r, k_type, k_rot = jax.random.split(key, 4)
    i = jax.random.randint(k_i, (b, 1), 1, eff - 1)
    r = jax.random.randint(k_r, (b,), 0, k_width)
    if mode != "gather":  # onehot/pallas: no elementwise gathers on TPU
        from vrpms_tpu.core.cost import _onehot, onehot_dtype

        dt_l = onehot_dtype(length)
        oh_i = _onehot(i[:, 0], length, dt_l)
        a = jnp.round(
            jnp.einsum(
                "bl,bl->b", oh_i, giants.astype(dt_l), precision=EXACT
            )
        ).astype(jnp.int32)
        dt_n = onehot_dtype(max(n_nodes, length))
        oh_a = _onehot(a, n_nodes, dt_n)
        rows = jnp.einsum(
            "bn,nk->bk", oh_a, knn.astype(dt_n), precision=EXACT
        )
        oh_r = _onehot(r, k_width, jnp.float32)
        bnode = jnp.round(
            jnp.einsum(
                "bk,bk->b", rows.astype(jnp.float32), oh_r, precision=EXACT
            )
        ).astype(jnp.int32)
    else:
        a = jnp.take_along_axis(giants, i, axis=1)[:, 0]
        bnode = knn[a, r]
    # Position of the neighbor node; a depot neighbor maps to the first
    # zero (position 0), clamped into the movable interior.
    j = jnp.argmax(giants == bnode[:, None], axis=1).astype(jnp.int32)
    j = jnp.clip(j, 1, eff - 2)[:, None]
    lo, hi = jnp.minimum(i, j), jnp.maximum(i, j)
    mt = jax.random.randint(k_type, (b, 1), 0, N_MOVE_TYPES)
    m = jax.random.randint(k_rot, (b, 1), 1, 4)
    return _segment_src_map(lo, hi, mt, m, length)


def knn_move_batch(
    key: jax.Array, giants: jax.Array, knn: jax.Array, mode: str = "gather",
    length_real=None,
) -> jax.Array:
    """Sample and apply one candidate-list move per chain."""
    src = knn_src_map(key, giants, knn, mode, length_real)
    return apply_src_map(giants, src, mode=mode)


def random_move(key: jax.Array, giant: jax.Array) -> jax.Array:
    """Sample and apply one uniformly-chosen move; used as the SA proposal.

    vmap this over (keys, giants) for batched chains.
    """
    length = giant.shape[0]
    k_pos, k_type, k_rot = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (2,), 1, length - 1)
    i = jnp.minimum(ij[0], ij[1])
    j = jnp.maximum(ij[0], ij[1])
    m = jax.random.randint(k_rot, (), 1, 4)
    move_type = jax.random.randint(k_type, (), 0, N_MOVE_TYPES)
    return jax.lax.switch(
        move_type,
        [
            lambda g: reverse_segment(g, i, j),
            lambda g: rotate_segment(g, i, j, m),
            lambda g: swap_positions(g, i, j),
        ],
        giant,
    )
