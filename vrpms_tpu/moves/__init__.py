from vrpms_tpu.moves.moves import (
    reverse_segment,
    rotate_segment,
    swap_positions,
    random_move,
    random_src_map,
    apply_src_map,
    random_move_batch,
    knn_table,
    proposal_knn,
    knn_src_map,
    knn_move_batch,
    N_MOVE_TYPES,
)
