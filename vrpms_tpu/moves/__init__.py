from vrpms_tpu.moves.moves import (
    reverse_segment,
    rotate_segment,
    swap_positions,
    random_move,
    N_MOVE_TYPES,
)
