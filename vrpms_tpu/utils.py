"""Small host-side utilities.

The reference keeps a date helper as its only utility (reference
src/utilities/helper.py:4-6, `get_current_date()` -> '%d-%m-%Y'); the
same stamp is attached to solve summaries here (see
vrpms_tpu.solvers.common.solve_info). The reference's other L4 duty —
loading `.env` secrets at package import (reference src/__init__.py:1-2,
README.md:53-66) — is `load_dotenv` below, dependency-free.
"""

from __future__ import annotations

import os
from datetime import datetime

from vrpms_tpu import config


def current_date() -> str:
    """Today as 'DD-MM-YYYY' (reference src/utilities/helper.py:4-6)."""
    return datetime.now().strftime("%d-%m-%Y")


def enable_compile_cache(path: str | None = None) -> str | None:
    """Turn on JAX's persistent (disk) compilation cache, best-effort.

    The north-star budget is quality-per-wall-clock INCLUDING what a
    fresh process pays before its first sweep (BASELINE.md config 3:
    <10 s on one chip). XLA compiles of the solver blocks cost ~30 s per
    shape on TPU; with this cache a restarted service/benchmark loads
    them from disk in well under a second each, so the 10 s budget goes
    to search, not recompilation.

    Path: explicit arg > $VRPMS_COMPILE_CACHE > ~/.cache/vrpms_tpu/xla.
    Set VRPMS_COMPILE_CACHE=off to disable. Returns the directory in
    effect, or None when disabled/unavailable. Safe to call repeatedly
    and before/after other jax.config updates; never raises (a broken
    cache dir must not take down a solve — caching is an optimization).
    """
    if path is None:
        path = config.raw("VRPMS_COMPILE_CACHE")
        if path is not None and str(path).lower() in ("off", "0", "none", ""):
            return None  # explicitly disabled (incl. VRPMS_COMPILE_CACHE=)
        path = path or os.path.join(
            os.path.expanduser("~"), ".cache", "vrpms_tpu", "xla"
        )
    elif str(path).lower() in ("off", "0", "none", ""):
        return None
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(path))
        # Cache EVERYTHING, even sub-second entries: through the
        # tunneled TPU plugin each tiny eager op (convert_element_type,
        # scatter, ...) costs ~0.6 s to compile, and a cold solve issues
        # dozens of them — measured ~25-35 s of a fresh process's wall
        # clock. The 1 s default threshold would skip exactly those.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        return str(path)
    except Exception as e:
        # the service runs on (degraded: every restart re-pays compiles)
        # but the condition must be visible — the store.degraded pattern
        try:
            from vrpms_tpu.obs import log_event

            log_event(
                "compile_cache.degraded",
                path=str(path),
                error=f"{type(e).__name__}: {e}",
            )
        except Exception:
            pass
        return None


def load_dotenv(path: str = ".env") -> bool:
    """Minimal python-dotenv equivalent (the reference pins the package
    only for this one call, reference requirements.txt + src/__init__.py:1-2).

    KEY=VALUE lines; blank lines and `#` comments ignored; an optional
    `export ` prefix and matching single/double quotes are stripped.
    Existing environment variables are NEVER overridden (python-dotenv's
    default), so deployment-provided secrets beat the checked-out file.
    Returns True iff a file was read.
    """
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return False
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        if line.startswith("export "):
            line = line[len("export "):].lstrip()
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        # python-dotenv semantics: strip an inline comment first (so a
        # quoted value followed by ` # ...` still unquotes), then strip
        # matching quotes
        if not (val[:1] in "\"'" and val[:1] == val[-1:] and len(val) >= 2):
            if " #" in val:
                val = val.split(" #", 1)[0].rstrip()
        if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
            val = val[1:-1]
        if key and key not in os.environ:
            os.environ[key] = val
    return True
