"""Small host-side utilities.

The reference keeps a date helper as its only utility (reference
src/utilities/helper.py:4-6, `get_current_date()` -> '%d-%m-%Y'); the
same stamp is attached to solve summaries here (see
vrpms_tpu.solvers.common.solve_info).
"""

from __future__ import annotations

from datetime import datetime


def current_date() -> str:
    """Today as 'DD-MM-YYYY' (reference src/utilities/helper.py:4-6)."""
    return datetime.now().strftime("%d-%m-%Y")
