"""Small host-side utilities.

The reference keeps a date helper as its only utility (reference
src/utilities/helper.py:4-6, `get_current_date()` -> '%d-%m-%Y'); the
same stamp is attached to solve summaries here (see
vrpms_tpu.solvers.common.solve_info). The reference's other L4 duty —
loading `.env` secrets at package import (reference src/__init__.py:1-2,
README.md:53-66) — is `load_dotenv` below, dependency-free.
"""

from __future__ import annotations

import os
from datetime import datetime


def current_date() -> str:
    """Today as 'DD-MM-YYYY' (reference src/utilities/helper.py:4-6)."""
    return datetime.now().strftime("%d-%m-%Y")


def load_dotenv(path: str = ".env") -> bool:
    """Minimal python-dotenv equivalent (the reference pins the package
    only for this one call, reference requirements.txt + src/__init__.py:1-2).

    KEY=VALUE lines; blank lines and `#` comments ignored; an optional
    `export ` prefix and matching single/double quotes are stripped.
    Existing environment variables are NEVER overridden (python-dotenv's
    default), so deployment-provided secrets beat the checked-out file.
    Returns True iff a file was read.
    """
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return False
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        if line.startswith("export "):
            line = line[len("export "):].lstrip()
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        # python-dotenv semantics: strip an inline comment first (so a
        # quoted value followed by ` # ...` still unquotes), then strip
        # matching quotes
        if not (val[:1] in "\"'" and val[:1] == val[-1:] and len(val) >= 2):
            if " #" in val:
                val = val.split(" #", 1)[0].rstrip()
        if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
            val = val[1:-1]
        if key and key not in os.environ:
            os.environ[key] = val
    return True
