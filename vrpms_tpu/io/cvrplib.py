"""Instance loaders: CVRPLIB (.vrp) and Solomon VRPTW formats.

The benchmark ladder in BASELINE.md names CVRPLIB instances (A-n32-k5,
X-n200-k36) and Solomon R101; these parsers turn the standard text
formats into core.Instance bundles. Supported CVRPLIB fields:
EDGE_WEIGHT_TYPE EUC_2D (with the library's nint rounding convention,
selectable) and EXPLICIT/FULL_MATRIX.
"""

from __future__ import annotations

import math
import re

import numpy as np

from vrpms_tpu.core.instance import make_instance


def _euc2d(coords: np.ndarray, round_nint: bool) -> np.ndarray:
    d = np.linalg.norm(coords[:, None] - coords[None, :], axis=-1)
    if round_nint:
        d = np.floor(d + 0.5)  # TSPLIB nint()
    return d


def parse_cvrplib(
    text: str,
    round_nint: bool = True,
    n_vehicles: int | None = None,
    max_dense_n: int | None = None,
):
    """Parse CVRPLIB .vrp text -> (Instance, meta dict).

    The vehicle count comes from (in priority order): the n_vehicles
    argument, the `-kV` suffix of the NAME field, or
    ceil(total demand / capacity) + 1 slack vehicle.

    `max_dense_n` gates the O(n^2) matrix materialization for giant
    EUC_2D instances: above it the Instance (and the dense matrix) is
    NOT built — the returned Instance is None and the meta dict carries
    everything the decomposition path needs instead (coords, demands,
    capacities, start_times, streamed=True). A 10k-customer file then
    parses in O(n) memory; per-shard submatrices are built later from
    the coords (shard_matrix), so nothing quadratic ever materializes.
    """
    fields: dict[str, str] = {}
    sections: dict[str, list[list[float]]] = {}
    cur = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line == "EOF":
            continue
        m = re.match(r"^([A-Z_0-9]+)\s*:\s*(.*)$", line)
        if m:
            fields[m.group(1)] = m.group(2).strip()
            cur = None
            continue
        if re.match(r"^[A-Z_]+$", line):
            cur = line
            sections[cur] = []
            continue
        if cur:
            sections[cur].append([float(x) for x in line.split()])

    dim = int(fields["DIMENSION"])
    capacity = float(fields.get("CAPACITY", 0) or 0)
    ew_type = fields.get("EDGE_WEIGHT_TYPE", "EUC_2D")

    # Node ids in the file are 1-based with the depot conventionally first
    # (DEPOT_SECTION confirms); we re-sort by id and index from 0.
    streamed = False
    if ew_type == "EUC_2D":
        rows = sorted(sections["NODE_COORD_SECTION"], key=lambda r: r[0])
        coords = np.asarray([[r[1], r[2]] for r in rows])
        streamed = max_dense_n is not None and dim > max_dense_n
        d = None if streamed else _euc2d(coords, round_nint)
    elif ew_type == "EXPLICIT":
        fmt = fields.get("EDGE_WEIGHT_FORMAT", "FULL_MATRIX")
        flat = [x for row in sections["EDGE_WEIGHT_SECTION"] for x in row]
        if fmt != "FULL_MATRIX":
            raise ValueError(f"unsupported EDGE_WEIGHT_FORMAT {fmt}")
        d = np.asarray(flat).reshape(dim, dim)
        coords = None
    else:
        raise ValueError(f"unsupported EDGE_WEIGHT_TYPE {ew_type}")

    demands = np.zeros(dim)
    for r in sections.get("DEMAND_SECTION", []):
        demands[int(r[0]) - 1] = r[1]

    depot = 0
    dep_rows = [int(r[0]) for r in sections.get("DEPOT_SECTION", []) if r[0] > 0]
    if dep_rows:
        depot = dep_rows[0] - 1
    if depot != 0:
        order = [depot] + [i for i in range(dim) if i != depot]
        if d is not None:
            d = d[np.ix_(order, order)]
        demands = demands[order]
        if coords is not None:
            coords = coords[order]

    name = fields.get("NAME", "")
    if n_vehicles is None:
        m = re.search(r"-k(\d+)", name)
        if m:
            n_vehicles = int(m.group(1))
        elif capacity > 0:
            n_vehicles = int(math.ceil(demands.sum() / capacity)) + 1
        else:
            n_vehicles = 1

    cap = capacity if capacity > 0 else 1e9
    meta = {"name": name, "dimension": dim, "capacity": capacity, "coords": coords}
    if streamed:
        meta.update(
            streamed=True,
            round_nint=round_nint,
            demands=demands,
            capacities=[cap] * n_vehicles,
            start_times=[0.0] * n_vehicles,
        )
        return None, meta
    inst = make_instance(
        d, demands=demands, capacities=[cap] * n_vehicles
    )
    return inst, meta


def shard_matrix(coords: np.ndarray, nodes, round_nint: bool = True):
    """The dense duration submatrix of one shard of a STREAMED giant
    instance (node 0 the depot plus the shard members), with the same
    nint rounding convention the full parse would have applied — so a
    shard of a streamed load prices identically to the same slice of a
    dense load. O(shard^2), never O(n^2)."""
    idx = np.asarray(nodes, dtype=np.int64)
    return _euc2d(np.asarray(coords)[idx], round_nint)


def load_cvrplib(path: str, **kw):
    with open(path) as f:
        return parse_cvrplib(f.read(), **kw)


def parse_solomon(
    text: str,
    n_vehicles: int | None = None,
    truncate_1dp: bool = True,
):
    """Parse Solomon VRPTW text -> (Instance, meta dict).

    Distances are euclidean; the Solomon literature convention truncates
    them to one decimal (selectable). Depot time window becomes
    ready/due of node 0; vehicle NUMBER/CAPACITY come from the VEHICLE
    block unless overridden.
    """
    lines = [ln.rstrip() for ln in text.splitlines()]
    name = next((ln.strip() for ln in lines if ln.strip()), "solomon")
    num = cap = None
    rows = []
    mode = None
    for ln in lines:
        s = ln.strip()
        if not s:
            continue
        up = s.upper()
        if up.startswith("VEHICLE"):
            mode = "vehicle"
            continue
        if up.startswith("CUSTOMER"):
            mode = "customer"
            continue
        if up.startswith("NUMBER") or up.startswith("CUST"):
            continue
        parts = s.split()
        if mode == "vehicle" and len(parts) == 2:
            num, cap = int(parts[0]), float(parts[1])
        elif mode == "customer" and len(parts) >= 7:
            rows.append([float(x) for x in parts[:7]])

    rows.sort(key=lambda r: r[0])
    coords = np.asarray([[r[1], r[2]] for r in rows])
    demands = np.asarray([r[3] for r in rows])
    ready = np.asarray([r[4] for r in rows])
    due = np.asarray([r[5] for r in rows])
    service = np.asarray([r[6] for r in rows])

    d = np.linalg.norm(coords[:, None] - coords[None, :], axis=-1)
    if truncate_1dp:
        d = np.floor(d * 10.0) / 10.0

    v = n_vehicles or num or 1
    inst = make_instance(
        d,
        demands=demands,
        capacities=[cap or 1e9] * v,
        ready=ready,
        due=due,
        service=service,
    )
    meta = {"name": name, "n_vehicles": v, "capacity": cap, "coords": coords}
    return inst, meta


def load_solomon(path: str, **kw):
    with open(path) as f:
        return parse_solomon(f.read(), **kw)
