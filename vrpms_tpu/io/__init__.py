from vrpms_tpu.io.cvrplib import load_cvrplib, load_solomon, parse_cvrplib, parse_solomon
from vrpms_tpu.io.synth import synth_cvrp, synth_td, synth_tsp, synth_vrptw
from vrpms_tpu.io.metrics import gap_percent
