"""Benchmark metrics (SURVEY.md §4 item 6): gap-to-best-known-solution."""

from __future__ import annotations


def gap_percent(cost: float, best_known: float) -> float:
    """Percent gap above the best known solution (0 == matched BKS)."""
    if best_known <= 0:
        raise ValueError("best_known must be positive")
    return 100.0 * (float(cost) - best_known) / best_known
