"""Benchmark metrics (SURVEY.md §4 item 6): gap-to-best-known-solution.

BEST_KNOWN carries published optima / best-known values for the classic
instances the BASELINE.md ladder names, so loading a real CVRPLIB or
Solomon file (vrpms_tpu.io.cvrplib) reports a true gap; synthetic
stand-ins have no BKS and report cost only. Values are the widely
published literature numbers: A-set and Solomon optima, X-set BKS as of
the CVRPLIB 2024 tables.
"""

from __future__ import annotations

# instance name (as in the file's NAME field, lowercased) -> BKS distance
BEST_KNOWN: dict[str, float] = {
    "e-n22-k4": 375.0,  # embedded fixture; optimum re-proven by solve_cvrp_bnb
    "a-n32-k5": 784.0,  # embedded fixture
    "a-n33-k5": 661.0,
    "a-n36-k5": 799.0,
    "a-n45-k6": 944.0,
    "a-n55-k9": 1073.0,
    "a-n60-k9": 1354.0,
    "x-n101-k25": 27591.0,
    "x-n110-k13": 14971.0,
    "x-n200-k36": 58578.0,
    "x-n303-k21": 21736.0,
    "x-n502-k39": 69226.0,
    # Solomon VRPTW distances (vehicle-count-then-distance objective's
    # distance component, 100-customer sets)
    "r101": 1650.8,
    "r201": 1252.4,
    "c101": 828.94,
    "c201": 591.56,
    "rc101": 1696.95,
    # 25-customer Solomon subsets (exact optima, Kohl et al.) — embedded
    # as fixtures (io/fixtures.py)
    "r101.25": 617.1,
    "c101.25": 191.3,
}


def best_known(name: str) -> float | None:
    """BKS lookup by instance name (case-insensitive), None if unknown."""
    return BEST_KNOWN.get(name.strip().lower())


def gap_percent(cost: float, best_known: float) -> float:
    """Percent gap above the best known solution (0 == matched BKS)."""
    if best_known <= 0:
        raise ValueError("best_known must be positive")
    return 100.0 * (float(cost) - best_known) / best_known
