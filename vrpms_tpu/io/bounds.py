"""Lower-bound certificates: make gaps measurable without network access.

The primary quality metric is gap-to-best-known (BASELINE.json), but in
a zero-egress container every benchmark instance is a synthetic stand-in
with no published optimum — a reported cost of 36.8k could be 2% or 25%
off and nobody could tell (VERDICT round-1 missing item #2). These
bounds turn any reported cost into a CERTIFIED statement:

    cost <= (1 + gap_ub) * OPT        because        LB <= OPT <= cost

All bounds run host-side in numpy/scipy (milliseconds at n=200; these
certify results, they are not on any hot path) and are classic
polynomial relaxations:

  * route_count_lb — bin-packing bound on the vehicles actually needed
    (fewest vehicles whose capacities cover total demand);
  * assignment_lb  — the assignment-problem relaxation of the VRP
    digraph: every customer needs one out-arc and one in-arc, the depot
    is duplicated once per vehicle (zero-cost depot->depot arcs model
    empty routes), subtour/capacity constraints dropped; exact AP via
    scipy's Hungarian;
  * mst_lb         — spanning-tree bound: a VRP solution is a connected
    spanning subgraph (every route touches the depot), so the symmetric
    MST weight is a lower bound; only valid for symmetric matrices;
  * held_karp_1tree_lb — for TSP (V == 1): minimum 1-tree with
    Lagrangian ascent on node potentials (Held & Karp 1970), typically
    within ~1% of the optimum on Euclidean instances; symmetric only.

`lower_bound` returns the best applicable max of these. Time-dependent
instances are certified against the elementwise cheapest slice (every
leg costs at least that — valid, somewhat looser). Validity is pinned
by tests against the exact BF/Held-Karp oracles on small instances
(tests/test_bounds.py).
"""

from __future__ import annotations

import numpy as np

from vrpms_tpu.core.instance import BIG, Instance


_HOST_CACHE: dict = {}
_WARNED_NO_SCIPY = False


def _host(inst: Instance):
    """Host copies of the bound inputs. One certificate calls this from
    several bounds; a tiny id-keyed cache (last instance only) avoids
    re-transferring [T,N,N] and re-reducing the slice minimum each time.
    """
    key = id(inst.durations)
    hit = _HOST_CACHE.get(key)
    # the cached entry holds references to ALL keyed arrays (so their
    # ids cannot be recycled while cached), and the identity checks
    # cover every field the cached value derives from — a replace()'d
    # Instance sharing durations but differing in demands/capacities
    # must miss, or the certificate could be built from stale inputs
    if (
        hit is not None
        and hit[0] is inst.durations
        and hit[1] is inst.demands
        and hit[2] is inst.capacities
    ):
        return hit[3]
    if inst.time_dependent:
        # every leg costs at least its cheapest time slice, so bounds
        # computed on the elementwise slice-minimum stay valid LBs for
        # the time-dependent objective (somewhat looser, never wrong)
        d = np.asarray(inst.durations, dtype=np.float64).min(axis=0)
    else:
        d = np.asarray(inst.durations[0], dtype=np.float64)
    demands = np.asarray(inst.demands, dtype=np.float64)
    caps = np.asarray(inst.capacities, dtype=np.float64)
    _HOST_CACHE.clear()  # keep exactly one entry
    _HOST_CACHE[key] = (
        inst.durations, inst.demands, inst.capacities, (d, demands, caps)
    )
    return d, demands, caps


def _symmetric(d: np.ndarray) -> bool:
    return bool(np.allclose(d, d.T, rtol=1e-6, atol=1e-9))


def route_count_lb(inst: Instance) -> int:
    """Fewest vehicles whose combined capacity covers total demand (a
    bin-packing relaxation: item splitting allowed, so it never
    overestimates). At least 1."""
    _, demands, caps = _host(inst)
    total = float(demands.sum())
    caps_desc = np.sort(caps)[::-1]
    covered = np.cumsum(caps_desc)
    idx = np.searchsorted(covered, total - 1e-9)
    return int(min(idx + 1, len(caps))) if total > 0 else 1


def assignment_lb(inst: Instance) -> float:
    """Assignment-problem relaxation of the VRP digraph (see module
    docstring). Valid for asymmetric matrices and any fleet; capacity
    and connectivity are relaxed, so the bound is safe but not tight."""
    d, _, caps = _host(inst)
    n = d.shape[0]
    v = len(caps)
    m = n - 1 + v  # customers 1..n-1 plus v depot copies
    c = np.zeros((m, m), dtype=np.float64)
    # block layout: indices 0..n-2 are customers 1..n-1; n-1..m-1 depot
    cust = np.arange(1, n)
    c[: n - 1, : n - 1] = d[np.ix_(cust, cust)]
    np.fill_diagonal(c[: n - 1, : n - 1], BIG)  # no self-arcs
    c[: n - 1, n - 1 :] = d[cust, 0][:, None]  # customer -> depot
    c[n - 1 :, : n - 1] = d[0, cust][None, :]  # depot -> customer
    c[n - 1 :, n - 1 :] = 0.0  # empty routes are free
    try:
        from scipy.optimize import linear_sum_assignment

        rows, cols = linear_sum_assignment(c)
        return float(c[rows, cols].sum())
    except ImportError:  # pragma: no cover - scipy is present in CI
        # degenerate fallback: cheapest out-arc per customer (the AP
        # without the one-in-arc constraint) — still a valid LB, but a
        # much weaker one, which silently loosens every certified gap;
        # warn ONCE so the degradation is visible (ADVICE round 2)
        global _WARNED_NO_SCIPY
        if not _WARNED_NO_SCIPY:
            _WARNED_NO_SCIPY = True
            import sys

            print(
                "vrpms_tpu.io.bounds: scipy unavailable — assignment_lb "
                "degrades to the cheapest-out-arc bound; certified gaps "
                "will be much looser (pip install scipy to fix)",
                file=sys.stderr,
            )
        out = np.where(np.eye(n, dtype=bool), np.inf, d)[1:, :].min(axis=1)
        return float(out.sum())


def mst_lb(inst: Instance) -> float:
    """Symmetric MST bound (0.0 — vacuous — for asymmetric matrices)."""
    d, _, _ = _host(inst)
    if not _symmetric(d):
        return 0.0
    # np.minimum: within the symmetry tolerance the SMALLER direction is
    # the safe one — maximum could push LB past OPT by the tolerance
    return float(_mst_weight(np.minimum(d, d.T)))


def _mst_edges(d: np.ndarray):
    """Prim over the full matrix: (total weight, list of (w, i, j)) —
    THE one MST implementation every bound derives from."""
    k = d.shape[0]
    in_tree = np.zeros(k, dtype=bool)
    in_tree[0] = True
    best = d[0].copy()
    frm = np.zeros(k, dtype=int)
    best[0] = np.inf
    edges = []
    for _ in range(k - 1):
        j = int(np.argmin(np.where(in_tree, np.inf, best)))
        edges.append((float(best[j]), int(frm[j]), j))
        in_tree[j] = True
        closer = d[j] < best
        frm = np.where(closer & ~in_tree, j, frm)
        best = np.where(closer, d[j], best)
        best[in_tree] = np.inf
    return sum(w for w, _, _ in edges), edges


def _mst_weight(d: np.ndarray, nodes: np.ndarray | None = None) -> float:
    """MST weight over the given node subset (via _mst_edges)."""
    if nodes is not None:
        d = d[np.ix_(nodes, nodes)]
    if d.shape[0] <= 1:
        return 0.0
    return _mst_edges(d)[0]


def held_karp_1tree_lb(
    inst: Instance, iters: int = 100, seed_step: float = 2.0
) -> float:
    """Held-Karp 1-tree bound for the TSP (V == 1), symmetric only.

    1-tree = MST over nodes 1..n-1 plus the depot's two cheapest edges;
    every tour is a 1-tree, so its weight bounds the tour. Lagrangian
    ascent on node potentials pi (reduced costs d + pi_i + pi_j, bound
    w(1-tree) - 2*sum(pi)) sharpens it; the step follows the classic
    degree-subgradient schedule with halving on stall.
    """
    d, _, _ = _host(inst)
    if not _symmetric(d):
        return 0.0
    d = np.minimum(d, d.T)  # safe direction within the symmetry tolerance
    n = d.shape[0]
    if n < 3:
        return float(d[0, 1] + d[1, 0]) if n == 2 else 0.0
    pi = np.zeros(n)
    best = 0.0
    step = seed_step * float(np.mean(d[d > 0])) / max(n, 1)
    for _ in range(iters):
        dr = d + pi[:, None] + pi[None, :]
        np.fill_diagonal(dr, np.inf)
        # MST over customers (via the shared Prim) + degree counts
        w_total, edges = _mst_edges(dr[1:, 1:])
        deg = np.zeros(n)
        for _, i, j in edges:
            deg[i + 1] += 1
            deg[j + 1] += 1
        # depot's two cheapest reduced edges
        two = np.sort(dr[0, 1:])[:2]
        w_total += float(two.sum())
        deg[0] = 2.0
        ends = np.argsort(dr[0, 1:])[:2] + 1
        deg[ends] += 1
        bound = w_total - 2.0 * float(pi.sum())
        if bound > best:
            best = bound
        else:
            step *= 0.9
        g = deg - 2.0
        if not g.any():
            break  # the 1-tree IS a tour: bound is the optimum
        pi = pi + step * g
    return float(best)


def cvrp_forest_lb(inst: Instance, iters: int = 80) -> float:
    """Lagrangian r-route forest bound for symmetric CVRP — the
    multi-vehicle analog of the Held-Karp 1-tree.

    Decomposition of any r-route solution: remove the depot and each
    route becomes a customer path, so the customer-customer edges form
    a spanning forest with r components (weight >= MST(customers) minus
    its r-1 heaviest edges); the depot contributes r out-arcs to
    DISTINCT customers and r in-arcs from distinct customers (>= the r
    smallest depot-edge values each way). r itself is unknown, so the
    bound takes the min over r in [route_count_lb, V]. Lagrangian
    ascent on customer potentials (every customer has degree exactly 2)
    sharpens it; every iterate is a valid bound, so the max is kept.
    """
    d, _, caps = _host(inst)
    if not _symmetric(d):
        return 0.0
    d = np.minimum(d, d.T)  # safe direction within the symmetry tolerance
    n = d.shape[0]
    if n <= 2:
        return 0.0
    v = len(caps)
    # r counts NON-empty routes (empty routes ride free 0-cost (0,0)
    # arcs): at most one per customer, at most the fleet size
    r_hi = min(v, n - 1)
    r_lo = min(route_count_lb(inst), r_hi)
    pi = np.zeros(n)  # pi[0] stays 0 (depot degree is not constrained)
    best_bound = 0.0
    step = 2.0 * float(np.mean(d[d > 0])) / max(n, 1)
    for _ in range(iters):
        dr = d + pi[:, None] + pi[None, :]
        np.fill_diagonal(dr, np.inf)
        mst_w, edges = _mst_edges(dr[1:, 1:])
        by_weight = sorted(edges, reverse=True)
        depot = dr[0, 1:]
        order = np.argsort(depot)
        cum_depot = np.concatenate([[0.0], np.cumsum(depot[order])])
        best_r, best_val = r_lo, np.inf
        for r in range(r_lo, r_hi + 1):
            drop = sum(w for w, _, _ in by_weight[: r - 1])
            val = (mst_w - drop) + 2.0 * cum_depot[min(r, n - 1)]
            if val < best_val:
                best_val, best_r = val, r
        bound = best_val - 2.0 * float(pi[1:].sum())
        if bound > best_bound:
            best_bound = bound
        else:
            step *= 0.9
        # subgradient from the minimizing structure's customer degrees
        deg = np.zeros(n)
        for w, i, j in by_weight[best_r - 1 :]:
            deg[i + 1] += 1
            deg[j + 1] += 1
        ends = order[: min(best_r, n - 1)] + 1
        deg[ends] += 2.0  # one out-arc + one in-arc per chosen customer
        g = deg[1:] - 2.0
        if not g.any():
            break
        pi[1:] = pi[1:] + step * g
    return float(best_bound)


def _scaled_demands(demands, caps, max_units: int):
    """(dem_s, cap_s, total_s) with demands/capacity divided by their gcd,
    or None when the q-route machinery does not apply (non-integer or
    non-positive demands, or a scaled capacity beyond max_units).

    The gcd reduction is what makes unit-indexed DP tables practical for
    instances like E-n22-k4 (demands in hundreds, capacity 6000 -> scaled
    capacity 60): every route load is a multiple of g, so states are
    exact, not approximated. A capacity not divisible by g rounds DOWN
    (floor(cap/g) scaled units is exactly what a route can carry).
    """
    dem = demands[1:]
    if len(dem) == 0 or not np.allclose(dem, np.round(dem)):
        return None
    dem_i = np.round(dem).astype(np.int64)
    if (dem_i < 1).any():
        return None
    g = int(np.gcd.reduce(dem_i))
    cap_s = int(np.floor(caps.max() / g))
    dem_s = (dem_i // g).astype(int)
    if cap_s < int(dem_s.max()) or cap_s > max_units:
        return None
    return dem_s, cap_s, int(dem_s.sum())


def qroute_lb(inst: Instance, max_units: int = 4096) -> float:
    """Capacity-aware q-route lower bound (Christofides-Mingozzi-Toth).

    A q-route is a depot-to-depot walk accumulating exactly q demand
    units, with elementarity relaxed except for 2-cycles (i -> j -> i
    immediately is forbidden via the classic best/second-best
    predecessor DP). Every real route serving q units IS such a walk,
    so cost(route) >= qroute(q) >= q * min_q' qroute(q')/q', and
    summing over routes gives  LB = total_units * best cost-per-unit.

    Valid for asymmetric matrices and heterogeneous fleets (Q = the
    LARGEST capacity bounds every route's load). Requires strictly
    positive integer demands (returns 0.0 — vacuous — otherwise:
    zero-demand customers would break the per-unit argument, and
    fractional demands the DP indexing).
    """
    d, demands, caps = _host(inst)
    n = d.shape[0]
    if n <= 2:
        return 0.0
    scaled = _scaled_demands(demands, caps, max_units)
    if scaled is None:
        return 0.0
    dem_s, q_max, total_s = scaled
    k = n - 1  # customers
    route_q, _ = _qroute_table(d, dem_s, q_max, np.zeros(k), want_visits=False)
    qs = np.arange(q_max + 1, dtype=np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        ratios = route_q[1:] / qs[1:]
    finite = np.isfinite(ratios)
    if not finite.any():
        return 0.0
    return float(ratios[finite].min() * total_s)


def _qroute_table(d, dem_i, q_max, lam, want_visits: bool = True):
    """(route_q, visits): best closed q-route cost per load q under
    in-arc penalties `lam`, and each route's customer-visit counts
    (reconstructed through the best-predecessor chain; the 2-cycle
    second-best branch is approximated by its best-path visits — only
    the subgradient uses visits, never the bound itself; pass
    want_visits=False to skip the reconstruction walk)."""
    n = d.shape[0]
    k = n - 1
    cust = np.arange(1, n)
    dc = d[np.ix_(cust, cust)] + lam[None, :]
    INF = np.inf
    A = np.full((q_max + 1, k), INF)
    P = np.full((q_max + 1, k), -2, dtype=int)
    B = np.full((q_max + 1, k), INF)
    for j in range(k):
        if dem_i[j] <= q_max:
            A[dem_i[j], j] = d[0, j + 1] + lam[j]
            P[dem_i[j], j] = -1
    for q in range(1, q_max + 1):
        for dv in np.unique(dem_i):
            qp = q - int(dv)
            if qp < 1:
                continue
            ks = np.where(dem_i == dv)[0]
            if not len(ks):
                continue
            vals = np.where(
                P[qp][:, None] == ks[None, :], B[qp][:, None], A[qp][:, None]
            ) + dc[:, ks]
            vals[ks[None, :] == np.arange(k)[:, None]] = INF
            order = np.argsort(vals, axis=0)
            b1, b2 = order[0], order[1]
            v1 = vals[b1, np.arange(len(ks))]
            v2 = vals[b2, np.arange(len(ks))]
            better = v1 < A[q, ks]
            B[q, ks] = np.where(
                better, np.minimum(A[q, ks], v2), np.minimum(B[q, ks], v1)
            )
            P[q, ks] = np.where(better, b1, P[q, ks])
            A[q, ks] = np.where(better, v1, A[q, ks])
    back = d[cust, 0]
    closed = A + back[None, :]
    route_q = closed.min(axis=1)
    ends = closed.argmin(axis=1)
    visits = np.zeros((q_max + 1, k))
    if not want_visits:
        return route_q, visits
    for q in range(1, q_max + 1):
        if not np.isfinite(route_q[q]):
            continue
        qq, j = q, int(ends[q])
        while j >= 0 and qq >= 1:
            visits[q, j] += 1
            j_next = int(P[qq, j])
            qq -= int(dem_i[j])
            j = j_next
    return route_q, visits


def _combo_bound(route_q, total: int, r_lo: int, r_hi: int):
    """(best_val, best_r, choices): min total cost of r in [r_lo, r_hi]
    q-routes whose loads sum to exactly `total`, by min-plus DP over the
    per-load route costs; `choices` backtracks one optimal combo."""
    G = np.full(total + 1, np.inf)
    G[0] = 0.0
    finite_q = [q for q in range(1, len(route_q)) if np.isfinite(route_q[q])]
    choices = []
    best_val, best_r = np.inf, -1
    for r in range(1, r_hi + 1):
        Gn = np.full(total + 1, np.inf)
        choice = np.full(total + 1, -1, dtype=int)
        for q in finite_q:
            u = np.arange(q, total + 1)
            cand = G[u - q] + route_q[q]
            better = cand < Gn[u]
            Gn[u] = np.where(better, cand, Gn[u])
            choice[u] = np.where(better, q, choice[u])
        choices.append(choice)
        G = Gn
        if r >= r_lo and np.isfinite(G[total]) and G[total] < best_val:
            best_val, best_r = float(G[total]), r
    return best_val, best_r, choices


def _lam_cache_path(inst: Instance):
    """Warm-start store for the ascent multipliers, keyed by instance
    content (round-5 certificate work: certificates are OFFLINE
    artifacts, so ascent progress should compound across processes and
    rounds instead of restarting from zero every time). Set
    VRPMS_CERT_CACHE=0 to disable, or to a directory to relocate."""
    import hashlib
    import os

    from vrpms_tpu import config

    root = config.get("VRPMS_CERT_CACHE")
    if root == "0":
        return None
    if not root:
        root = os.path.join(
            os.path.expanduser("~"), ".cache", "vrpms_tpu_certs"
        )
    d, demands, caps = _host(inst)
    h = hashlib.sha1()
    for a in (d, demands, caps):
        h.update(np.ascontiguousarray(a).tobytes())
    return os.path.join(root, h.hexdigest()[:20] + ".npz")


def _lam_cache_load(path):
    if path is None:
        return None, 0.0
    try:
        with np.load(path) as z:
            return z["lam"].astype(np.float64), float(z["bound"])
    except (OSError, ValueError, KeyError):
        return None, 0.0


def _lam_cache_save(path, lam, bound: float) -> None:
    if path is None:
        return
    import os

    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.npz"  # np.savez appends .npz itself
        np.savez(tmp[:-4], lam=lam, bound=bound)
        os.replace(tmp, path)
    except OSError:  # best-effort: a cache must never fail a certificate
        pass


def cmt_qroute_ascent(
    inst: Instance,
    iters: int = 60,
    max_units: int = 4096,
    ub: float | None = None,
    ng_sharpen: bool = True,
    warm_start: bool = True,
):
    """Christofides-Mingozzi-Toth q-route bound with route-combination
    DP and Lagrangian ascent on customer penalties — the strongest
    capacity-aware bound here. Returns None when inapplicable, else a
    dict with the bound AND the artifacts the branch-and-bound pruner
    reuses (best multipliers, scaled demands).

    For penalties lam (free sign), a real solution costs
        cost = cost_lam - sum(lam)        (every customer has 1 in-arc)
    and its routes are closed q-routes under the penalized arcs, loads
    summing to total demand with the route count in [r_lo, r_hi]; so
        cost >= min_{k, load combo} sum of k penalized q-route costs
                - sum(lam)
    — computed exactly by a (routes x units) min-plus DP over the
    penalized q-route table. Every iterate is valid; the max is kept.

    Step management (VERDICT round-2: the old ascent was flat — it
    descended, its subgradient had the wrong sign: dL/dlam_j at the
    minimizing combo is visits_j - 1, so an OVER-visited customer must
    get MORE expensive): Polyak steps theta*(ub - L)/||g||^2 against an
    upper bound `ub` (any feasible cost — the incumbent being
    certified; absent, 1.5x the best bound so far stands in), theta
    decayed on stall. Multipliers are clamped to
    lam_j >= -0.95 * min-in-arc(j): a more negative penalty would make
    some arc profitable to cycle through, visits would explode, and one
    overshooting step could collapse the iterate permanently (measured:
    unclamped, one step sent the E-n22-k4 bound from 232 to -22000 with
    no recovery). Demands/capacity are gcd-scaled (_scaled_demands),
    which is what makes hundred-unit-demand instances (E-n22-k4,
    scaled capacity 60) tractable.
    """
    d, demands, caps = _host(inst)
    n = d.shape[0]
    if n <= 2:
        return None
    scaled = _scaled_demands(demands, caps, max_units)
    if scaled is None:
        return None
    dem_s, q_max, total = scaled
    k = n - 1
    r_hi = min(len(caps), k)
    r_lo = min(route_count_lb(inst), r_hi)
    in_arcs = d[:, 1:]
    lam_lo = -(np.where(in_arcs > 0, in_arcs, np.inf).min(axis=0)) * 0.95
    lam_hi = float(d.max()) * 2.0
    lam = np.zeros(k)
    best_bound, best_lam = 0.0, lam.copy()
    # warm-start from the persisted multipliers of a previous ascent on
    # the SAME instance: every lam is valid, so resuming from the best
    # known point can only help (the stored bound is NOT trusted — it
    # is re-derived below before it can beat best_bound)
    cache_path = _lam_cache_path(inst) if warm_start else None
    lam_w, _ = _lam_cache_load(cache_path)
    if lam_w is not None and lam_w.shape == lam.shape:
        lam = np.clip(lam_w, lam_lo, lam_hi)
    # ascent snapshots for the ng pass: the k best DISTINCT multiplier
    # points seen (the ng bound is valid at ANY lam, and the max of
    # valid bounds is valid — round-5 certificate work; evaluating ng
    # at several snapshots costs k native DP passes, all offline)
    snaps: list[tuple[float, np.ndarray]] = []
    theta = 0.5
    stall = 0
    for _ in range(iters):
        route_q, visits = _qroute_table(d, dem_s, q_max, lam)
        best_val, best_r, choices = _combo_bound(route_q, total, r_lo, r_hi)
        if not np.isfinite(best_val):
            break
        bound = best_val - float(lam.sum())
        if bound > best_bound + 1e-9:
            best_bound, best_lam = bound, lam.copy()
            snaps.append((bound, lam.copy()))
            if len(snaps) > 24:
                snaps = snaps[-24:]
            stall = 0
        else:
            stall += 1
            if stall >= 8:
                theta *= 0.7
                stall = 0
        if theta < 3e-3:
            # RESTART from the best point instead of terminating: the
            # decayed-step walk parks in a corner of multiplier space,
            # and a fresh step from the incumbent keeps climbing
            # (measured on synth X-n200: terminal decay converged at
            # 31.9k while restarts reached 32.6k at 1200 iterations
            # and were still improving — round-4 certificate work)
            theta = 0.3
            lam = best_lam.copy()
        # backtrack the winning combo once for the visit subgradient
        total_visits = np.zeros(k)
        u, ok = total, True
        for r in range(best_r - 1, -1, -1):
            q = int(choices[r][u])
            if q <= 0:
                ok = False
                break
            total_visits += visits[q]
            u -= q
        if not ok:
            break
        g = total_visits - 1.0  # dL/dlam: over-visited -> raise the price
        gnorm2 = float(g @ g)
        if gnorm2 == 0.0:
            break
        target = (ub if ub is not None else 1.5 * max(best_bound, 1e-6)) - bound
        lam = np.clip(lam + theta * max(target, 1e-6) / gnorm2 * g, lam_lo, lam_hi)
    # ng-route sharpening at the best multipliers (round 4): the ascent
    # iterates on the fast 2-cycle table, then ONE ng evaluation pass
    # lifts the final bound — any lam yields a valid bound, so taking
    # the max is safe, and the ng table kills the local cycles that
    # kept the 2-cycle certificate loose (VERDICT round-3 item 4). The
    # tables are returned in the artifact so qpath_completion_tables
    # (the B&B pruner) reuses them instead of re-running the native DP.
    # `ng_sharpen=False` skips the pass entirely: it costs seconds of
    # native DP (plus a one-time g++ build on first use), which a
    # deadline-bounded caller (solve_cvrp_bnb with a small timeLimit)
    # cannot afford before its search even starts (ADVICE r4).
    ng = ngroute_lb_tables(inst, best_lam, max_units=max_units) \
        if ng_sharpen else None
    if ng is not None:
        route_q_ng, _R_ng = ng
        route_q_2c, _ = _qroute_table(
            d, dem_s, q_max, best_lam, want_visits=False
        )
        best_val, _, _ = _combo_bound(
            np.maximum(route_q_2c, route_q_ng), total, r_lo, r_hi
        )
        if np.isfinite(best_val):
            best_bound = max(best_bound, float(best_val - best_lam.sum()))
        # ... and at a few earlier ascent snapshots: the 2-cycle-best
        # lam is not necessarily the ng-best lam (different relaxation,
        # different maximizer); widely-spaced snapshots cost one native
        # DP each and the max over them is valid
        seen = 0
        for b_s, lam_s in reversed(snaps[:-1]):
            if seen >= 3:
                break
            if np.allclose(lam_s, best_lam):
                continue
            seen += 1
            ng_s = ngroute_lb_tables(inst, lam_s, max_units=max_units)
            if ng_s is None:
                continue
            rq_2c, _ = _qroute_table(d, dem_s, q_max, lam_s, want_visits=False)
            v, _, _ = _combo_bound(
                np.maximum(rq_2c, ng_s[0]), total, r_lo, r_hi
            )
            if np.isfinite(v) and float(v - lam_s.sum()) > best_bound:
                best_bound = float(v - lam_s.sum())
                best_lam = lam_s
                ng = ng_s
    # persist only on IMPROVEMENT: a short deadline-bounded ascent (the
    # B&B root runs 5-80 iterations) must not overwrite the multipliers
    # a long offline certificate run climbed to
    _, stored_bound = _lam_cache_load(cache_path)
    if best_bound > stored_bound + 1e-9:
        _lam_cache_save(cache_path, best_lam, best_bound)
    return {
        "bound": float(best_bound),
        "lam": best_lam,
        "dem_s": dem_s,
        "cap_s": q_max,
        "total_s": total,
        "r_lo": r_lo,
        "r_hi": r_hi,
        "ng_tables": ng,  # (route_q, R) at best_lam, or None
    }


def cmt_qroute_lb(
    inst: Instance,
    iters: int = 60,
    max_units: int = 4096,
    ub: float | None = None,
) -> float:
    """The CMT q-route bound value (see cmt_qroute_ascent); 0.0 when the
    machinery does not apply."""
    out = cmt_qroute_ascent(inst, iters=iters, max_units=max_units, ub=ub)
    return 0.0 if out is None else out["bound"]


def _ng_sets(d: np.ndarray, g: int = 8) -> np.ndarray:
    """(n, g) ng neighbor sets: customer i remembers itself plus its
    g-1 nearest customers (1-based ids, native/ngroute.cpp layout)."""
    n = d.shape[0] - 1
    g = min(g, n)
    dc = d[1:, 1:].copy()
    np.fill_diagonal(dc, np.inf)
    order = np.argsort(dc, axis=1)[:, : g - 1] + 1  # nearest customer ids
    ng = np.zeros((n, g), np.int32)
    ng[:, 0] = np.arange(1, n + 1)
    if g > 1:
        ng[:, 1:] = order
    return ng


def _ng_budget_ok(cap_s: int, n: int, g: int = 8) -> bool:
    """Host memory/time guard for the ng DP: states*(n transitions)."""
    states = (cap_s + 1) * n * (1 << g)
    return states * 8 <= 600e6 and states * n <= 4e9


def ngroute_lb_tables(inst: Instance, lam: np.ndarray, max_units: int = 4096,
                      g: int = 8):
    """ng-route relaxation tables (native/ngroute.cpp) at multipliers
    `lam` -> (route_q, R) or None when inapplicable/unbuildable.

    Strictly finer than 2-cycle elimination for cycles WITHIN the
    neighbor sets (nearby customers remember each other — exactly where
    the cheap ping-pongs live), but not pointwise dominant (a walk may
    still 2-cycle through a far customer), so callers take the
    elementwise MAX with the 2-cycle tables: both are valid lower
    bounds on elementary completions.
    """
    d, demands, caps = _host(inst)
    scaled = _scaled_demands(demands, caps, max_units)
    if scaled is None:
        return None
    dem_s, cap_s, _total = scaled
    n = d.shape[0] - 1
    if not _ng_budget_ok(cap_s, n, g):
        return None
    from vrpms_tpu.native import ngroute_tables_native

    out = ngroute_tables_native(d, dem_s, lam, _ng_sets(d, g), cap_s)
    if out is None:
        return None
    route_q, R = out
    # the native sentinel 1e300 is FINITE to numpy — promote to inf so
    # the combo DP's isfinite filter skips those loads. An ng-unreachable
    # load is elementary-unreachable too (elementary walks are
    # ng-feasible), so inf there is valid and strictly tighter.
    route_q = np.where(route_q > 1e299, np.inf, route_q)
    R = np.where(R > 1e299, np.inf, R)
    return route_q, R


def qpath_completion_tables(inst: Instance, lam: np.ndarray, max_units: int = 4096,
                            ng_tables=None, build_ng: bool = True):
    """Per-node pruning tables for the branch-and-bound, from root
    multipliers `lam` -> (R, Psi) or None when inapplicable.

    R[q, i] (i = customer index 1..n-1, column i-1) is a relaxed min
    cost of a walk  i -> ... -> depot  that collects q more scaled
    demand units, each entered customer k contributing lam[k]. Psi[m, u]
    is the min cost of at most m closed penalized q-routes covering u
    units. Any true completion of a partial solution (finish the open
    route from position p with q1 more units, then run <= m fresh
    routes over the remaining demand) therefore costs at least

        min_{q1} R[q1, p] + Psi[m, dem_left - q1]  -  sum_{j in S} lam_j

    because the completion visits each remaining customer exactly once
    (collecting its lam) and both walk families are relaxations over
    ALL customers — restriction to S only raises the true cost. The
    subtraction term is maintained incrementally by the search.
    """
    d, demands, caps = _host(inst)
    scaled = _scaled_demands(demands, caps, max_units)
    if scaled is None:
        return None
    dem_s, cap_s, total = scaled
    n = d.shape[0]
    k = n - 1
    # R by reverse DP over walks ending at the depot, WITH 2-cycle
    # elimination (the classic best/second-best trick): the walk chosen
    # from j must not immediately hop back to i, so each state keeps its
    # best value A, that walk's first hop F, and the best value B among
    # walks with a DIFFERENT first hop; extending i -> j reads B when
    # F[j] == i. Without this, cheap i<->j ping-pongs dominate the table
    # and the bound loses most of its bite at exactly the depths the
    # branch-and-bound needs it.
    A = np.full((cap_s + 1, k), np.inf)  # best walk value
    F = np.full((cap_s + 1, k), -1, dtype=int)  # its first hop (customer col)
    B = np.full((cap_s + 1, k), np.inf)  # best with a different first hop
    A[0] = d[1:, 0]  # straight home (no hop: F = -1 matches no i)
    B[0] = d[1:, 0]
    dc = d[1:, 1:] + lam[None, :]  # entering customer j costs lam[j]
    rows = np.arange(k)
    cand = np.empty((k, k))
    for q in range(1, cap_s + 1):
        cand[:] = np.inf
        for dv in np.unique(dem_s):
            qp = q - int(dv)
            if qp < 0:
                continue
            js = np.where(dem_s == dv)[0]
            # extend: i -> j (j collects dv units), then best walk from j
            # whose first hop is not i
            vals = np.where(
                F[qp, js][None, :] == rows[:, None], B[qp, js][None, :],
                A[qp, js][None, :],
            ) + dc[:, js]
            vals[js, np.arange(len(js))] = np.inf  # no i -> i
            cand[:, js] = vals
        best_j = np.argmin(cand, axis=1)
        A[q] = cand[rows, best_j]
        F[q] = np.where(np.isfinite(A[q]), best_j, -1)
        cand[rows, best_j] = np.inf
        B[q] = cand.min(axis=1)
    R = A
    # closed penalized q-routes and their <=m-combo DP
    route_q, _ = _qroute_table(d, dem_s, cap_s, lam, want_visits=False)
    # ng-route sharpening (round 4): elementwise max with the ng tables
    # — each is a valid LB on elementary completions, and the ng side
    # kills the short cycles the 2-cycle relaxation can't see, which is
    # where both the B&B's per-node pruning and the X-n200 certificate
    # were leaking (VERDICT round-3 items 4/6). `ng_tables` accepts the
    # ascent's precomputed pair (cmt_qroute_ascent returns them) so the
    # B&B root does not run the native DP twice; they MUST correspond
    # to the same `lam`.
    # `build_ng=False` skips the rebuild entirely: a deadline-bounded
    # caller that deliberately ran its ascent with ng_sharpen=False must
    # not pay for the seconds-long native DP here instead (the fallback
    # would otherwise defeat the whole skip — code review r5)
    if ng_tables is not None:
        ng = ng_tables
    elif build_ng:
        ng = ngroute_lb_tables(inst, lam, max_units=max_units)
    else:
        ng = None
    if ng is not None:
        route_q_ng, R_ng = ng
        route_q = np.maximum(route_q, route_q_ng)
        R = np.maximum(R, R_ng)
    r_hi = min(len(caps), k)
    G = np.full((r_hi + 1, total + 1), np.inf)
    G[0, 0] = 0.0
    # loads past the total demand can never be used — and q > total + 1
    # would slice G with a NEGATIVE stop index, silently wrapping (it
    # raised a broadcast error on capacity > total-demand instances)
    finite_q = [
        q for q in range(1, min(cap_s, total) + 1) if np.isfinite(route_q[q])
    ]
    for r in range(1, r_hi + 1):
        G[r] = G[r - 1]
        for q in finite_q:
            # slice (not fancy-index) assignment: out= into G[r, u] with an
            # index array would write a temporary copy, leaving G untouched
            G[r, q:] = np.minimum(G[r, q:], G[r - 1, : total + 1 - q] + route_q[q])
    # G[r] is already "at most r routes" (the copy-forward above), i.e. Psi
    return R, G


def lower_bound(inst: Instance, ub: float | None = None) -> float:
    """Best applicable lower bound on the total-distance objective.

    TSP (single BIG-capacity vehicle): Held-Karp 1-tree (symmetric) or
    the AP relaxation (asymmetric). VRP: max of the AP relaxation, the
    symmetric MST bound, the Lagrangian forest bound, and the CMT
    q-route bound (the only capacity-aware one; its ascent is Polyak-
    stepped when a feasible cost `ub` is supplied, which is how the
    certificate path calls it).
    """
    d, _, caps = _host(inst)
    tsp = len(caps) == 1 and caps[0] >= BIG / 2
    bounds = [assignment_lb(inst)]
    if tsp:
        bounds.append(held_karp_1tree_lb(inst))
    else:
        bounds.append(mst_lb(inst))
        bounds.append(cvrp_forest_lb(inst))
        # certificates are offline artifacts: spend a long ascent. With
        # the round-4 theta-restart schedule the bound keeps climbing
        # where the old terminal decay plateaued (synth X-n200: 31.9k
        # flat at 300 iters vs 32.6k and rising at 1200; ~55 ms/iter
        # there, so ~80 s per certificate — offline money well spent)
        bounds.append(cmt_qroute_lb(inst, iters=1500, ub=ub))
    return float(max(bounds))


#: node-count cap for the O(n^3) Hungarian solve inside the QUICK bound
#: (past it the assignment relaxation costs more than a solve block)
QUICK_ASSIGNMENT_MAX_N = 256


def quick_lower_bound(inst: Instance) -> float | None:
    """Cheap applicable lower bound for LIVE gap telemetry — the
    milliseconds-scale subset of `lower_bound` (no Lagrangian ascent:
    that is an offline certificate tool at ~minutes per instance,
    while this runs once per submitted job on the HTTP thread).

    TSP: a short Held-Karp 1-tree ascent (symmetric) or the AP
    relaxation. VRP: max of the AP relaxation and the symmetric MST
    bound. Tier-padded instances are fine as-is: phantom customers are
    zero-cost depot aliases, so bounds on the padded tensor remain
    valid lower bounds of the real objective. Returns None when every
    applicable bound is vacuous (then gaps are simply not reported) —
    and on ANY failure: telemetry must never fail a submit.
    """
    try:
        d, _, caps = _host(inst)
        n = d.shape[0]
        tsp = len(caps) == 1 and caps[0] >= BIG / 2
        bounds = [0.0]
        if n <= QUICK_ASSIGNMENT_MAX_N:
            bounds.append(assignment_lb(inst))
        if tsp:
            if n <= 128:
                bounds.append(held_karp_1tree_lb(inst, iters=30))
        else:
            bounds.append(mst_lb(inst))
        lb = float(max(bounds))
        return lb if lb > 0 else None
    except Exception:
        return None


def certified_gap_percent(cost: float, inst: Instance) -> float | None:
    """Certified upper bound (percent) on this cost's optimality gap:
    gap_true <= (cost - LB) / LB. None when the bound is vacuous. The
    cost being certified doubles as the ascent's Polyak upper bound."""
    lb = lower_bound(inst, ub=float(cost))
    if lb <= 0:
        return None
    return 100.0 * (float(cost) - lb) / lb
