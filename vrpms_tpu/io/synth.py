"""Deterministic synthetic instance generators.

The container has no network egress, so CVRPLIB files can't be fetched;
these generators produce instances with the same statistical shape as
the benchmark families (uniform customer placement like the X set,
Solomon-style time windows) from a seed, for benches and tests. Sizes/
naming mirror the BASELINE.md ladder (e.g. synth_cvrp(200, 36) stands in
for X-n200-k36).
"""

from __future__ import annotations

import numpy as np

from vrpms_tpu.core.instance import Instance, make_instance


def _euclid(coords: np.ndarray) -> np.ndarray:
    return np.linalg.norm(coords[:, None] - coords[None, :], axis=-1)


def synth_tsp(n_nodes: int, seed: int = 0) -> Instance:
    """Uniform random points on [0, 1000]^2; node 0 is the start."""
    rng = np.random.default_rng(seed)
    coords = rng.uniform(0, 1000, size=(n_nodes, 2))
    return make_instance(_euclid(coords), n_vehicles=1)


def synth_cvrp(n_nodes: int, n_vehicles: int, seed: int = 0) -> Instance:
    """X-style CVRP: uniform points, unit-ish demands, capacity chosen so
    the expected route count matches n_vehicles with ~8% slack."""
    rng = np.random.default_rng(seed)
    coords = rng.uniform(0, 1000, size=(n_nodes, 2))
    demands = np.concatenate([[0], rng.integers(1, 10, size=n_nodes - 1)])
    capacity = float(np.ceil(demands.sum() * 1.08 / n_vehicles))
    return make_instance(
        _euclid(coords),
        demands=demands,
        capacities=[capacity] * n_vehicles,
    )


def synth_vrptw(
    n_nodes: int,
    n_vehicles: int,
    seed: int = 0,
    horizon: float = 1000.0,
    window: float = 120.0,
) -> Instance:
    """Solomon-R-style VRPTW: uniform points, random time windows of the
    given width inside the horizon, constant service time, depot open the
    whole horizon."""
    rng = np.random.default_rng(seed)
    coords = rng.uniform(0, 100, size=(n_nodes, 2))
    d = _euclid(coords)
    demands = np.concatenate([[0], rng.integers(1, 10, size=n_nodes - 1)])
    capacity = float(np.ceil(demands.sum() * 1.2 / n_vehicles))
    centers = rng.uniform(window, horizon - window, size=n_nodes)
    ready = np.maximum(centers - window / 2, 0.0)
    due = np.minimum(centers + window / 2, horizon)
    ready[0], due[0] = 0.0, horizon
    service = np.full(n_nodes, 10.0)
    return make_instance(
        d,
        demands=demands,
        capacities=[capacity] * n_vehicles,
        ready=ready,
        due=due,
        service=service,
    )


def synth_clustered_coords(
    n_nodes: int,
    n_clusters: int,
    seed: int = 0,
    extent: float = 1000.0,
    spread: float = 25.0,
):
    """Clustered customer COORDINATES (CVRPLIB XL-style): cluster
    centers uniform on [0, extent]^2, customers gaussian around them,
    depot at the centroid. Returns (coords [n, 2], demands [n]) WITHOUT
    building the O(n^2) matrix — the giant-instance decomposition path
    (core.decompose) consumes coordinates directly, and shard
    submatrices are built per shard (O(n * shard) total)."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(0, extent, size=(n_clusters, 2))
    which = rng.integers(0, n_clusters, size=n_nodes - 1)
    pts = centers[which] + rng.normal(0, spread, size=(n_nodes - 1, 2))
    pts = np.clip(pts, 0, extent)
    coords = np.concatenate([[pts.mean(axis=0)], pts])
    demands = np.concatenate([[0], rng.integers(1, 10, size=n_nodes - 1)])
    return coords, demands


def synth_clustered_cvrp(
    n_nodes: int,
    n_vehicles: int,
    n_clusters: int = 8,
    seed: int = 0,
    spread: float = 25.0,
) -> Instance:
    """Clustered CVRP as a dense Instance (tests / moderate sizes; for
    giant n keep the coords from synth_clustered_coords and let the
    decomposition build per-shard submatrices instead)."""
    coords, demands = synth_clustered_coords(
        n_nodes, n_clusters, seed=seed, spread=spread
    )
    capacity = float(np.ceil(demands.sum() * 1.15 / n_vehicles))
    return make_instance(
        _euclid(coords),
        demands=demands,
        capacities=[capacity] * n_vehicles,
    )


def synth_td(
    n_nodes: int,
    n_vehicles: int,
    seed: int = 0,
    t_slices: int = 24,
    rank: int = 1,
    slice_minutes: float = 60.0,
) -> Instance:
    """Time-dependent CVRP with an EXACTLY factorizable rank-R profile:
    durations[t] = sum_r profile_r(t) * basis_r, basis_r symmetric —
    the instance class the TD delta kernel admits (reference
    src/solver.py:7 `time_of_day` shape)."""
    rng = np.random.default_rng(seed)
    coords = rng.uniform(0, 1000, size=(n_nodes, 2))
    d = _euclid(coords)
    demands = np.concatenate([[0], rng.integers(1, 10, size=n_nodes - 1)])
    capacity = float(np.ceil(demands.sum() * 1.08 / n_vehicles))
    tt = np.arange(t_slices)
    slices = np.zeros((t_slices, n_nodes, n_nodes))
    for r in range(rank):
        profile = 1.0 / rank + 0.3 * np.sin(
            2 * np.pi * (r + 1) * tt / t_slices + r
        )
        # rank-r basis: smooth symmetric reweighting of the base matrix
        u = rng.uniform(0.5, 1.5, size=n_nodes)
        basis = d * np.sqrt(np.outer(u, u)) / rank
        slices += profile[:, None, None] * basis[None]
    slices = np.maximum(slices, 0.0)
    return make_instance(
        slices,
        demands=demands,
        capacities=[capacity] * n_vehicles,
        slice_axis="first",
        slice_minutes=slice_minutes,
    )
