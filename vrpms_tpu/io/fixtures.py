"""Checked-in public benchmark instances — the TRUE gap-to-BKS anchors.

The container has zero network egress, so the classic public instances the
north-star metric names (SURVEY.md §6: "CVRPLIB gap-to-best-known-solution")
are embedded here as text fixtures in their native formats and parsed by the
unchanged `io.cvrplib` parsers. Only instances small enough to transcribe
reliably are included; each one is defended by a three-way cross-check
(tests/test_fixtures.py):

  (a) file self-consistency — demand totals vs capacity×k, coordinate
      ranges, required-vehicle arithmetic;
  (b) `lower_bound(inst) <= BKS` — a violated lower bound would prove the
      transcription wrong;
  (c) the solver lands inside a sane band of BKS, and NEVER below it — a
      solution strictly better than the published optimum also proves the
      data wrong. For the small CVRP instances the branch-and-bound solver
      (solvers.exact.solve_cvrp_bnb) *proves* the optimum equals the
      published value, which pins the transcription exactly.

Sources (public domain benchmark data):
  E-n22-k4, A-n32-k5, A-n33-k5 — CVRPLIB (Christofides-Eilon / Augerat),
    optima 375 / 784 / 661 under the TSPLIB nint() edge rounding.
  E-n51-k5 — Christofides-Eilon 50-customer instance (the eil51
    coordinate set), optimum 521 under nint() rounding; transcription
    certified in round 5 by THREE independent published anchors on the
    same data: the TSP tour over the identical coordinates is TSPLIB
    eil51 (optimum 426 — hit exactly, never beaten), the real-distance
    variant is CMT1 (BKS 524.61 — hit to 0.01, never beaten), and
    lower_bound 508.5 <= 521 (benchmarks/verify_r5.py).
  R101.25, C101.25 — the first 25 customers of Solomon's R101/C101 with
    the standard 1-decimal-truncation distance convention; exact optima
    617.1 (8 vehicles) / 191.3 (3 vehicles), Kohl et al.
  R101 — the full 100-customer Solomon R101 (fixtures/R101.txt):
    rows 1-25 are byte-identical to the certified R101.25 prefix, the
    first-50 sub-instance (Kohl exact optimum 1044.0) and the full
    instance (distance-minimizing optimum 1637.7, 19-vehicle
    hierarchical BKS 1650.8) were both solved ABOVE and near their
    published optima, never below (verify_r5.py trail in BASELINE.md).
"""

from __future__ import annotations

import os

from vrpms_tpu.io.cvrplib import load_cvrplib, load_solomon

_DIR = os.path.join(os.path.dirname(__file__), "fixtures")

# name -> (filename, kind, BKS distance, vehicles in the BKS solution)
#
# Every CVRP entry has k == the bin-packing minimum fleet, so the free-fleet
# objective here coincides with the published fixed-fleet one. (P-n16-k8 was
# considered and rejected: its k=8 exceeds the 7-bin packing minimum, and a
# free fleet legally beats the published 450 with 7 routes — measured 428 —
# so its BKS is not comparable under this framework's idle-vehicle-allowed
# objective.)
FIXTURES: dict[str, tuple[str, str, float, int]] = {
    "E-n22-k4": ("E-n22-k4.vrp", "cvrp", 375.0, 4),
    "A-n32-k5": ("A-n32-k5.vrp", "cvrp", 784.0, 5),
    "E-n51-k5": ("E-n51-k5.vrp", "cvrp", 521.0, 5),
    "R101.25": ("R101_25.txt", "vrptw", 617.1, 8),
    "C101.25": ("C101_25.txt", "vrptw", 191.3, 3),
}

# XL fixtures: real instances too large for the quick per-fixture ILS
# band test (tests/test_fixtures.py runs a SHORT CPU solve on every
# FIXTURES entry; R101's 100 tight windows need minutes-to-hours of CPU
# there). They load through the same load_fixture and are defended by
# their own targeted checks (tests/test_fixtures.py::TestR101Full:
# certified-prefix identity, window sanity, LB <= BKS) plus the solve
# trail in BASELINE.md (zero-lateness 1797.4 at 20 vehicles on TPU —
# above the published optimum 1637.7, never below).
FIXTURES_XL: dict[str, tuple[str, str, float, int]] = {
    "R101": ("R101.txt", "vrptw", 1637.7, 20),
}

# A-n33-k5.vrp is on disk but OUT of the registry: the branch-and-bound
# PROVED its transcription's optimum is 690 (8.3B nodes exhausted), not
# the published 661 — the hand transcription is definitively wrong
# somewhere, and shipping it as truth would corrupt the gap metric. It
# stays as a record of the cross-check methodology doing its job (the
# same proof certifies A-n32-k5's transcription: proven optimum 784 ==
# published).


def fixture_names() -> list[str]:
    return list(FIXTURES)


def _entry(name: str) -> tuple[str, str, float, int]:
    return FIXTURES.get(name) or FIXTURES_XL[name]


def fixture_path(name: str) -> str:
    fname, _, _, _ = _entry(name)
    return os.path.join(_DIR, fname)


def load_fixture(name: str, n_vehicles: int | None = None):
    """Load an embedded instance -> (Instance, meta).

    meta gains `bks` (published best-known/optimal distance) and
    `bks_vehicles`. CVRP files use nint() rounding and the `-kV` fleet from
    the NAME field; Solomon files use 1-decimal truncation and, by default,
    the BKS vehicle count (the full-file fleet of 25 would leave most
    vehicles idle and make the minimum-distance objective trivially match
    the minimum-vehicle convention anyway — the BKS fleet keeps the
    comparison honest and the padded shapes small).
    """
    fname, kind, bks, bks_k = _entry(name)
    path = os.path.join(_DIR, fname)
    if kind == "cvrp":
        inst, meta = load_cvrplib(path, round_nint=True, n_vehicles=n_vehicles)
    else:
        inst, meta = load_solomon(
            path, n_vehicles=n_vehicles or bks_k, truncate_1dp=True
        )
    meta["name"] = name
    meta["bks"] = bks
    meta["bks_vehicles"] = bks_k
    meta["kind"] = kind
    return inst, meta
