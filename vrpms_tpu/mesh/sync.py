"""Single-source host decisions for multi-controller (jax.distributed) runs.

The blocked/chunked deadline drivers (mesh.islands._deadline_driver,
solvers.ils.ils_loop) gate further shard_map chunks on the host wall
clock. Under a multi-host mesh every controller runs that host loop, and
two controllers observing different elapsed times would issue different
chunk counts — collectives (ppermute, and the broadcast here) that one
process never joins, i.e. a distributed hang. The fix is the standard
SPMD rule: any data-dependent *control flow* decision must come from ONE
source. `controller_value` broadcasts process 0's measurement to every
process (identity in the common single-controller case), so all hosts
take identical branch sequences.

Discipline for callers: call sites must themselves be reached
identically on every process (the broadcast is a collective). That is
true ONLY for solves whose mesh spans every process — gate on
`mesh_spans_processes` before broadcasting; a process-local solve (e.g.
plain solve_ils without islands) must never call the collective, or it
blocks forever waiting for processes that never entered the solve.
"""

from __future__ import annotations

import numpy as np


def mesh_spans_processes(mesh) -> bool:
    """True iff this Mesh's devices live on more than one JAX process —
    the precise condition under which host-side control decisions must
    be broadcast (and under which broadcasting is safe: every process
    owning mesh devices runs the same host driver)."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def controller_value(value):
    """Process 0's `value` (a host float/bool scalar) on every process.

    Single-process: returns `value` unchanged, no collective, no device
    work — the fast path for every non-distributed deployment.
    """
    import jax

    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    out = multihost_utils.broadcast_one_to_all(
        np.asarray(value, dtype=np.float64)
    )
    return type(value)(np.asarray(out))
