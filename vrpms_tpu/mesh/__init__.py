from vrpms_tpu.mesh.islands import (
    make_mesh,
    solve_aco_islands,
    solve_sa_islands,
    solve_ga_islands,
    solve_ils_islands,
    IslandParams,
)
