"""Island-model parallel search over a TPU device mesh.

This is the distributed layer the reference never had (SURVEY.md §2.3
verifies no DP/TP/NCCL/MPI exists there; its only gesture at parallelism
is the unused `multiThreaded` flag, reference api/parameters.py:20).
TPU-natively, the "communication backend" is XLA collectives over ICI:

  * each device ("island") runs an independent SA chain-batch or GA
    sub-population under `jax.shard_map` over a 1-D `Mesh('islands')`;
  * every `migrate_every` steps the islands exchange their elite
    individuals around a ring via `lax.ppermute` (the combinatorial
    analog of ring attention's block rotation);
  * per-island champions come back sharded [n_islands, ...] and the
    final argmin runs in plain jit-land as a cross-device reduction.

Budget semantics: exactly `n_iters` (resp. `generations`) steps run —
whole migration blocks plus a migration-free tail — and the per-island
batch is the ceiling division of the requested total, so the effective
totals only ever round *up* to island multiples (reported faithfully via
SolveResult.evals).

Design rule (SURVEY.md §5): communicate small things — elite genomes and
costs, a few KB — never the durations matrix, which is replicated into
each island's closure once per solve. Multi-host (DCN) runs reuse this
unchanged: `jax.distributed.initialize()` + a mesh spanning all hosts'
devices makes ppermute ride DCN across slice boundaries transparently.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from vrpms_tpu.core.cost import (
    CostWeights,
    evaluate_giant,
    objective_batch_mode,
    resolve_eval_mode,
    total_cost,
)
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.moves import knn_table
from vrpms_tpu.solvers.common import SolveResult, perm_fitness_fn
from vrpms_tpu.solvers.ga import GAParams, ga_generation, initial_perms
from vrpms_tpu.solvers.sa import (
    SAParams,
    _auto_temps,
    initial_giants,
    sa_chain_step,
)


@dataclasses.dataclass(frozen=True)
class IslandParams:
    migrate_every: int = 100   # steps between ring migrations
    n_migrants: int = 4        # elites sent to the ring neighbor


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D island mesh over the available (or given) devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), ("islands",))


def _ring(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _migrate(pop, scores, k: int, axis: str, n_islands: int):
    """Send my k best to the next island; they replace my k worst."""
    order = jnp.argsort(scores)
    mig = pop[order[:k]]
    mig_s = scores[order[:k]]
    recv = jax.lax.ppermute(mig, axis, _ring(n_islands))
    recv_s = jax.lax.ppermute(mig_s, axis, _ring(n_islands))
    worst = order[-k:]
    pop = pop.at[worst].set(recv)
    scores = scores.at[worst].set(recv_s)
    return pop, scores


def _pick_champion(per_island_best, per_island_score):
    """Reduce per-island champions (sharded [n_isl, ...]) to the winner.

    Runs outside shard_map in plain jit-land, where XLA turns the argmin
    over the islands axis into the natural cross-device reduction.
    """
    j = jnp.argmin(per_island_score)
    return per_island_best[j], per_island_score[j]


def _blocked_schedule(total: int, block: int):
    """(n_full_blocks, tail) with n_full_blocks*block + tail == total."""
    return total // block, total % block


@lru_cache(maxsize=64)
def _sa_islands_fn(mesh: Mesh, n_iters: int, island_params: IslandParams, mode: str):
    """Build (and cache) the jitted sharded SA run for one configuration.

    Cached on the hashable statics — Mesh, n_iters, migration schedule,
    eval mode — so repeated solves reuse the compile; instance data,
    temperatures, and keys stay dynamic arguments (keying on the full
    SAParams would recompile whenever t_initial/t_final change, which
    the trace never sees). A per-call jit(shard_map(...)) closure would
    recompile every request.
    """
    n_isl = mesh.shape["islands"]
    block_len = island_params.migrate_every
    n_blocks, tail = _blocked_schedule(n_iters, block_len)
    k_mig = island_params.n_migrants

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("islands"), P(), P(), P(), P(), P(), P()),
        out_specs=(P("islands"), P("islands")),
        # Library scans (split/cost kernels) carry unvarying literals;
        # skip the VMA replication checker rather than pvary them all.
        check_vma=False,
    )
    def run(giants, k_run, inst, w, t0, t1, knn):
        isl = jax.lax.axis_index("islands")
        k_isl = jax.random.fold_in(k_run, isl)
        costs = objective_batch_mode(giants, inst, w, mode)

        def inner(st, it):
            giants, costs, best_g, best_c = st
            giants, costs = sa_chain_step(
                giants, costs, k_isl, it, t0, t1, n_iters, inst, w, mode, knn
            )
            better = costs < best_c
            best_g = jnp.where(better[:, None], giants, best_g)
            best_c = jnp.where(better, costs, best_c)
            return (giants, costs, best_g, best_c), None

        def block(state, b):
            state, _ = jax.lax.scan(
                inner, state, b * block_len + jnp.arange(block_len)
            )
            giants, costs, best_g, best_c = state
            giants, costs = _migrate(giants, costs, k_mig, "islands", n_isl)
            return (giants, costs, best_g, best_c), None

        state = (giants, costs, giants, costs)
        state, _ = jax.lax.scan(block, state, jnp.arange(n_blocks))
        if tail:
            state, _ = jax.lax.scan(
                inner, state, n_blocks * block_len + jnp.arange(tail)
            )
        _, _, best_g, best_c = state
        champ = jnp.argmin(best_c)
        return best_g[champ][None], best_c[champ][None]

    return jax.jit(run)


def solve_sa_islands(
    inst: Instance,
    key: jax.Array | int = 0,
    mesh: Mesh | None = None,
    params: SAParams = SAParams(),
    island_params: IslandParams = IslandParams(),
    weights: CostWeights | None = None,
    mode: str = "auto",
) -> SolveResult:
    """SA with per-device chain batches + ring elite migration."""
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    if isinstance(key, int):
        key = jax.random.key(key)
    mesh = mesh or make_mesh()
    n_isl = mesh.shape["islands"]
    chains_local = max(
        -(-params.n_chains // n_isl), island_params.n_migrants + 1
    )
    t0, t1 = _auto_temps(inst, params)
    n_iters = params.n_iters

    k_init, k_run = jax.random.split(key)
    giants0 = initial_giants(k_init, n_isl * chains_local, inst, params, mode)

    knn = knn_table(inst.durations[0], params.knn_k) if params.knn_k > 0 else None
    run = _sa_islands_fn(mesh, n_iters, island_params, mode)
    g_all, c_all = run(
        giants0, k_run, inst, w, jnp.float32(t0), jnp.float32(t1), knn
    )
    g, c = _pick_champion(g_all, c_all)
    bd = evaluate_giant(g, inst)
    return SolveResult(
        g,
        total_cost(bd, w),
        bd,
        jnp.int32(n_isl * chains_local * n_iters),
    )


@lru_cache(maxsize=64)
def _ga_islands_fn(
    mesh: Mesh, local_params: GAParams, island_params: IslandParams, mode: str
):
    """Build (and cache) the jitted sharded GA run (see _sa_islands_fn)."""
    n_isl = mesh.shape["islands"]
    generations = local_params.generations
    block_len = island_params.migrate_every
    n_blocks, tail = _blocked_schedule(generations, block_len)
    k_mig = island_params.n_migrants

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("islands"), P(), P(), P()),
        out_specs=(P("islands"), P("islands")),
        check_vma=False,
    )
    def run(perms, k_run, inst, w):
        fitness = perm_fitness_fn(inst, w, local_params.fleet_penalty, mode=mode)
        isl = jax.lax.axis_index("islands")
        k_isl = jax.random.fold_in(k_run, isl)
        fits = fitness(perms)
        champ0 = jnp.argmin(fits)

        def inner(st, gen):
            perms, fits, best_p, best_f = st
            perms, fits = ga_generation(
                perms, fits, k_isl, gen, fitness, local_params, mode
            )
            champ = jnp.argmin(fits)
            better = fits[champ] < best_f
            best_p = jnp.where(better, perms[champ], best_p)
            best_f = jnp.where(better, fits[champ], best_f)
            return (perms, fits, best_p, best_f), None

        def block(state, b):
            state, _ = jax.lax.scan(
                inner, state, b * block_len + jnp.arange(block_len)
            )
            perms, fits, best_p, best_f = state
            perms, fits = _migrate(perms, fits, k_mig, "islands", n_isl)
            return (perms, fits, best_p, best_f), None

        state = (perms, fits, perms[champ0], fits[champ0])
        state, _ = jax.lax.scan(block, state, jnp.arange(n_blocks))
        if tail:
            state, _ = jax.lax.scan(
                inner, state, n_blocks * block_len + jnp.arange(tail)
            )
        _, _, best_p, best_f = state
        return best_p[None], best_f[None]

    return jax.jit(run)


def solve_ga_islands(
    inst: Instance,
    key: jax.Array | int = 0,
    mesh: Mesh | None = None,
    params: GAParams = GAParams(),
    island_params: IslandParams = IslandParams(),
    weights: CostWeights | None = None,
    mode: str = "auto",
) -> SolveResult:
    """GA with per-device sub-populations + ring elite migration."""
    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)
    mesh = mesh or make_mesh()
    n_isl = mesh.shape["islands"]
    pop_local = max(
        -(-params.population // n_isl),
        max(params.elites, island_params.n_migrants) + 1,
    )
    local_params = dataclasses.replace(params, population=pop_local)
    generations = params.generations

    k_init, k_run = jax.random.split(key)
    perms0 = initial_perms(
        k_init, n_isl * pop_local, inst, params, resolve_eval_mode(mode)
    )

    run = _ga_islands_fn(
        mesh, local_params, island_params, resolve_eval_mode(mode)
    )
    p_all, f_all = run(perms0, k_run, inst, w)
    best_perm, _ = _pick_champion(p_all, f_all)
    giant = greedy_split_giant(best_perm, inst)
    bd = evaluate_giant(giant, inst)
    return SolveResult(
        giant,
        total_cost(bd, w),
        bd,
        jnp.int32(n_isl * pop_local * generations),
    )
