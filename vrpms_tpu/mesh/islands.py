"""Island-model parallel search over a TPU device mesh.

This is the distributed layer the reference never had (SURVEY.md §2.3
verifies no DP/TP/NCCL/MPI exists there; its only gesture at parallelism
is the unused `multiThreaded` flag, reference api/parameters.py:20).
TPU-natively, the "communication backend" is XLA collectives over ICI:

  * each device ("island") runs an independent SA chain-batch or GA
    sub-population under `jax.shard_map` over a 1-D `Mesh('islands')`;
  * every `migrate_every` steps the islands exchange their elite
    individuals around a ring via `lax.ppermute` (the combinatorial
    analog of ring attention's block rotation);
  * per-island champions come back sharded [n_islands, ...] and the
    final argmin runs in plain jit-land as a cross-device reduction.

Budget semantics: exactly `n_iters` (resp. `generations`) steps run —
whole migration blocks plus a migration-free tail — and the per-island
batch is the ceiling division of the requested total, so the effective
totals only ever round *up* to island multiples (reported faithfully via
SolveResult.evals).

Design rule (SURVEY.md §5): communicate small things — elite genomes and
costs, a few KB — never the durations matrix, which is replicated into
each island's closure once per solve. Multi-host (DCN) runs reuse this
unchanged: `jax.distributed.initialize()` + a mesh spanning all hosts'
devices makes ppermute ride DCN across slice boundaries transparently.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from vrpms_tpu.core.cost import (
    CostWeights,
    exact_cost,
    resolve_eval_mode,
)
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.moves import proposal_knn
from vrpms_tpu.solvers.common import SolveResult, perm_fitness_fn
from vrpms_tpu.solvers.ga import (
    GAParams,
    ga_generation,
    immigrants_for,
    initial_perms,
)
from vrpms_tpu.solvers.sa import (
    SAParams,
    _auto_temps,
    initial_giants,
    sa_chain_step,
)


@dataclasses.dataclass(frozen=True)
class IslandParams:
    migrate_every: int = 100   # steps between ring migrations
    n_migrants: int = 4        # elites sent to the ring neighbor


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D island mesh over the available (or given) devices.

    Canonicalized: the same device set always returns the SAME Mesh
    object. Every jitted-factory cache below is keyed on the mesh, and
    the service builds a mesh per request (_island_setup) — identity
    reuse guarantees those caches hit regardless of how a given jax
    version hashes Mesh, so no request can rebuild (and recompile) the
    sharded programs.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return _mesh_for(tuple(devices))


@lru_cache(maxsize=16)
def _mesh_for(devices: tuple) -> Mesh:
    return Mesh(np.array(devices), ("islands",))


def _ring(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _migrate(pop, scores, k: int, axis: str, n_islands: int):
    """Send my k best to the next island; they replace my k worst."""
    order = jnp.argsort(scores)
    mig = pop[order[:k]]
    mig_s = scores[order[:k]]
    recv = jax.lax.ppermute(mig, axis, _ring(n_islands))
    recv_s = jax.lax.ppermute(mig_s, axis, _ring(n_islands))
    worst = order[-k:]
    pop = pop.at[worst].set(recv)
    scores = scores.at[worst].set(recv_s)
    return pop, scores


def _pick_champion(per_island_best, per_island_score):
    """Reduce per-island champions (sharded [n_isl, ...]) to the winner.

    Runs outside shard_map in plain jit-land, where XLA turns the argmin
    over the islands axis into the natural cross-device reduction.
    """
    j = jnp.argmin(per_island_score)
    return per_island_best[j], per_island_score[j]


def _blocked_schedule(total: int, block: int):
    """(n_full_blocks, tail) with n_full_blocks*block + tail == total."""
    return total // block, total % block


@lru_cache(maxsize=64)
def _sa_islands_chunk_fn(
    mesh: Mesh, n_blocks: int, block_len: int, k_mig: int, mode: str
):
    """One jitted CHUNK of n_blocks migration blocks over the mesh.

    The deadline-aware twin of _sa_islands_fn: full sharded state in and
    out, with the absolute iteration offset and the schedule horizon as
    dynamic scalars — chunks compose to exactly the single-shot program
    (same fold-in indices, same migration points), so the host can check
    the wall clock between chunks (_deadline_driver's contract).
    `block_len` == 0 marks a migration-free tail chunk of n_blocks
    single iterations.
    """
    n_isl = mesh.shape["islands"]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("islands"), P(), P(), P(), P(), P(), P(), P(), P()),
        out_specs=P("islands"),
        check_vma=False,
    )
    def run(state, k_run, inst, w, t0, t1, knn, start_it, horizon):
        isl = jax.lax.axis_index("islands")
        k_isl = jax.random.fold_in(k_run, isl)

        def inner(st, it):
            giants, costs, best_g, best_c = st
            giants, costs = sa_chain_step(
                giants, costs, k_isl, it, t0, t1, horizon, inst, w, mode, knn
            )
            better = costs < best_c
            best_g = jnp.where(better[:, None], giants, best_g)
            best_c = jnp.where(better, costs, best_c)
            return (giants, costs, best_g, best_c), None

        if block_len == 0:  # tail: plain iterations, no migration
            state, _ = jax.lax.scan(
                inner, state, start_it + jnp.arange(n_blocks)
            )
            return state

        def block(st, b):
            st, _ = jax.lax.scan(
                inner, st, start_it + b * block_len + jnp.arange(block_len)
            )
            giants, costs, best_g, best_c = st
            giants, costs = _migrate(giants, costs, k_mig, "islands", n_isl)
            return (giants, costs, best_g, best_c), None

        state, _ = jax.lax.scan(block, state, jnp.arange(n_blocks))
        return state

    return jax.jit(run)


# the chunked paths reduce full sharded best-pools with the same rule
_champion = jax.jit(_pick_champion)


def _deadline_driver(
    call,
    state,
    total: int,
    block_len: int,
    sync_iters: int,
    deadline_s: float,
    multi_controller: bool = False,
    best_of=None,
    evals_per_iter: float | None = None,
):
    """Host-clock-checked execution of `total` island iterations: full
    migration blocks in chunks of ~sync_iters iterations, then the
    migration-free tail in chunks of the same budget — ONE driver for SA
    and GA so deadline semantics cannot diverge. call(state, n, bl,
    start) runs n blocks of bl iterations (bl == 0: n single iterations)
    from absolute iteration offset `start`. At least one chunk always
    runs; afterwards the clock is checked before and after every chunk.
    With `multi_controller` (the solve's mesh spans processes), every
    stop decision comes from process 0's clock (mesh.sync.
    controller_value) so all hosts issue identical chunk sequences —
    local clocks diverging would strand the ppermute collectives of the
    extra chunks. Process-local solves must NOT set it: the broadcast
    is itself a collective the other processes would never join.
    Returns (state, done).

    `best_of(state)`, when given, feeds the per-request convergence
    trace (vrpms_tpu.obs.trace) at every host sync — same contract as
    solvers.common.run_blocked's recording; a no-op without an active
    collector."""
    import time

    from vrpms_tpu.mesh.sync import controller_value
    from vrpms_tpu.obs.trace import active_trace

    trace = active_trace() if best_of is not None else None
    n_blocks, tail = _blocked_schedule(total, block_len)
    chunk = max(1, sync_iters // max(block_len, 1))
    t_start = time.monotonic()

    def spent():
        over = time.monotonic() - t_start >= deadline_s
        return controller_value(over) if multi_controller else over

    def sync(st, iters):
        jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
        if trace is not None:
            trace.record(best_of(st), iters, evals_per_iter)

    done = 0
    b = 0
    while b < n_blocks:
        nb = min(chunk, n_blocks - b)
        state = call(state, nb, block_len, b * block_len)
        sync(state, nb * block_len)
        b += nb
        done = b * block_len
        if spent():
            return state, done
    t = 0
    while t < tail:
        if done > 0 and spent():
            break
        nt = min(sync_iters, tail - t)
        state = call(state, nt, 0, n_blocks * block_len + t)
        sync(state, nt)
        t += nt
        done += nt
        if spent():
            break
    return state, done


def solve_sa_islands(
    inst: Instance,
    key: jax.Array | int = 0,
    mesh: Mesh | None = None,
    params: SAParams = SAParams(),
    island_params: IslandParams = IslandParams(),
    weights: CostWeights | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
    init_giants: jax.Array | None = None,
    pool: int = 0,
) -> SolveResult:
    """SA with per-device chain batches + ring elite migration.

    With `deadline_s`, migration blocks (and the migration-free tail)
    run in host-clock-checked chunks; the chunked program reproduces the
    single-shot one exactly when the deadline is never hit.
    `init_giants` ([B, L], B a multiple of the island count) overrides
    the constructive seeds — the warm-start/ILS-reseed hook. `pool` > 0
    returns an elite pool (SolveResult.pool, best first): the global
    top chains of the final sharded state.
    """
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    if isinstance(key, int):
        key = jax.random.key(key)
    mesh = mesh or make_mesh()
    n_isl = mesh.shape["islands"]
    t0, t1 = _auto_temps(inst, params)
    n_iters = params.n_iters

    k_init, k_run = jax.random.split(key)
    if init_giants is None:
        chains_local = max(
            -(-params.n_chains // n_isl), island_params.n_migrants + 1
        )
        giants0 = initial_giants(k_init, n_isl * chains_local, inst, params, mode)
    else:
        if init_giants.shape[0] % n_isl:
            raise ValueError(
                f"init_giants batch {init_giants.shape[0]} must divide "
                f"across {n_isl} islands"
            )
        chains_local = init_giants.shape[0] // n_isl
        if chains_local <= island_params.n_migrants:
            raise ValueError(
                "per-island chains must exceed n_migrants"
            )
        giants0 = init_giants

    knn = proposal_knn(inst, params.knn_k) if params.knn_k > 0 else None
    t0j, t1j = jnp.float32(t0), jnp.float32(t1)
    elite = None
    from vrpms_tpu.solvers.sa import _sa_init_fn

    block_len = island_params.migrate_every
    k_mig = island_params.n_migrants
    horizon = jnp.float32(n_iters)
    costs0 = _sa_init_fn(mode)(giants0, inst, w)
    state = (giants0, costs0, giants0, costs0)

    def call(st, n, bl, start):
        return _sa_islands_chunk_fn(mesh, n, bl, k_mig, mode)(
            st, k_run, inst, w, t0j, t1j, knn, jnp.int32(start), horizon
        )

    from vrpms_tpu.mesh.sync import mesh_spans_processes

    # Deadline-free solves drive the SAME bounded set of chunked
    # programs with an infinite budget (the offsets/horizon are dynamic
    # scalars), instead of the old single-shot factory keyed on the
    # request's raw n_iters — which minted one fresh XLA program per
    # distinct iteration budget, a per-request recompile under varied
    # traffic. ~512 iterations per host sync.
    state, done = _deadline_driver(
        call, state, n_iters, block_len, 512,
        float("inf") if deadline_s is None else deadline_s,
        multi_controller=mesh_spans_processes(mesh),
        best_of=lambda st: st[3],
        evals_per_iter=n_isl * chains_local,
    )
    done = max(done, n_iters) if deadline_s is None else done
    _, _, best_g, best_c = state
    g, c = _champion(best_g, best_c)
    if pool > 0:
        order = jnp.argsort(best_c)[: min(pool, best_g.shape[0])]
        elite = best_g[order]
    bd, cost = exact_cost(g, inst, w)
    return SolveResult(
        g,
        cost,
        bd,
        jnp.int32(n_isl * chains_local * done),
        elite,
    )


@lru_cache(maxsize=64)
def _ga_islands_chunk_fn(
    mesh: Mesh,
    n_blocks: int,
    block_len: int,
    local_params: GAParams,
    k_mig: int,
    mode: str,
):
    """One jitted chunk of n_blocks GA migration blocks (the deadline-
    aware twin of _ga_islands_fn — see _sa_islands_chunk_fn's contract).
    Per-island bests travel as [1, n]/[1] rows so the sharded state
    round-trips between chunks. Callers normalize `generations` to 0 in
    local_params (the chunk never reads it). block_len == 0 marks a
    migration-free tail of n_blocks single generations."""
    n_isl = mesh.shape["islands"]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("islands"), P(), P(), P(), P()),
        out_specs=P("islands"),
        check_vma=False,
    )
    def run(state, k_run, inst, w, start_gen):
        fitness = perm_fitness_fn(inst, w, local_params.fleet_penalty, mode=mode)
        isl = jax.lax.axis_index("islands")
        k_isl = jax.random.fold_in(k_run, isl)
        nrp = inst.perm_limit
        perms, fits, best_p1, best_f1 = state
        st = (perms, fits, best_p1[0], best_f1[0])

        def inner(st, gen):
            perms, fits, best_p, best_f = st
            perms, fits = ga_generation(
                perms, fits, k_isl, gen, fitness, local_params, mode,
                d=inst.durations[0], n_real_perm=nrp,
            )
            champ = jnp.argmin(fits)
            better = fits[champ] < best_f
            best_p = jnp.where(better, perms[champ], best_p)
            best_f = jnp.where(better, fits[champ], best_f)
            return (perms, fits, best_p, best_f), None

        if block_len == 0:
            st, _ = jax.lax.scan(inner, st, start_gen + jnp.arange(n_blocks))
        else:
            def block(st, b):
                st, _ = jax.lax.scan(
                    inner, st, start_gen + b * block_len + jnp.arange(block_len)
                )
                perms, fits, best_p, best_f = st
                perms, fits = _migrate(perms, fits, k_mig, "islands", n_isl)
                return (perms, fits, best_p, best_f), None

            st, _ = jax.lax.scan(block, st, jnp.arange(n_blocks))
        perms, fits, best_p, best_f = st
        return (perms, fits, best_p[None], best_f[None])

    return jax.jit(run)


@lru_cache(maxsize=8)
def _ga_islands_init_fn(fleet_penalty: float, n_isl: int, mode: str):
    """Jitted initial fitness + per-island incumbent extraction."""

    @jax.jit
    def init(perms0, inst, w):
        fitness = perm_fitness_fn(inst, w, fleet_penalty, mode=mode)
        fits0 = fitness(perms0)
        pop_local = perms0.shape[0] // n_isl
        fr = fits0.reshape(n_isl, pop_local)
        idx = jnp.argmin(fr, axis=1)
        rows = jnp.arange(n_isl)
        best_p = perms0.reshape(n_isl, pop_local, -1)[rows, idx]
        best_f = fr[rows, idx]
        return fits0, best_p, best_f

    return init


def solve_ga_islands(
    inst: Instance,
    key: jax.Array | int = 0,
    mesh: Mesh | None = None,
    params: GAParams = GAParams(),
    island_params: IslandParams = IslandParams(),
    weights: CostWeights | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
    pool: int = 0,
    init_perms: jax.Array | None = None,
) -> SolveResult:
    """GA with per-device sub-populations + ring elite migration.

    With `deadline_s`, migration blocks run in host-clock-checked chunks
    (see solve_sa_islands). `pool` > 0 returns the per-island champion
    genomes as split giants (SolveResult.pool, best first; at most one
    per island). `init_perms` ([B, n], B a multiple of the island count,
    per-island shards exceeding max(elites, n_migrants)) overrides the
    constructive seeds — the warm-start hook (VERDICT round-2 item 8:
    islands + warmStart silently dropped the checkpoint for GA).
    """
    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)
    mesh = mesh or make_mesh()
    n_isl = mesh.shape["islands"]
    if init_perms is None:
        pop_local = max(
            -(-params.population // n_isl),
            max(params.elites, island_params.n_migrants) + 1,
        )
    else:
        if init_perms.shape[0] % n_isl:
            raise ValueError(
                f"init_perms batch {init_perms.shape[0]} must divide "
                f"across {n_isl} islands"
            )
        pop_local = init_perms.shape[0] // n_isl
        if pop_local <= max(params.elites, island_params.n_migrants):
            raise ValueError(
                "per-island population must exceed max(elites, n_migrants)"
            )
    local_params = dataclasses.replace(params, population=pop_local)
    generations = params.generations
    mode = resolve_eval_mode(mode)
    per_gen = pop_local + (
        0
        if inst.n_real is not None
        else immigrants_for(local_params, pop_local, inst.n_customers)
    )

    k_init, k_run = jax.random.split(key)
    if init_perms is None:
        perms0 = initial_perms(k_init, n_isl * pop_local, inst, params, mode)
    else:
        perms0 = init_perms

    block_len = island_params.migrate_every
    k_mig = island_params.n_migrants
    chunk_params = dataclasses.replace(local_params, generations=0)
    fits0, best_p0, best_f0 = _ga_islands_init_fn(
        params.fleet_penalty, n_isl, mode
    )(perms0, inst, w)
    state = (perms0, fits0, best_p0, best_f0)

    def call(st, n, bl, start):
        return _ga_islands_chunk_fn(
            mesh, n, bl, chunk_params, k_mig, mode
        )(st, k_run, inst, w, jnp.int32(start))

    from vrpms_tpu.mesh.sync import mesh_spans_processes

    # One bounded set of chunked programs for every budget (deadline-
    # free solves pass an infinite budget) — the old single-shot
    # factory keyed on raw `generations` recompiled per distinct
    # budget. ~128 generations per host sync (a generation costs more).
    state, done = _deadline_driver(
        call, state, generations, block_len, 128,
        float("inf") if deadline_s is None else deadline_s,
        multi_controller=mesh_spans_processes(mesh),
        best_of=lambda st: st[3],
        evals_per_iter=n_isl * per_gen,
    )
    done = max(done, generations) if deadline_s is None else done
    _, _, best_p, best_f = state
    best_perm, _ = _champion(best_p, best_f)
    pool_perms, pool_fits = best_p, best_f
    giant = greedy_split_giant(best_perm, inst)
    bd, cost = exact_cost(giant, inst, w)
    elite = None
    if pool > 0:
        order = jnp.argsort(pool_fits)[: min(pool, pool_perms.shape[0])]
        elite = jax.vmap(lambda p: greedy_split_giant(p, inst))(
            pool_perms[order]
        )
    return SolveResult(
        giant,
        cost,
        bd,
        jnp.int32(n_isl * per_gen * done),
        elite,
    )


@lru_cache(maxsize=32)
def _aco_islands_chunk_fn(mesh: Mesh, n_blocks: int, block_len: int, aco_params):
    """One jitted chunk of n_blocks ACO migration blocks over the mesh.

    Per-island colonies with PHEROMONE-FREE elite exchange: each island
    evolves its own dense tau matrix; at migration only the incumbent
    genome + fitness cross the ring (a few hundred bytes — the
    communicate-small-things rule; shipping tau would be N^2 floats per
    hop). A received better elite replaces the local incumbent AND is
    deposited into the local tau, so the information actually steers
    construction. block_len == 0 marks a migration-free tail of
    n_blocks single iterations. Chunks compose exactly (absolute
    iteration offsets), so _deadline_driver can clock-check between
    them like SA/GA.
    """
    from vrpms_tpu.core.cost import resolve_eval_mode
    from vrpms_tpu.solvers.aco import aco_iteration, deposit

    n_isl = mesh.shape["islands"]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("islands"), P(), P(), P(), P(), P()),
        out_specs=P("islands"),
        check_vma=False,
    )
    def run(state, k_run, inst, w, knn_mask, start_it):
        hot = resolve_eval_mode("auto") != "gather"
        isl = jax.lax.axis_index("islands")
        k_isl = jax.random.fold_in(k_run, isl)
        tau1, bp1, bf1 = state
        st = (
            tau1[0], bp1[0], bf1[0],
            jnp.zeros((0, bp1.shape[-1]), bp1.dtype), jnp.zeros((0,)),
        )

        def iteration(st, it):
            return aco_iteration(
                st, it, k_isl, inst, w, aco_params, knn_mask, hot
            ), None

        def migrate(st):
            tau, bp, bf, pp, pf = st
            rbp = jax.lax.ppermute(bp, "islands", _ring(n_isl))
            rbf = jax.lax.ppermute(bf, "islands", _ring(n_isl))
            better = rbf < bf
            bp = jnp.where(better, rbp, bp)
            bf = jnp.where(better, rbf, bf)
            # deposit the adopted elite so construction feels it; a
            # zero amount makes the rejected case a no-op
            amount = jnp.where(better, 1.0 / jnp.maximum(rbf, 1e-6), 0.0)
            tau = deposit(tau, greedy_split_giant(rbp, inst), amount, hot)
            return tau, bp, bf, pp, pf

        if block_len == 0:
            def tail(st, it):
                return iteration(st, it)

            st, _ = jax.lax.scan(tail, st, start_it + jnp.arange(n_blocks))
        else:
            def block(st, b):
                st, _ = jax.lax.scan(
                    iteration, st, start_it + b * block_len + jnp.arange(block_len)
                )
                return migrate(st), None

            st, _ = jax.lax.scan(block, st, jnp.arange(n_blocks))
        tau, bp, bf, _, _ = st
        return tau[None], bp[None], bf[None]

    return jax.jit(run)


def solve_aco_islands(
    inst: Instance,
    key: jax.Array | int = 0,
    mesh: Mesh | None = None,
    params=None,  # solvers.aco.ACOParams
    island_params: IslandParams = IslandParams(),
    weights: CostWeights | None = None,
    deadline_s: float | None = None,
    init_perm: jax.Array | None = None,
    pool: int = 0,
) -> SolveResult:
    """ACO with per-device colonies + ring elite migration.

    Every island runs an independent MMAS colony (own pheromone
    matrix, decorrelated keys); every `migrate_every` iterations the
    incumbents circulate the ring and better arrivals are adopted and
    deposited (see _aco_islands_chunk_fn). With `deadline_s` the blocks
    run under the host-clock-checked _deadline_driver. `init_perm`
    warm-starts EVERY island's incumbent; `pool` > 0 returns the
    per-island champions as split giants (best first, at most one per
    island) — the multi-start polish hook.
    """
    import dataclasses as _dc

    from vrpms_tpu.solvers.aco import ACOParams, _aco_init_fn, aco_knn_mask

    params = params or ACOParams()
    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)
    mesh = mesh or make_mesh()
    n_isl = mesh.shape["islands"]
    block_params = _dc.replace(params, n_iters=0, knn_k=0)

    warm = init_perm is not None
    if init_perm is None:
        init_perm = jnp.arange(1, inst.n_customers + 1, dtype=jnp.int32)
    tau0, bp0, bf0, _, _ = _aco_init_fn(block_params, 0, warm)(inst, w, init_perm)
    state = (
        jnp.tile(tau0[None], (n_isl, 1, 1)),
        jnp.tile(bp0[None], (n_isl, 1)),
        jnp.tile(bf0[None], (n_isl,)),
    )
    knn_mask = aco_knn_mask(inst, params.knn_k)
    block_len = island_params.migrate_every

    def call(st, n, bl, start):
        return _aco_islands_chunk_fn(mesh, n, bl, block_params)(
            st, key, inst, w, knn_mask, jnp.int32(start)
        )

    if deadline_s is None:
        n_blocks, tail = _blocked_schedule(params.n_iters, block_len)
        if n_blocks:
            state = call(state, n_blocks, block_len, 0)
        if tail:
            state = call(state, tail, 0, n_blocks * block_len)
        done = params.n_iters
    else:
        from vrpms_tpu.mesh.sync import mesh_spans_processes

        # ~64 colony iterations per host sync (an iteration is heavy)
        state, done = _deadline_driver(
            call, state, params.n_iters, block_len, 64, deadline_s,
            multi_controller=mesh_spans_processes(mesh),
            best_of=lambda st: st[2],
            evals_per_iter=n_isl * params.n_ants,
        )
    _, best_p, best_f = state
    best_perm, _ = _champion(best_p, best_f)
    giant = greedy_split_giant(best_perm, inst)
    bd, cost = exact_cost(giant, inst, w)
    if warm:
        from vrpms_tpu.solvers.aco import warm_floor

        giant, bd, cost = warm_floor(giant, bd, cost, init_perm, inst, w)
    elite = None
    if pool > 0:
        from vrpms_tpu.core.cost import exact_cost_batch

        order = jnp.argsort(best_f)[: min(pool, best_p.shape[0])]
        elite = jax.vmap(lambda p: greedy_split_giant(p, inst))(best_p[order])
        # exact re-rank + champion upgrade (see solve_aco: colony
        # fitness can disagree with the bounded-fleet objective)
        ecosts = exact_cost_batch(elite, inst, w)
        order2 = jnp.argsort(ecosts)
        elite = elite[order2]
        if float(ecosts[order2[0]]) < float(cost):
            giant = elite[0]
            bd, cost = exact_cost(giant, inst, w)
    return SolveResult(
        giant,
        cost,
        bd,
        jnp.int32(n_isl * params.n_ants * done),
        elite,
    )


def solve_ils_islands(
    inst: Instance,
    key: jax.Array | int = 0,
    mesh: Mesh | None = None,
    params=None,  # solvers.ils.ILSParams
    island_params: IslandParams = IslandParams(),
    weights: CostWeights | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
    init_giants: jax.Array | None = None,
) -> SolveResult:
    """Iterated local search with the anneal phase sharded over islands.

    Each round runs the ring-migration island SA (per-device chain
    batches, ppermute elite exchange), polishes the returned elite pool
    (the per-island champions; global top chains under a deadline) with
    the delta descent, and reseeds EVERY island's chains from the
    best-so-far (sa.perturbed_clones). Only the pool and the reseed
    clones cross the host boundary between rounds — the communicate-
    small-things rule (module docstring) carried up to the ILS level.
    Round/polish/reseed/deadline semantics are solvers.ils.ils_loop's,
    shared verbatim with the single-device solve_ils.
    """
    from vrpms_tpu.solvers.ils import ILSParams, ils_loop

    params = params or ILSParams()
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    if isinstance(key, int):
        key = jax.random.key(key)
    mesh = mesh or make_mesh()
    n_isl = mesh.shape["islands"]
    if init_giants is None:
        chains_local = max(
            -(-params.sa.n_chains // n_isl), island_params.n_migrants + 1
        )
    else:
        # warm-start hook: the first round's chains come from the caller
        # (perturbed checkpoint clones); solve_sa_islands validates the
        # per-island shard size
        if init_giants.shape[0] % n_isl:
            raise ValueError(
                f"init_giants batch {init_giants.shape[0]} must divide "
                f"across {n_isl} islands"
            )
        chains_local = init_giants.shape[0] // n_isl

    def anneal(k_round, init, budget):
        return solve_sa_islands(
            inst,
            key=k_round,
            mesh=mesh,
            params=params.sa,
            island_params=island_params,
            weights=w,
            mode=mode,
            deadline_s=budget,
            init_giants=init,
            pool=params.pool,
        )

    from vrpms_tpu.mesh.sync import mesh_spans_processes

    return ils_loop(
        anneal,
        n_isl * chains_local,
        inst,
        key,
        params,
        w,
        mode,
        deadline_s,
        init_giants,
        multi_controller=mesh_spans_processes(mesh),
    )
