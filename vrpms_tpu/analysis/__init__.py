"""vrpms-lint: project-native static analysis for vrpms-tpu.

One AST pass per file, checkers as pluggable rules, findings as
structured records, inline ``# vrpms-lint: disable=<rule> (<reason>)``
suppressions. Run it as ``python -m vrpms_tpu.analysis`` (the tier-1 CI
gate) or programmatically via :func:`run`.

Rule families (see each module's docstring for the full contract):

  * lock discipline  — ``# guarded-by:`` annotations (analysis.locks)
  * tracing hygiene  — jit/scan-body purity hazards (analysis.tracing)
  * service contracts — envelopes, metrics, spans (analysis.contracts)
  * config discipline — env reads via vrpms_tpu.config
    (analysis.config_rules)
  * dead code — unused imports / private symbols (analysis.deadcode)
"""

from __future__ import annotations

from pathlib import Path

from vrpms_tpu.analysis.base import (
    Finding,
    Report,
    Rule,
    run_rules,
)
from vrpms_tpu.analysis.config_rules import (
    DocSyncRule,
    EnvReadRule,
    UnknownVarRule,
)
from vrpms_tpu.analysis.contracts import (
    DeadSpanRule,
    EnvelopeRule,
    MetricContractRule,
    SpanNameRule,
)
from vrpms_tpu.analysis.deadcode import DeadImportRule, DeadPrivateSymbolRule
from vrpms_tpu.analysis.locks import LockDisciplineRule
from vrpms_tpu.analysis.tracing import TraceHygieneRule

#: repo root = the directory holding the vrpms_tpu package
REPO_ROOT = Path(__file__).resolve().parents[2]

#: what `python -m vrpms_tpu.analysis` scans by default. tests/ and
#: benchmarks/ are in scope for dead-private-symbol aliveness (a test
#: poking mod._helper keeps it alive) but rules that encode production
#: contracts scope themselves (e.g. contract-envelope to service/).
DEFAULT_PATHS = ("vrpms_tpu", "service", "store", "main.py")
#: scanned for symbol references only (keeps dead-code honest) — not
#: for production-contract rules
REFERENCE_PATHS = ("tests", "benchmarks")


def default_rules() -> list:
    return [
        LockDisciplineRule(),
        TraceHygieneRule(),
        EnvelopeRule(),
        MetricContractRule(),
        SpanNameRule(),
        DeadSpanRule(),
        EnvReadRule(),
        UnknownVarRule(),
        DocSyncRule(),
        DeadImportRule(),
        DeadPrivateSymbolRule(),
    ]


def run(paths=None, root: Path | None = None, rules=None,
        reference_paths=None) -> Report:
    """Run the analyzer. `paths` defaults to the production tree;
    tests/ and benchmarks/ are parsed as reference-only (they feed
    symbol-aliveness to project rules but are not themselves checked)."""
    root = Path(root) if root is not None else REPO_ROOT
    if paths is None:
        paths = [p for p in (root / d for d in DEFAULT_PATHS) if p.exists()]
    else:
        paths = [Path(p) for p in paths]
    if reference_paths is None:
        reference_paths = [
            p for p in (root / d for d in REFERENCE_PATHS) if p.exists()
        ]
    else:
        reference_paths = [Path(p) for p in reference_paths]
    return run_rules(rules if rules is not None else default_rules(),
                     paths, root, reference_paths=reference_paths)
