"""Service-contract checkers: envelopes, metric names, span names.

Three contracts every surface must honor, today enforced only by
review:

  * ``contract-envelope`` — every JSON envelope a handler writes goes
    through :func:`service.helpers.attach_ids` (directly or via the
    responder helpers), so `requestId` + `traceId` ride EVERY response,
    429s and 503s included. The rule flags any
    ``wfile.write(json.dumps(X))`` in ``service/`` where X is neither
    an ``attach_ids(...)`` call nor a name assigned from one in the
    same function.
  * ``contract-metric-once`` / ``contract-metric-labels`` — every
    ``vrpms_*`` metric name is registered exactly once project-wide,
    and every ``.labels(...)`` call site uses exactly the label set the
    registration declared (a mismatched call raises at runtime — on
    whatever rare path reaches it; this finds it before a request
    does).
  * ``contract-span-name`` — every literal span name appears in
    ``vrpms_tpu.obs.spans.KNOWN_SPAN_NAMES``, the span registry the
    dashboards and tests key on. Dynamic names (the HTTP root span) are
    out of scope.
  * ``contract-span-dead`` — the inverse direction: every
    ``KNOWN_SPAN_NAMES`` entry is still emitted by at least one literal
    ``span()``/``span_at()`` call somewhere in the production tree. A
    registered-but-never-emitted name is dead registry weight —
    dashboards and waterfall tests key on a span that can never appear.
"""

from __future__ import annotations

import ast

from vrpms_tpu.analysis.base import Finding, Rule, call_name, first_str_arg

_REG_METHODS = {"counter", "gauge", "histogram"}
_RESPONDERS = {"attach_ids"}


def _span_registry() -> frozenset:
    from vrpms_tpu.obs.spans import KNOWN_SPAN_NAMES

    return KNOWN_SPAN_NAMES


class EnvelopeRule(Rule):
    name = "contract-envelope"
    scopes = ("service/",)

    def check_file(self, ctx):
        findings: list = []
        for fn in [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            attached: set = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        call_name(node.value.func).split(".")[-1] in \
                        _RESPONDERS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            attached.add(tgt.id)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = call_name(node.func)
                if not callee.endswith("wfile.write") or not node.args:
                    continue
                payload = self._json_dumps_arg(node.args[0])
                if payload is None:
                    continue  # not a JSON envelope write (SSE, bytes)
                if isinstance(payload, ast.Call) and \
                        call_name(payload.func).split(".")[-1] in \
                        _RESPONDERS:
                    continue
                if isinstance(payload, ast.Name) and payload.id in attached:
                    continue
                findings.append(Finding(
                    rule=self.name,
                    file=ctx.rel,
                    line=node.lineno,
                    message=(
                        "JSON envelope written without attach_ids(): the "
                        "response will miss requestId/traceId correlation"
                    ),
                ))
        return findings

    @staticmethod
    def _json_dumps_arg(node):
        """X from `json.dumps(X)[.encode(...)]`, else None."""
        cur = node
        if isinstance(cur, ast.Call) and \
                isinstance(cur.func, ast.Attribute) and \
                cur.func.attr == "encode":
            cur = cur.func.value
        if isinstance(cur, ast.Call) and \
                call_name(cur.func) in ("json.dumps", "dumps") and cur.args:
            return cur.args[0]
        return None


class MetricContractRule(Rule):
    """Project rule: registrations + label-call sites, checked at
    finalize."""

    name = "contract-metric"
    finding_names = ("contract-metric-once", "contract-metric-labels")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        #: metric name -> [(file, line, labels tuple)]
        self.registrations: dict = {}
        #: instrument var name -> (metric name, labels, file, line)
        self.instruments: dict = {}
        #: [(var name, kwargs frozenset, file, line)]
        self.label_calls: list = []

    def collect(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node.func)
            leaf = callee.split(".")[-1]
            if leaf in _REG_METHODS and callee.split(".")[0] in (
                "REGISTRY", "registry",
            ):
                name = first_str_arg(node)
                if name is None or not name.startswith("vrpms_"):
                    continue
                labels: tuple = ()
                for kw in node.keywords:
                    if kw.arg == "labels" and isinstance(
                        kw.value, (ast.Tuple, ast.List)
                    ):
                        labels = tuple(
                            el.value for el in kw.value.elts
                            if isinstance(el, ast.Constant)
                        )
                if len(node.args) > 2 and isinstance(
                    node.args[2], (ast.Tuple, ast.List)
                ):
                    labels = tuple(
                        el.value for el in node.args[2].elts
                        if isinstance(el, ast.Constant)
                    )
                self.registrations.setdefault(name, []).append(
                    (ctx.rel, node.lineno, labels)
                )
            elif leaf == "labels":
                base = callee.rsplit(".", 1)[0]
                var = base.split(".")[-1]
                if not var.isupper():
                    continue
                kwargs = frozenset(
                    kw.arg for kw in node.keywords if kw.arg is not None
                )
                self.label_calls.append(
                    (var, kwargs, ctx.rel, node.lineno)
                )
        # map instrument variable names to registrations
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                callee = call_name(node.value.func)
                if callee.split(".")[-1] in _REG_METHODS and \
                        callee.split(".")[0] in ("REGISTRY", "registry"):
                    name = first_str_arg(node.value)
                    if name is None:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            regs = self.registrations.get(name, ())
                            labels = regs[-1][2] if regs else ()
                            self.instruments[tgt.id] = (
                                name, labels, ctx.rel, node.lineno
                            )

    def finalize(self, project):
        findings: list = []
        for name, regs in sorted(self.registrations.items()):
            if len(regs) > 1:
                first = regs[0]
                for rel, line, _labels in regs[1:]:
                    findings.append(Finding(
                        rule="contract-metric-once",
                        file=rel,
                        line=line,
                        message=(
                            f"metric {name!r} registered more than once "
                            f"(first at {first[0]}:{first[1]}) — the "
                            "registry raises on the second registration"
                        ),
                    ))
            label_sets = {labels for _f, _l, labels in regs}
            if len(label_sets) > 1:
                rel, line, _labels = regs[-1]
                findings.append(Finding(
                    rule="contract-metric-labels",
                    file=rel,
                    line=line,
                    message=(
                        f"metric {name!r} registered with inconsistent "
                        f"label sets {sorted(map(list, label_sets))}"
                    ),
                ))
        for var, kwargs, rel, line in self.label_calls:
            inst = self.instruments.get(var)
            if inst is None:
                continue  # not one of ours (or dynamically built)
            name, labels, _f, _l = inst
            if kwargs != frozenset(labels):
                findings.append(Finding(
                    rule="contract-metric-labels",
                    file=rel,
                    line=line,
                    message=(
                        f"{var}.labels({', '.join(sorted(kwargs))}) does "
                        f"not match {name!r}'s declared labels "
                        f"({', '.join(labels)}) — this raises at runtime"
                    ),
                ))
        return findings


class SpanNameRule(Rule):
    name = "contract-span-name"

    def __init__(self, registry=None):
        self._registry = registry

    @property
    def registry(self):
        if self._registry is None:
            self._registry = _span_registry()
        return self._registry

    def check_file(self, ctx):
        findings: list = []
        if ctx.rel.endswith("obs/spans.py"):
            return findings  # the registry + collector itself
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node.func)
            leaf = callee.split(".")[-1]
            if leaf not in ("span", "span_at"):
                continue
            name = first_str_arg(node)
            if name is None:
                continue  # dynamic span names are out of scope
            if name not in self.registry:
                findings.append(Finding(
                    rule=self.name,
                    file=ctx.rel,
                    line=node.lineno,
                    message=(
                        f"span name {name!r} is not in "
                        "obs.spans.KNOWN_SPAN_NAMES — register it so "
                        "dashboards and waterfall tests see it"
                    ),
                ))
        return findings


class DeadSpanRule(Rule):
    """Project rule: flag KNOWN_SPAN_NAMES entries no scanned file
    emits through a literal ``span()``/``span_at()`` call. Findings
    anchor at the registry declaration (that is the line to fix —
    delete the entry or re-emit the span). A scan that never saw the
    declaration site stays silent: a partial scan has not seen the
    emission universe, so it cannot honestly call a name dead."""

    name = "contract-span-dead"

    def __init__(self, registry=None):
        self._registry = registry
        self.reset()

    def reset(self) -> None:
        #: literal span names seen emitted anywhere in the scan
        self.emitted: set = set()
        #: (file, line) of the KNOWN_SPAN_NAMES assignment, if scanned
        self.registry_site: tuple | None = None

    @property
    def registry(self):
        if self._registry is None:
            self._registry = _span_registry()
        return self._registry

    def collect(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and \
                            tgt.id == "KNOWN_SPAN_NAMES":
                        self.registry_site = (ctx.rel, node.lineno)
        if ctx.rel.endswith("obs/spans.py"):
            return  # the collector's own internals are not emissions
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node.func).split(".")[-1] not in (
                "span", "span_at",
            ):
                continue
            name = first_str_arg(node)
            if name is not None:
                self.emitted.add(name)

    def finalize(self, project):
        if self.registry_site is None:
            return []
        rel, line = self.registry_site
        return [
            Finding(
                rule=self.name,
                file=rel,
                line=line,
                message=(
                    f"span name {name!r} is registered in "
                    "KNOWN_SPAN_NAMES but no span()/span_at() call "
                    "emits it — drop the entry or restore the emission"
                ),
            )
            for name in sorted(set(self.registry) - self.emitted)
        ]
