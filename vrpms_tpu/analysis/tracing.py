"""JAX tracing-hygiene checkers.

The tier cache and bit-identical-replay guarantees (PRs 4-7) hold only
if traced code stays pure and shape-stable: a `float()` on a traced
value concretizes (TracerError at best, silent recompile pinning at
worst), Python `random` inside a trace freezes one sample into the
compiled program, a `jax.jit` constructed per call throws away the
compile cache the tiers exist to protect. These rules flag the hazard
patterns statically.

Traced-context discovery (per file, intentionally local — the kernels
keep their helpers in-module):

  * functions decorated `@jax.jit` / `@jit` / `@partial(jax.jit, ...)`;
  * functions passed BY NAME to jit/vmap/pmap/grad/checkpoint or as
    `lax.scan` / `while_loop` / `fori_loop` / `cond` / `switch` / `map`
    bodies (their lambdas too);
  * transitively, same-module functions CALLED from a traced body, and
    functions defined inside one.

Rules:

  * ``trace-host-coercion`` — `.item()`, `np.asarray(...)` /
    `np.array(...)`, and `float()/int()/bool()` applied directly to a
    parameter of a traced function (shape reads like `x.shape[0]` and
    `len(x)` are trace-time constants and stay legal);
  * ``trace-python-random`` — `random.*` / `np.random.*` calls inside a
    traced body (host RNG freezes into the trace; use `jax.random`);
  * ``trace-traced-branch`` — `if`/`while` on a parameter of a
    definitely-traced control-flow body (scan/while/fori/cond callees:
    every parameter is a tracer, so the branch concretizes);
  * ``trace-jit-in-loop`` — `jax.jit(...)` constructed inside a
    `for`/`while` body (a fresh jit per iteration compiles every time)
    unless the enclosing function is `lru_cache`d;
  * ``trace-unhashable-static`` — calling an in-module jitted function
    with a list/dict/set/lambda literal in a declared static position
    (unhashable or fresh-per-call statics miss the compile cache on
    every call).
"""

from __future__ import annotations

import ast

from vrpms_tpu.analysis.base import Finding, Rule, call_name

_JIT_NAMES = {"jax.jit", "jit", "jax.pmap", "pmap"}
_WRAPPER_ARG0 = {
    "jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.lax.map", "lax.map",
}
#: callee -> indices of function-valued args whose params are tracers
_BODY_ARGS = {
    "lax.scan": (0,), "jax.lax.scan": (0,),
    "lax.while_loop": (0, 1), "jax.lax.while_loop": (0, 1),
    "lax.fori_loop": (2,), "jax.lax.fori_loop": (2,),
    "lax.cond": (1, 2), "jax.lax.cond": (1, 2),
    "lax.switch": (1,), "jax.lax.switch": (1,),
    "lax.map": (0,), "jax.lax.map": (0,),
}
_NP_MODULES = {"np", "numpy", "onp"}
_CACHE_DECORATORS = {
    "lru_cache", "functools.lru_cache", "cache", "functools.cache",
}


def _decorator_names(fn) -> list:
    names = []
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            names.append(call_name(dec.func))
            # @partial(jax.jit, ...) -> also record the wrapped callee
            if call_name(dec.func).split(".")[-1] == "partial" and dec.args:
                names.append(call_name(dec.args[0]))
        else:
            names.append(call_name(dec))
    return names


class _Module:
    """Per-file function table + traced-set computation."""

    def __init__(self, tree: ast.Module):
        #: every (Async)FunctionDef/Lambda node -> enclosing function
        self.parent: dict = {}
        #: name -> [function nodes] (module + nested + methods, by name)
        self.by_name: dict = {}
        self.functions: list = []
        self._index(tree, None)
        self.traced: set = set()       # function nodes considered traced
        self.body_traced: set = set()  # subset: control-flow bodies
        self._discover()

    def _index(self, node, enclosing) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                self.parent[child] = enclosing
                self.functions.append(child)
                if not isinstance(child, ast.Lambda):
                    self.by_name.setdefault(child.name, []).append(child)
                self._index(child, child)
            else:
                self._index(child, enclosing)

    def _mark(self, fn, body: bool = False) -> None:
        if fn in self.traced:
            if body:
                self.body_traced.add(fn)
            return
        self.traced.add(fn)
        if body:
            self.body_traced.add(fn)
        # everything defined inside a traced function is traced too
        for other, parent in self.parent.items():
            if parent is fn:
                self._mark(other)
        # and every same-module function it calls by name
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node.func)
                for callee in self.by_name.get(name, ()):
                    self._mark(callee)

    def _mark_arg(self, arg, body: bool = False) -> None:
        if isinstance(arg, ast.Lambda):
            self._mark(arg, body)
        elif isinstance(arg, (ast.Name, ast.Attribute)):
            name = call_name(arg)
            for fn in self.by_name.get(name.split(".")[-1], ()):
                self._mark(fn, body)

    def _discover(self) -> None:
        for fn in list(self.functions):
            if isinstance(fn, ast.Lambda):
                continue
            decs = _decorator_names(fn)
            if any(d in _JIT_NAMES for d in decs):
                self._mark(fn)

    def discover_calls(self, tree) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node.func)
            if callee in _WRAPPER_ARG0 and node.args:
                self._mark_arg(node.args[0])
            indices = _BODY_ARGS.get(callee)
            if indices:
                for i in indices:
                    if i < len(node.args):
                        self._mark_arg(node.args[i], body=True)

    def enclosing_traced(self, fn) -> bool:
        return fn in self.traced


def _param_names(fn) -> set:
    args = fn.args
    names = [a.arg for a in args.args + args.posonlyargs + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def _mentions_any(node, names: set) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node)
    )


class TraceHygieneRule(Rule):
    name = "trace-hygiene"  # umbrella; concrete findings carry sub-rules
    finding_names = (
        "trace-host-coercion", "trace-python-random",
        "trace-traced-branch", "trace-jit-in-loop",
        "trace-unhashable-static",
    )

    def check_file(self, ctx):
        findings: list = []
        mod = _Module(ctx.tree)
        mod.discover_calls(ctx.tree)
        for fn in mod.functions:
            if fn in mod.traced:
                findings.extend(self._check_traced(ctx, mod, fn))
        findings.extend(self._check_jit_construction(ctx, mod))
        findings.extend(self._check_static_args(ctx, mod))
        return findings

    def _find(self, ctx, rule, node, message) -> Finding:
        return Finding(
            rule=rule, file=ctx.rel, line=node.lineno, message=message
        )

    def _check_traced(self, ctx, mod, fn):
        findings = []
        params = _param_names(fn)
        # nodes of fn's own body, excluding nested function bodies
        # (those are traced functions in their own right when reachable)
        own_nodes: list = []

        def gather(node) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                own_nodes.append(child)
                gather(child)

        gather(fn)
        for node in own_nodes:
            if isinstance(node, ast.Call):
                callee = call_name(node.func)
                # .item() on anything inside a trace
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    findings.append(self._find(
                        ctx, "trace-host-coercion", node,
                        ".item() inside a traced function forces a host "
                        "sync / concretization",
                    ))
                # np.asarray / np.array on traced data
                elif callee.split(".")[0] in _NP_MODULES and \
                        callee.split(".")[-1] in ("asarray", "array"):
                    findings.append(self._find(
                        ctx, "trace-host-coercion", node,
                        f"{callee}() inside a traced function pulls the "
                        "value to host (use jnp)",
                    ))
                elif callee.split(".")[0] == "random" or \
                        callee.startswith("np.random.") or \
                        callee.startswith("numpy.random."):
                    findings.append(self._find(
                        ctx, "trace-python-random", node,
                        f"host RNG {callee}() inside a traced function "
                        "freezes one sample into the compiled program "
                        "(use jax.random)",
                    ))
                elif callee in ("float", "int", "bool") and \
                        len(node.args) == 1 and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in params:
                    findings.append(self._find(
                        ctx, "trace-host-coercion", node,
                        f"{callee}() applied directly to traced parameter "
                        f"{node.args[0].id!r} concretizes it",
                    ))
        if fn in mod.body_traced and params:
            for node in own_nodes:
                if isinstance(node, (ast.If, ast.While)) and \
                        _mentions_any(node.test, params):
                    findings.append(self._find(
                        ctx, "trace-traced-branch", node,
                        "Python branch on a traced control-flow-body "
                        "parameter (use lax.cond/select)",
                    ))
        return findings

    def _check_jit_construction(self, ctx, mod):
        """jax.jit(...) built inside a for/while loop body."""
        findings = []

        def cached(fn) -> bool:
            return not isinstance(fn, ast.Lambda) and any(
                d in _CACHE_DECORATORS for d in _decorator_names(fn)
            )

        def walk(node, in_loop: bool, fn) -> None:
            for child in ast.iter_child_nodes(node):
                child_fn = fn
                child_loop = in_loop
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    child_fn = child
                    child_loop = False
                elif isinstance(child, (ast.For, ast.While)):
                    child_loop = True
                elif isinstance(child, ast.Call) and in_loop:
                    if call_name(child.func) in _JIT_NAMES and \
                            not (fn is not None and cached(fn)):
                        findings.append(self._find(
                            ctx, "trace-jit-in-loop", child,
                            "jax.jit constructed inside a loop compiles "
                            "fresh every iteration (hoist it or lru_cache "
                            "the factory)",
                        ))
                walk(child, child_loop, child_fn)

        walk(ctx.tree, False, None)
        return findings

    def _check_static_args(self, ctx, mod):
        """g = jax.jit(f, static_argnums=(k,)); g(..., [unhashable] @ k)."""
        findings = []
        static_of: dict = {}  # jitted-name -> set of static positions
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if call_name(call.func) not in _JIT_NAMES:
                continue
            positions: set = set()
            for kw in call.keywords:
                if kw.arg == "static_argnums" and \
                        isinstance(kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, int):
                            positions.add(el.value)
                elif kw.arg == "static_argnums" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, int):
                    positions.add(kw.value.value)
            if not positions:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    static_of[tgt.id] = positions
        if not static_of:
            return findings
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Name):
                continue
            positions = static_of.get(node.func.id)
            if not positions:
                continue
            for i, arg in enumerate(node.args):
                if i in positions and isinstance(
                    arg, (ast.List, ast.Dict, ast.Set, ast.Lambda)
                ):
                    findings.append(self._find(
                        ctx, "trace-unhashable-static", arg,
                        f"unhashable/fresh literal passed in static "
                        f"position {i} of jitted {node.func.id!r} — every "
                        "call misses the compile cache",
                    ))
        return findings
