"""CLI: ``python -m vrpms_tpu.analysis [paths...]``.

Exits 0 when the tree is clean, 1 on any unsuppressed finding (or a
file that fails to parse) — the tier-1 CI gate contract. ``--json``
emits the structured findings for tooling; ``--list-rules`` documents
the rule catalogue.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from vrpms_tpu import analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m vrpms_tpu.analysis",
        description="vrpms-lint: project-native static analysis",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to scan (default: the production tree)",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root for relative paths + README lookup "
        "(default: the checkout containing vrpms_tpu)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON records",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in analysis.default_rules():
            doc = (sys.modules[type(rule).__module__].__doc__ or "")
            first = doc.strip().splitlines()[0] if doc.strip() else ""
            # list the CONCRETE finding ids — the names findings carry
            # and a `# vrpms-lint: disable=<id>` must use
            for name in rule.finding_names or (rule.name,):
                print(f"{name:26s} {first}")
        return 0

    report = analysis.run(
        paths=args.paths or None,
        root=args.root,
    )
    if args.as_json:
        print(json.dumps(
            {
                "findings": [
                    dataclasses.asdict(f) for f in report.findings
                ],
                "suppressed": [
                    dataclasses.asdict(f) for f in report.suppressed
                ],
                "parseErrors": [
                    {"file": p, "error": e} for p, e in report.parse_errors
                ],
            },
            indent=2,
        ))
    else:
        print(report.render())
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
