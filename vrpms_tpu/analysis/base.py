"""vrpms-lint core: file model, rule protocol, suppressions, runner.

The analyzer is one AST + tokenize pass per file (a `FileContext`),
with checkers as pluggable rules. Two rule shapes:

  * **file rules** — `check_file(ctx) -> list[Finding]`, purely local;
  * **project rules** — `collect(ctx)` per file, then
    `finalize(project) -> list[Finding]` once every file has been seen
    (cross-file contracts: metric registered exactly once, span names
    in the registry, every registered config var documented).

Findings are structured `{rule, file, line, message}` records. Inline
suppressions:

    some_code()  # vrpms-lint: disable=rule-name (why this is OK)

apply to their own line or, as a standalone comment, to the next
code line. A reason in parentheses is REQUIRED — a bare disable is
itself reported (rule `suppression-no-reason`), so every exception in
the tree documents why it exists. The runner counts suppressions and
reports them next to the findings; tests/test_analysis.py pins the
repo-wide count so new suppressions are a reviewed, deliberate act.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

#: directories never scanned (caches, VCS internals)
SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

_SUPPRESS_RE = re.compile(
    r"#\s*vrpms-lint:\s*disable=([a-z0-9_,-]+)\s*(?:\(([^)]*)\))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured analyzer finding."""

    rule: str
    file: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    rule: str
    line: int
    reason: str


class FileContext:
    """One parsed file: source, AST, per-line comments, suppressions."""

    def __init__(self, path: Path, root: Path, reference_only: bool = False):
        self.path = path
        #: reference-only files (tests/benchmarks) contribute symbol
        #: references to project rules but are not themselves checked
        self.reference_only = reference_only
        self.rel = str(path.relative_to(root)) if root in path.parents or \
            path == root else str(path)
        self.root = root
        self.source = path.read_text(encoding="utf-8")
        self.tree = ast.parse(self.source, filename=str(path))
        self.lines = self.source.splitlines()
        #: {line -> comment text} (one comment token per line max)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                io.StringIO(self.source).readline
            ):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # pragma: no cover - parse caught it
            pass
        #: {line -> [Suppression]}: a suppression governs its own line;
        #: a comment-only line also governs the next code line
        self.suppressions: dict[int, list[Suppression]] = {}
        self.bad_suppressions: list[Finding] = []
        for line_no, comment in self.comments.items():
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad_suppressions.append(Finding(
                    rule="suppression-no-reason",
                    file=self.rel,
                    line=line_no,
                    message=(
                        "vrpms-lint suppression without a (reason); every "
                        "disable must say why"
                    ),
                ))
                continue
            targets = [line_no]
            stripped = self.lines[line_no - 1].strip()
            if stripped.startswith("#"):
                # a standalone suppression comment governs the NEXT code
                # line — skipping blank and further comment lines, so a
                # wrapped reason or spacing can't silently void it
                for nxt in range(line_no + 1, len(self.lines) + 1):
                    text = self.lines[nxt - 1].strip()
                    if text and not text.startswith("#"):
                        targets.append(nxt)
                        break
            for rule in rules:
                for target in targets:
                    self.suppressions.setdefault(target, []).append(
                        Suppression(rule=rule, line=line_no, reason=reason)
                    )

    def comment_on(self, line: int) -> str:
        return self.comments.get(line, "")

    def suppressed(self, rule: str, line: int) -> bool:
        return any(
            s.rule in (rule, "all")
            for s in self.suppressions.get(line, ())
        )


class Rule:
    """Base rule: subclass and implement check_file and/or
    collect+finalize. `name` is the id used in findings and
    suppressions; rules that emit several finding kinds list every
    concrete id in `finding_names` (what --list-rules shows and what a
    suppression must name)."""

    name = "rule"
    #: every finding id this rule can emit (suppressions name these)
    finding_names: tuple = ()
    #: glob-ish path prefixes this rule applies to; empty = everywhere
    scopes: tuple = ()

    def reset(self) -> None:
        """Drop per-run collect() state. Called by run_rules before the
        first file, so a rule instance can be reused across runs."""

    def applies(self, ctx: FileContext) -> bool:
        if not self.scopes:
            return True
        return any(
            ctx.rel == s or ctx.rel.startswith(s.rstrip("/") + "/")
            for s in self.scopes
        )

    def check_file(self, ctx: FileContext):
        return []

    def collect(self, ctx: FileContext) -> None:
        return None

    def finalize(self, project: "Project"):
        return []


class Project:
    """What project rules see at finalize time."""

    def __init__(self, root: Path, contexts: list[FileContext]):
        self.root = root
        self.contexts = contexts


@dataclasses.dataclass
class Report:
    """One analyzer run: kept findings, suppressed findings, errors."""

    findings: list
    suppressed: list  # (Finding, Suppression reason line)
    parse_errors: list

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.parse_errors) else 0

    def render(self) -> str:
        out = []
        for f in self.findings:
            out.append(f.render())
        for path, err in self.parse_errors:
            out.append(f"{path}:0: [parse-error] {err}")
        out.append(
            f"vrpms-lint: {len(self.findings)} finding(s), "
            f"{len(self.suppressed)} suppressed"
        )
        return "\n".join(out)


def iter_python_files(paths: list[Path]):
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if not any(part in SKIP_DIRS for part in sub.parts):
                    yield sub


def run_rules(rules: list, paths: list[Path], root: Path,
              reference_paths: list[Path] = ()) -> Report:
    """Parse every file once, run every rule, partition findings by the
    suppression table. Suppressions apply to file-rule AND project-rule
    findings (matched by file+line). `reference_paths` are parsed and
    fed to project rules that opt in (``collects_references = True``)
    but produce no findings of their own."""
    contexts: list[FileContext] = []
    parse_errors: list = []
    raw: list = []
    for rule in rules:
        rule.reset()
    for path in iter_python_files(paths):
        try:
            ctx = FileContext(path, root)
        except SyntaxError as e:
            parse_errors.append((str(path), f"SyntaxError: {e.msg}"))
            continue
        contexts.append(ctx)
        raw.extend(ctx.bad_suppressions)
        for rule in rules:
            if not rule.applies(ctx):
                continue
            raw.extend(rule.check_file(ctx))
            rule.collect(ctx)
    for path in iter_python_files(list(reference_paths)):
        try:
            ctx = FileContext(path, root, reference_only=True)
        except SyntaxError as e:
            parse_errors.append((str(path), f"SyntaxError: {e.msg}"))
            continue
        for rule in rules:
            if getattr(rule, "collects_references", False):
                rule.collect(ctx)
    project = Project(root, contexts)
    for rule in rules:
        raw.extend(rule.finalize(project))
    by_rel = {ctx.rel: ctx for ctx in contexts}
    findings: list = []
    suppressed: list = []
    for f in sorted(raw, key=lambda f: (f.file, f.line, f.rule)):
        ctx = by_rel.get(f.file)
        if ctx is not None and ctx.suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            findings.append(f)
    return Report(
        findings=findings, suppressed=suppressed, parse_errors=parse_errors
    )


# -- shared AST helpers ------------------------------------------------------

def call_name(node: ast.AST) -> str:
    """Dotted name of a call target: `a.b.c(...)` -> "a.b.c"; "" when
    the callee is not a plain name/attribute chain."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def first_str_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return call.args[0].value
    return None
