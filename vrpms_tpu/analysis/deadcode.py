"""Dead-code checkers: unused imports, unreferenced private symbols.

The drift class ADVICE rounds keep finding by hand (the sa_delta_td
unused-import round): imports that outlive a refactor and private
module-level helpers nothing calls anymore.

  * ``dead-import`` — a name imported but never referenced in its
    module. ``__init__.py`` files are exempt (imports ARE their export
    surface), as are ``__future__`` imports, underscore-renamed
    imports (``import x as _x`` — an explicit "for side effects"
    idiom), names in ``__all__``, and import lines carrying a ``noqa``
    comment (the conventional deliberate-re-export marker — a consumer
    may reach the name as an attribute from another module, which a
    per-module pass cannot see).
  * ``dead-private-symbol`` — a module-level ``_name`` function /
    class / constant referenced nowhere in the ENTIRE scanned project
    (including as an attribute, so ``mod._helper`` from a test keeps it
    alive when tests are in scope). Project rule: collected per file,
    decided once every file — including tests, which the CLI scans for
    exactly this reason — has been seen.
"""

from __future__ import annotations

import ast

from vrpms_tpu.analysis.base import Finding, Rule

_EXEMPT_MODULES = {"__future__"}


def _module_all(tree: ast.Module) -> set:
    names: set = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    names.update(
                        el.value for el in node.value.elts
                        if isinstance(el, ast.Constant)
                    )
    return names


def _used_names(tree: ast.Module) -> set:
    """Every identifier referenced anywhere (names, attributes, and
    bare strings — a name quoted in __all__ or a dispatch table counts
    as a use)."""
    used: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            used.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value.isidentifier():
                used.add(node.value)
    return used


class DeadImportRule(Rule):
    name = "dead-import"

    def check_file(self, ctx):
        if ctx.rel.endswith("__init__.py"):
            return []
        imported: list = []  # (bound name, line, shown as)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    shown = alias.name + (
                        f" as {alias.asname}" if alias.asname else ""
                    )
                    imported.append((bound, node.lineno, shown))
            elif isinstance(node, ast.ImportFrom):
                if node.module in _EXEMPT_MODULES:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    shown = alias.name + (
                        f" as {alias.asname}" if alias.asname else ""
                    )
                    imported.append((bound, node.lineno, shown))
        if not imported:
            return []
        used = _used_names(ctx.tree)
        exported = _module_all(ctx.tree)
        findings: list = []
        seen_lines: set = set()
        for bound, line, shown in imported:
            if bound.startswith("_"):
                continue  # explicit side-effect / re-export idiom
            if "noqa" in ctx.comment_on(line):
                continue  # marked deliberate (re-export surface)
            # a used import's own binding line also counts one Name use
            # (the alias node isn't a Name) — so plain membership works
            if bound in used or bound in exported:
                continue
            key = (line, bound)
            if key in seen_lines:
                continue
            seen_lines.add(key)
            findings.append(Finding(
                rule=self.name,
                file=ctx.rel,
                line=line,
                message=f"import {shown!r} is never used in this module",
            ))
        return findings


class DeadPrivateSymbolRule(Rule):
    name = "dead-private-symbol"
    collects_references = True

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        #: symbol -> (file, line)
        self.defined: dict = {}
        #: every identifier referenced anywhere in the project,
        #: excluding each symbol's own definition line
        self.used: dict = {}

    @staticmethod
    def _definitions(tree: ast.Module):
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                yield node.name, node
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        yield tgt.id, node
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                yield node.target.id, node

    def collect(self, ctx):
        own_defs: dict = {}
        if not ctx.reference_only:
            for name, node in self._definitions(ctx.tree):
                if not name.startswith("_") or name.startswith("__"):
                    continue
                own_defs[name] = node
                self.defined[(ctx.rel, name)] = node.lineno
        for node in ast.walk(ctx.tree):
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value.isidentifier():
                name = node.value
            if name is None:
                continue
            defn = own_defs.get(name)
            if defn is not None and self._is_definition_ref(node, defn):
                continue
            self.used[name] = self.used.get(name, 0) + 1

    @staticmethod
    def _is_definition_ref(node, defn) -> bool:
        """The definition's own binding occurrence (def/class name isn't
        an ast.Name; assignment targets are — skip Store-context names
        on the definition node's line)."""
        return (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, (ast.Store, ast.Del))
            and node.lineno == defn.lineno
        )

    def finalize(self, project):
        findings: list = []
        for (rel, name), line in sorted(self.defined.items()):
            if self.used.get(name, 0) == 0:
                findings.append(Finding(
                    rule=self.name,
                    file=rel,
                    line=line,
                    message=(
                        f"private module-level symbol {name!r} is "
                        "referenced nowhere in the scanned tree"
                    ),
                ))
        return findings
