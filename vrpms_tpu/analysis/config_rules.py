"""Config-discipline checkers: env reads, registry coverage, doc sync.

Every knob goes through :mod:`vrpms_tpu.config` — the typed registry is
the single parse-and-default point and the README table's source of
truth. Three rules keep that closed:

  * ``config-env-read`` — any direct environment READ
    (``os.environ.get`` / ``os.getenv`` / ``os.environ[...]`` as a
    value) outside ``vrpms_tpu/config.py``. Writes
    (``os.environ[k] = v``, setdefault, membership tests) stay legal —
    the CLI and tests stage env state; it's the scattered
    parse-and-default reads that drift.
  * ``config-unknown-var`` — a ``VRPMS_*`` string literal that is not a
    registered variable name (typo'd knobs read as "unset" forever and
    are unfindable at runtime).
  * ``config-doc-sync`` — every registered variable appears in
    README.md (project rule; anchored to the registry entry).
"""

from __future__ import annotations

import ast
import re

from vrpms_tpu.analysis.base import Finding, Rule, call_name

_VRPMS_LITERAL = re.compile(r"^VRPMS_[A-Z0-9_]+$")


def _registry_names() -> frozenset:
    from vrpms_tpu import config

    return frozenset(config.REGISTRY)


class EnvReadRule(Rule):
    name = "config-env-read"

    def check_file(self, ctx):
        if ctx.rel.endswith("vrpms_tpu/config.py") or \
                ctx.rel == "vrpms_tpu/config.py":
            return []
        findings: list = []
        for node in ast.walk(ctx.tree):
            line = None
            what = None
            if isinstance(node, ast.Call):
                callee = call_name(node.func)
                if callee in ("os.environ.get", "environ.get", "os.getenv",
                              "getenv"):
                    line, what = node.lineno, callee
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    call_name(node.value) in ("os.environ", "environ"):
                line, what = node.lineno, "os.environ[...]"
            if line is not None:
                findings.append(Finding(
                    rule=self.name,
                    file=ctx.rel,
                    line=line,
                    message=(
                        f"direct env read {what} — go through "
                        "vrpms_tpu.config (get/raw/enabled) so the knob "
                        "is registered, typed, and documented"
                    ),
                ))
        return findings


class UnknownVarRule(Rule):
    name = "config-unknown-var"

    def __init__(self, registry=None):
        self._registry = registry

    @property
    def registry(self):
        if self._registry is None:
            self._registry = _registry_names()
        return self._registry

    def check_file(self, ctx):
        findings: list = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _VRPMS_LITERAL.match(node.value) and \
                    node.value not in self.registry:
                findings.append(Finding(
                    rule=self.name,
                    file=ctx.rel,
                    line=node.lineno,
                    message=(
                        f"{node.value!r} is not in the "
                        "vrpms_tpu.config registry — typo, or a new knob "
                        "that needs registering (and documenting)"
                    ),
                ))
        return findings


class DocSyncRule(Rule):
    """Every registered var documented in README.md (project rule)."""

    name = "config-doc-sync"

    def __init__(self, readme_name: str = "README.md"):
        self.readme_name = readme_name

    def finalize(self, project):
        config_ctx = None
        for ctx in project.contexts:
            if ctx.rel.replace("\\", "/").endswith("vrpms_tpu/config.py"):
                config_ctx = ctx
                break
        if config_ctx is None:
            return []  # registry not in scope for this run
        readme = project.root / self.readme_name
        try:
            text = readme.read_text(encoding="utf-8")
        except OSError:
            return [Finding(
                rule=self.name,
                file=config_ctx.rel,
                line=1,
                message=f"{self.readme_name} not found next to the "
                "registry — the config table has nowhere to live",
            )]
        findings: list = []
        for node in ast.walk(config_ctx.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _VRPMS_LITERAL.match(node.value) and \
                    node.value not in text:
                findings.append(Finding(
                    rule=self.name,
                    file=config_ctx.rel,
                    line=node.lineno,
                    message=(
                        f"registered variable {node.value!r} is not "
                        f"documented in {self.readme_name}"
                    ),
                ))
        return findings
