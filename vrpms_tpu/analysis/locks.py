"""Lock-discipline checker: `# guarded-by: <lock>` annotations.

The concurrency conventions of the scheduler/obs/store layers are
invisible to the runtime — a read of `self._items` outside
`with self._lock` works fine until the one interleaving where it
doesn't. This rule makes the convention machine-checked:

  * annotate the attribute's initialisation line (in ``__init__`` or at
    module scope) with ``# guarded-by: _lock`` — the named lock is
    ``self._lock`` for instance attributes, a module global for
    module-level state;
  * every later read/write of that attribute inside a method/function
    must happen lexically inside ``with self._lock:`` (or ``with
    _lock:`` for globals), else it is a finding.

What counts as holding the lock:

  * a ``with`` statement on the guarding lock (any position in a
    multi-item ``with``);
  * a ``with`` on a ``threading.Condition`` constructed FROM the
    guarding lock (``self._new = threading.Condition(self._lock)`` —
    entering the condition acquires the lock);
  * the body of ``__init__``/``__new__`` (construction happens before
    the object is shared) and module top-level code (import is
    effectively single-threaded);
  * methods whose name ends in ``_locked`` or whose ``def`` line
    carries ``# holds-lock: <lock>`` — the documented "caller holds
    the lock" convention (the checker trusts the suffix; the call
    sites of such helpers are themselves checked).

A function DEFINED inside a locked region does not inherit the lock —
closures outlive the ``with`` block that created them.
"""

from __future__ import annotations

import ast
import re

from vrpms_tpu.analysis.base import Finding, Rule, call_name

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _guard_annotation(ctx, line: int) -> str | None:
    m = _GUARD_RE.search(ctx.comment_on(line))
    return m.group(1) if m else None


class _Scope:
    """One class (or the module itself): guarded names + lock aliases."""

    def __init__(self):
        self.guards: dict[str, tuple[str, int]] = {}  # attr -> (lock, line)
        self.aliases: dict[str, str] = {}  # condition name -> lock name


def _lock_exprs_held(items, is_self: bool, scope: _Scope) -> set:
    """Lock names a `with` statement's items acquire for this scope."""
    held = set()
    for item in items:
        expr = item.context_expr
        name = None
        if is_self:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        if name is not None:
            held.add(name)
            alias = scope.aliases.get(name)
            if alias is not None:
                held.add(alias)
    return held


class _BodyChecker(ast.NodeVisitor):
    """Walk one function body tracking which locks are lexically held."""

    def __init__(self, rule, ctx, scope: _Scope, is_self: bool,
                 held: set, findings: list):
        self.rule = rule
        self.ctx = ctx
        self.scope = scope
        self.is_self = is_self
        self.held = set(held)
        self.findings = findings

    def visit_With(self, node: ast.With) -> None:
        acquired = _lock_exprs_held(node.items, self.is_self, self.scope)
        for item in node.items:
            self.visit(item.context_expr)
        before = set(self.held)
        self.held |= acquired
        for stmt in node.body:
            self.visit(stmt)
        self.held = before

    # a nested def/lambda runs later: it does NOT inherit held locks,
    # and its body is checked in its own pass by the rule driver
    def visit_FunctionDef(self, node) -> None:
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        return

    def _check(self, attr: str, line: int) -> None:
        guard = self.scope.guards.get(attr)
        if guard is None:
            return
        lock, _decl_line = guard
        if lock in self.held:
            return
        owner = "self." if self.is_self else ""
        self.findings.append(Finding(
            rule=self.rule.name,
            file=self.ctx.rel,
            line=line,
            message=(
                f"access to {owner}{attr} (guarded-by {owner}{lock}) "
                f"outside `with {owner}{lock}`"
            ),
        ))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            self.is_self
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            self._check(node.attr, node.lineno)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if not self.is_self:
            self._check(node.id, node.lineno)
        self.generic_visit(node)


def _class_own_nodes(cls: ast.ClassDef) -> list:
    """Every node of `cls` excluding nested class subtrees (a nested
    class's annotations belong to ITS scope, checked in its own pass)."""
    nodes: list = []

    def gather(node) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            nodes.append(child)
            gather(child)

    gather(cls)
    return nodes


def _collect_class_scope(ctx, cls: ast.ClassDef) -> _Scope:
    scope = _Scope()
    for node in _class_own_nodes(cls):
        # self.<attr> = ...  # guarded-by: <lock>
        if isinstance(node, ast.Assign):
            guard = _guard_annotation(ctx, node.lineno)
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    if guard:
                        scope.guards[tgt.attr] = (guard, node.lineno)
                    _note_condition_alias(scope, tgt.attr, node.value)
        elif isinstance(node, ast.AnnAssign):
            guard = _guard_annotation(ctx, node.lineno)
            tgt = node.target
            if (
                guard
                and isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                scope.guards[tgt.attr] = (guard, node.lineno)
    return scope


def _note_condition_alias(scope: _Scope, attr: str, value) -> None:
    """`self._new = threading.Condition(self._lock)` -> _new aliases
    _lock (same for module-level conditions over module locks)."""
    if not isinstance(value, ast.Call):
        return
    callee = call_name(value.func)
    if callee.split(".")[-1] != "Condition" or not value.args:
        return
    arg = value.args[0]
    if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name) \
            and arg.value.id == "self":
        scope.aliases[attr] = arg.attr
    elif isinstance(arg, ast.Name):
        scope.aliases[attr] = arg.id


def _collect_module_scope(ctx, module: ast.Module) -> _Scope:
    scope = _Scope()
    for node in module.body:
        if isinstance(node, ast.Assign):
            guard = _guard_annotation(ctx, node.lineno)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    if guard:
                        scope.guards[tgt.id] = (guard, node.lineno)
                    _note_condition_alias(scope, tgt.id, node.value)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            guard = _guard_annotation(ctx, node.lineno)
            if guard:
                scope.guards[node.target.id] = (guard, node.lineno)
    return scope


def _held_at_entry(ctx, fn, scope: _Scope) -> set | None:
    """Locks a function may assume held, or None -> skip the body."""
    if fn.name in ("__init__", "__new__"):
        return None
    held = set()
    if fn.name.endswith("_locked"):
        held.update(lock for lock, _ in scope.guards.values())
        held.update(scope.aliases.values())
    for line in range(fn.lineno, fn.body[0].lineno):
        m = _HOLDS_RE.search(ctx.comment_on(line))
        if m:
            held.add(m.group(1))
    return held


class LockDisciplineRule(Rule):
    name = "lock-discipline"

    def check_file(self, ctx):
        findings: list = []
        module_scope = _collect_module_scope(ctx, ctx.tree)
        # module-level guarded globals: check every function in the file
        if module_scope.guards:
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    held = _held_at_entry(ctx, node, module_scope)
                    if held is None:
                        continue
                    checker = _BodyChecker(
                        self, ctx, module_scope, is_self=False,
                        held=held, findings=findings,
                    )
                    for stmt in node.body:
                        checker.visit(stmt)
        # class-level guarded attributes
        for cls in [
            n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)
        ]:
            scope = _collect_class_scope(ctx, cls)
            if not scope.guards:
                continue
            for node in cls.body:
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                held = _held_at_entry(ctx, node, scope)
                if held is None:
                    continue
                checker = _BodyChecker(
                    self, ctx, scope, is_self=True,
                    held=held, findings=findings,
                )
                for stmt in node.body:
                    checker.visit(stmt)
        return findings
