"""Deadline-aware QoS: priority classes, EDF ordering, selective shed.

The scheduling policy layer ISSUE 12 adds on top of the flat FIFO
queues. Three request classes — ``interactive`` > ``standard`` >
``batch`` — plus the deadline budget every request already carries
(PR 2's ``timeLimit``) turn into ONE ordering rule used everywhere a
job is picked:

    (class rank, deadline, arrival)      — "EDF within class, higher
                                            class first across classes"

With every field at its default (class ``standard``, no deadline) the
rule degrades to pure FIFO, which is what keeps the ``VRPMS_QOS=off``
byte-identity guard cheap: the off switch simply builds no policy at
all and nothing here runs.

Pieces:

  * class parsing/ranking + the shared order keys (local ``Job``s and
    store queue entries use the same tuple, so the local pop, the
    store ``claim``/``claim_batch``, and tests all agree);
  * :class:`QosPolicy` — the object ``sched.queue.JobQueue`` consults
    when one is attached: priority pop order, class-fraction admission
    shed with per-class Retry-After from observed per-class drain, and
    the free-rider micro-batch fill rule (same-class mates first,
    lower classes ride along, a same-class member is never displaced);
  * tenant identity (auth-scoped, the PR-3 degraded-cache-key rule:
    the raw token never leaves the process) for per-tenant fairness
    quotas.

Stdlib-only besides :mod:`vrpms_tpu.config` (itself stdlib-only) — no
jax, no service imports — like the rest of the sched package.
"""

from __future__ import annotations

import hashlib
import math
import threading

from vrpms_tpu import config

#: priority classes, highest first; rank = index (lower = sooner)
CLASSES = ("interactive", "standard", "batch")
DEFAULT_CLASS = "standard"
RANK = {name: i for i, name in enumerate(CLASSES)}

#: the class that absorbs sheds first is the LAST one — shedding walks
#: the tuple back to front as depth crosses each class's fraction of
#: the admission bound (shed_fraction)
_INF = math.inf


def enabled() -> bool:
    """The one QoS switch (``VRPMS_QOS``): off builds no policy, adds
    no request fields, and restores plain-FIFO behavior everywhere."""
    return config.enabled("VRPMS_QOS")


def parse_class(value) -> str:
    """Normalize a request's ``qos`` value to a class name.

    None/absent means :data:`DEFAULT_CLASS`; anything else must be one
    of :data:`CLASSES` (case-insensitive) — junk raises ValueError so
    the request parser can reject it with a 400 envelope instead of
    silently scheduling it into the wrong class.
    """
    if value is None:
        return DEFAULT_CLASS
    if isinstance(value, str) and value.strip().lower() in RANK:
        return value.strip().lower()
    raise ValueError(
        f"'qos' must be one of {'|'.join(CLASSES)}, got {value!r}"
    )


def rank(qos_class) -> int:
    """Class rank (0 = highest priority); unknown/None ranks standard,
    so entries written by builds that predate a class still order
    sanely instead of raising mid-claim."""
    return RANK.get(qos_class, RANK[DEFAULT_CLASS])


def class_of_rank(r) -> str:
    try:
        return CLASSES[int(r)]
    except (TypeError, ValueError, IndexError):
        return DEFAULT_CLASS


def deadline_at(submitted_at, time_limit) -> float | None:
    """Absolute EDF deadline (epoch seconds): submit + budget. Only a
    POSITIVE budget makes a deadline — explicit 0 keeps its stop-ASAP
    meaning and None is unbounded (both sort after every real
    deadline, FIFO among themselves)."""
    try:
        if submitted_at is None or time_limit is None:
            return None
        tl = float(time_limit)
        if tl <= 0:
            return None
        return float(submitted_at) + tl
    except (TypeError, ValueError):
        return None


def order_key(qos_class, deadline) -> tuple:
    """The claim-ordering tuple: class first, then EDF (no deadline
    sorts last within its class). Callers tie-break by arrival order —
    every consumer picks the MIN over a FIFO-ordered sequence with a
    stable selection, so equal keys preserve FIFO."""
    return (rank(qos_class), _INF if deadline is None else float(deadline))


def job_order_key(job) -> tuple:
    """order_key over a sched.queue.Job (duck-typed: anything with
    .qos/.deadline_at works, so tests can use stubs)."""
    return order_key(
        getattr(job, "qos", None), getattr(job, "deadline_at", None)
    )


def entry_order_key(entry: dict) -> tuple:
    """order_key over a store queue entry dict (the claim-ordering
    columns: ``qos`` + ``deadline_at``; both absent = FIFO)."""
    return order_key(entry.get("qos"), entry.get("deadline_at"))


def select_mates(leader, candidates: list, max_n: int, key=None) -> list:
    """The free-rider micro-batch fill rule, shared by the local
    gather (JobQueue.take_matching) and the store's claim_batch: from
    same-bucket `candidates`, prefer mates of the LEADER's class (in
    their existing EDF/FIFO order), then fill remaining slots with
    other classes highest-first — lower classes ride a launch that was
    happening anyway, but when slots run out a same-class member is
    never displaced by a free rider. Stable: within each preference
    tier the input (FIFO) order is kept."""
    if max_n <= 0:
        return []
    key = key or job_order_key
    lead_rank = key(leader)[0]
    ordered = sorted(
        range(len(candidates)),
        key=lambda i: (
            0 if key(candidates[i])[0] == lead_rank else 1,
            key(candidates[i]),
            i,
        ),
    )
    return [candidates[i] for i in ordered[:max_n]]


def tenant_id(auth) -> str | None:
    """Auth-scoped tenant identity for fairness quotas: a stable hash
    of the token (the PR-3 rule — the raw credential is never used as
    a key), or None for anonymous requests. Quotas apply only to
    identified tenants: every anonymous caller would otherwise share
    ONE bucket and a single hot anonymous client could lock out all
    the others while looking like 'fairness'."""
    if not auth:
        return None
    return hashlib.sha256(repr(auth).encode()).hexdigest()[:12]


def shed_fraction(qos_class: str) -> float:
    """What fraction of the admission bound this class may fill before
    its submits shed. Interactive always gets the full bound; standard
    and batch shed earlier (VRPMS_QOS_SHED_STANDARD / _BATCH), which
    is exactly what makes overload selective: as depth grows, batch
    429s first, then standard, and interactive only at the hard
    bound."""
    r = rank(qos_class)
    if r <= RANK["interactive"]:
        return 1.0
    if r == RANK["standard"]:
        frac = config.get("VRPMS_QOS_SHED_STANDARD")
    else:
        frac = config.get("VRPMS_QOS_SHED_BATCH")
    return min(1.0, max(0.0, float(frac)))


def tenant_quota() -> int:
    """Max jobs one tenant may have active across the fleet (0 = no
    quota)."""
    return max(0, int(config.get("VRPMS_QOS_TENANT_QUOTA")))


class QosPolicy:
    """The pluggable policy a QoS-enabled JobQueue (and the service's
    admission paths) consult. Holds the per-class drain-rate EWMAs
    that price each class's Retry-After; everything else is stateless
    delegation to the module functions above so the ordering rule has
    exactly one definition."""

    #: EWMA weight for per-class service seconds (the JobQueue
    #: _job_seconds constant)
    ALPHA = 0.2

    def __init__(self):
        self._lock = threading.Lock()
        # per-class EWMA of observed per-job service seconds — the
        # denominator of each class's Retry-After estimate
        self._class_seconds: dict = {}  # guarded-by: _lock

    # -- ordering -----------------------------------------------------------
    def job_key(self, job) -> tuple:
        return job_order_key(job)

    def select_mates(self, leader, candidates: list, max_n: int) -> list:
        return select_mates(leader, candidates, max_n)

    # -- drain accounting ---------------------------------------------------
    def note_done(self, qos_class, seconds: float) -> None:
        cls = qos_class if qos_class in RANK else DEFAULT_CLASS
        with self._lock:
            prev = self._class_seconds.get(cls, 1.0)
            self._class_seconds[cls] = (
                (1 - self.ALPHA) * prev + self.ALPHA * max(seconds, 1e-3)
            )

    def class_seconds(self, qos_class) -> float:
        cls = qos_class if qos_class in RANK else DEFAULT_CLASS
        with self._lock:
            return self._class_seconds.get(cls, 1.0)

    def retry_after(self, qos_class, backlog: int, drains: int = 1) -> float:
        """When should a shed request of this class retry: the work
        ahead of it divided by this CLASS's observed drain rate (its
        EWMA per-job seconds), spread over `drains` parallel drains
        (fleet members). Bounded to [1, 60] like the queue's own
        estimate."""
        per_job = self.class_seconds(qos_class)
        return min(
            max(1.0, backlog * per_job / max(1, drains)), 60.0
        )

    # -- admission ----------------------------------------------------------
    def admit(self, job, items: list, limit: int) -> float | None:
        """Selective-shed check, called by JobQueue.push UNDER the
        queue lock (must not call back into the queue): None admits;
        a float sheds the job and is the 429's Retry-After. The
        effective bound for a class is its shed fraction of the hard
        limit — jobs of a class shed once TOTAL depth reaches it, so
        the headroom between a lower class's bound and the hard limit
        is reserved for the classes above it."""
        if getattr(job, "preadmitted", False):
            # already admitted elsewhere (a store-claimed entry):
            # shedding it here would bounce it between the shared
            # queue and this box forever — only the hard bound applies
            return None
        cls = getattr(job, "qos", None) or DEFAULT_CLASS
        effective = int(limit * shed_fraction(cls))
        depth = len(items)
        if depth < max(1, effective):
            return None
        # work that must drain before a retry of this class gets in:
        # everything at-or-above its priority, plus itself
        my_rank = rank(cls)
        ahead = sum(
            1 for j in items if job_order_key(j)[0] <= my_rank
        )
        return self.retry_after(cls, max(1, ahead))

    def depth_by_class(self, items: list) -> dict:
        """{class: count} over a job list (the readiness probe's
        per-class queue view; zero-filled so the map's shape is
        stable)."""
        out = {name: 0 for name in CLASSES}
        for j in items:
            out[class_of_rank(job_order_key(j)[0])] += 1
        return out
