"""Consistent-hash ring over tier keys: which replica owns which tiers.

The scale-out design routes jobs to replicas BY TIER (the padded-shape
key service.jobs coarsens requests to), because compile-cache locality
is the scarce resource: a replica that has compiled tier 16x4's
programs serves every 16x4 job at steady-state latency, while an
unrouted claim spreads every tier across every replica and each one
pays the whole ladder's cold compiles. Consistent hashing gives that
routing two properties FIFO sharding would not:

  * determinism without coordination — every replica derives the same
    owner for a tier key from nothing but the live membership list (the
    store's heartbeat registry), so there is no leader and no
    assignment table to keep consistent;
  * minimal movement — a replica joining or dying remaps only the arcs
    it gains or loses (~1/N of the ring), so a scale-out event does not
    cold-start every replica's compile cache from scratch.

Slots are sha256-derived (stable across processes and Python runs —
`hash()` is salted per process and would give every replica a different
ring). `vnodes` virtual nodes per member smooth the arc distribution.

Stdlib-only by design, like the rest of vrpms_tpu.sched.
"""

from __future__ import annotations

import bisect
import hashlib

#: ring positions (slot space). 2^16 keeps slots small ints that index
#: cleanly into SQL range predicates (store/schema.sql `slot integer`).
SLOTS = 1 << 16


def slot(token: str) -> int:
    """Stable ring position of a routing token (tier key, member#vnode)."""
    digest = hashlib.sha256(token.encode("utf-8")).digest()
    return int.from_bytes(digest[:4], "big") % SLOTS


class HashRing:
    """Immutable ring over a membership snapshot.

    Ownership rule: slot `s` belongs to the member whose vnode point is
    the clockwise successor of `s` (first point with position >= s,
    wrapping). `arcs(member)` returns the same ownership as half-open
    [lo, hi) slot ranges — the form both the in-memory claim filter and
    the SQL range predicates consume — so `owner(s) == m` iff `s` falls
    in one of `arcs(m)`.
    """

    def __init__(self, members: list[str], vnodes: int = 64):
        self.members = sorted(set(members))
        self.vnodes = max(1, int(vnodes))
        points: list[tuple[int, str]] = []
        for m in self.members:
            for i in range(self.vnodes):
                points.append((slot(f"{m}#{i}"), m))
        # sort by (slot, member): equal-slot collisions resolve to the
        # lexicographically first member, identically everywhere
        points.sort()
        self._points = points
        self._positions = [p for p, _ in points]

    def owner(self, s: int) -> str | None:
        """The member owning slot `s` (None on an empty ring)."""
        if not self._points:
            return None
        idx = bisect.bisect_left(self._positions, s % SLOTS)
        if idx == len(self._points):
            idx = 0  # wrap: successor of the last gap is the first point
        return self._points[idx][1]

    def arcs(self, member: str) -> list[tuple[int, int]]:
        """Half-open [lo, hi) slot ranges owned by `member`.

        A single-member ring owns everything; an unknown member owns
        nothing. Wraparound arcs split into a tail and a head range.
        """
        if member not in self.members:
            return []
        if len(self.members) == 1:
            return [(0, SLOTS)]
        out: list[tuple[int, int]] = []
        pts = self._points
        for i, (pos, m) in enumerate(pts):
            if m != member:
                continue
            prev = pts[i - 1][0]  # i == 0 wraps to the last point
            # this point owns (prev, pos] == [prev + 1, pos + 1)
            lo, hi = prev + 1, pos + 1
            if lo == hi:
                continue  # duplicate-slot point: empty arc
            if lo < hi:
                out.append((lo, hi))
            else:  # wraparound
                if lo < SLOTS:
                    out.append((lo, SLOTS))
                if hi > 0:
                    out.append((0, hi))
        out.sort()
        # merge adjacent/overlapping ranges: fewer predicates downstream
        merged: list[tuple[int, int]] = []
        for lo, hi in out:
            if merged and lo <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(hi, merged[-1][1]))
            else:
                merged.append((lo, hi))
        return merged

    def share(self, member: str) -> float:
        """Fraction of the slot space `member` owns (readiness surface)."""
        return sum(hi - lo for lo, hi in self.arcs(member)) / SLOTS
