"""Batched SA over K stacked same-shape instances — one vmapped launch.

The micro-batcher's payoff: K concurrent requests whose instances share
a padded shape (and solver schedule) run as ONE device program with a
leading instance axis, instead of K sequential launches each paying
per-launch fixed costs (dispatch, host sync, scan-step overhead, the
threefry presample chain). The batched block's step body is the same
primitive chain as the single-instance block (_batch_block_fn), vmapped
over instances, with the presampled move-parameter stream SHARED across
the batch — so per-instance anneal semantics cannot drift, and only the
RNG stream differs from a solo solve.

Batch sizes are padded up to a power of two (replicating the last
instance) so the set of compiled batched programs stays tiny — at most
log2(max_batch) variants per bucket shape, each persistent-cacheable.

Deadline semantics match solve_sa: the whole batch runs under ONE
run_blocked loop whose budget is the CALLER's minimum remaining budget
across the batch, so no merged job ever overshoots its own deadline
(beyond the shared one-block granularity contract).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, resolve_eval_mode
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.solvers.common import (
    SolveResult,
    donate_safe_state,
    maybe_donate_jit,
    run_blocked,
)
from vrpms_tpu.solvers.sa import (
    SAParams,
    _rate_get,
    _rate_put,
    _sa_prep_fn,
)


def stack_instances(insts: list[Instance]) -> Instance:
    """K same-shape instances -> one Instance pytree with a leading
    instance axis on every array leaf. Static metadata (has_tw,
    slice_minutes, het_fleet, td_rank) must agree — the bucket key the
    service batches on guarantees it; mismatches raise here."""
    first = insts[0]
    for other in insts[1:]:
        if (
            other.has_tw != first.has_tw
            or other.slice_minutes != first.slice_minutes
            or other.het_fleet != first.het_fleet
            or other.td_rank != first.td_rank
        ):
            raise ValueError("instances in one batch must share metadata")
        if other.durations.shape != first.durations.shape:
            raise ValueError("instances in one batch must share shapes")
        if (other.n_real is None) != (first.n_real is None):
            # pytree structures differ; the bucket key's padded marker
            # should have split these
            raise ValueError("padded and unpadded instances cannot stack")
    # tier-padded instances: n_real/v_real are data leaves, so each
    # stacked instance keeps its own traced real size — one vmapped
    # launch serves a MIX of real sizes within the tier
    return jax.tree.map(lambda *xs: jnp.stack(xs), *insts)


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@lru_cache(maxsize=8)
def _keys_fn():
    @jax.jit
    def keys(seeds):
        base = jax.random.key(0)
        return jax.vmap(lambda s: jax.random.fold_in(base, s))(seeds)

    return keys


@lru_cache(maxsize=8)
def _batch_prep_fn(n_chains: int, mode: str):
    """vmap of the fused single-instance cold-start prep (NN seed +
    clones + initial eval + temperature scale)."""
    prep = _sa_prep_fn(n_chains, mode)
    return jax.jit(jax.vmap(prep, in_axes=(0, 0, None)))


@lru_cache(maxsize=32)
def _batch_block_fn(n_block: int, mode: str):
    """One anneal block over [K, B, L] stacked state with a SHARED
    presampled move-parameter stream.

    The step body is the same primitive chain as solvers.sa._sa_block_fn
    (presample -> move_batch_from_params -> objective -> the one
    metropolis_accept), vmapped over the instance axis per step — so no
    per-instance anneal semantics can drift. The block's randomness is
    presampled ONCE for the whole batch (common random numbers: every
    instance's chains see the same proposal positions/uniforms, applied
    to its OWN tours against its OWN durations): on CPU the threefry
    presample chain is a large slice of the per-iteration fixed cost, so
    sharing it is a big part of the batched launch's amortization — and
    for INDEPENDENT instances, cross-request stream correlation changes
    no per-request result distribution.

    On accelerators the stacked loop state (arg 0) is DONATED — see
    sa._sa_block_fn; solve_sa_batch enters through donate_safe_state.
    """

    @maybe_donate_jit
    def run(state, key, binst, w, t0s, t1s, knns, start_it, horizon):
        from vrpms_tpu.moves.moves import (
            move_batch_from_params,
            presample_move_params,
        )
        from vrpms_tpu.solvers.sa import (
            anneal_temperature,
            metropolis_accept,
        )

        giants, costs, best_g, best_c = state
        _, b, length = giants.shape
        kb = jax.random.fold_in(key, start_it)
        width = 0 if knns is None else knns.shape[-1]
        pri, prr, prmt, prm, pru = presample_move_params(
            kb, b, length, n_block, width
        )

        def step(st, xs):
            it, i, r, mt, m, u = xs
            giants, costs, best_g, best_c = st
            temps = anneal_temperature(it, t0s, t1s, horizon)

            def one(g, c, inst, knn, temp):
                # the presampled stream is SHARED across the batch and
                # drawn over the full padded length; tier-padded
                # instances fold each draw into their OWN real prefix
                # (positions {1..L_real-2}) so moves never touch the
                # phantom tail. A modulo remap keeps the stream shared
                # (its slight nonuniformity is irrelevant to SA).
                lim = inst.move_limit
                if lim is None:
                    i2, r2 = i, r
                else:
                    span = lim - 2  # movable position count
                    i2 = 1 + (i - 1) % span
                    r2 = r if knns is not None else 1 + (r - 1) % span
                cands = move_batch_from_params(
                    i2, r2, mt, m, g, knn, mode, length_real=lim
                )
                cand_costs = objective_batch_mode_(cands, inst, w)
                return metropolis_accept(g, c, cands, cand_costs, u, temp)

            giants, costs = jax.vmap(one)(giants, costs, binst, knns, temps)
            better = costs < best_c
            best_g = jnp.where(better[..., None], giants, best_g)
            best_c = jnp.where(better, costs, best_c)
            return (giants, costs, best_g, best_c), None

        def objective_batch_mode_(cands, inst, w):
            from vrpms_tpu.core.cost import objective_batch_mode

            return objective_batch_mode(cands, inst, w, mode)

        xs = (start_it + jnp.arange(n_block), pri, prr, prmt, prm, pru)
        state, _ = jax.lax.scan(step, state, xs)
        return state

    return run


@lru_cache(maxsize=8)
def _batch_final_fn():
    """Per-instance champion + exact pricing, vmapped."""
    from vrpms_tpu.core.cost import exact_cost

    @jax.jit
    def final(best_g, best_c, binst, w):
        def one(bg, bc, inst):
            champ = jnp.argmin(bc)
            g = bg[champ]
            bd, cost = exact_cost(g, inst, w)
            return g, bd, cost

        return jax.vmap(one, in_axes=(0, 0, 0))(best_g, best_c, binst)

    return final


def solve_sa_batch(
    insts: list[Instance],
    seeds: list[int],
    params: SAParams = SAParams(),
    weights: CostWeights | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
) -> list[SolveResult]:
    """Solve K same-shape instances with SA in one vmapped launch.

    Returns one SolveResult per input instance, in order. The anneal
    uses the nn-seeded cool schedule (solve_sa's default path) with
    per-instance temperatures from each instance's own duration scale;
    candidate-list proposals use per-instance knn tables.
    """
    from vrpms_tpu.moves import proposal_knn

    k = len(insts)
    if k == 0:
        return []
    if len(seeds) != k:
        raise ValueError(f"{k} instances but {len(seeds)} seeds")
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)

    # pad to a power of two with clones of the last instance: bounds the
    # compiled batched-program variants at log2(max_batch) per shape
    p = _pad_pow2(k)
    from vrpms_tpu.obs.analytics import current_timer

    _timer = current_timer()
    if _timer is not None:  # flight record: batch fill = members/padded
        _timer.batch_members = k
        _timer.batch_padded = p
    padded = list(insts) + [insts[-1]] * (p - k)
    pad_seeds = [int(s) & 0x7FFFFFFF for s in seeds] + [0] * (p - k)

    binst = stack_instances(padded)
    seeds_j = jnp.asarray(pad_seeds, jnp.int32)
    k_init = _keys_fn()(seeds_j)
    # ONE run key for the whole batch (the shared presampled stream),
    # mixed from every job's seed so any seed change reshuffles it
    mix = 0
    for s in pad_seeds:
        mix = (mix * 1000003 ^ s) & 0x7FFFFFFF
    k_run = jax.random.fold_in(jax.random.key(1), mix)

    giants, costs, means = _batch_prep_fn(params.n_chains, mode)(
        k_init, binst, w
    )
    # per-instance geometric schedule endpoints (nn-seeded cool start,
    # matching solvers.sa._temps_from_scale for init='nn')
    t0s = 0.05 * means
    t1s = jnp.maximum(1e-3, 0.002 * means)

    # stackable by construction: the bucket key fixes the padded node
    # count and knn_k, and proposal_knn returns a size-independent
    # (tier-constant) width for padded instances
    knns = (
        jnp.stack([proposal_knn(inst, params.knn_k) for inst in padded])
        if params.knn_k > 0
        else None
    )
    n_iters = params.n_iters
    horizon = jnp.float32(n_iters)
    # donate_safe_state: the four slots must donate DISTINCT buffers on
    # accelerators (giants appears twice); identity on CPU
    state = donate_safe_state((giants, costs, giants, costs))

    def step_block(st, nb, start):
        return _batch_block_fn(nb, mode)(
            st, k_run, binst, w, t0s, t1s, knns, jnp.int32(start), horizon
        )

    rate_key = ("sa_batch", p, params.n_chains, giants.shape[-1], mode)
    import time as _time

    t_run = _time.monotonic()
    state, done = run_blocked(
        step_block,
        state,
        n_iters,
        512,
        deadline_s,
        lambda st: st[3],
        rate_hint=_rate_get(rate_key),
        evals_per_iter=p * params.n_chains,
    )
    if deadline_s is not None and done:
        el = _time.monotonic() - t_run
        if el > 0.05:
            _rate_put(rate_key, done / el)

    _, _, best_g, best_c = state
    g, bd, cost = _batch_final_fn()(best_g, best_c, binst, w)
    evals = jnp.float32(params.n_chains * done)
    return [
        SolveResult(
            g[i],
            cost[i],
            jax.tree.map(lambda a: a[i], bd),
            evals,
        )
        for i in range(k)
    ]
