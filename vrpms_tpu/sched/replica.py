"""Replica: one process's membership in the distributed job queue.

The scale-out counterpart of sched.worker's supervision story. Each
service process runs ONE Replica; together they turn N disjoint
schedulers into one deployment:

  * **identity + ring** — the replica heartbeats itself into the store's
    membership registry and derives the consistent-hash ring
    (sched.ring) from the live id set, so every peer computes the same
    tier->replica ownership with no coordinator;
  * **tier-affinity claiming** — the claim loop first asks the queue
    store for jobs whose ring slot falls in its OWN arcs (compile-cache
    locality: the tiers it warmed are the tiers it serves); only when
    its arc is empty does it steal off-arc work, so a hot replica never
    idles while peers drown, but routing holds whenever there is a
    choice;
  * **lease lifecycle** — every claimed job is executed under a
    heartbeat-renewed lease; completion acks conditionally (a replica
    that lost its lease must NOT publish the job's terminal record —
    the reclaimer owns it now, and double records are exactly the bug
    leases exist to prevent);
  * **exactly-once reclaim** — the loop also scans for expired leases:
    a crashed peer's in-flight jobs re-queue exactly once (the store's
    conditional update arbitrates racing scanners), carrying the
    attempt counter so a job that kills its SECOND replica dies with a
    clean failure record instead of crash-looping the fleet — the
    cross-replica generalization of the PR-3 watchdog's at-most-one
    requeue.

The Replica knows nothing about HTTP, jax, or stores' internals: the
service injects `materialize` (entry -> local Job), `submit` (Job ->
local scheduler), `complete` (terminal + ack outcome) and `dead`
(twice-crashed entry -> failure record); all store calls go through the
JobQueueStore seam. Store failures never propagate: the loop logs,
backs off, and keeps polling — a queue outage means this replica claims
nothing for a while, never that it crashes.
"""

from __future__ import annotations

import threading
import time

from vrpms_tpu.sched.queue import FAILED, Job, QueueFull
from vrpms_tpu.sched.ring import HashRing


class Replica:
    """Claim/lease/reclaim loop against a shared JobQueueStore."""

    def __init__(
        self,
        store,
        replica_id: str,
        materialize,
        submit,
        complete=None,
        dead=None,
        on_event=None,
        *,
        lease_s: float = 15.0,
        poll_s: float = 0.05,
        heartbeat_s: float = 5.0,
        reclaim_s: float = 1.0,
        max_inflight: int = 16,
        max_attempts: int = 2,
        steal: bool = True,
        vnodes: int = 64,
    ):
        self.store = store
        self.replica_id = replica_id
        self._materialize = materialize
        self._submit = submit
        self._complete = complete
        self._dead = dead
        self._on_event = on_event
        self.lease_s = max(0.05, float(lease_s))
        self.poll_s = max(0.005, float(poll_s))
        self.heartbeat_s = max(0.05, float(heartbeat_s))
        self.reclaim_s = max(0.05, float(reclaim_s))
        self.max_inflight = max(1, int(max_inflight))
        self.max_attempts = max(1, int(max_attempts))
        self.steal = steal
        self.vnodes = vnodes
        self._halt = threading.Event()
        self._stopping = False  # drain mode: ack/renew, claim nothing
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # {job_id: (job, entry, lost)} — claimed, not yet acked
        self._inflight: dict[str, tuple[Job, dict, bool]] = {}  # guarded-by: _lock
        self._next_heartbeat = 0.0
        self._next_reclaim = 0.0
        self._ring: HashRing | None = None  # guarded-by: _lock
        # EWMA of per-job service seconds (shared-depth Retry-After)
        self._job_seconds = 1.0  # guarded-by: _lock
        self._backoff_until = 0.0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Replica":
        if self._thread is None or not self._thread.is_alive():
            self._halt.clear()
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run,
                name=f"vrpms-replica-{self.replica_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Graceful exit: stop CLAIMING first, then give in-flight jobs
        `drain_s` to finish (and ack), then halt. Claiming must stop
        before the drain wait — otherwise every ack frees a slot the
        claim loop refills and the drain never converges, orphaning a
        full window of fresh leases (each a burned attempt on a peer).
        Jobs still running after the window keep their leases and are
        reclaimed by peers on expiry."""
        self._stopping = True
        deadline = time.monotonic() + max(0.0, drain_s)
        while self.inflight() and time.monotonic() < deadline:
            time.sleep(min(0.02, self.poll_s))
        self._halt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=drain_s + 1.0)

    def kill(self) -> None:
        """Simulated crash (tests/bench): halt instantly WITHOUT acking
        or draining — in-flight leases are orphaned and expire, which is
        exactly what peers' reclaim scans exist for."""
        self._halt.set()

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._halt.is_set()

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def job_seconds_ewma(self) -> float:
        with self._lock:
            return self._job_seconds

    def ring(self) -> HashRing | None:
        """Latest membership snapshot this replica derived (readiness)."""
        with self._lock:
            return self._ring

    def owns_slot(self, s: int) -> bool:
        ring = self.ring()
        if ring is None:
            ring = self._refresh_ring()
        return ring is not None and ring.owner(s) == self.replica_id

    # -- events -------------------------------------------------------------
    def _emit(self, name: str, **kw) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(name, **kw)
        except Exception:
            pass  # observers must never kill the claim loop

    def _store_error(self, op: str, exc: Exception) -> None:
        self._emit("store_error", op=op, error=f"{type(exc).__name__}: {exc}")
        # linear backoff, capped: a down queue store must not busy-spin
        self._backoff_until = time.monotonic() + min(
            1.0, 10 * self.poll_s
        )

    # -- loop ---------------------------------------------------------------
    def _run(self) -> None:
        while not self._halt.is_set():
            now = time.monotonic()
            if now >= self._backoff_until:
                if now >= self._next_heartbeat:
                    self._heartbeat()
                    self._next_heartbeat = now + self.heartbeat_s
                if now >= self._next_reclaim:
                    self._reclaim()
                    self._next_reclaim = now + self.reclaim_s
                progressed = self._monitor()
                claimed = self._claim_one()
                if claimed or progressed:
                    continue  # momentum: drain acks/claims back to back
            self._halt.wait(self.poll_s)

    def _heartbeat(self) -> None:
        try:
            # membership TTL = 3 heartbeats: one missed beat (GC pause,
            # slow store call) must not flap the ring
            self.store.register_replica(self.replica_id, 3 * self.heartbeat_s)
        except Exception as exc:
            self._store_error("register_replica", exc)
            return
        self._refresh_ring()

    def _refresh_ring(self) -> HashRing | None:
        try:
            members = self.store.replicas()
        except Exception as exc:
            self._store_error("replicas", exc)
            return None
        if self.replica_id not in members:
            members = members + [self.replica_id]
        ring = HashRing(members, vnodes=self.vnodes)
        with self._lock:
            self._ring = ring
        return ring

    def _reclaim(self) -> None:
        try:
            requeued, dead = self.store.reclaim_expired(self.max_attempts)
        except Exception as exc:
            self._store_error("reclaim_expired", exc)
            return
        for entry in requeued:
            self._emit(
                "lease_reclaimed",
                jobId=entry.get("id"),
                attempt=entry.get("attempt"),
            )
        for entry in dead:
            self._emit(
                "lease_expired_dead",
                jobId=entry.get("id"),
                attempt=entry.get("attempt"),
            )
            if self._dead is not None:
                try:
                    self._dead(entry)
                except Exception:
                    pass

    def _monitor(self) -> bool:
        """Ack finished jobs, renew live leases. Returns True if any
        job reached terminal (momentum for the outer loop)."""
        with self._lock:
            items = list(self._inflight.items())
        progressed = False
        now = time.monotonic()
        for job_id, (job, entry, lost) in items:
            if job.done_event.is_set():
                acked = False
                if not lost:
                    try:
                        acked = self.store.ack(self.replica_id, job_id)
                    except Exception as exc:
                        self._store_error("ack", exc)
                        continue  # retry the ack next pass
                with self._lock:
                    self._inflight.pop(job_id, None)
                    if job.started_at and job.finished_at:
                        dt = max(1e-3, job.finished_at - job.started_at)
                        self._job_seconds = (
                            0.8 * self._job_seconds + 0.2 * dt
                        )
                if not acked:
                    self._emit("ack_lost", jobId=job_id)
                self._finish(job, entry, acked)
                progressed = True
                continue
            # renew at half-life so one slow store call cannot let a
            # healthy lease lapse
            renew_due = entry.get("_renew_mono", 0.0)
            if lost or now < renew_due:
                continue
            try:
                ok = self.store.renew(self.replica_id, job_id, self.lease_s)
            except Exception as exc:
                self._store_error("renew", exc)
                continue
            if ok:
                entry["_renew_mono"] = now + self.lease_s / 2.0
                self._emit("lease_renewed", jobId=job_id)
            else:
                # the lease is someone else's now: stop renewing, ask
                # the local solve to stand down at its next boundary
                # (cooperative — the result, if any, is discarded)
                with self._lock:
                    if job_id in self._inflight:
                        self._inflight[job_id] = (job, entry, True)
                self._emit("lease_lost", jobId=job_id)
                sink = getattr(job, "sink", None)
                if sink is not None:
                    try:
                        sink.cancel()
                    except Exception:
                        pass
        return progressed

    def _finish(self, job: Job, entry: dict, acked: bool) -> None:
        if self._complete is None:
            return
        try:
            self._complete(job, entry, acked)
        except Exception:
            pass

    def _claim_one(self) -> bool:
        if self._stopping:
            return False
        with self._lock:
            room = len(self._inflight) < self.max_inflight
            ring = self._ring
        if not room:
            return False
        if ring is None:
            ring = self._refresh_ring()
            if ring is None:
                return False
        arcs = ring.arcs(self.replica_id)
        entry = None
        stolen = False
        try:
            entry = self.store.claim(self.replica_id, self.lease_s, arcs)
            if entry is None and self.steal:
                # own arc empty: steal ANY queued work — affinity is a
                # preference, idle capacity is waste
                entry = self.store.claim(self.replica_id, self.lease_s, None)
                stolen = entry is not None
        except Exception as exc:
            self._store_error("claim", exc)
            return False
        if entry is None:
            return False
        entry["_renew_mono"] = time.monotonic() + self.lease_s / 2.0
        self._emit(
            "claim",
            jobId=entry.get("id"),
            kind="steal" if stolen else "own",
            attempt=entry.get("attempt"),
            slot=entry.get("slot"),
        )
        try:
            job = self._materialize(entry)
        except Exception as exc:
            # materialize must not raise; if it does, fail the entry
            # clean rather than leave the lease to expire into a
            # pointless second attempt of a job that cannot build
            job = Job(payload={})
            job.id = str(entry.get("id"))
            job.errors = [{
                "what": "Scheduler error",
                "reason": f"materialize failed: {type(exc).__name__}: {exc}",
            }]
            job.finish(FAILED)
        if job.done_event.is_set():
            # born terminal (cache hit, trivial, or failed to build):
            # nothing to schedule — ack and publish right here
            acked = False
            try:
                acked = self.store.ack(self.replica_id, job.id)
            except Exception as exc:
                self._store_error("ack", exc)
            self._finish(job, entry, acked)
            return True
        try:
            self._submit(job)
        except QueueFull:
            # local admission full: hand the entry back untouched (no
            # attempt burned) and back off — a peer with room takes it
            try:
                self.store.nack(self.replica_id, job.id)
            except Exception as exc:
                self._store_error("nack", exc)
            self._emit("nack", jobId=job.id)
            self._backoff_until = time.monotonic() + 5 * self.poll_s
            return False
        with self._lock:
            self._inflight[job.id] = (job, entry, False)
        return True
