"""Replica: one process's membership in the distributed job queue.

The scale-out counterpart of sched.worker's supervision story. Each
service process runs ONE Replica; together they turn N disjoint
schedulers into one deployment:

  * **identity + ring** — the replica heartbeats itself into the store's
    membership registry and derives the consistent-hash ring
    (sched.ring) from the live id set, so every peer computes the same
    tier->replica ownership with no coordinator;
  * **tier-affinity claiming** — the claim loop first asks the queue
    store for jobs whose ring slot falls in its OWN arcs (compile-cache
    locality: the tiers it warmed are the tiers it serves); only when
    its arc is empty does it steal off-arc work, so a hot replica never
    idles while peers drown, but routing holds whenever there is a
    choice;
  * **claim-K batching** — each claim leases up to K same-ring-token
    entries in ONE conditional update (store.claim_batch; K clamps to
    local admission headroom) and submits the set together with batch
    hints, so the worker's gather window assembles it into one vmapped
    launch instead of a single-claim fleet's K sequential round trips;
    leases stay per entry, so crash semantics are unchanged;
  * **lease lifecycle** — every claimed job is executed under a
    heartbeat-renewed lease; completion acks conditionally (a replica
    that lost its lease must NOT publish the job's terminal record —
    the reclaimer owns it now, and double records are exactly the bug
    leases exist to prevent);
  * **exactly-once reclaim** — the loop also scans for expired leases:
    a crashed peer's in-flight jobs re-queue exactly once (the store's
    conditional update arbitrates racing scanners), carrying the
    attempt counter so a job that kills its SECOND replica dies with a
    clean failure record instead of crash-looping the fleet — the
    cross-replica generalization of the PR-3 watchdog's at-most-one
    requeue.

The Replica knows nothing about HTTP, jax, or stores' internals: the
service injects `materialize` (entry -> local Job), `submit` (Job ->
local scheduler), `complete` (terminal + ack outcome) and `dead`
(twice-crashed entry -> failure record); all store calls go through the
JobQueueStore seam. Store failures never propagate: the loop logs,
backs off, and keeps polling — a queue outage means this replica claims
nothing for a while, never that it crashes.
"""

from __future__ import annotations

import threading
import time

from vrpms_tpu.sched.queue import FAILED, Job, QueueFull
from vrpms_tpu.sched.ring import HashRing


class Replica:
    """Claim/lease/reclaim loop against a shared JobQueueStore."""

    def __init__(
        self,
        store,
        replica_id: str,
        materialize,
        submit,
        complete=None,
        dead=None,
        on_event=None,
        *,
        lease_s: float = 15.0,
        poll_s: float = 0.05,
        heartbeat_s: float = 5.0,
        reclaim_s: float = 1.0,
        max_inflight: int = 16,
        max_attempts: int = 2,
        steal: bool = True,
        vnodes: int = 64,
        claim_batch: int = 0,
        info=None,
        on_tick=None,
    ):
        self.store = store
        self.replica_id = replica_id
        self._materialize = materialize
        self._submit = submit
        self._complete = complete
        self._dead = dead
        self._on_event = on_event
        # optional heartbeat status doc provider: () -> dict, published
        # with each membership beat so peers' fleet rollups
        # (GET /api/debug/fleet) see this replica's inflight/claim-mix/
        # warmed-tier state without any replica-to-replica RPC
        self._info = info
        # optional per-heartbeat standing-work hook: () -> None, run on
        # the claim-loop thread at the heartbeat cadence while NOT
        # draining. The service wires the subscription manager's
        # due-generation check here, so cadence re-solves and
        # drain/crash adoptions fire on any replica that is alive —
        # no dedicated timer infrastructure per standing entity.
        self._on_tick = on_tick
        self.lease_s = max(0.05, float(lease_s))
        self.poll_s = max(0.005, float(poll_s))
        self.heartbeat_s = max(0.05, float(heartbeat_s))
        self.reclaim_s = max(0.05, float(reclaim_s))
        self.max_inflight = max(1, int(max_inflight))
        self.max_attempts = max(1, int(max_attempts))
        self.steal = steal
        self.vnodes = vnodes
        # claim-K ceiling: how many same-ring-token entries one claim
        # may lease together (store.base.JobQueueStore.claim_batch).
        # <= 0 = auto: size each claim to the local admission headroom
        # (max_inflight minus current leases), so a claim can never
        # overfill this box; 1 = the pre-batching single-claim loop.
        self.claim_batch = int(claim_batch)
        self._halt = threading.Event()
        self._stopping = False  # drain mode: ack/renew, claim nothing
        self._draining = False  # graceful drain: also stop heartbeating
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # {job_id: (job, entry, lost)} — claimed, not yet acked
        self._inflight: dict[str, tuple[Job, dict, bool]] = {}  # guarded-by: _lock
        self._next_heartbeat = 0.0
        self._next_reclaim = 0.0
        self._ring: HashRing | None = None  # guarded-by: _lock
        # EWMA of per-job service seconds (shared-depth Retry-After)
        self._job_seconds = 1.0  # guarded-by: _lock
        # decayed per-ring-token claim counter: which tiers the ring
        # actually routes here, hottest first — the arc-weighted warmup
        # order (service.warmup) reads it via claim_mix()
        self._claim_mix: dict[str, float] = {}  # guarded-by: _lock
        self._backoff_until = 0.0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Replica":
        if self._thread is None or not self._thread.is_alive():
            self._halt.clear()
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run,
                name=f"vrpms-replica-{self.replica_id}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Graceful exit: stop CLAIMING first, then give in-flight jobs
        `drain_s` to finish (and ack), then halt. Claiming must stop
        before the drain wait — otherwise every ack frees a slot the
        claim loop refills and the drain never converges, orphaning a
        full window of fresh leases (each a burned attempt on a peer).
        Jobs still running after the window keep their leases and are
        reclaimed by peers on expiry."""
        self._stopping = True
        deadline = time.monotonic() + max(0.0, drain_s)
        while self.inflight() and time.monotonic() < deadline:
            time.sleep(min(0.02, self.poll_s))
        self._halt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=drain_s + 1.0)

    def drain(self, grace_s: float, requeue=None) -> int:
        """Graceful drain (POST /api/admin/drain, SIGTERM): stop
        CLAIMING and HEARTBEATING, give in-flight jobs `grace_s` to
        finish (the monitor keeps acking them), then hand the leftovers
        back to the shared queue for a peer: `requeue(job, entry)` — the
        service's checkpoint-flush hook — returns an optional payload
        note the nack merges in (e.g. {"ckpt": true}), the entry nacks
        WITHOUT burning an attempt, the local lease is marked lost so a
        late completion never publishes, and the solve is cooperatively
        cancelled to free the device. Finally the membership heartbeat
        deregisters so peers' next ring refresh moves our arcs at once.
        The loop thread stays alive (lost-lease completions still need
        their non-publishing cleanup); stop()/kill() end it. Returns
        the number of jobs requeued."""
        self._stopping = True
        self._draining = True
        deadline = time.monotonic() + max(0.0, grace_s)
        while self.inflight() and time.monotonic() < deadline:
            time.sleep(min(0.02, self.poll_s))
        with self._lock:
            items = list(self._inflight.items())
        nacked = 0
        for job_id, (job, entry, lost) in items:
            if lost or job.done_event.is_set():
                continue
            note = None
            if requeue is not None:
                try:
                    note = requeue(job, entry)
                except Exception:
                    note = None  # a broken hook must not stop the drain
            try:
                try:
                    ok = self.store.nack(self.replica_id, job_id, note)
                except TypeError:
                    # backend predates the note parameter: the entry
                    # still requeues, the claimant just probes the
                    # checkpoint store on attempt alone
                    ok = self.store.nack(self.replica_id, job_id)
            except Exception as exc:
                self._store_error("nack", exc)
                continue
            if not ok:
                continue  # lease already lost: the peer owns it
            nacked += 1
            self._emit("drain_requeued", jobId=job_id)
            with self._lock:
                if job_id in self._inflight:
                    # never publish: the entry is queued again — a peer
                    # will complete it (the lease_lost discipline)
                    self._inflight[job_id] = (job, entry, True)
            sink = getattr(job, "sink", None)
            if sink is not None:
                try:
                    sink.cancel()
                except Exception:
                    pass
        try:
            self.store.deregister_replica(self.replica_id)
        except Exception as exc:
            self._store_error("deregister_replica", exc)
        return nacked

    @property
    def draining(self) -> bool:
        return self._draining

    def kill(self) -> None:
        """Simulated crash (tests/bench): halt instantly WITHOUT acking
        or draining — in-flight leases are orphaned and expire, which is
        exactly what peers' reclaim scans exist for."""
        self._halt.set()

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._halt.is_set()

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def job_seconds_ewma(self) -> float:
        with self._lock:
            return self._job_seconds

    #: claim-mix decay per claim round and the key-count bound: recent
    #: traffic dominates (≈ the last ~50 claims) and the counter can
    #: never grow with tier-space cardinality
    MIX_DECAY = 0.98
    MIX_KEYS = 32

    def claim_mix(self) -> dict[str, float]:
        """Decayed claim counts by ring token, hot tiers first — what
        this replica has actually been leased lately (arc-weighted
        warmup orders the tier ladder by it)."""
        with self._lock:
            return dict(
                sorted(
                    self._claim_mix.items(),
                    key=lambda kv: kv[1],
                    reverse=True,
                )
            )

    def _note_claims(self, entries: list) -> None:
        with self._lock:
            for key in self._claim_mix:
                self._claim_mix[key] *= self.MIX_DECAY
            for entry in entries:
                token = entry.get("bucket")
                if not token:
                    continue
                self._claim_mix[token] = (
                    self._claim_mix.get(token, 0.0) + 1.0
                )
            while len(self._claim_mix) > self.MIX_KEYS:
                coldest = min(self._claim_mix, key=self._claim_mix.get)
                del self._claim_mix[coldest]

    def ring(self) -> HashRing | None:
        """Latest membership snapshot this replica derived (readiness)."""
        with self._lock:
            return self._ring

    def owns_slot(self, s: int) -> bool:
        ring = self.ring()
        if ring is None:
            ring = self._refresh_ring()
        return ring is not None and ring.owner(s) == self.replica_id

    def owner_of(self, job_id: str) -> str | None:
        """The replica currently holding `job_id`'s live lease, or None
        (unleased, lease expired, backend predates get_entry, or the
        store is down — federated readers fall back to the checkpoint
        row in every None case, so this is strictly best-effort)."""
        try:
            entry = self.store.get_entry(str(job_id))
        except Exception as exc:
            self._store_error("get_entry", exc)
            return None
        if not isinstance(entry, dict) or entry.get("state") != "leased":
            return None
        expires = entry.get("lease_expires_at")
        if expires is not None and float(expires) <= time.time():
            return None  # an expired lease names a dead/absent owner
        owner = entry.get("lease_owner")
        return str(owner) if owner else None

    # -- events -------------------------------------------------------------
    def _emit(self, name: str, **kw) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(name, **kw)
        except Exception:
            pass  # observers must never kill the claim loop

    def _store_error(self, op: str, exc: Exception) -> None:
        self._emit("store_error", op=op, error=f"{type(exc).__name__}: {exc}")
        # linear backoff, capped: a down queue store must not busy-spin
        self._backoff_until = time.monotonic() + min(
            1.0, 10 * self.poll_s
        )

    # -- loop ---------------------------------------------------------------
    def _run(self) -> None:
        while not self._halt.is_set():
            now = time.monotonic()
            if now >= self._backoff_until:
                if now >= self._next_heartbeat:
                    if not self._draining:
                        # a draining replica must STAY deregistered:
                        # re-heartbeating would put its arcs back on
                        # the ring after drain() removed them
                        self._heartbeat()
                        if self._on_tick is not None:
                            # standing-work scheduling rides the same
                            # beat (a draining replica fires nothing —
                            # its durable state is a peer's to adopt)
                            try:
                                self._on_tick()
                            except Exception:
                                pass  # a broken hook must not stop the loop
                    self._next_heartbeat = now + self.heartbeat_s
                if now >= self._next_reclaim:
                    self._reclaim()
                    self._next_reclaim = now + self.reclaim_s
                progressed = self._monitor()
                claimed = self._claim_one()
                if claimed or progressed:
                    continue  # momentum: drain acks/claims back to back
            self._halt.wait(self.poll_s)

    def _heartbeat(self) -> None:
        if self._draining:
            # drain() flipped the flag after the loop's own check: a
            # beat landing now would re-register the row drain() is
            # about to (or just did) deregister. Re-checking here
            # narrows the race to a store write already in flight —
            # whose resurrected row the membership TTL still expires,
            # the documented fallback.
            return
        doc = None
        if self._info is not None:
            try:
                doc = self._info()
            except Exception:
                doc = None  # a broken provider must not stop the beat
        try:
            # membership TTL = 3 heartbeats: one missed beat (GC pause,
            # slow store call) must not flap the ring
            ttl = 3 * self.heartbeat_s
            if doc is None:
                self.store.register_replica(self.replica_id, ttl)
            else:
                try:
                    self.store.register_replica(self.replica_id, ttl, doc)
                except TypeError:
                    # backend predates the info parameter: membership
                    # still beats, the fleet rollup just loses the doc
                    self.store.register_replica(self.replica_id, ttl)
        except Exception as exc:
            self._store_error("register_replica", exc)
            return
        self._refresh_ring()

    def _refresh_ring(self) -> HashRing | None:
        try:
            members = self.store.replicas()
        except Exception as exc:
            self._store_error("replicas", exc)
            return None
        if self.replica_id not in members:
            members = members + [self.replica_id]
        ring = HashRing(members, vnodes=self.vnodes)
        with self._lock:
            self._ring = ring
        return ring

    def _reclaim(self) -> None:
        try:
            requeued, dead = self.store.reclaim_expired(self.max_attempts)
        except Exception as exc:
            self._store_error("reclaim_expired", exc)
            return
        for entry in requeued:
            self._emit(
                "lease_reclaimed",
                jobId=entry.get("id"),
                attempt=entry.get("attempt"),
            )
        for entry in dead:
            self._emit(
                "lease_expired_dead",
                jobId=entry.get("id"),
                attempt=entry.get("attempt"),
            )
            if self._dead is not None:
                try:
                    self._dead(entry)
                except Exception:
                    pass

    def _monitor(self) -> bool:
        """Ack finished jobs, renew live leases. Returns True if any
        job reached terminal (momentum for the outer loop)."""
        with self._lock:
            items = list(self._inflight.items())
        progressed = False
        now = time.monotonic()
        for job_id, (job, entry, lost) in items:
            if job.done_event.is_set():
                acked = False
                if not lost:
                    try:
                        acked = self.store.ack(self.replica_id, job_id)
                    except Exception as exc:
                        self._store_error("ack", exc)
                        continue  # retry the ack next pass
                with self._lock:
                    self._inflight.pop(job_id, None)
                    if job.started_at and job.finished_at:
                        dt = max(1e-3, job.finished_at - job.started_at)
                        self._job_seconds = (
                            0.8 * self._job_seconds + 0.2 * dt
                        )
                if not acked:
                    self._emit("ack_lost", jobId=job_id)
                self._finish(job, entry, acked)
                progressed = True
                continue
            # renew at half-life so one slow store call cannot let a
            # healthy lease lapse
            renew_due = entry.get("_renew_mono", 0.0)
            if lost or now < renew_due:
                continue
            try:
                ok = self.store.renew(self.replica_id, job_id, self.lease_s)
            except Exception as exc:
                self._store_error("renew", exc)
                continue
            if ok:
                entry["_renew_mono"] = now + self.lease_s / 2.0
                self._emit("lease_renewed", jobId=job_id)
            else:
                # the lease is someone else's now: stop renewing, ask
                # the local solve to stand down at its next boundary
                # (cooperative — the result, if any, is discarded)
                with self._lock:
                    if job_id in self._inflight:
                        self._inflight[job_id] = (job, entry, True)
                self._emit("lease_lost", jobId=job_id)
                sink = getattr(job, "sink", None)
                if sink is not None:
                    try:
                        sink.cancel()
                    except Exception:
                        pass
        return progressed

    def _finish(self, job: Job, entry: dict, acked: bool) -> None:
        if self._complete is None:
            return
        try:
            self._complete(job, entry, acked)
        except Exception:
            pass

    def _claim_one(self) -> bool:
        """Claim up to K same-token entries in one conditional update,
        materialize them all, then submit the set together with batch
        hints so the worker's gather treats it as an already-assembled
        batch — one vmapped launch where a single-claim fleet would pay
        K device round trips. K is the claim-K ceiling clamped to local
        admission headroom (a claim can never overfill this box); the
        per-entry lease lifecycle is untouched, so a crash mid-batch
        re-queues exactly the unfinished members."""
        if self._stopping:
            return False
        with self._lock:
            room = self.max_inflight - len(self._inflight)
            ring = self._ring
        if room <= 0:
            return False
        k = room if self.claim_batch <= 0 else min(self.claim_batch, room)
        if ring is None:
            ring = self._refresh_ring()
            if ring is None:
                return False
        arcs = ring.arcs(self.replica_id)
        entries: list = []
        stolen = False
        try:
            entries = self.store.claim_batch(
                self.replica_id, self.lease_s, k, arcs
            )
            if not entries and self.steal:
                # own arc empty: steal ANY queued work — affinity is a
                # preference, idle capacity is waste
                entries = self.store.claim_batch(
                    self.replica_id, self.lease_s, k, None
                )
                stolen = bool(entries)
        except Exception as exc:
            self._store_error("claim", exc)
            return False
        if not entries:
            return False
        kind = "steal" if stolen else "own"
        self._note_claims(entries)
        self._emit("claim_batch", size=len(entries), kind=kind)
        now = time.monotonic()
        jobs: list[tuple[Job, dict]] = []
        for entry in entries:
            entry["_renew_mono"] = now + self.lease_s / 2.0
            # the materialized job's trace records how it was claimed
            entry["_claim_batch"] = len(entries)
            entry["_claim_kind"] = kind
            self._emit(
                "claim",
                jobId=entry.get("id"),
                kind=kind,
                attempt=entry.get("attempt"),
                slot=entry.get("slot"),
                batch=len(entries),
            )
            try:
                job = self._materialize(entry)
            except Exception as exc:
                # materialize must not raise; if it does, fail the
                # entry clean rather than leave the lease to expire
                # into a pointless second attempt of a job that cannot
                # build
                job = Job(payload={})
                job.id = str(entry.get("id"))
                job.errors = [{
                    "what": "Scheduler error",
                    "reason": (
                        f"materialize failed: {type(exc).__name__}: {exc}"
                    ),
                }]
                job.finish(FAILED)
            jobs.append((job, entry))
        # pre-assembly hints by LOCAL bucket: same-claim entries share a
        # ring token but may split into different launch buckets (budget
        # variants). Hints DESCEND through each group (G, G-1, ..., 1):
        # a member's hint counts itself plus the mates submitted AFTER
        # it, so whichever member leads a gather — the group's first, or
        # the first leftover after a max_batch-capped launch consumed
        # the rest — knows exactly how many same-claim jobs can still
        # arrive and never sleeps out the window waiting for members
        # that already launched.
        counts: dict = {}
        for job, _ in jobs:
            if not job.done_event.is_set() and job.bucket is not None:
                counts[job.bucket] = counts.get(job.bucket, 0) + 1
        progressed = False
        for job, entry in jobs:
            if job.done_event.is_set():
                # born terminal (cache hit, trivial, or failed to
                # build): nothing to schedule — ack and publish here
                acked = False
                try:
                    acked = self.store.ack(self.replica_id, job.id)
                except Exception as exc:
                    self._store_error("ack", exc)
                self._finish(job, entry, acked)
                progressed = True
                continue
            job.batch_hint = counts.get(job.bucket, 0)
            if job.bucket is not None:
                counts[job.bucket] -= 1
            try:
                self._submit(job)
            except QueueFull:
                # local admission full: hand the entry back untouched
                # (no attempt burned) and back off — a peer with room
                # takes it. Batch-mates already submitted keep running;
                # their gather hint is bounded by the window, so a
                # nacked mate costs latency, never a hang.
                try:
                    self.store.nack(self.replica_id, job.id)
                except Exception as exc:
                    self._store_error("nack", exc)
                self._emit("nack", jobId=job.id)
                self._backoff_until = time.monotonic() + 5 * self.poll_s
                continue
            with self._lock:
                self._inflight[job.id] = (job, entry, False)
            progressed = True
        return progressed
