"""Device-owning workers + the Scheduler facade + the watchdog.

One Worker thread per backend label owns that backend's device queue:
it is the ONLY thread that runs solver code for its backend, so N HTTP
threads can never contend the accelerator (they park on Job.done_event
instead). The worker's loop is: pop oldest job -> gather same-bucket
jobs for the micro-batch window (sched.batcher) -> expire jobs whose
queue wait already spent their deadline budget -> hand the batch to the
injected `runner`.

The runner is dependency-injected (the service provides one that knows
how to prepare/solve/finish requests) so this package stays free of
jax/service imports and testable with stub runners. Contract:

    runner(jobs: list[Job]) -> None

It must fill each job's `result` (success) or `errors` (failure); the
worker owns every status transition and ALWAYS completes each job
(runner exceptions fail the whole batch cleanly — a job can never be
left un-terminal, so a submit-and-wait caller can never hang).

Supervision (ISSUE 3): a dead or wedged worker must not strand every
future job. The Scheduler runs a watchdog thread that checks each
worker every `watchdog_s` seconds:

  * **dead** — the thread exited (a runner raised a BaseException the
    batch guard does not catch, or a bug in the loop itself);
  * **wedged** — a batch has been running past every member job's
    remaining deadline budget plus `wedge_grace_s` (deadline checks
    inside solvers are block-granular; the grace absorbs that).
    Batches containing any unbounded job are exempt — there is no
    budget to measure against.

Recovery swaps in a fresh Worker (new thread + new queue: the old
queue is closed so an abandoned-but-alive thread can never race the
replacement for new work), restores the old queue's pending jobs in
FIFO order, and re-admits the in-flight batch exactly once per job
(`job.requeued`); a job whose SECOND run also crashes fails with a
clean "Scheduler crashed" envelope instead of crash-looping. Job
events: `requeued`, `crashed`; worker events via `on_worker_event`.

`on_event(name, job)` is an optional observer hook (the service wires
metrics + structured logs + store persistence there); observer failures
are swallowed — telemetry must never kill the device loop. Events:
queued, expired, started, done, failed, runner_error, requeued,
crashed, drained.
"""

from __future__ import annotations

import threading
import time

from vrpms_tpu.sched.batcher import gather_batch
from vrpms_tpu.sched.queue import (
    DONE,
    FAILED,
    RUNNING,
    Job,
    JobQueue,
    QueueFull,
)


def expired(job: Job, now_mono: float | None = None) -> bool:
    """Queue wait already spent the job's whole budget?

    Only a POSITIVE time limit can expire: explicit 0 keeps its
    "stop as soon as possible" semantics (service.solve._deadline) and
    None is unbounded — both always run.
    """
    if not job.time_limit or job.time_limit <= 0:
        return False
    now = time.monotonic() if now_mono is None else now_mono
    return (now - job.submitted_mono) >= job.time_limit


class Worker(threading.Thread):
    """Drains one backend's queue forever (daemon; stop() to end)."""

    def __init__(
        self,
        backend: str,
        queue: JobQueue,
        runner,
        window_s: float,
        max_batch: int,
        on_event=None,
    ):
        super().__init__(name=f"vrpms-sched-{backend}", daemon=True)
        self.backend = backend
        self.queue = queue
        self._runner = runner
        self._window_s = window_s
        self._max_batch = max_batch
        self._on_event = on_event
        self._halt = threading.Event()
        # supervision surface: what is in flight and for how long it
        # may legitimately run (None budget = unbounded, never wedged)
        self._inflight_lock = threading.Lock()
        self._inflight: list[Job] = []  # guarded-by: _inflight_lock
        self._inflight_since: float | None = None  # guarded-by: _inflight_lock
        self._inflight_budget: float | None = None  # guarded-by: _inflight_lock

    def stop(self) -> None:
        self._halt.set()

    def _emit(self, name: str, job: Job) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(name, job)
        except Exception:
            pass  # observers must never kill the device loop

    # -- supervision surface ------------------------------------------------
    def snapshot_inflight(self) -> list[Job]:
        with self._inflight_lock:
            return list(self._inflight)

    def wedged(self, now_mono: float, grace_s: float) -> bool:
        """Running past every member job's budget (plus grace)?"""
        with self._inflight_lock:
            since, budget = self._inflight_since, self._inflight_budget
        if since is None or budget is None:
            return False
        return now_mono - since > budget + grace_s

    def run(self) -> None:  # pragma: no cover - exercised via Scheduler
        while not self._halt.is_set():
            job = self.queue.pop(timeout=0.1)
            if job is None:
                continue
            # the popped job is in NO queue now — and neither is any
            # batch-mate the gather takes: publish each to the
            # supervision snapshot the moment it leaves the queue, so
            # a thread death anywhere from here on loses nothing
            # (budget stays None until the batch actually starts — no
            # wedge detection against gather time)
            with self._inflight_lock:
                self._inflight = [job]
                self._inflight_since = self._inflight_budget = None
            batch = gather_batch(
                self.queue, job, self._window_s, self._max_batch,
                on_take=self._publish_inflight,
            )
            self._publish_inflight(batch)
            self._run_batch(batch)

    def _publish_inflight(self, jobs: list[Job]) -> None:
        with self._inflight_lock:
            self._inflight = list(jobs)

    def _run_batch(self, batch: list[Job]) -> None:
        now = time.monotonic()
        live: list[Job] = []
        for job in batch:
            if job.done_event.is_set():
                # a requeued job the abandoned worker later completed
                continue
            job.queue_wait_s = now - job.submitted_mono
            if expired(job, now):
                # never start a job with a spent budget — the client's
                # deadline contract includes the time WE made it wait
                job.errors = [{
                    "what": "Deadline exceeded",
                    "reason": (
                        f"job waited {job.queue_wait_s:.3f}s in queue, "
                        f"past its timeLimit of {job.time_limit}s"
                    ),
                }]
                job.finish(FAILED)
                self._emit("expired", job)
            else:
                live.append(job)
        if not live:
            with self._inflight_lock:
                self._inflight = []
                self._inflight_since = self._inflight_budget = None
            return
        t0 = time.monotonic()
        # wedge budget = SUM of member budgets: the runner may legally
        # run members sequentially (batched-launch fallback retries
        # each solo; sub-half-budget members are split to the solo path
        # too — service.jobs._runner), so the max alone would declare a
        # healthy sequential worker wedged and double-solve its batch
        budget = 0.0
        for job in live:
            if not job.time_limit or job.time_limit <= 0:
                budget = None  # any unbounded job exempts the batch
                break
            budget += max(0.0, job.time_limit - (job.queue_wait_s or 0.0))
        with self._inflight_lock:
            self._inflight = list(live)
            self._inflight_since = t0
            self._inflight_budget = budget
        for job in live:
            job.status = RUNNING
            job.started_at = time.time()
            job.batch_size = len(live)
            self._emit("started", job)
        # NOTE deliberately no `finally` around the runner: on a
        # BaseException (thread death) the in-flight snapshot must
        # SURVIVE so the watchdog can requeue exactly these jobs.
        try:
            self._runner(live)
        except Exception as e:  # a runner bug must not strand waiters
            for job in live:
                if not job.done_event.is_set():
                    job.errors = job.errors or [{
                        "what": "Scheduler error",
                        "reason": f"{type(e).__name__}: {e}",
                    }]
                    # the envelope alone leaves scheduler bugs invisible
                    # to operators: surface a reason-labeled failure
                    # metric + structured event (service maps this to
                    # jobs_failed{reason="runner"})
                    self._emit("runner_error", job)
        elapsed = time.monotonic() - t0
        self.queue.note_job_seconds(elapsed / len(live))
        if self.queue.policy is not None:
            # per-class drain rate: each member's class observed at the
            # batch's per-job cost — the policy prices that class's
            # Retry-After from it (sched.qos.QosPolicy.retry_after)
            for job in live:
                self.queue.policy.note_done(job.qos, elapsed / len(live))
        for job in live:
            if job.done_event.is_set():
                continue
            if job.result is not None:
                job.finish(DONE)
                self._emit("done", job)
            else:
                job.errors = job.errors or [{
                    "what": "Scheduler error",
                    "reason": "runner returned neither result nor errors",
                }]
                job.finish(FAILED)
                self._emit("failed", job)
        with self._inflight_lock:
            self._inflight = []
            self._inflight_since = self._inflight_budget = None


class Scheduler:
    """Admission front + per-backend workers + watchdog + drain.

    submit() never blocks and never runs solver code; it either admits
    the job to its backend's bounded queue or raises QueueFull. Workers
    are created lazily per backend label so a deployment that only ever
    sees default-backend requests runs exactly one device loop.
    """

    def __init__(
        self,
        runner,
        queue_limit: int = 64,
        window_s: float = 0.01,
        max_batch: int = 16,
        on_event=None,
        watchdog_s: float = 0.5,
        wedge_grace_s: float = 10.0,
        on_worker_event=None,
        queue_policy=None,
    ):
        self._runner = runner
        self._queue_limit = queue_limit
        self._window_s = window_s
        self._max_batch = max_batch
        self._on_event = on_event
        # QoS policy (sched.qos.QosPolicy) shared by every backend's
        # queue: priority pop / selective shed / free-rider gather.
        # None (the default, and VRPMS_QOS=off) = plain FIFO queues.
        self._queue_policy = queue_policy
        self._watchdog_s = watchdog_s
        self._wedge_grace_s = wedge_grace_s
        self._on_worker_event = on_worker_event
        self._workers: dict[str, Worker] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._shutdown = False  # guarded-by: _lock
        self._watchdog: threading.Thread | None = None  # guarded-by: _lock
        self.restarts: dict[str, int] = {}  # guarded-by: _lock
        self.last_restart_mono: float | None = None  # guarded-by: _lock

    @property
    def is_shutdown(self) -> bool:
        with self._lock:
            return self._shutdown

    def _make_worker(self, backend: str) -> Worker:
        return Worker(
            backend,
            JobQueue(self._queue_limit, policy=self._queue_policy),
            self._runner,
            self._window_s,
            self._max_batch,
            self._on_event,
        )

    def _worker(self, backend: str) -> Worker:
        with self._lock:
            if self._shutdown:
                raise QueueFull(0, 1.0)
            w = self._workers.get(backend)
            if w is None:
                w = self._make_worker(backend)
                self._workers[backend] = w
                w.start()
            if self._watchdog is None and self._watchdog_s:
                self._watchdog = threading.Thread(
                    target=self._watch, name="vrpms-sched-watchdog",
                    daemon=True,
                )
                self._watchdog.start()
            return w

    def submit(self, job: Job, backend: str = "default") -> Job:
        """Admit `job` onto `backend`'s queue (QueueFull on rejection)."""
        worker = self._worker(backend or "default")
        worker.queue.push(job)
        if self._on_event is not None:
            try:
                self._on_event("queued", job)
            except Exception:
                pass
        return job

    def depth(self, backend: str = "default") -> int:
        with self._lock:
            w = self._workers.get(backend or "default")
        return 0 if w is None else len(w.queue)

    def queues(self) -> dict[str, int]:
        with self._lock:
            return {b: len(w.queue) for b, w in self._workers.items()}

    def queues_by_class(self) -> dict[str, dict]:
        """{backend: {class: depth}} — per-class admission-queue view
        for the readiness probe; empty maps with no QoS policy."""
        with self._lock:
            pairs = list(self._workers.items())
        return {b: w.queue.depth_by_class() for b, w in pairs}

    # -- supervision --------------------------------------------------------
    def worker_health(self) -> dict[str, str]:
        """{backend: ok|wedged|dead} — the readiness probe's view."""
        with self._lock:
            pairs = list(self._workers.items())
        now = time.monotonic()
        out = {}
        for backend, w in pairs:
            if not w.is_alive():
                out[backend] = "dead"
            elif w.wedged(now, self._wedge_grace_s):
                out[backend] = "wedged"
            else:
                out[backend] = "ok"
        return out

    def _watch(self) -> None:  # pragma: no cover - timing-driven loop
        while True:
            time.sleep(self._watchdog_s)
            with self._lock:
                if self._shutdown:
                    return
                pairs = list(self._workers.items())
            now = time.monotonic()
            for backend, w in pairs:
                reason = None
                if not w.is_alive():
                    reason = "died"
                elif w.wedged(now, self._wedge_grace_s):
                    reason = "wedged"
                if reason is not None:
                    try:
                        self._restart(backend, w, reason)
                    except Exception:
                        pass  # the watchdog itself must never die

    def _emit_job(self, name: str, job: Job) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(name, job)
        except Exception:
            pass

    def _restart(self, backend: str, old: Worker, reason: str) -> None:
        """Replace `old` with a fresh worker, preserving its work.

        Swap first (new submits land on the replacement's queue), THEN
        move jobs, THEN start the thread — so restored jobs keep their
        FIFO position ahead of anything submitted during the swap.

        A WEDGED (still-alive) worker cannot be killed, only
        superseded: until its runner returns, its solve runs
        concurrently with the replacement's — the one deliberate breach
        of the one-solver-thread-per-backend invariant, priced against
        stranding every future job. Size wedge_grace_s above the
        slowest legitimate stall (cold jit compiles!) so a slow batch
        is never mistaken for a hung one.
        """
        with self._lock:
            if self._shutdown or self._workers.get(backend) is not old:
                return  # already replaced (or shutting down)
            replacement = self._make_worker(backend)
            self._workers[backend] = replacement
            self.restarts[backend] = self.restarts.get(backend, 0) + 1
            self.last_restart_mono = time.monotonic()
        old.stop()
        pending = old.queue.drain()  # closes the old queue for good
        readmit: list[Job] = []
        for job in old.snapshot_inflight():
            if job.done_event.is_set():
                continue
            if job.requeued:
                # second loss of the same job: poison — fail it clean,
                # with an honest cause (a wedged worker never crashed;
                # it overran its budget — likely the job itself is the
                # reason both runs stalled)
                if reason == "died":
                    what, how = "Scheduler crashed", "crashed"
                else:
                    what, how = "Scheduler stalled", "overran its budget"
                job.errors = [{
                    "what": what,
                    "reason": (
                        f"worker {how} twice while running this job; "
                        "not requeueing again"
                    ),
                }]
                job.finish(FAILED)
                self._emit_job("crashed", job)
            elif job.reopen_for_requeue():
                # atomic vs. a racing finish() from a still-alive
                # wedged thread; result/errors are left alone (that
                # thread may be writing them — the retry overwrites)
                readmit.append(job)
                self._emit_job("requeued", job)
        rejected = replacement.queue.restore(readmit + pending)
        for job in rejected:  # only possible if shutdown raced us
            job.errors = [{
                "what": "Service unavailable",
                "reason": "scheduler shut down during worker restart",
            }]
            job.finish(FAILED)
            self._emit_job("drained", job)
        replacement.start()
        if self._on_worker_event is not None:
            try:
                self._on_worker_event("restart", backend, reason)
            except Exception:
                pass

    def shutdown(self, timeout: float = 5.0) -> int:
        """Drain: stop admission, fail every queued job cleanly, stop
        workers. Returns the number of jobs drained. Idempotent."""
        with self._lock:
            if self._shutdown:
                return 0
            self._shutdown = True
            workers = list(self._workers.values())
        drained = 0
        for w in workers:
            w.stop()
            for job in w.queue.drain():
                job.errors = [{
                    "what": "Service unavailable",
                    "reason": "scheduler shutting down before this job ran",
                }]
                job.finish(FAILED)
                drained += 1
                if self._on_event is not None:
                    try:
                        self._on_event("drained", job)
                    except Exception:
                        pass
        for w in workers:
            # a restart racing shutdown may have swapped in a
            # replacement that was never started (its halt flag and
            # closed queue make start-after-shutdown a no-op loop);
            # joining an unstarted thread raises
            if w.is_alive():
                w.join(timeout)
        return drained
