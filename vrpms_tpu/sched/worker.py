"""Device-owning workers + the Scheduler facade.

One Worker thread per backend label owns that backend's device queue:
it is the ONLY thread that runs solver code for its backend, so N HTTP
threads can never contend the accelerator (they park on Job.done_event
instead). The worker's loop is: pop oldest job -> gather same-bucket
jobs for the micro-batch window (sched.batcher) -> expire jobs whose
queue wait already spent their deadline budget -> hand the batch to the
injected `runner`.

The runner is dependency-injected (the service provides one that knows
how to prepare/solve/finish requests) so this package stays free of
jax/service imports and testable with stub runners. Contract:

    runner(jobs: list[Job]) -> None

It must fill each job's `result` (success) or `errors` (failure); the
worker owns every status transition and ALWAYS completes each job
(runner exceptions fail the whole batch cleanly — a job can never be
left un-terminal, so a submit-and-wait caller can never hang).

`on_event(name, job)` is an optional observer hook (the service wires
metrics + structured logs + store persistence there); observer failures
are swallowed — telemetry must never kill the device loop. Events:
queued, expired, started, done, failed, drained.
"""

from __future__ import annotations

import threading
import time

from vrpms_tpu.sched.batcher import gather_batch
from vrpms_tpu.sched.queue import (
    DONE,
    FAILED,
    RUNNING,
    Job,
    JobQueue,
    QueueFull,
)


def expired(job: Job, now_mono: float | None = None) -> bool:
    """Queue wait already spent the job's whole budget?

    Only a POSITIVE time limit can expire: explicit 0 keeps its
    "stop as soon as possible" semantics (service.solve._deadline) and
    None is unbounded — both always run.
    """
    if not job.time_limit or job.time_limit <= 0:
        return False
    now = time.monotonic() if now_mono is None else now_mono
    return (now - job.submitted_mono) >= job.time_limit


class Worker(threading.Thread):
    """Drains one backend's queue forever (daemon; stop() to end)."""

    def __init__(
        self,
        backend: str,
        queue: JobQueue,
        runner,
        window_s: float,
        max_batch: int,
        on_event=None,
    ):
        super().__init__(name=f"vrpms-sched-{backend}", daemon=True)
        self.backend = backend
        self.queue = queue
        self._runner = runner
        self._window_s = window_s
        self._max_batch = max_batch
        self._on_event = on_event
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()

    def _emit(self, name: str, job: Job) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(name, job)
        except Exception:
            pass  # observers must never kill the device loop

    def run(self) -> None:  # pragma: no cover - exercised via Scheduler
        while not self._halt.is_set():
            job = self.queue.pop(timeout=0.1)
            if job is None:
                continue
            batch = gather_batch(
                self.queue, job, self._window_s, self._max_batch
            )
            self._run_batch(batch)

    def _run_batch(self, batch: list[Job]) -> None:
        now = time.monotonic()
        live: list[Job] = []
        for job in batch:
            job.queue_wait_s = now - job.submitted_mono
            if expired(job, now):
                # never start a job with a spent budget — the client's
                # deadline contract includes the time WE made it wait
                job.errors = [{
                    "what": "Deadline exceeded",
                    "reason": (
                        f"job waited {job.queue_wait_s:.3f}s in queue, "
                        f"past its timeLimit of {job.time_limit}s"
                    ),
                }]
                job.finish(FAILED)
                self._emit("expired", job)
            else:
                live.append(job)
        if not live:
            return
        t0 = time.monotonic()
        for job in live:
            job.status = RUNNING
            job.started_at = time.time()
            job.batch_size = len(live)
            self._emit("started", job)
        try:
            self._runner(live)
        except Exception as e:  # a runner bug must not strand waiters
            for job in live:
                if not job.done_event.is_set():
                    job.errors = job.errors or [{
                        "what": "Scheduler error",
                        "reason": f"{type(e).__name__}: {e}",
                    }]
        elapsed = time.monotonic() - t0
        self.queue.note_job_seconds(elapsed / len(live))
        for job in live:
            if job.done_event.is_set():
                continue
            if job.result is not None:
                job.finish(DONE)
                self._emit("done", job)
            else:
                job.errors = job.errors or [{
                    "what": "Scheduler error",
                    "reason": "runner returned neither result nor errors",
                }]
                job.finish(FAILED)
                self._emit("failed", job)


class Scheduler:
    """Admission front + per-backend workers + drain-on-shutdown.

    submit() never blocks and never runs solver code; it either admits
    the job to its backend's bounded queue or raises QueueFull. Workers
    are created lazily per backend label so a deployment that only ever
    sees default-backend requests runs exactly one device loop.
    """

    def __init__(
        self,
        runner,
        queue_limit: int = 64,
        window_s: float = 0.01,
        max_batch: int = 16,
        on_event=None,
    ):
        self._runner = runner
        self._queue_limit = queue_limit
        self._window_s = window_s
        self._max_batch = max_batch
        self._on_event = on_event
        self._workers: dict[str, Worker] = {}
        self._lock = threading.Lock()
        self._shutdown = False

    def _worker(self, backend: str) -> Worker:
        with self._lock:
            if self._shutdown:
                raise QueueFull(0, 1.0)
            w = self._workers.get(backend)
            if w is None:
                w = Worker(
                    backend,
                    JobQueue(self._queue_limit),
                    self._runner,
                    self._window_s,
                    self._max_batch,
                    self._on_event,
                )
                self._workers[backend] = w
                w.start()
            return w

    def submit(self, job: Job, backend: str = "default") -> Job:
        """Admit `job` onto `backend`'s queue (QueueFull on rejection)."""
        worker = self._worker(backend or "default")
        worker.queue.push(job)
        if self._on_event is not None:
            try:
                self._on_event("queued", job)
            except Exception:
                pass
        return job

    def depth(self, backend: str = "default") -> int:
        w = self._workers.get(backend or "default")
        return 0 if w is None else len(w.queue)

    def queues(self) -> dict[str, int]:
        with self._lock:
            return {b: len(w.queue) for b, w in self._workers.items()}

    def shutdown(self, timeout: float = 5.0) -> int:
        """Drain: stop admission, fail every queued job cleanly, stop
        workers. Returns the number of jobs drained. Idempotent."""
        with self._lock:
            if self._shutdown:
                return 0
            self._shutdown = True
            workers = list(self._workers.values())
        drained = 0
        for w in workers:
            w.stop()
            for job in w.queue.drain():
                job.errors = [{
                    "what": "Service unavailable",
                    "reason": "scheduler shutting down before this job ran",
                }]
                job.finish(FAILED)
                drained += 1
                if self._on_event is not None:
                    try:
                        self._on_event("drained", job)
                    except Exception:
                        pass
        for w in workers:
            w.join(timeout)
        return drained
