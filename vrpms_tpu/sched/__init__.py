"""Async solve scheduler: admission queue, shape-bucketed micro-batcher,
device-owning workers, watchdog supervision.

The subsystem between the HTTP layer and the jit-compiled solvers
(ROADMAP "serves heavy traffic"): requests become Jobs on a bounded
queue; one worker per backend drains it, merging same-shape jobs into
one batched/vmapped launch (sched.batch.solve_sa_batch) within a small
gather window. A watchdog restarts dead/wedged workers and re-admits
their in-flight batch exactly once (sched.worker). With a QoS policy
attached (sched.qos) the queues become deadline- and class-aware:
priority pop, EDF within class, selective shed, free-rider batch
fill. Generic pieces here are stdlib-only; the service wires the
runner, the jobs HTTP surface, and persistence (service.jobs).
"""

from vrpms_tpu.sched import qos
from vrpms_tpu.sched.batcher import gather_batch
from vrpms_tpu.sched.queue import (
    DONE,
    FAILED,
    QUEUED,
    RUNNING,
    Job,
    JobQueue,
    QueueFull,
)
from vrpms_tpu.sched.replica import Replica
from vrpms_tpu.sched.ring import SLOTS, HashRing, slot
from vrpms_tpu.sched.worker import Scheduler, Worker, expired

__all__ = [
    "DONE",
    "FAILED",
    "QUEUED",
    "RUNNING",
    "SLOTS",
    "HashRing",
    "Job",
    "JobQueue",
    "QueueFull",
    "Replica",
    "Scheduler",
    "Worker",
    "expired",
    "gather_batch",
    "qos",
    "slot",
]
