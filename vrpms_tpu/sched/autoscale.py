"""Elastic-fleet controller: how many replicas does the backlog need.

ISSUE 18's policy layer. Every *signal* it consumes already exists —
shared queue depth by class (PR 11's depth memo), per-class drain EWMAs
(PR 12's ``QosPolicy.class_seconds``), per-replica claim mix and warmed
tiers (PR 14's status docs) — and every *actuator* exists too
(checkpoint-drain from PR 15, arc-weighted warmup from PR 11). This
module closes the loop as pure arithmetic:

    work_seconds   = sum over classes of depth_c x drain_seconds_c
    raw            = ceil(work_seconds / (headroom_s x per_replica))
    desired        = clamp(raw, [VRPMS_AUTOSCALE_MIN, VRPMS_AUTOSCALE_MAX])

i.e. "the smallest fleet that drains today's backlog inside the
deadline headroom, given each replica runs ``per_replica`` concurrent
leases". Two dampers keep the signal actuator-safe:

  * **hysteresis** — a downward move is only eligible when the smaller
    fleet would still sit below ``1 - VRPMS_AUTOSCALE_HYSTERESIS`` of
    its capacity, so a marginal backlog wiggle at the boundary cannot
    flap the recommendation;
  * **cooldown** — scale-UP applies immediately (deadlines are at
    stake), scale-DOWN only after the down-signal has persisted for
    ``VRPMS_AUTOSCALE_COOLDOWN_S`` seconds.

The controller *fails open*: when the store is unreadable the inputs
are ``None`` and :meth:`Controller.observe` freezes the last-known
recommendation marked ``degraded`` — it never guesses from partial
data and never touches the solve path.

Also here, because they are pure functions of ring snapshots / status
docs and the tests want them without HTTP:

  * :func:`inherited_tokens` — which routing tokens a member owns on
    the new ring but not the old one (exactly what churn-hardening
    warmup must compile);
  * :func:`moved_fraction` — fraction of slot space whose owner
    changed between two rings (the ~1/N churn bound);
  * :func:`choose_victim` — scale-in victim by claim-mix overlap:
    drain the replica whose hot tiers the survivors already have warm.

Stdlib-only besides :mod:`vrpms_tpu.config` and the sibling
:mod:`vrpms_tpu.sched.ring`, like the rest of the sched package.
"""

from __future__ import annotations

import math
import threading

from vrpms_tpu import config
from vrpms_tpu.sched.ring import SLOTS, slot


def enabled() -> bool:
    """The one autoscale switch (``VRPMS_AUTOSCALE``): off runs no
    controller, adds no fleet block, and keeps every pre-autoscale
    response byte-identical."""
    return config.enabled("VRPMS_AUTOSCALE")


def work_seconds(depth, class_depths, class_seconds, job_seconds) -> float:
    """Backlog expressed as drain work: each class's depth priced at
    its observed per-job drain seconds. Jobs outside the per-class
    split (or the whole backlog, when no split is readable) price at
    the class-agnostic ``job_seconds`` EWMA."""
    per_job = max(1e-3, float(job_seconds or 1.0))
    total_depth = max(0, int(depth or 0))
    if not class_depths:
        return total_depth * per_job
    secs = class_seconds or {}
    total = 0.0
    counted = 0
    for cls, n in class_depths.items():
        n = max(0, int(n or 0))
        total += n * max(1e-3, float(secs.get(cls) or per_job))
        counted += n
    # depth memo and class split are separate reads; price any
    # remainder the split missed at the class-agnostic rate
    total += max(0, total_depth - counted) * per_job
    return total


def required_replicas(work_s: float, headroom_s: float, per_replica: int) -> int:
    """The QoS-feasible minimum: smallest fleet whose combined lease
    concurrency drains ``work_s`` seconds of backlog within the
    deadline headroom. Always at least 1 — an idle fleet still serves."""
    capacity = max(1e-3, float(headroom_s)) * max(1, int(per_replica))
    return max(1, math.ceil(max(0.0, float(work_s)) / capacity))


class Controller:
    """Hysteresis + cooldown state machine over the raw recommendation.

    One instance per process (the service layer owns the singleton).
    ``observe(inputs, now)`` is the whole API: inputs is either a dict
    of signals or ``None`` for "store unreadable", and the return value
    is the JSON-safe recommendation block ``/api/debug/fleet`` and the
    ``vrpms_fleet_desired_replicas`` gauge publish.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._desired: int | None = None  # guarded-by: _lock
        self._degraded = False  # guarded-by: _lock
        self._changed_at: float | None = None  # guarded-by: _lock
        self._down_since: float | None = None  # guarded-by: _lock
        self._last: dict = {}  # guarded-by: _lock

    def _clamp(self, raw: int) -> int:
        lo = max(1, int(config.get("VRPMS_AUTOSCALE_MIN")))
        hi = int(config.get("VRPMS_AUTOSCALE_MAX"))
        if hi > 0:
            raw = min(raw, max(lo, hi))
        return max(lo, raw)

    def observe(self, inputs: dict | None, now: float) -> dict:
        """Fold one observation into the recommendation.

        ``inputs`` keys (all optional): ``depth`` (shared queue depth),
        ``classDepths`` ({class: depth}), ``classSeconds`` ({class:
        drain EWMA}), ``jobSeconds`` (class-agnostic EWMA),
        ``members`` (live fleet size), ``perReplica`` (max concurrent
        leases per replica). ``None`` inputs = store unreadable: the
        last-known recommendation is frozen and marked degraded.
        """
        with self._lock:
            if inputs is None:
                self._degraded = True
                self._down_since = None  # a blind down-signal never ages
                if self._desired is None:
                    self._desired = self._clamp(1)
                rec = dict(
                    self._last,
                    desired=self._desired,
                    degraded=True,
                    decision="frozen",
                )
                self._last = rec
                return dict(rec)

            headroom = max(1e-3, float(config.get("VRPMS_AUTOSCALE_HEADROOM_S")))
            cooldown = max(0.0, float(config.get("VRPMS_AUTOSCALE_COOLDOWN_S")))
            hyst = min(0.9, max(0.0, float(config.get("VRPMS_AUTOSCALE_HYSTERESIS"))))
            per_replica = max(1, int(inputs.get("perReplica") or 1))
            work_s = work_seconds(
                inputs.get("depth"),
                inputs.get("classDepths"),
                inputs.get("classSeconds"),
                inputs.get("jobSeconds"),
            )
            raw = self._clamp(required_replicas(work_s, headroom, per_replica))

            self._degraded = False
            if self._desired is None:
                self._desired = raw
                self._changed_at = now
                decision = "init"
            elif raw > self._desired:
                # deadlines are at stake: scale-up is immediate
                self._desired = raw
                self._changed_at = now
                self._down_since = None
                decision = "up"
            elif raw < self._desired:
                # hysteresis: the smaller fleet must keep slack, or a
                # boundary wiggle would re-raise the signal next tick
                fits = work_s <= (1.0 - hyst) * raw * headroom * per_replica
                if not fits:
                    self._down_since = None
                    decision = "hold"
                else:
                    if self._down_since is None:
                        self._down_since = now
                    if now - self._down_since >= cooldown:
                        self._desired = raw
                        self._changed_at = now
                        self._down_since = None
                        decision = "down"
                    else:
                        decision = "cooldown"
            else:
                self._down_since = None
                decision = "hold"

            rec = {
                "desired": self._desired,
                "raw": raw,
                "decision": decision,
                "degraded": False,
                "workSeconds": round(work_s, 4),
                "headroomS": headroom,
                "cooldownS": cooldown,
                "hysteresis": hyst,
                "perReplica": per_replica,
                "members": max(0, int(inputs.get("members") or 0)),
                "depth": max(0, int(inputs.get("depth") or 0)),
                "classDepths": dict(inputs.get("classDepths") or {}),
                "cooldownRemaining": (
                    round(max(0.0, cooldown - (now - self._down_since)), 3)
                    if self._down_since is not None
                    else 0.0
                ),
                "changedAt": self._changed_at,
            }
            self._last = rec
            return dict(rec)

    def desired(self) -> int:
        """Last published recommendation (gauge value); 1 before any
        observation — a fleet that has seen nothing still serves."""
        with self._lock:
            return self._desired if self._desired is not None else 1

    def last(self) -> dict:
        """Last recommendation block (empty dict before first observe)."""
        with self._lock:
            return dict(self._last)


# -- churn geometry ---------------------------------------------------------


def inherited_tokens(old_ring, new_ring, member: str, tokens) -> list:
    """Routing tokens `member` owns on `new_ring` that it did NOT own
    on `old_ring` — exactly the tiers churn-hardening warmup must
    compile. ``old_ring=None`` means the member is new: everything it
    now owns is inherited. Order of `tokens` is preserved."""
    out = []
    for tok in tokens:
        s = slot(tok)
        if new_ring is None or new_ring.owner(s) != member:
            continue
        if old_ring is None or old_ring.owner(s) != member:
            out.append(tok)
    return out


def moved_fraction(old_ring, new_ring) -> float:
    """Fraction of the slot space whose owner differs between two ring
    snapshots. Exact (walks the union of both rings' arc boundaries,
    inside which ownership is constant on both sides) — the property
    test asserts single-member churn moves ~1/N, the consistent-hash
    guarantee FIFO sharding lacks."""
    cuts = {0}
    for r in (old_ring, new_ring):
        for m in r.members:
            for lo, hi in r.arcs(m):
                cuts.add(lo % SLOTS)
                cuts.add(hi % SLOTS)
    bounds = sorted(cuts)
    moved = 0
    for i, lo in enumerate(bounds):
        hi = bounds[i + 1] if i + 1 < len(bounds) else SLOTS
        if hi > lo and old_ring.owner(lo) != new_ring.owner(lo):
            moved += hi - lo
    return moved / SLOTS


# -- scale-in victim selection ----------------------------------------------


def mix_tier(token) -> str | None:
    """Map a claim-mix ring token (``vrp:NxNxV:tw..:het..:td..``) to
    the warmed-tier key the warmup ledger uses (``NxV``); None for
    tokens that don't parse (claim mix may hold legacy keys)."""
    try:
        shape = str(token).split(":")[1]
        dims = shape.split("x")
        if len(dims) < 2:
            return None
        int(dims[0]), int(dims[-1])  # both must be numeric
        return f"{dims[0]}x{dims[-1]}"
    except (IndexError, ValueError):
        return None


def choose_victim(docs: dict) -> tuple[str | None, dict]:
    """Pick the scale-in victim from per-replica status docs: the
    non-draining replica whose claim-mix weight is best covered by the
    tiers the OTHER survivors already have warm — draining it re-homes
    its hot tiers onto warm caches, so scale-in costs the fewest cold
    compiles. Ties break toward fewer inflight jobs, then the lowest
    replica id (deterministic everywhere). Returns ``(victim, scores)``
    where scores maps each candidate to its coverage/inflight; victim
    is None when fewer than two candidates exist (never drain the last
    replica)."""
    candidates = [
        rid for rid, d in docs.items() if not (d or {}).get("draining")
    ]
    scores: dict = {}
    if len(candidates) < 2:
        return None, scores
    for rid in candidates:
        doc = docs.get(rid) or {}
        survivors_warm = set()
        for other in candidates:
            if other == rid:
                continue
            survivors_warm.update((docs.get(other) or {}).get("tiersWarmed") or [])
        mix = doc.get("claimMix") or {}
        total = sum(float(w or 0.0) for w in mix.values())
        covered = sum(
            float(w or 0.0)
            for tok, w in mix.items()
            if mix_tier(tok) in survivors_warm
        )
        # an idle replica (no claim mix) is perfectly safe to drain
        coverage = covered / total if total > 0 else 1.0
        scores[rid] = {
            "coverage": round(coverage, 4),
            "inflight": max(0, int(doc.get("inflight") or 0)),
        }
    victim = sorted(
        candidates,
        key=lambda r: (-scores[r]["coverage"], scores[r]["inflight"], r),
    )[0]
    return victim, scores
