"""Shape-bucketed micro-batching: gather same-bucket jobs into one launch.

The economics (vrpms paper: a serverless solve API; ROADMAP: serve it
at scale): jit-compiled solver programs are specialized by padded
instance shape, so K concurrent requests whose instances share a shape
can amortize ONE batched/vmapped launch instead of K sequential device
round trips. The bucket key is computed by the service when it prepares
the instance (service.jobs._bucket_key) — equal keys guarantee equal
array shapes, equal static metadata, and equal solver schedule, i.e.
everything a stacked launch requires.

The gather protocol: the worker pops the oldest job, then holds it for
at most `window_s` while same-bucket jobs accumulate, taking them out
of FIFO order (other buckets keep their order and are served next).
The window bounds added latency for the FIRST request of a burst; a
bucket that fills `max_batch` early launches immediately.
"""

from __future__ import annotations

import time

from vrpms_tpu.sched.queue import Job, JobQueue


def gather_batch(
    queue: JobQueue,
    first: Job,
    window_s: float,
    max_batch: int,
    on_take=None,
) -> list[Job]:
    """Collect jobs batchable with `first` (first included, FIFO order).

    Non-batchable jobs (bucket None) and a zero window return
    immediately — the solo path must not pay any gather latency beyond
    one lock acquisition.

    `on_take(batch)` fires with the full batch-so-far each time jobs
    are extracted from the queue: once taken they are in NO queue, so
    the caller must be able to publish them to its supervision
    snapshot immediately — a worker thread dying mid-gather must not
    strand batch-mates the watchdog cannot see (sched.worker).
    """
    batch = [first]
    if first.bucket is None or max_batch <= 1:
        return batch
    # a job from an already-assembled store claim (sched.replica) knows
    # how many same-bucket mates were submitted at or after it (hints
    # descend through the claim group): once they are all here there is
    # nothing left of ITS assembly to wait for, so sleeping out the
    # window would be dead latency (it still bounds the wait when a
    # hinted mate is late or died before reaching the queue). A
    # leftover group left behind by a max_batch-capped launch leads
    # with its own remaining count, so it never waits for members that
    # already launched. Deliberate tradeoff: a later claim ROUND could
    # still deliver same-bucket work inside the window, but coalescing
    # across rounds is claim-K's job at the store — the fleet contract
    # (ISSUE 11) prices per-job window latency above that long shot.
    hint = getattr(first, "batch_hint", 0) or 0
    deadline = time.monotonic() + max(window_s, 0.0)
    while len(batch) < max_batch:
        # the leader rides along so a QoS policy can apply the
        # free-rider fill rule (same-class mates first, lower classes
        # top off, same-class members never displaced) — with no
        # policy attached the extra argument changes nothing
        taken = queue.take_matching(
            first.bucket, max_batch - len(batch), leader=first
        )
        if taken:
            batch.extend(taken)
            if on_take is not None:
                on_take(batch)
        if len(batch) >= max_batch:
            break
        if hint and len(batch) >= hint:
            break  # the assembled set is complete
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        queue.wait_for_more(remaining)
    return batch
