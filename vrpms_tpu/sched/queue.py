"""Bounded admission queue for solve jobs.

The service's HTTP layer is a ThreadingHTTPServer: without admission
control, N concurrent requests mean N threads all dispatching to the
one accelerator at once — contending for the device queue and each
holding a connection for its full solver deadline. This queue is the
seam that decouples them: HTTP threads `push` (never block, never
solve), a single device-owning worker (sched.worker) drains.

Admission is strictly bounded: a full queue raises QueueFull
immediately (the service turns that into 429 + Retry-After) instead of
queueing unbounded work that would start with an already-spent deadline
budget. Jobs carry their submission clock so the worker can account
queue wait against the job's own time limit (sched.worker.expired).

Stdlib-only by design — no jax, no service imports — so the queue and
its tests run anywhere.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import typing
import uuid


#: Lifecycle states (the jobs API contract exposes these verbatim).
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


class QueueFull(Exception):
    """Admission rejected: the bounded queue is at capacity.

    `retry_after_s` is the queue's own estimate of when capacity frees
    up (depth x recent per-job seconds) — the service echoes it as the
    429 response's Retry-After header.
    """

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"queue full ({depth} jobs pending)")
        self.depth = depth
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class Job:
    """One unit of solver work moving through the scheduler.

    `payload` is opaque to this package (the service stores its prepared
    instance + request context there). `bucket` is the shape-batching
    key: jobs with EQUAL buckets may be merged into one batched launch
    (sched.batcher); None means never merge. `time_limit` is the
    request's nominal wall budget in seconds (None/0 = unbounded /
    stop-ASAP semantics, matching service._deadline).
    """

    payload: typing.Any
    bucket: typing.Hashable = None
    time_limit: float | None = None
    request_id: str | None = None
    # distributed-trace context: the submitting thread's Trace collector
    # and the Span worker-side spans should parent under. Opaque to this
    # package (vrpms_tpu.obs.spans objects in practice) — they simply
    # ride the Job through push/pop/take_matching/restore so the runner
    # can re-activate them on the far side of every thread hop,
    # including the watchdog's requeue (the retry keeps the same trace).
    trace: typing.Any = dataclasses.field(
        default=None, repr=False, compare=False
    )
    span: typing.Any = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # live-progress mailbox (vrpms_tpu.obs.progress.ProgressSink in
    # practice): opaque to this package, rides the Job through every
    # hop — queue, micro-batch gather, worker, watchdog requeue — so
    # the runner can publish block-cadence incumbents and honor
    # cooperative cancellation wherever the job lands
    sink: typing.Any = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # fleet-wide micro-batching: this job plus the number of same-bucket
    # mates submitted AFTER it from ONE already-assembled store claim
    # (sched.replica assigns G, G-1, ..., 1 through the group). The
    # gather window treats the set as pre-assembled: whichever member
    # leads a gather stops waiting the moment its hint is satisfied —
    # including the first leftover after a max_batch-capped launch
    # consumed its elders — and a hint of 1 means no batch-mate can
    # arrive, so the window is skipped entirely. 0 = a normal local
    # submit (window applies).
    batch_hint: int = 0
    # QoS (sched.qos): the request's priority class, its absolute EDF
    # deadline (epoch seconds; None = no deadline, sorts last within
    # its class), and the auth-scoped tenant identity fairness quotas
    # count against. All defaulted so a QoS-less submit (or
    # VRPMS_QOS=off, which attaches no policy at all) schedules
    # exactly like the pre-QoS FIFO contract.
    qos: str = "standard"
    deadline_at: float | None = None
    tenant: str | None = None
    # True for jobs that already passed an admission decision elsewhere
    # (store-claimed entries re-entering a local queue: they were
    # admitted at the SHARED bound when first submitted). The QoS
    # class-fraction shed skips them — shedding a claimed entry back
    # to the store would nack/re-claim it in a livelock, never solving
    # and never 429ing. The hard queue bound still applies (QueueFull
    # -> the replica's nack flow control, as before).
    preadmitted: bool = False
    # supervision: True once the watchdog re-admitted this job after a
    # worker crash — the SECOND crash fails it instead (at-most-one
    # requeue keeps a poison job from crash-looping the worker forever)
    requeued: bool = False
    id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:16]
    )
    status: str = QUEUED
    result: typing.Any = None
    errors: list = dataclasses.field(default_factory=list)
    # clocks: monotonic for wait accounting, epoch for the job record
    submitted_mono: float = dataclasses.field(default_factory=time.monotonic)
    submitted_at: float = dataclasses.field(default_factory=time.time)
    started_at: float | None = None
    finished_at: float | None = None
    queue_wait_s: float | None = None
    batch_size: int = 0
    done_event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False, compare=False
    )

    # guards finish vs. reopen_for_requeue: the watchdog must never
    # overwrite the status of a job a still-alive wedged thread is
    # finishing at the same instant
    _term_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def finish(self, status: str) -> None:
        """First terminal transition wins: after a wedged worker is
        superseded and its batch requeued, BOTH the abandoned thread
        (if it ever wakes) and the replacement may try to finish the
        same job — the late call must not flip an already-terminal
        status under a woken waiter."""
        with self._term_lock:
            if self.done_event.is_set():
                return
            self.status = status
            self.finished_at = time.time()
            self.done_event.set()

    def reopen_for_requeue(self) -> bool:
        """Atomically mark this job requeued-and-queued for its ONE
        supervised retry — or return False if a racing finish() already
        made it terminal (then the watchdog must leave it alone). The
        crashed run's elapsed time is forgiven: without a fresh
        submission clock the retry would expire the instant it popped."""
        with self._term_lock:
            if self.done_event.is_set():
                return False
            self.requeued = True
            self.status = QUEUED
            self.submitted_mono = time.monotonic()
            return True

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job reaches a terminal state."""
        return self.done_event.wait(timeout)


class JobQueue:
    """Bounded FIFO with bucket-aware extraction — and, with a QoS
    `policy` attached (sched.qos.QosPolicy), a priority queue.

    `pop` hands the worker the oldest job (policy attached: the
    highest-priority one — class rank then EDF, FIFO-stable on ties);
    `take_matching` then pulls additional same-bucket jobs out of FIFO
    order (the micro-batcher's gather — skipped jobs keep their
    relative order; policy attached: same-class mates fill first,
    lower classes ride as free riders). The policy also makes
    admission selective: `push` sheds lower classes before the hard
    bound (policy.admit). All operations are O(depth) under one lock;
    depth is bounded, so that is bounded too. No policy = the exact
    pre-QoS FIFO behavior.
    """

    def __init__(self, limit: int = 64, policy=None):
        if limit < 1:
            raise ValueError(f"queue limit must be >= 1, got {limit}")
        self.limit = limit
        #: sched.qos.QosPolicy or None; read-only after construction
        self.policy = policy
        self._items: list[Job] = []  # guarded-by: _lock
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False  # guarded-by: _lock
        self._pushes = 0  # guarded-by: _lock (wait_for_more watches this)
        # EWMA of per-job service seconds, maintained by the worker via
        # note_job_seconds — the Retry-After estimate's rate term.
        self._job_seconds = 1.0  # guarded-by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def note_job_seconds(self, seconds: float) -> None:
        with self._lock:
            self._job_seconds = 0.8 * self._job_seconds + 0.2 * max(
                seconds, 1e-3
            )

    def depth_by_class(self) -> dict:
        """{class: queued count} — the readiness probe's per-class
        view; empty when no policy is attached (QoS off)."""
        if self.policy is None:
            return {}
        with self._lock:
            return self.policy.depth_by_class(self._items)

    def _retry_after_locked(self) -> float:
        return min(max(1.0, len(self._items) * self._job_seconds), 60.0)

    def retry_after_s(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def push(self, job: Job) -> None:
        """Admit a job or raise QueueFull; never blocks. With a QoS
        policy attached the hard bound stays, but the policy may shed
        FIRST — lower classes stop admitting at their fraction of the
        bound, and the QueueFull carries that class's own Retry-After
        (policy.admit runs under the queue lock; it only reads)."""
        with self._lock:
            if self._closed:
                raise QueueFull(len(self._items), 1.0)
            if len(self._items) >= self.limit:
                raise QueueFull(
                    len(self._items), self._retry_after_locked()
                )
            if self.policy is not None:
                retry_after = self.policy.admit(
                    job, self._items, self.limit
                )
                if retry_after is not None:
                    raise QueueFull(len(self._items), retry_after)
            self._items.append(job)
            self._pushes += 1
            self._not_empty.notify_all()

    def pop(self, timeout: float | None = None) -> Job | None:
        """Oldest job (policy attached: min by class rank then EDF,
        arrival-stable on ties), or None on timeout/close."""
        with self._lock:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._items:
                if self._closed:
                    return None
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._not_empty.wait(remaining)
            if self.policy is None:
                return self._items.pop(0)
            # stable min over insertion order: equal keys = FIFO
            best = min(
                range(len(self._items)),
                key=lambda i: (self.policy.job_key(self._items[i]), i),
            )
            return self._items.pop(best)

    def take_matching(self, bucket, max_n: int, leader: Job | None = None) -> list[Job]:
        """Remove and return up to max_n jobs whose bucket equals
        `bucket` (None never matches); remaining jobs keep FIFO order.
        With a policy AND a `leader` (the job the gather is assembling
        around), slot assignment follows the free-rider rule: mates of
        the leader's class first, lower classes fill what is left — a
        capped batch never displaces a same-class member for a free
        rider."""
        if bucket is None or max_n <= 0:
            return []
        with self._lock:
            matching = [j for j in self._items if j.bucket == bucket]
            if self.policy is not None and leader is not None:
                taken = self.policy.select_mates(leader, matching, max_n)
            else:
                taken = matching[:max_n]
            chosen = {id(j) for j in taken}
            self._items = [
                j for j in self._items if id(j) not in chosen
            ]
        return taken

    def wait_for_more(self, timeout: float) -> None:
        """Sleep until a NEW push lands or `timeout` elapses (the gather
        window's clock — jobs already queued in other buckets must not
        turn this into a busy-wait; spurious wakeups are fine, the
        caller rechecks)."""
        with self._lock:
            seen = self._pushes
            deadline = time.monotonic() + timeout
            while self._pushes == seen and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._not_empty.wait(remaining)

    def restore(self, jobs: list[Job]) -> list[Job]:
        """Re-admit supervised jobs at the FRONT, bypassing the
        admission bound (they were admitted once already — shedding
        them during a worker restart would turn supervision into data
        loss). Returns the jobs that could NOT be restored (closed
        queue) so the caller can fail them cleanly."""
        if not jobs:
            return []
        with self._lock:
            if self._closed:
                return list(jobs)
            self._items[:0] = jobs
            self._pushes += 1
            self._not_empty.notify_all()
        return []

    def drain(self) -> list[Job]:
        """Close admission and return every queued job (shutdown path:
        the caller fails them cleanly instead of abandoning waiters)."""
        with self._lock:
            self._closed = True
            items, self._items = self._items, []
            self._not_empty.notify_all()
        return items
