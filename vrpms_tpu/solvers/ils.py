"""Iterated local search: alternate batched SA with the delta polish.

The strongest pipeline in this framework (measured on synth X-n200-k36,
equal 2048x20k sweep budget on one TPU v5e chip): one long anneal +
polish reaches 37.3k, while four rounds of (anneal from perturbed
champion seeds -> elite-pool delta polish -> reseed) reach **36.8k in a
third of the wall time** — the classic ILS effect, with both phases
already TPU-resident (the SA rounds reuse one compiled block, the
polish is the MXU delta descent of solvers.delta_ls).

Round structure:
  round 0: SA from the standard perturbed-NN seeds (or caller-provided
           warm seeds), elite pool polished, champion kept;
  round r: every chain reseeded from the best-so-far champion — by
           default via spatial ruin-and-recreate (solvers.perturb;
           chain 0 stays the exact incumbent), optionally via a few
           random moves (sa.perturbed_clones, ILSParams.reseed) — a
           cool anneal refines, pool polished, champion kept.

This fills the reference's SA endpoint slot (reference
api/vrp/sa/index.py:40-45) at its highest quality setting; the service
exposes it as the `ilsRounds` request option.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import (
    CostWeights,
    exact_cost,
    resolve_eval_mode,
)
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.solvers.common import SolveResult
from vrpms_tpu.solvers.delta_ls import delta_polish_batch
from vrpms_tpu.solvers.sa import SAParams, perturbed_clones, solve_sa


@dataclasses.dataclass(frozen=True)
class ILSParams:
    rounds: int = 4
    sa: SAParams = SAParams(n_chains=1024, n_iters=5000)
    pool: int = 32           # elite pool polished per round
    polish_sweeps: int = 128
    polish_block: int = 16   # sweeps per deadline-checked polish block
    min_round_s: float = 1.0  # don't START a round with less than this
                             # much budget left: a round commits to at
                             # least one anneal block + one polish block
                             # + reseed (~1-2 s at production shapes),
                             # so opening one at remaining ~0 overshoots
                             # the deadline by that whole tail
    reseed: str = "ruin"     # "ruin": spatial ruin-and-recreate
                             # (solvers.perturb) — the default; measured
                             # on synth X-n200 at equal 30 s budget:
                             # 36647/36881 vs 36951/37147 for "moves"
                             # (a few random moves per clone,
                             # sa.perturbed_clones), and 36647 BEATS the
                             # old 123 s record 36803
    polish_reserve_s: float = 2.0  # deadline slice withheld from each
                             # round's anneal so the polish actually
                             # runs (measured: the polish converts an
                             # anneal champion -7% in ~1.5 s warm — far
                             # more valuable than the anneal's last
                             # seconds; without the reserve a tight
                             # deadline degenerates to plain SA)

    @staticmethod
    def from_budget(
        rounds: int, sa: SAParams, total_iters: int, **kw
    ) -> "ILSParams":
        """The ONE place the total sweep budget splits across rounds
        (callers hand `iterationCount` straight through)."""
        per_round = max(1, total_iters // max(1, rounds))
        return ILSParams(
            rounds=rounds, sa=dataclasses.replace(sa, n_iters=per_round), **kw
        )


def solve_ils(
    inst: Instance,
    key: jax.Array | int = 0,
    params: ILSParams = ILSParams(),
    weights: CostWeights | None = None,
    init_giants: jax.Array | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
) -> SolveResult:
    """Iterated SA + polish; returns the best champion over all rounds.

    `deadline_s` bounds the WHOLE loop: the remaining budget is handed
    to each round's anneal (which truncates block-wise), the clock is
    checked between phases, and the loop exits early once spent. The
    polish acceptance is exact, so the result is never worse than the
    best unpolished champion seen.
    """
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    if isinstance(key, int):
        key = jax.random.key(key)
    # one host-side KNN build for ALL rounds (each rebuild re-transfers
    # the durations matrix — a wasted round trip per round on TPU)
    from vrpms_tpu.moves import proposal_knn

    knn = (
        proposal_knn(inst, params.sa.knn_k)
        if params.sa.knn_k > 0
        else None
    )

    # the fused delta-step kernel does ~20x the moves/s of the full-eval
    # step at indistinguishable per-sweep quality (kernels.sa_delta), so
    # every supported instance anneals with it
    from vrpms_tpu.solvers.sa import _delta_supported, solve_sa_delta

    use_delta = _delta_supported(inst, w, mode) and params.sa.n_chains % 128 == 0

    def anneal(k_round, init, budget):
        if use_delta:
            return solve_sa_delta(
                inst,
                key=k_round,
                params=params.sa,
                weights=w,
                init_giants=init,
                deadline_s=budget,
                pool=params.pool,
                knn=knn,
            )
        return solve_sa(
            inst,
            key=k_round,
            params=params.sa,
            weights=w,
            init_giants=init,
            mode=mode,
            deadline_s=budget,
            pool=params.pool,
            knn=knn,
        )

    return ils_loop(
        anneal,
        params.sa.n_chains,
        inst,
        key,
        params,
        w,
        mode,
        deadline_s,
        init_giants,
    )


def ils_loop(
    anneal,
    reseed_batch: int,
    inst: Instance,
    key: jax.Array,
    params: ILSParams,
    w: CostWeights,
    mode: str,
    deadline_s: float | None,
    init_giants: jax.Array | None,
    multi_controller: bool = False,
) -> SolveResult:
    """The ONE round/polish/reseed/deadline loop behind every ILS
    variant (single-device solve_ils, mesh.solve_ils_islands) — the
    anneal is the only thing that varies, so deadline semantics, the
    polish convergence heuristic, and the reseed keying cannot diverge.

    anneal(key, init_giants, budget) -> SolveResult; a returned elite
    pool is polished whole, otherwise the champion alone.

    Deadline/cancel granularity: each round's anneal runs under
    common.run_blocked, whose pipelined driver (VRPMS_PIPELINE, default
    on) defers deadline and cancel reaction by at most one in-flight
    device block — the round budgets computed here (min_round_s,
    fixed_tail, polish_reserve_s) already absorb that slack because a
    block has always been the loop's overshoot unit; the round-boundary
    cancel checks below are host-side and react immediately.
    """
    if params.rounds < 1:
        raise ValueError(f"ILSParams.rounds must be >= 1, got {params.rounds}")
    if params.reseed not in ("ruin", "moves"):
        # silent fallback would hide a quality regression (the modes
        # measure ~0.7% apart on X-n200)
        raise ValueError(
            f"ILSParams.reseed must be 'ruin' or 'moves', got {params.reseed!r}"
        )
    t_start = time.monotonic()

    import sys

    from vrpms_tpu import config

    trace = config.get("VRPMS_ILS_TRACE")

    def tlog(msg):
        if trace:
            print(
                f"[ils {time.monotonic() - t_start:7.2f}s] {msg}",
                file=sys.stderr, flush=True,
            )

    def remaining():
        if deadline_s is None:
            return None
        elapsed = time.monotonic() - t_start
        if multi_controller:
            # A mesh-spanning solve (solve_ils_islands over a multi-
            # process mesh) must take the same round/polish branches on
            # every controller, so the budget is process 0's clock
            # everywhere. Process-local solves must NOT broadcast: the
            # other processes never enter this loop (see mesh.sync).
            from vrpms_tpu.mesh.sync import controller_value

            elapsed = controller_value(elapsed)
        return deadline_s - elapsed

    from vrpms_tpu.obs.progress import cancel_requested

    best_g = None
    best_c = float("inf")
    evals = 0
    init = init_giants
    # A round commits to its FIXED tail (>= one polish block + exact
    # champion eval + reseed) no matter how little clock is left, so the
    # don't-start gate must know what that tail actually costs HERE —
    # ~0.3 s locally, 1-2 s through a tunneled TPU. Measure it from the
    # previous round instead of trusting the static min_round_s floor
    # (26-round budget solves overshot ~25% on the static floor alone).
    fixed_tail = 0.0
    for r in range(params.rounds):
        if cancel_requested() and best_g is not None:
            break  # cooperative cancel: the incumbent is the answer
        budget = remaining()
        if (
            budget is not None
            and budget <= max(0.0, params.min_round_s, fixed_tail)
            and best_g is not None
        ):
            break
        if budget is not None:
            # withhold the polish reserve from the anneal (the anneal
            # still runs at least one block on a non-positive budget)
            budget = budget - params.polish_reserve_s
        t_round = time.monotonic()
        res = anneal(jax.random.fold_in(key, r), init, budget)
        t_anneal_done = time.monotonic()
        evals += int(res.evals)
        tlog(f"round {r}: anneal done ({int(res.evals)} evals)")
        # Polish in deadline-checked blocks (the same never-overshoot-
        # by-more-than-a-block contract as the service's _polish); an
        # exhausted budget falls back to the unpolished best.
        giants = res.pool if res.pool is not None else res.giant[None]
        costs = None
        best_block = None
        sweeps_left = params.polish_sweeps
        top_k = 8  # delta_polish_batch default; fixed for the eval test
        first_polish = True
        while sweeps_left > 0 and not cancel_requested():
            # At least ONE polish block always runs (same rule as the
            # deadline drivers' at-least-one-chunk): the polish is part
            # of the ILS algorithm, measured −7% on an anneal champion
            # for ~0.15 s warm — a deadline consumed by the anneal must
            # not silently turn ILS into plain SA.
            budget = remaining()
            if budget is not None and budget <= 0 and not first_polish:
                break
            first_polish = False
            block = min(params.polish_block, sweeps_left)
            giants, costs, p_evals = delta_polish_batch(
                giants, inst, w, mode=mode, max_sweeps=block, top_k=top_k
            )
            evals += int(p_evals)
            sweeps_left -= block
            tlog(f"round {r}: polish block done ({int(p_evals)} evals)")
            if int(p_evals) < block * giants.shape[0] * top_k:
                break  # converged mid-block
            # a descent that converges exactly ON the block boundary
            # reports a full eval count; catch it by the pool best not
            # moving, saving the redundant (and, for a partial final
            # block, separately-compiled) extra call
            new_best = float(jnp.min(costs))
            if best_block is not None and new_best >= best_block - 1e-6:
                break
            best_block = new_best
        champ = int(jnp.argmin(costs)) if costs is not None else 0
        # mode-precision pool costs rank the pool (pool[0] is the
        # anneal's best when unpolished); the champion is re-evaluated
        # exactly before it may displace the incumbent
        cand = giants[champ]
        cand_cost = float(exact_cost(cand, inst, w)[1])
        tlog(f"round {r}: exact champion {cand_cost:.1f}")
        if cand_cost < best_c:
            best_c, best_g = cand_cost, cand
        budget = remaining()
        if r + 1 < params.rounds and (
            budget is None or budget > max(0.0, params.min_round_s)
        ):
            # reseed every chain from the incumbent, decorrelated (the
            # next round's nn-init would discard what was just learned)
            # — skipped when the next round cannot start anyway
            k_reseed = jax.random.fold_in(key, 1000 + r)
            if params.reseed == "ruin":
                from vrpms_tpu.solvers.perturb import ruin_recreate_clones

                init = ruin_recreate_clones(
                    k_reseed, reseed_batch, jnp.asarray(best_g), inst
                )
            else:
                init = perturbed_clones(
                    k_reseed, reseed_batch, best_g, mode,
                    length_real=inst.move_limit,
                )
            tlog(f"round {r}: reseeded ({params.reseed})")
        # everything after the anneal is this round's fixed tail
        fixed_tail = time.monotonic() - t_anneal_done

    bd, cost = exact_cost(best_g, inst, w)
    # saturate rather than overflow: extreme budgets exceed int32
    return SolveResult(
        best_g, cost, bd, jnp.int32(min(evals, 2**31 - 1))
    )
