"""Simulated annealing: thousands of independent Metropolis chains in one jit.

Fills the reference's SA endpoints (`# TODO: Run algorithm`, reference
api/vrp/sa/index.py:40-45, api/tsp/sa/index.py) with the TPU-shaped
design from SURVEY.md §2.3: the anneal is a single `lax.scan` over
iterations whose body proposes one random move per chain (vmap over the
chain axis), evaluates candidates with the batched cost kernel, and
applies the Metropolis rule — so the entire search runs on device with
one host sync at the end. Chain-parallelism replaces the reference's
parsed-but-unused `multiThreaded` flag (reference api/parameters.py:20).

PRNG discipline: one fold-in per iteration, one split per chain, so no
key is ever reused across chains or steps (SURVEY.md §5 "race detection"
analog for a functional runtime).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import (
    CostWeights,
    exact_cost,
    objective_batch_mode,
    resolve_eval_mode,
)
from vrpms_tpu.core.encoding import random_giant_batch
from vrpms_tpu.core.instance import Instance, mean_duration
from vrpms_tpu.moves import knn_move_batch, proposal_knn, random_move_batch
from vrpms_tpu.solvers.common import (
    SolveResult,
    donate_safe_state,
    maybe_donate_jit,
    rate_get as _rate_get,
    rate_put as _rate_put,
)

# The measured sweeps/s hint cache lives in solvers.common now (ISSUE
# 19 satellite: GA/ACO and the batched launch share it); the _rate_get/
# _rate_put aliases above keep this module's historical seam — callers
# (sched.batch) import them from here.


@dataclasses.dataclass(frozen=True)
class SAParams:
    n_chains: int = 1024
    n_iters: int = 20_000
    t_initial: float | None = None  # None: scaled from mean duration
    t_final: float | None = None
    knn_k: int = 16  # candidate-list width for proposals; 0 = uniform
    init: str = "nn"  # "nn": perturbed nearest-neighbor seeds; "random"


def _temps_from_scale(scale: float, params: SAParams) -> tuple[float, float]:
    """Geometric schedule endpoints from the mean-duration scale.

    The start temperature depends on the initialization: random starts
    need a hot anneal (0.8x scale) to unscramble, but good constructive
    seeds need a cool one (0.05x) that refines instead of destroying
    them — measured on synth X-n200 at 10k sweeps: nn-seeded 0.05x
    reaches 15.7% lower cost than random 0.8x, while nn-seeded at the
    hot temperature loses most of the seed's head start.
    """
    hot = 0.8 if params.init == "random" else 0.05
    t0 = params.t_initial if params.t_initial is not None else hot * scale
    t1 = params.t_final if params.t_final is not None else max(1e-3, 0.002 * scale)
    return float(t0), float(t1)


def _auto_temps(inst: Instance, params: SAParams) -> tuple[float, float]:
    """Schedule endpoints from the instance (one jitted mean dispatch)."""
    return _temps_from_scale(float(_mean_fn()(inst)), params)


@lru_cache(maxsize=1)
def _mean_fn():
    """Jitted real-region matrix mean (one cacheable dispatch; the eager
    reduction costs a multi-second compile round trip per process on a
    tunneled TPU — see _perturb_fn). Masked on tier-padded instances so
    the temperature scale tracks the real problem, not the tier size."""
    return jax.jit(mean_duration)


@lru_cache(maxsize=8)
def _nn_seed_fn():
    """Jitted NN-construct + greedy split (ONE device program — the
    eager composition was ~50 dispatches, which through a tunneled TPU
    dominated cold-solve latency; see perturbed_clones)."""
    from vrpms_tpu.core.split import greedy_split_giant
    from vrpms_tpu.solvers.local_search import nearest_neighbor_perm

    @jax.jit
    def fn(inst):
        return greedy_split_giant(nearest_neighbor_perm(inst), inst)

    return fn


@lru_cache(maxsize=8)
def _random_padded_fn(batch: int, length: int):
    """Jitted uniform random padded giants: the canonical padded layout
    (real customers + real separators in [1, L_real-2], phantoms then
    zeros in the tail) with the movable interior uniformly shuffled —
    the padded twin of encoding.random_giant_batch."""

    @jax.jit
    def fn(key, inst):
        nr, vr = inst.n_real, inst.v_real
        lim = nr + vr
        n_phantom = inst.n_nodes - nr  # traced
        pos = jnp.arange(length, dtype=jnp.int32)
        # canonical values: customers 1..nr-1, zeros to L_real-1, the
        # phantoms nr..N-1, zeros for the phantom vehicles
        is_cust = (pos >= 1) & (pos <= nr - 1)
        is_phan = (pos >= lim) & (pos < lim + n_phantom)
        canonical = jnp.where(
            is_cust, pos, jnp.where(is_phan, nr + (pos - lim), 0)
        )
        movable = (pos >= 1) & (pos <= lim - 2)

        def one(k):
            u = jax.random.uniform(k, (length,))
            order = jnp.argsort(jnp.where(movable, u, jnp.inf))
            src = jnp.where(movable, jnp.roll(order, 1), pos)
            return canonical[src]

        return jax.vmap(one)(jax.random.split(key, batch))

    return fn


def _random_padded_giants(key, batch: int, inst: Instance) -> jax.Array:
    length = inst.n_customers + inst.n_vehicles + 1
    return _random_padded_fn(batch, length)(key, inst)


def initial_giants(
    key: jax.Array, batch: int, inst: Instance, params: SAParams, mode: str
) -> jax.Array:
    """Chain-start tours per SAParams.init.

    "nn": one nearest-neighbor + greedy-split tour, cloned per chain and
    decorrelated by a few random moves — a far better basin than random
    permutations (the seed alone beats most of a random-start anneal).
    "random": uniform random giants (the reference stub's shuffle,
    reference src/solver.py:22-24, batched).
    """
    if params.init == "random":
        if inst.n_real is not None:
            return _random_padded_giants(key, batch, inst)
        return random_giant_batch(key, batch, inst.n_customers, inst.n_vehicles)
    if params.init != "nn":
        raise ValueError(f"SAParams.init must be 'nn' or 'random', got {params.init!r}")
    seed = _nn_seed_fn()(inst)
    return perturbed_clones(key, batch, seed, mode, length_real=inst.move_limit)


@lru_cache(maxsize=32)
def _perturb_fn(batch: int, mode: str, n_moves: int):
    """Jitted clone-and-decorrelate (cached per shape/mode like the
    anneal blocks). Eagerly, the n_moves sequential random_move_batch
    calls issue dozens of small device programs; on a tunneled TPU that
    cost ~45 s of pure dispatch latency per cold solve (measured on the
    X-n200 shape) — as ONE jitted program it is milliseconds warm and
    one persistent-cacheable compile cold. `lim` is the move bound
    (tour length, or the traced real prefix of a padded tour) — a
    dynamic scalar, so padded sizes share the compile."""

    @jax.jit
    def fn(key, giant, lim):
        giants = jnp.tile(giant[None], (batch, 1))
        for _ in range(n_moves):
            key, k = jax.random.split(key)
            giants = random_move_batch(k, giants, mode=mode, length_real=lim)
        return giants.at[0].set(giant)

    return fn


def perturbed_clones(
    key: jax.Array, batch: int, giant: jax.Array, mode: str,
    n_moves: int = 8, length_real=None,
) -> jax.Array:
    """One seed tour cloned per chain, decorrelated by a few random
    moves — the chain-start recipe for any constructive or warm seed.
    Clone 0 stays EXACTLY the seed, so best-so-far tracking guarantees
    the solve never returns worse than what it started from (warm
    re-solves with tiny budgets must not regress below their
    checkpoint). Callers pairing this with solve_sa should keep the
    default (cool) schedule: seeded starts are refined, not unscrambled.
    `length_real` (Instance.move_limit) confines the moves to a padded
    tour's real prefix.
    """
    lim = giant.shape[0] if length_real is None else length_real
    return _perturb_fn(batch, mode, n_moves)(key, giant, jnp.int32(lim))


#: continuation re-entry temperature, as a fraction of the seed's mean
#: LEG cost: a typical neighborhood move rewires O(1) legs, so t0 at
#: half a mean leg accepts only small local worsenings — the anneal
#: CONTINUES refining the repaired incumbent instead of re-running the
#: high-temperature phase that built it (a dynamic re-solve's seed is
#: an already-annealed tour of a neighboring instance, not a raw
#: constructive seed — even the warm-start 0.05x schedule re-melts more
#: of it than a small delta warrants)
CONTINUATION_LEG_FRACTION = 0.5


def continuation_params(
    inst: Instance,
    params: SAParams,
    seed_giant,
    weights: CostWeights | None = None,
) -> SAParams:
    """SAParams for a CONTINUATION re-solve: skip the high-temperature
    phase by estimating the initial temperature from the repaired seed
    tour's cost (mean leg cost x CONTINUATION_LEG_FRACTION), clamped
    into [t_final, warm-start t0] so the schedule never inverts and
    never runs hotter than a plain warm start. Explicit t_initial wins
    untouched. The budget interpretation follows: with the same n_iters
    the geometric schedule now spends every sweep in the refinement
    band, which is what lets a warm delta re-solve match a cold solve's
    cost at a fraction of the evals (benchmarks/resolve_delta.py)."""
    if params.t_initial is not None:
        return params
    from vrpms_tpu.solvers.common import seed_objective

    scale = float(_mean_fn()(inst))
    cost = seed_objective(seed_giant, inst, weights)
    nr = inst.n_customers if inst.n_real is None else int(inst.n_real) - 1
    vr = inst.n_vehicles if inst.v_real is None else int(inst.v_real)
    n_legs = max(1, nr + vr)
    t_warm, t1 = _temps_from_scale(scale, params)
    t0 = min(t_warm, max(CONTINUATION_LEG_FRACTION * cost / n_legs, t1))
    return dataclasses.replace(params, t_initial=float(t0), t_final=t1)


def anneal_temperature(it, t0, t1, horizon):
    """Geometric schedule value at iteration `it` of `horizon`."""
    frac = it.astype(jnp.float32) / jnp.maximum(
        jnp.asarray(horizon, jnp.float32) - 1.0, 1.0
    )
    return t0 * (t1 / t0) ** frac


def metropolis_accept(giants, costs, cands, cand_costs, u, temp):
    """The ONE acceptance rule (shared by the per-step-RNG chain step and
    the presampled block step, so the two can never anneal differently):
    accept improving moves always, worsening ones with probability
    exp(-delta/temp) against the provided uniforms."""
    accept = (cand_costs < costs) | (
        u < jnp.exp(jnp.minimum((costs - cand_costs) / temp, 0.0))
    )
    giants = jnp.where(accept[:, None], cands, giants)
    costs = jnp.where(accept, cand_costs, costs)
    return giants, costs


def sa_chain_step(
    giants, costs, key, it, t0, t1, n_iters, inst, w, mode="auto", knn=None
):
    """One Metropolis sweep of every chain; the flagship compiled step.

    Exposed standalone (not just inside solve_sa's scan) so the graft
    entry point and the island-model driver can reuse the exact same
    step function. `mode` picks the hot-path formulation (see
    core.cost.resolve_eval_mode): 'onehot'/'pallas' keep the
    proposal-apply and objective on the MXU (no elementwise gathers —
    the TPU profile shows those lower to a ~140M elem/s scalar loop),
    'gather' is the CPU path. With a `knn` candidate table, the second
    move endpoint is sampled from the current node's nearest neighbors
    instead of uniformly (moves.knn_table rationale).
    """
    mode = resolve_eval_mode(mode)
    b = giants.shape[0]
    # n_iters may be a dynamic scalar (deadline-chunked solves pass the
    # schedule horizon as a traced value)
    temp = anneal_temperature(it, t0, t1, n_iters)
    k_it = jax.random.fold_in(key, it)
    k_moves, k_accept = jax.random.split(k_it)
    lim = inst.move_limit  # traced real prefix on tier-padded instances
    if knn is not None:
        cands = knn_move_batch(k_moves, giants, knn, mode=mode, length_real=lim)
    else:
        cands = random_move_batch(k_moves, giants, mode=mode, length_real=lim)
    cand_costs = objective_batch_mode(cands, inst, w, mode)
    u = jax.random.uniform(k_accept, (b,))
    return metropolis_accept(giants, costs, cands, cand_costs, u, temp)


@lru_cache(maxsize=32)
def _sa_block_fn(n_block: int, mode: str):
    """Build (and cache) one jitted anneal block of n_block sweeps.

    Hoisted to module level so the compile caches across solves — a
    `@jax.jit` defined inside solve_sa would be a fresh function object
    per call, recompiling on every service request (tens of seconds of
    latency for a cached-size problem). The bounded lru_cache (rather
    than a bare jitted function with static_argnames) matters in a
    long-running service: request bodies control iteration counts, and
    jit's own cache is unbounded, so eviction here is what frees stale
    compiled executables. Temperatures, the global iteration offset, and
    the schedule horizon arrive as dynamic scalars so deadline-driven
    chunking and retuning never recompile; only shapes, n_block, and
    mode specialize a trace.

    Blocks compose: solve_sa runs the whole anneal as one block, or — to
    honor a wall-clock deadline — as several, checking the clock on the
    host between device-side blocks (SURVEY.md §5 failure-detection:
    a solve must be stoppable at a request deadline).

    On accelerators the loop state (arg 0) is DONATED: chained blocks
    update the chain/best arrays in place, so the pipelined driver
    (common.run_blocked) never holds two full copies of the state while
    a block is in flight. Callers enter through donate_safe_state.
    """

    @maybe_donate_jit
    def run(state, key, inst, w, t0, t1, knn, start_it, horizon):
        from vrpms_tpu.moves.moves import (
            move_batch_from_params,
            presample_move_params,
        )

        giants, costs, best_g, best_c = state
        b, length = giants.shape
        # ALL of the block's randomness in one draw (fold_in by the block
        # start keeps blocks decorrelated): the per-step threefry chain
        # was the single costliest part of the anneal step — ~0.76 ms of
        # the ~1.35 ms step at B=4096/n=200 on v5e, more than the move
        # apply plus the one-hot objective (presample_move_params).
        kb = jax.random.fold_in(key, start_it)
        width = 0 if knn is None else knn.shape[1]
        lim = inst.move_limit  # traced real prefix on padded instances
        pri, prr, prmt, prm, pru = presample_move_params(
            kb, b, length, n_block, width, length_real=lim
        )

        def step(state, xs):
            it, i, r, mt, m, u = xs
            giants, costs, best_g, best_c = state
            temp = anneal_temperature(it, t0, t1, horizon)
            cands = move_batch_from_params(
                i, r, mt, m, giants, knn, mode, length_real=lim
            )
            cand_costs = objective_batch_mode(cands, inst, w, mode)
            giants, costs = metropolis_accept(
                giants, costs, cands, cand_costs, u, temp
            )
            better = costs < best_c
            best_g = jnp.where(better[:, None], giants, best_g)
            best_c = jnp.where(better, costs, best_c)
            return (giants, costs, best_g, best_c), None

        xs = (start_it + jnp.arange(n_block), pri, prr, prmt, prm, pru)
        state, _ = jax.lax.scan(step, (giants, costs, best_g, best_c), xs)
        return state

    return run


@lru_cache(maxsize=8)
def _sa_init_fn(mode: str):
    """Jitted initial chain evaluation (kept compiled like the blocks)."""

    @jax.jit
    def init(giants, inst, w):
        return objective_batch_mode(giants, inst, w, mode)

    return init


@lru_cache(maxsize=32)
def _sa_prep_fn(batch: int, mode: str, n_moves: int = 8):
    """Fused cold-start prep: NN seed + clone/decorrelate + initial
    evaluation + the temperature scale, as ONE jitted program.

    A fresh process otherwise pays a separate program load + dispatch
    round trip for each of those four steps (~0.5 s apiece through a
    tunneled TPU) before the first anneal block can launch; fusing them
    puts the whole cold path one dispatch from the anneal — the
    north-star response budget is wall-clock INCLUDING this.
    """

    @jax.jit
    def prep(key, inst, w):
        # inline (not via the cached single-purpose fns) so everything
        # traces into one program
        from vrpms_tpu.core.split import greedy_split_giant
        from vrpms_tpu.solvers.local_search import nearest_neighbor_perm

        seed = greedy_split_giant(nearest_neighbor_perm(inst), inst)
        giants = jnp.tile(seed[None], (batch, 1))
        lim = inst.move_limit  # traced real prefix on padded instances
        for _ in range(n_moves):
            key, k = jax.random.split(key)
            giants = random_move_batch(k, giants, mode=mode, length_real=lim)
        giants = giants.at[0].set(seed)
        costs = objective_batch_mode(giants, inst, w, mode)
        return giants, costs, mean_duration(inst)

    return prep


def solve_sa(
    inst: Instance,
    key: jax.Array | int = 0,
    params: SAParams = SAParams(),
    weights: CostWeights | None = None,
    init_giants: jax.Array | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
    pool: int = 0,
    knn: jax.Array | None = None,
) -> SolveResult:
    """Batched-chain SA; returns the best solution over all chains.

    `knn` optionally passes a precomputed candidate table (knn_table) —
    repeat callers (the ILS round loop) avoid re-transferring the
    durations matrix to host every round.

    `pool` > 0 additionally returns the top-`pool` per-chain bests
    (SolveResult.pool, best first) — distinct chains sit in distinct
    local basins, so polishing the whole pool and keeping the winner
    beats polishing the champion alone (measured −0.9% at K=32 on
    synth X-n200).

    With `deadline_s`, the anneal runs in fixed 512-sweep device-side
    blocks under common.run_blocked's granularity contract (the cooling
    schedule still targets the full n_iters, so a truncated run behaves
    like an interrupted anneal, not a faster one).
    """
    from vrpms_tpu.solvers.common import run_blocked

    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    if isinstance(key, int):
        key = jax.random.key(key)
    k_init, k_run = jax.random.split(key)
    if init_giants is None and params.init == "nn":
        # fused cold path: seed + clones + eval + temp scale in ONE
        # dispatch (see _sa_prep_fn)
        giants, costs, mean = _sa_prep_fn(params.n_chains, mode)(k_init, inst, w)
        t0, t1 = _temps_from_scale(float(mean), params)
    else:
        t0, t1 = _auto_temps(inst, params)
        if init_giants is None:
            giants = initial_giants(k_init, params.n_chains, inst, params, mode)
        else:
            giants = init_giants
        costs = _sa_init_fn(mode)(giants, inst, w)
    n_iters = params.n_iters

    # solve_sa requires a concrete instance (the temp scale above
    # already forced durations to a value), so the table can be built.
    if knn is None:
        knn = proposal_knn(inst, params.knn_k) if params.knn_k > 0 else None
    t0j, t1j = jnp.float32(t0), jnp.float32(t1)
    horizon = jnp.float32(n_iters)
    # donate_safe_state: under donation the four slots must be DISTINCT
    # buffers (giants appears twice) and caller-owned init_giants must
    # survive the first block; identity on CPU
    state = donate_safe_state((giants, costs, giants, costs))

    def step_block(st, nb, start):
        return _sa_block_fn(nb, mode)(
            st, k_run, inst, w, t0j, t1j, knn, jnp.int32(start), horizon
        )

    # measured sweep rate per shape, fed back as run_blocked's first-
    # block fit hint so late ILS rounds stop overshooting their budget
    rate_key = (giants.shape[0], giants.shape[1], mode)
    import time as _time

    t_run = _time.monotonic()
    state, done = run_blocked(
        step_block, state, n_iters, 512, deadline_s, lambda st: st[3],
        rate_hint=_rate_get(rate_key), evals_per_iter=giants.shape[0],
        # durable-checkpoint capture: the champion chain's best giant,
        # extracted only when the sink's checkpoint cadence is due
        incumbent=lambda st: st[2][jnp.argmin(st[3])],
    )
    if deadline_s is not None and done:
        el = _time.monotonic() - t_run
        if el > 0.05:
            _rate_put(rate_key, done / el)

    _, _, best_g, best_c = state
    champ = jnp.argmin(best_c)
    g = best_g[champ]
    bd, cost = exact_cost(g, inst, w)
    elite = None
    if pool > 0:
        order = jnp.argsort(best_c)[: min(pool, best_g.shape[0])]
        elite = best_g[order]
    # evals from the actual batch (init_giants may differ from n_chains).
    # f32 (not int32): B=16k chains overflow int32 past ~131k iterations
    # (ADVICE r4); the <= 2^-24 relative rounding above 16.7M counts is
    # noise for a throughput metric
    return SolveResult(
        g, cost, bd, jnp.float32(giants.shape[0] * done), elite
    )


def warm_anneal_blocks(
    inst: Instance,
    n_chains: int,
    weights: CostWeights | None = None,
    blocks: tuple = (128, 256, 384, 512),
    mode: str = "auto",
) -> None:
    """Compile/load every deadline-block shape a (B, L) solve can need
    and seed the persistent sweep-rate cache.

    run_blocked shrinks blocks to 128-multiples, so a deadline-bounded
    anneal touches at most the four shapes here; a fresh process that
    meets them for the FIRST time inside a timed solve pays each one's
    compile-or-load round trip against the user's budget (VERDICT
    round 3: the 30 s budget point ran 51.5 s cold). Calling this at
    service/ladder startup moves that cost out of every solve and
    persists a measured sweeps/s per shape, so even the first
    tight-deadline solve of the NEXT process opens with a fitted block.
    Routes through solve_sa_delta/solve_sa exactly as a request would
    (same prep, block, resync, and final-eval programs).
    """
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    # same guard as solve_ils: the delta kernel needs a 128-multiple batch
    use_delta = _delta_supported(inst, w, mode) and n_chains % 128 == 0
    # ascending: the rate-less first call opens with a 128 block anyway
    # (run_blocked's conservative opener), so going small-to-large
    # compiles each shape exactly once
    for nb in sorted(blocks):
        p = SAParams(n_chains=n_chains, n_iters=nb)
        # the generous deadline only engages run_blocked's timed path so
        # the measured rate lands in the persistent cache
        if use_delta:
            solve_sa_delta(inst, key=1, params=p, deadline_s=3600.0)
        else:
            solve_sa(inst, key=1, params=p, mode=mode, deadline_s=3600.0)


# ---------------------------------------------------------------------------
# Delta-evaluated anneal (fused Pallas step kernel)
# ---------------------------------------------------------------------------


def _delta_supported(inst: Instance, w: CostWeights, mode: str) -> bool:
    """Host-side gate for the fused delta-step paths: symmetric
    uniform-capacity instances on a TPU backend (the reverse-move legs
    reuse needs symmetry; TD/makespan change non-local terms the
    kernels don't model; heterogeneous fleets break the uniform-
    capacity excess recompute). Demands must admit a bf16-exact gcd
    scaling (kernels.sa_eval.demand_scale) — dp_init and the resync's
    packed demand column are bf16, and rounded demands let slightly
    infeasible tours rank feasible (ADVICE r3).

    Time-windowed instances are supported since round 4 via the sibling
    TW kernel (kernels.sa_delta_tw), under extra gates: uniform shift
    starts with the depot window open at the start (trailing pad legs
    must be lateness-free), and ids/table within one 256 lane tile.
    """
    import numpy as np

    from vrpms_tpu.kernels.sa_delta import _PALLAS_OK
    from vrpms_tpu.kernels.sa_eval import demand_scale

    if mode != "pallas" or not _PALLAS_OK:
        return False
    if inst.n_real is not None:
        # tier-padded instances: the fused kernels' packed route state
        # keys on literal zeros and does not model phantom separators;
        # padded traffic stays on the XLA one-hot paths (which ARE
        # tier-shared and persistent-cacheable)
        return False
    if w.use_makespan or inst.het_fleet:
        return False
    # raised from 512 in round 5 (VERDICT r4 item 10: the X series runs
    # to n=1001); lhat=2048 state still fits the raised scoped-VMEM cap
    # at tile_b=128, and ids to 1024 are exact under the kernels'
    # f32-accumulated one-hot dots (bit-checked at n=1001 on hardware —
    # the round-4 precision lesson says test exactly there)
    if inst.n_nodes > 1024:
        return False
    if demand_scale(inst.demands) is None:
        return False
    if inst.time_dependent:
        # factorized TD rides the frozen-slice surrogate kernel
        # (kernels.sa_delta_td) since round 5; the combined TD+TW class
        # and unfactorized (full-rank) profiles still fall back
        if inst.has_tw or not (1 <= inst.td_rank <= 2):
            return False
        if inst.n_nodes > 512:
            # the shared delta bound above was raised to 1024 in round
            # 5, but the TD surrogate path has only ever been hardware-
            # validated to n=512 (the scale_n1001 bench family exercises
            # the untimed kernel alone) — gate TD there until a
            # 512-1024 coverage point exists (ADVICE round 5)
            return False
        # basis symmetry is the exact invariant the reverse move's
        # interior-leg reuse needs, and (with the factorization exact
        # and factor rows independent) is equivalent to every-slice
        # symmetry at ~T/R the host cost of checking [T, N, N]
        bas = np.asarray(inst.td_basis)
        return bool(
            np.allclose(bas, np.swapaxes(bas, 1, 2), rtol=1e-6, atol=1e-6)
        )
    if inst.has_tw:
        length = inst.n_customers + inst.n_vehicles + 1
        if inst.n_nodes > 256 or length > 256:
            return False
        st = np.asarray(inst.start_times)
        ready = np.asarray(inst.ready)
        due = np.asarray(inst.due)
        if not np.all(st == st[0]):
            return False
        if max(float(st[0]), float(ready[0])) > float(due[0]):
            return False
    d = np.asarray(inst.durations[0])
    return bool(np.allclose(d, d.T, rtol=1e-6, atol=1e-6))


def _pow2_at_least(x: int) -> int:
    p = 1
    while p < x:
        p <<= 1
    return p


def _delta_prep(giants, inst, w, lhat: int, nhat: int, tile_b: int,
                dem_g: float = 1.0, interpret: bool = False):
    """giants [B, L] -> transposed padded state + exact dist/cape.

    Everything stays on device: dist/cape via two fused-eval kernel
    passes (see _delta_resync_fn), per-position demands via the dp_init
    kernel (the XLA one-hot einsum moved ~2 GB of intermediates at
    B=16k, and a host fancy-index round-trips the state through the
    TPU tunnel — both measured slower than the 512 steps they set up).
    Demands and the returned cape are in demand/dem_g units (the gcd
    scaling that keeps dp_init's bf16 matvecs exact; the kernel's
    excess weight carries the g factor back — see solve_sa_delta)."""
    import numpy as np

    from vrpms_tpu.kernels.sa_delta import dp_init

    b, length = giants.shape
    gt_t = jnp.zeros((lhat, b), jnp.int32).at[:length].set(giants.T)
    dist, cape = _delta_resync_fn(length, interpret)(gt_t, inst, w)
    cape = cape / dem_g  # resync returns real-unit excess
    dem_row = np.zeros((1, nhat), np.float32)
    dem_row[0, : inst.n_nodes] = np.asarray(inst.demands) / dem_g
    dp_t = dp_init(gt_t, jnp.asarray(dem_row), tile_b=tile_b, interpret=interpret)
    return gt_t, dp_t, dist, cape


@lru_cache(maxsize=16)
def _delta_resync_fn(length: int, interpret: bool = False):
    """Exact dist/cape of the transposed state — the block-boundary
    drift killer (f32 sums of the SAME bf16 table the deltas read).
    Runs as TWO fused-eval kernel passes (wcap 0 then 1; their
    difference isolates the capacity excess): the XLA one-hot resync
    moved ~2 GB of (B, L, N) intermediates at B=16k and cost more than
    the 512 delta steps it certified."""

    @jax.jit
    def resync(gt_t, inst, w):
        import dataclasses as _dc

        from vrpms_tpu.kernels.sa_eval import (
            pallas_objective_batch,
            pallas_supported,
        )

        gt = gt_t[:length]
        w0 = _dc.replace(w, cap=0.0)
        w1 = _dc.replace(w, cap=1.0)
        if pallas_supported(inst, gt.shape[1]):
            dist = pallas_objective_batch(
                gt, inst, w0, transposed=True, interpret=interpret
            )
            both = pallas_objective_batch(
                gt, inst, w1, transposed=True, interpret=interpret
            )
            return dist[None, :], (both - dist)[None, :]
        # huge-N shapes the fused evaluator's tiles can't fit (the
        # round-5 n<=1024 gate admits more than sa_eval does): the XLA
        # one-hot path prices the SAME bf16 table, and a resync runs
        # once per 512-step launch, so its (B, L, N) intermediates are
        # amortized noise here
        from vrpms_tpu.core.cost import objective_batch_mode

        c0 = objective_batch_mode(gt.T, inst, w0, "onehot")
        c1 = objective_batch_mode(gt.T, inst, w1, "onehot")
        return c0[None, :], (c1 - c0)[None, :]

    return resync


@lru_cache(maxsize=32)
def _sa_delta_block_fn(
    n_block: int, length: int, tile_b: int, has_knn: bool,
    interpret: bool = False,
):
    """One jitted block of n_block fused delta steps + best tracking:
    presample the block's randomness and temperatures, then ONE
    delta_block kernel launch with state VMEM-resident for the whole
    block (measured the same step rate as a scan of per-step kernel
    calls — the compute, not the dispatch, bounds the step — but the
    single launch compiles far faster than a 512-call scan program,
    which matters when each compile is a tunnel round trip)."""
    from vrpms_tpu.kernels.sa_delta import delta_block
    from vrpms_tpu.moves.moves import presample_move_params

    @jax.jit
    def run(state, key, d_bf16, knn_f, scal2, t0, t1, start_it, horizon):
        gt_t, dp_t, dist, cape, best_t, best_c = state
        b = gt_t.shape[1]
        kb = jax.random.fold_in(key, start_it)
        kw = knn_f.shape[1] if has_knn else 0
        pri, prr, prmt, prm, pru = presample_move_params(
            kb, b, length, n_block, kw
        )
        temps = anneal_temperature(
            start_it + jnp.arange(n_block), t0, t1, horizon
        )[None, :].astype(jnp.float32)
        return delta_block(
            gt_t, dp_t, dist, cape, best_t, best_c,
            pri, prr, prmt, prm, pru, temps,
            d_bf16, knn_f, scal2,
            length=length, tile_b=tile_b, has_knn=has_knn,
            interpret=interpret,
        )

    return run


@lru_cache(maxsize=32)
def _sa_delta_tw_block_fn(
    n_block: int, length: int, tile_b: int, has_knn: bool,
    interpret: bool = False,
):
    """One jitted block of n_block fused VRPTW delta steps (the TW twin
    of _sa_delta_block_fn; kernels.sa_delta_tw)."""
    from vrpms_tpu.kernels.sa_delta_tw import delta_tw_block
    from vrpms_tpu.moves.moves import presample_move_params

    @jax.jit
    def run(state, key, d_bf16, knn_f, scal, t0, t1, start_it, horizon):
        gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost, best_t, best_c = state
        b = gt_t.shape[1]
        kb = jax.random.fold_in(key, start_it)
        kw = knn_f.shape[1] if has_knn else 0
        pri, prr, prmt, prm, pru = presample_move_params(
            kb, b, length, n_block, kw
        )
        temps = anneal_temperature(
            start_it + jnp.arange(n_block), t0, t1, horizon
        )[None, :].astype(jnp.float32)
        return delta_tw_block(
            gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost, best_t, best_c,
            pri, prr, prmt, prm, pru, temps, d_bf16, knn_f, scal,
            length=length, tile_b=tile_b, has_knn=has_knn,
            interpret=interpret,
        )

    return run


@lru_cache(maxsize=32)
def _sa_delta_td_block_fn(
    n_block: int, length: int, rr: int, tile_b: int, has_knn: bool,
    interpret: bool = False,
):
    """One jitted block of n_block fused TD delta steps (the
    time-dependent twin of _sa_delta_block_fn; kernels.sa_delta_td).
    `fw_t` rides as an ARGUMENT, not state: it is constant within a
    launch and refreshed by the driver's resync."""
    from vrpms_tpu.kernels.sa_delta_td import delta_td_block
    from vrpms_tpu.moves.moves import presample_move_params

    @jax.jit
    def run(state, fw_t, key, d_cat, knn_f, scal, t0, t1, start_it, horizon):
        gt_t, dp_t, lgr_t, cost, best_t, best_c = state
        b = gt_t.shape[1]
        kb = jax.random.fold_in(key, start_it)
        kw = knn_f.shape[1] if has_knn else 0
        pri, prr, prmt, prm, pru = presample_move_params(
            kb, b, length, n_block, kw
        )
        temps = anneal_temperature(
            start_it + jnp.arange(n_block), t0, t1, horizon
        )[None, :].astype(jnp.float32)
        return delta_td_block(
            gt_t, dp_t, lgr_t, cost, best_t, best_c,
            pri, prr, prmt, prm, pru, temps, d_cat, knn_f, fw_t, scal,
            length=length, rr=rr, tile_b=tile_b, has_knn=has_knn,
            interpret=interpret,
        )

    return run


def _tile_interleave_r(x, tile_b: int):
    """(L-hat, R, B) -> the kernel's (L-hat, R*B) tile-interleaved
    layout: the BlockSpec hands each grid step one contiguous
    R*tile_b-wide chunk, so the R sections of one chain tile must be
    adjacent (section r of tile g at columns [g*R*tile + r*tile ...])."""
    lhat, rr, b = x.shape
    g = b // tile_b
    return x.reshape(lhat, rr, g, tile_b).transpose(0, 2, 1, 3).reshape(
        lhat, rr * b
    )


@lru_cache(maxsize=16)
def _td_fw_fn(length: int, tile_b: int):
    """Jitted TRUE-timeline pass for the TD delta driver: from committed
    giants, propagate the departure clock exactly (core.cost._td_eval
    semantics — per-route start times, service, cyclic slices) over the
    bf16-rounded basis legs, and emit

      fw_t   — (L-hat, R*B) tile-interleaved factor weights
               fw[r][k] = factors[r, slice(depart_k)],
      lgr_t  — the matching basis-leg state layout,
      dist   — (1, B) true surrogate distance (sum of true travels),

    which is everything a launch-boundary resync must refresh."""

    @jax.jit
    def fw(giants, inst, bas):  # bas: (R, N-hat, N-hat) f32(bf16) tables
        from vrpms_tpu.core.cost import _rid_batch

        b = giants.shape[0]
        rr = bas.shape[0]
        lhat = _pow2_at_least(length)
        prev, cur = giants[:, :-1], giants[:, 1:]
        blegs = bas[:, prev, cur]  # [R, B, K]
        v = inst.n_vehicles
        rid = _rid_batch(giants)
        route_of_leg = jnp.minimum(rid[:, :-1], v - 1)
        start = inst.start_times[route_of_leg]  # [B, K]
        svc = inst.service[prev]
        rdy = inst.ready[cur]
        reset = prev == 0
        t_slices = inst.n_slices
        factors = inst.td_factors  # [R, T]

        def step(clock, x):
            blegs_k, reset_k, start_k, svc_k, rdy_k = x
            depart = jnp.where(reset_k, start_k, clock + svc_k)
            sidx = (depart // inst.slice_minutes).astype(jnp.int32) % t_slices
            # plain gather, NOT a one-hot matmul: this is ordinary
            # jitted XLA (gather is fine here), and a default-precision
            # dot would bf16-truncate the f32 factor values — the exact
            # class of silent bias the EXACT-einsum discipline exists
            # for (code review r5)
            fac_rb = factors[:, sidx]  # [R, B]
            travel = (fac_rb * blegs_k).sum(axis=0)
            arrive = jnp.maximum(depart + travel, rdy_k)
            return arrive, (fac_rb, travel)

        xs = (
            jnp.moveaxis(blegs, 2, 0),  # [K, R, B]
            reset.T, start.T, svc.T, rdy.T,
        )
        _, (facs, travel) = jax.lax.scan(
            step, jnp.zeros((b,), jnp.float32), xs
        )
        # facs: [K, R, B] -> (L-hat, R, B), pad rows zero (pad legs are
        # zero-valued in lgr, so their fw is irrelevant; zero keeps the
        # product exactly zero)
        fw_full = jnp.zeros((lhat, rr, b), jnp.float32).at[: length - 1].set(
            facs
        )
        lg_full = jnp.zeros((lhat, rr, b), jnp.float32).at[: length - 1].set(
            jnp.moveaxis(blegs, 2, 0)
        )
        dist = jnp.sum(travel, axis=0)[None]  # (1, B)
        return (
            _tile_interleave_r(fw_full, tile_b),
            _tile_interleave_r(lg_full, tile_b),
            dist,
        )

    return fw


@lru_cache(maxsize=16)
def _td_best_rank_fn(length: int):
    """Exact one-hot-basis TD costs of the best pool (final champion /
    elite selection through the shared TD hot path)."""

    @jax.jit
    def rank(best_t, inst, w):
        from vrpms_tpu.core.cost import objective_hot_batch

        g = best_t[:length].T
        return objective_hot_batch(g, inst, w)

    return rank


def _solve_sa_delta_td(
    inst, giants, t0, t1, k_run, params, w, deadline_s, pool, knn
) -> SolveResult:
    """Time-dependent delta-anneal driver (dispatched from
    solve_sa_delta; kernels.sa_delta_td rationale).

    The kernel prices moves with POSITION-FROZEN factor weights; this
    driver refreshes them (plus the committed cost row) with the exact
    timeline at every launch boundary, and the final champion/elite
    ranking runs through the exact TD hot path — so the reported result
    is exactly priced regardless of in-launch surrogate noise."""
    import numpy as np

    from vrpms_tpu.kernels.sa_delta import _cap_excess_of, dp_init

    b, length = giants.shape
    lhat = _pow2_at_least(length)
    rr = inst.td_rank
    # the TD step carries 3 + 2R tall arrays (gt/dp/best + lgr/fw per
    # rank); scale the chain tile down with both lhat and R to stay
    # inside the scoped-VMEM cap (same discipline as the TW driver)
    if lhat * (3 + 2 * rr) <= 128 * 7:
        prefs = (512, 256, 128)
    elif lhat * (3 + 2 * rr) <= 256 * 7:
        prefs = (256, 128)
    else:
        prefs = (128,)
    tile_b = next((tb for tb in prefs if b % tb == 0), None)
    if tile_b is None:
        raise ValueError(f"delta path needs a 128-multiple batch, got {b}")
    nhat, dem_g, _d_bf16, knn_f, has_knn, cap0, interpret = (
        _delta_common_setup(inst, params, knn)
    )
    scal = jnp.asarray(
        [[cap0 / dem_g, float(w.cap) * dem_g]], jnp.float32
    )
    # basis tables: bf16-rounded once (the kernel's pair lookups read
    # bf16; the resync timeline must price the SAME rounded legs), then
    # lane-concatenated for the kernel's stacked lookup
    bas_np = np.zeros((rr, nhat, nhat), np.float32)
    bas_np[:, : inst.n_nodes, : inst.n_nodes] = np.asarray(inst.td_basis)
    bas_bf = jnp.asarray(bas_np, jnp.bfloat16)
    bas_f32 = bas_bf.astype(jnp.float32)
    d_cat = jnp.concatenate([bas_bf[r] for r in range(rr)], axis=1)

    gt_t = jnp.zeros((lhat, b), jnp.int32).at[:length].set(giants.T)
    dem_row = np.zeros((1, nhat), np.float32)
    dem_row[0, : inst.n_nodes] = np.asarray(inst.demands) / dem_g
    dp_t = dp_init(gt_t, jnp.asarray(dem_row), tile_b=tile_b,
                   interpret=interpret)

    fw_fn = _td_fw_fn(length, tile_b)
    fw_t, lgr_t, dist0 = fw_fn(giants, inst, bas_f32)
    cape0 = _cap_excess_of(gt_t, dp_t, scal[0, 0], lhat)
    cost0 = dist0 + scal[0, 1] * cape0
    state = (gt_t, dp_t, lgr_t, cost0, gt_t, cost0)
    t0j, t1j = jnp.float32(t0), jnp.float32(t1)
    horizon = jnp.float32(params.n_iters)
    fw_box = [fw_t]  # step_block closure reads the latest resync's fw

    def step_block(st, nb, start):
        return _sa_delta_td_block_fn(
            nb, length, rr, tile_b, has_knn, interpret
        )(st, fw_box[0], k_run, d_cat, knn_f, scal,
          t0j, t1j, jnp.int32(start), horizon)

    def resync_state(st):
        # refresh the frozen factor weights + committed cost in the
        # exact timeline of the committed tours (the surrogate's only
        # drift source); lgr re-derives exactly so it stays as-is
        gt_t, dp_t, lgr_t, _cost, best_t, _best_c = st
        g = gt_t[:length].T
        fw_new, _lg, dist = fw_fn(g, inst, bas_f32)
        fw_box[0] = fw_new
        cape = _cap_excess_of(gt_t, dp_t, scal[0, 0], lhat)
        # re-price best_t in the SAME fresh timeline: a best_c priced
        # under old (optimistic) factor weights would otherwise sit
        # below what any genuinely better tour can score under the new
        # ones, silently suppressing later improvements for the rest of
        # the run. One extra fw/dp pass per 512-step launch (~1/512 of
        # a full eval per step) keeps tracker and candidates comparable.
        dist_b = fw_fn(best_t[:length].T, inst, bas_f32)[2]
        dp_b = dp_init(best_t, jnp.asarray(dem_row), tile_b=tile_b,
                       interpret=interpret)
        best_c = dist_b + scal[0, 1] * _cap_excess_of(
            best_t, dp_b, scal[0, 0], lhat
        )
        return (gt_t, dp_t, lgr_t, dist + scal[0, 1] * cape, best_t, best_c)

    state, done = _delta_launch_loop(
        step_block, state, params.n_iters, deadline_s,
        ("delta_td", b, length), lambda s: s[5], resync=resync_state,
        evals_per_iter=b,
    )

    best_t = state[4]
    best_exact = _td_best_rank_fn(length)(best_t, inst, w)
    champ = jnp.argmin(best_exact)
    g = best_t[:length, champ].T
    bd, cost = exact_cost(g, inst, w)
    elite = None
    if pool > 0:
        order = jnp.argsort(best_exact)[: min(pool, b)]
        elite = best_t[:length, :].T[order]
    return SolveResult(g, cost, bd, jnp.float32(b * done), elite)


@lru_cache(maxsize=16)
def _tw_delta_prep_fn(length: int):
    """Jitted TW state prep: bf16-selected legs of each giant plus the
    kernel-basis initial cost row (the same formulas the kernel applies
    per candidate — sum of legs, scaled capacity excess, max-plus
    lateness — so step 1's accept compares like with like)."""

    @jax.jit
    def prep(giants, gt_t, dp_t, sv_t, rd_t, du_t, inst, scal):
        from vrpms_tpu.core.cost import _legs_hot
        from vrpms_tpu.kernels.sa_delta import _cap_excess_of
        from vrpms_tpu.kernels.sa_delta_tw import tw_timeline_late

        lhat = gt_t.shape[0]
        _, _, legs, _ = _legs_hot(giants, inst)
        lg_t = jnp.zeros_like(dp_t).at[: length - 1].set(legs.T)
        dist = jnp.sum(lg_t, axis=0, keepdims=True)
        cape = _cap_excess_of(gt_t, dp_t, scal[0, 0], lhat)
        late = tw_timeline_late(
            gt_t, lg_t, sv_t, rd_t, du_t, scal[0, 3], lhat
        )
        return lg_t, dist + scal[0, 1] * cape + scal[0, 2] * late

    return prep


@lru_cache(maxsize=16)
def _tw_best_rank_fn(length: int):
    """Exact one-hot-basis costs of the best pool (final champion/elite
    selection; the kernel's tracker is its own basis, so ranking goes
    through the shared tw_components_batch)."""

    @jax.jit
    def rank(best_t, inst, w):
        from vrpms_tpu.core.cost import tw_components_batch

        g = best_t[:length].T
        dist, cape, late, _, _ = tw_components_batch(g, inst)
        return dist + w.cap * cape + w.tw * late

    return rank


def _delta_launch_loop(
    step_block, state, n_iters, deadline_s, rate_key, sync, resync=None,
    evals_per_iter=None,
):
    """The 512-step Pallas-launch loop shared by both delta drivers.

    Each launch's presampled param streams are VMEM blocks, so launches
    stay bounded at 512 steps regardless of the iteration budget;
    step_block receives GLOBAL iteration offsets (the schedule and the
    presampled RNG streams must not restart per launch). `resync`, when
    given, re-derives exact state between launches (the untimed
    kernel's drift kill; the TW kernel recomputes everything fresh and
    passes None). The sweep rate persists to the hint cache only on the
    DEADLINE path — run_blocked syncs the device there, so the clock is
    honest; a deadline-free loop's dispatches are asynchronous and
    would record inflated rates.
    """
    import time as _time

    from vrpms_tpu.obs.progress import cancel_requested
    from vrpms_tpu.solvers.common import run_blocked

    t_run = _time.monotonic()
    done = 0
    remaining = n_iters
    while remaining > 0:
        block = min(512, remaining)
        base = done

        def offset_block(st, nb, start, _base=base):
            return step_block(st, nb, _base + start)

        state, did = run_blocked(
            offset_block, state, block, 512,
            None if deadline_s is None else max(
                0.0, deadline_s - (_time.monotonic() - t_run)
            ),
            sync, rate_hint=_rate_get(rate_key),
            evals_per_iter=evals_per_iter,
        )
        done += did
        remaining -= block
        if deadline_s is not None and did:
            el = _time.monotonic() - t_run
            if el > 0.05:
                _rate_put(rate_key, done / el)
        if resync is not None:
            state = resync(state)
        if deadline_s is not None and (
            _time.monotonic() - t_run >= deadline_s or did < block
        ):
            break
        # cooperative cancel between launches: run_blocked already
        # stopped its inner loop; without this the deadline-free outer
        # loop would keep issuing (instantly-skipped) launches
        if cancel_requested():
            break
    return state, done


def _delta_common_setup(inst, params, knn):
    """The device inputs both delta drivers share: padded bf16 d-table,
    padded knn table, demand gcd scale, uniform capacity, interpret
    flag (ONE construction so the TW and untimed paths cannot drift)."""
    import numpy as np

    from vrpms_tpu import config

    from vrpms_tpu.kernels.sa_eval import demand_scale

    nhat = -(-inst.n_nodes // 128) * 128
    dem_g = demand_scale(inst.demands)
    if dem_g is None:
        raise ValueError(
            "solve_sa_delta needs bf16-exact-scalable demands "
            "(integral, max/gcd <= 256); see _delta_supported"
        )
    d_np = np.zeros((nhat, nhat), np.float32)
    d_np[: inst.n_nodes, : inst.n_nodes] = np.asarray(inst.durations[0])
    d_bf16 = jnp.asarray(d_np, jnp.bfloat16)
    if knn is None and params.knn_k > 0:
        knn = proposal_knn(inst, params.knn_k)
    has_knn = knn is not None
    if has_knn:
        kf = np.zeros((nhat, knn.shape[1]), np.float32)
        kf[: inst.n_nodes] = np.asarray(knn, np.float32)
        knn_f = jnp.asarray(kf)
    else:
        knn_f = jnp.zeros((nhat, 8), jnp.float32)
    cap0 = float(np.asarray(inst.capacities)[0])
    interpret = bool(config.raw("VRPMS_DELTA_INTERPRET"))
    return nhat, dem_g, d_bf16, knn_f, has_knn, cap0, interpret


def _solve_sa_delta_tw(
    inst, giants, t0, t1, k_run, params, w, deadline_s, pool, knn
) -> SolveResult:
    """VRPTW delta-anneal driver (dispatched from solve_sa_delta).

    Simpler than the untimed driver in one way: the TW kernel
    recomputes distance, capacity excess and lateness FRESH from the
    exactly-moved state arrays at every step, so nothing accumulates
    and there is nothing to resync at block boundaries — just an exact
    re-rank of the best pool at the end. Launches are still capped at
    512 steps like the untimed driver: the presampled param streams are
    VMEM blocks of the single Pallas launch, so an unbounded-n_steps
    launch scales its VMEM with the whole iteration budget.
    """
    import numpy as np

    from vrpms_tpu.kernels.sa_delta import dp_init

    b, length = giants.shape
    lhat = _pow2_at_least(length)
    # 512-chain tiles measured fastest (15.9 vs 14.5M moves/s at 128 on
    # v5e, R101 shape) under the raised scoped-VMEM cap (delta_tw_block)
    # — but that budget was validated at lhat=128 with no headroom, and
    # per-step temporaries scale with lhat, so halve the tile when the
    # gate admits longer tours (lhat=256) instead of blowing VMEM
    # (ADVICE r4 medium).
    prefs = (512, 256, 128) if lhat <= 128 else (256, 128)
    tile_b = next((tb for tb in prefs if b % tb == 0), None)
    if tile_b is None:
        raise ValueError(f"delta path needs a 128-multiple batch, got {b}")
    nhat, dem_g, d_bf16, knn_f, has_knn, cap0, interpret = (
        _delta_common_setup(inst, params, knn)
    )
    start0 = float(np.asarray(inst.start_times)[0])
    scal = jnp.asarray(
        [[cap0 / dem_g, float(w.cap) * dem_g, float(w.tw), start0]],
        jnp.float32,
    )
    gt_t = jnp.zeros((lhat, b), jnp.int32).at[:length].set(giants.T)

    def attr_row(vec):
        row = np.zeros((1, nhat), np.float32)
        row[0, : inst.n_nodes] = np.asarray(vec)
        return jnp.asarray(row)

    dp_t = dp_init(
        gt_t, attr_row(np.asarray(inst.demands) / dem_g),
        tile_b=tile_b, interpret=interpret,
    )
    sv_t = dp_init(
        gt_t, attr_row(inst.service),
        tile_b=tile_b, exact_f32=True, interpret=interpret,
    )
    rd_t = dp_init(
        gt_t, attr_row(inst.ready),
        tile_b=tile_b, exact_f32=True, interpret=interpret,
    )
    du_t = dp_init(
        gt_t, attr_row(inst.due),
        tile_b=tile_b, exact_f32=True, interpret=interpret,
    )
    lg_t, cost0 = _tw_delta_prep_fn(length)(
        giants, gt_t, dp_t, sv_t, rd_t, du_t, inst, scal
    )
    state = (gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0, gt_t, cost0)
    t0j, t1j = jnp.float32(t0), jnp.float32(t1)
    horizon = jnp.float32(params.n_iters)

    def step_block(st, nb, start):
        # `start` is the GLOBAL iteration offset (_delta_launch_loop)
        return _sa_delta_tw_block_fn(nb, length, tile_b, has_knn, interpret)(
            st, k_run, d_bf16, knn_f, scal, t0j, t1j,
            jnp.int32(start), horizon,
        )

    # the TW kernel recomputes dist/cape/lateness fresh each step, so
    # there is nothing to resync between launches
    state, done = _delta_launch_loop(
        step_block, state, params.n_iters, deadline_s,
        ("delta_tw", b, length), lambda st: st[8], evals_per_iter=b,
    )

    best_t = state[7]
    best_exact = _tw_best_rank_fn(length)(best_t, inst, w)
    champ = jnp.argmin(best_exact)
    g = best_t[:length, champ].T
    bd, cost = exact_cost(g, inst, w)
    elite = None
    if pool > 0:
        order = jnp.argsort(best_exact)[: min(pool, b)]
        elite = best_t[:length, :].T[order]
    return SolveResult(g, cost, bd, jnp.float32(b * done), elite)


def solve_sa_delta(
    inst: Instance,
    key: jax.Array | int = 0,
    params: SAParams = SAParams(),
    weights: CostWeights | None = None,
    init_giants: jax.Array | None = None,
    deadline_s: float | None = None,
    pool: int = 0,
    knn: jax.Array | None = None,
) -> SolveResult:
    """Batched-chain SA with the FUSED delta step (kernels.sa_delta;
    time-windowed instances take the sibling TW kernel,
    kernels.sa_delta_tw).

    Same contract as solve_sa (deadline blocks, pool, warm init); the
    per-move work drops from a full O(L * N^2) evaluation to closed-form
    deltas + a capacity recompute, all inside one VMEM-resident kernel.
    Callers must pass instances _delta_supported approves.
    """
    import numpy as np

    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)
    k_init, k_run = jax.random.split(key)
    mode = "pallas"
    if init_giants is None and params.init == "nn":
        giants, _costs, mean = _sa_prep_fn(params.n_chains, "onehot")(
            k_init, inst, w
        )
        t0, t1 = _temps_from_scale(float(mean), params)
    else:
        t0, t1 = _auto_temps(inst, params)
        giants = (
            initial_giants(k_init, params.n_chains, inst, params, "onehot")
            if init_giants is None
            else init_giants
        )
    if inst.has_tw:
        return _solve_sa_delta_tw(
            inst, giants, t0, t1, k_run, params, w, deadline_s, pool, knn
        )
    if inst.time_dependent:
        return _solve_sa_delta_td(
            inst, giants, t0, t1, k_run, params, w, deadline_s, pool, knn
        )
    b, length = giants.shape
    lhat = _pow2_at_least(length)
    # 256-chain tiles measured fastest for the block kernel (512 blows
    # the VMEM budget once the per-block param streams move in); above
    # the old n=512 gate (lhat 2048) the per-move roll temporaries
    # double again, so drop to 128
    prefs = (256, 128) if lhat <= 1024 else (128,)
    tile_b = next((t for t in prefs if b % t == 0), None)
    if tile_b is None:
        raise ValueError(f"delta path needs a 128-multiple batch, got {b}")
    # gcd demand scaling (kernels.sa_eval.demand_scale): the kernel's
    # dp/cape state runs in demand/g units against capacity/g, with the
    # g folded into the excess weight — bf16-exact for any integral
    # demands with max/gcd <= 256 (the _delta_supported gate).
    nhat, dem_g, d_bf16, knn_f, has_knn, cap0, interpret = (
        _delta_common_setup(inst, params, knn)
    )
    scal2 = jnp.asarray(
        [[cap0 / dem_g, float(w.cap) * dem_g]], jnp.float32
    )
    gt_t, dp_t, dist, cape = _delta_prep(
        giants, inst, w, lhat, nhat, tile_b, dem_g, interpret
    )
    best_c = dist + float(w.cap) * dem_g * cape
    state = (gt_t, dp_t, dist, cape, gt_t, best_c)
    t0j, t1j = jnp.float32(t0), jnp.float32(t1)
    horizon = jnp.float32(params.n_iters)

    def step_block(st, nb, start):
        # `start` arrives as the GLOBAL iteration offset from
        # _delta_launch_loop (the schedule and the presampled RNG
        # streams must not restart per launch)
        return _sa_delta_block_fn(nb, length, tile_b, has_knn, interpret)(
            st, k_run, d_bf16, knn_f, scal2, t0j, t1j,
            jnp.int32(start), horizon,
        )

    # block-wise with an exact resync between launches (drift kill); the
    # same deadline/rate contract as solve_sa
    resync = _delta_resync_fn(length, interpret)

    def resync_state(st):
        # exact resync of the committed state (fp drift accumulates in
        # the f32 delta sums; measured well under 1e-3 per 512 steps,
        # but exactness is the contract)
        gt_t, dp_t, _, _, best_t, best_c = st
        dist, cape = resync(gt_t, inst, w)
        return (gt_t, dp_t, dist, cape / dem_g, best_t, best_c)

    state, done = _delta_launch_loop(
        step_block, state, params.n_iters, deadline_s,
        ("delta", b, length), lambda s: s[5], resync=resync_state,
        evals_per_iter=b,
    )

    gt_t, dp_t, dist, cape, best_t, best_c = state
    # Champion/elite selection by EXACT re-evaluated cost of the best
    # pool: the kernel-tracked best_c carries accumulated delta drift
    # that the block-boundary resync corrects only for the CURRENT
    # state, so argmin over the raw tracker could discard a genuinely
    # better elite (ADVICE round 3). Two fused-eval passes fix it.
    bdist, bcape = resync(best_t, inst, w)
    best_exact = bdist + float(w.cap) * bcape  # bcape is real-unit excess
    champ = jnp.argmin(best_exact[0])
    g = best_t[:length, champ].T
    bd, cost = exact_cost(g, inst, w)
    elite = None
    if pool > 0:
        order = jnp.argsort(best_exact[0])[: min(pool, b)]
        elite = best_t[:length, :].T[order]
    return SolveResult(g, cost, bd, jnp.float32(b * done), elite)
