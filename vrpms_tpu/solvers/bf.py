"""Brute force: exact enumeration, the golden oracle for every other solver.

Fills the `# TODO: Run algorithm` hole of the reference's BF endpoints
(reference api/vrp/bf/index.py:39-44, api/tsp/bf/index.py:39-43) the TPU
way: permutations are *generated on device* by decoding a linear index
through the factorial number system (Lehmer code), so enumeration is a
`lax.scan` over fixed-size vmapped batches — no host loop, no dynamic
shapes, and millions of candidate tours evaluated per scan step.

TSP: all n! customer orders, evaluated directly.
VRP: all n! orders, each priced by the bounded-fleet optimal split
(core.split) — order enumeration x optimal split = exact CVRP.
"""

from __future__ import annotations

import math
from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import giant_length
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant, optimal_split_cost, optimal_split_routes
from vrpms_tpu.core.encoding import giant_from_routes
from vrpms_tpu.solvers.common import SolveResult

MAX_BF_CUSTOMERS = 10
_BATCH = 1 << 13


def _perm_from_index(idx: jax.Array, n: int) -> jax.Array:
    """Lehmer decode: index in [0, n!) -> permutation of 0..n-1.

    Static n (<= MAX_BF_CUSTOMERS) keeps the selection loop unrolled;
    each step picks the d-th not-yet-used element via a cumulative count.
    """
    facts = [math.factorial(k) for k in range(n)]
    used = jnp.zeros(n, dtype=jnp.bool_)
    out = []
    rem = idx
    for i in range(n):
        f = facts[n - 1 - i]
        d = (rem // f).astype(jnp.int32)
        rem = rem % f
        avail_rank = jnp.cumsum(~used) - 1  # rank among unused, -1 if used
        choice = jnp.argmax((~used) & (avail_rank == d))
        out.append(choice)
        used = used.at[choice].set(True)
    return jnp.stack(out).astype(jnp.int32)


def _enumerate_min(n_perms: int, score_fn, n: int):
    """Scan over fixed-size index batches; returns (best_idx, best_score).

    score_fn: i32[B] perm-indices -> f32[B] scores (BIG for padding).
    """
    n_batches = (n_perms + _BATCH - 1) // _BATCH

    def step(carry, b):
        best_idx, best_val = carry
        idx = b * _BATCH + jnp.arange(_BATCH)
        valid = idx < n_perms
        scores = jnp.where(valid, score_fn(idx), jnp.inf)
        j = jnp.argmin(scores)
        better = scores[j] < best_val
        return (
            jnp.where(better, idx[j], best_idx),
            jnp.where(better, scores[j], best_val),
        ), None

    (best_idx, best_val), _ = jax.lax.scan(
        step, (jnp.int32(0), jnp.float32(jnp.inf)), jnp.arange(n_batches)
    )
    return best_idx, best_val


def _check_size(inst: Instance):
    n = inst.n_customers
    if n > MAX_BF_CUSTOMERS:
        raise ValueError(
            f"brute force is exact enumeration; {n} customers exceeds the "
            f"{MAX_BF_CUSTOMERS}-customer bound ({math.factorial(n):,} orders)"
        )
    return n


def _giant_of(idx, inst: Instance, n: int):
    perm = _perm_from_index(idx, n) + 1
    zeros = jnp.zeros(inst.n_vehicles, dtype=jnp.int32)
    return jnp.concatenate([jnp.zeros(1, jnp.int32), perm, zeros])


@lru_cache(maxsize=MAX_BF_CUSTOMERS + 1)
def _tsp_bf_run_fn(n: int):
    """Build (and cache) the jitted enumeration; the compile caches
    across solves (a per-call jit(lambda) would recompile per request).
    n is bounded by MAX_BF_CUSTOMERS, so the cache covers every size."""

    @jax.jit
    def run(inst, w):
        def score(idx_batch):
            giants = jax.vmap(lambda i: _giant_of(i, inst, n))(idx_batch)
            return jax.vmap(lambda g: total_cost(evaluate_giant(g, inst), w))(giants)

        return _enumerate_min(math.factorial(n), score, n)

    return run


def solve_tsp_bf(inst: Instance, weights: CostWeights | None = None) -> SolveResult:
    """Exact TSP by full enumeration (single vehicle assumed)."""
    n = _check_size(inst)
    w = weights or CostWeights.make()
    n_perms = math.factorial(n)
    length = giant_length(n, inst.n_vehicles)

    best_idx, _ = _tsp_bf_run_fn(n)(inst, w)
    giant = _giant_of(best_idx, inst, n)
    assert giant.shape == (length,)
    bd = evaluate_giant(giant, inst)
    return SolveResult(giant, total_cost(bd, w), bd, jnp.int32(n_perms))


@lru_cache(maxsize=MAX_BF_CUSTOMERS + 1)
def _vrp_bf_run_fn(n: int):
    """Build (and cache) the jitted enumeration (see _tsp_bf_run_fn).
    The timed-vs-plain dispatch keys off static Instance metadata, so
    each variant compiles once."""

    @jax.jit
    def run(inst, w):
        # Orders score by pure optimal-split distance only when that IS
        # the objective; time windows or a makespan weight need the full
        # giant evaluation (w.use_makespan is static metadata, so each
        # variant still compiles once).
        full = inst.has_tw or inst.time_dependent or w.use_makespan

        def perm_of(idx):
            return _perm_from_index(idx, n) + 1

        if full:
            def score(idx_batch):
                giants = jax.vmap(lambda i: greedy_split_giant(perm_of(i), inst))(idx_batch)
                return jax.vmap(lambda g: total_cost(evaluate_giant(g, inst), w))(giants)
        else:
            def score(idx_batch):
                perms = jax.vmap(perm_of)(idx_batch)
                return jax.vmap(lambda p: optimal_split_cost(p, inst))(perms)

        return _enumerate_min(math.factorial(n), score, n)

    return run


def solve_vrp_bf(inst: Instance, weights: CostWeights | None = None) -> SolveResult:
    """Exact CVRP: every customer order priced by its optimal split.

    Assumes a homogeneous fleet (split uses capacities[0], like the GA/
    ACO fitness path). Time windows and makespan-priced objectives fall
    back to enumerating orders and evaluating the greedy-split giant —
    exact over that split space, matching the solver fitness paths.
    """
    n = _check_size(inst)
    w = weights or CostWeights.make()
    n_perms = math.factorial(n)
    full = inst.has_tw or inst.time_dependent or w.use_makespan

    best_idx, _ = _vrp_bf_run_fn(n)(inst, w)
    perm = _perm_from_index(best_idx, n) + 1
    if full:
        giant = greedy_split_giant(perm, inst)
    else:
        routes = optimal_split_routes(perm, inst)
        giant = giant_from_routes(routes, n, inst.n_vehicles)
    bd = evaluate_giant(giant, inst)
    return SolveResult(giant, total_cost(bd, w), bd, jnp.int32(n_perms))
