"""Brute force: exact enumeration, the golden oracle for every other solver.

Fills the `# TODO: Run algorithm` hole of the reference's BF endpoints
(reference api/vrp/bf/index.py:39-44, api/tsp/bf/index.py:39-43) the TPU
way: permutations are *generated on device* by decoding a linear index
through the factorial number system (Lehmer code), so enumeration is a
`lax.scan` over fixed-size vmapped batches — no host loop, no dynamic
shapes, and millions of candidate tours evaluated per scan step.

TSP: all n! customer orders, evaluated directly.
VRP: all n! orders, each priced by the bounded-fleet optimal split
(core.split) — order enumeration x optimal split = exact CVRP.
"""

from __future__ import annotations

import math
import time
from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import giant_length
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant, optimal_split_cost, optimal_split_routes
from vrpms_tpu.core.encoding import giant_from_routes
from vrpms_tpu.solvers.common import SolveResult

MAX_BF_CUSTOMERS = 10
_BATCH = 1 << 13
_CHUNK_BATCHES = 32  # ~262k orders between host deadline checks


def _perm_from_index(idx: jax.Array, n: int) -> jax.Array:
    """Lehmer decode: index in [0, n!) -> permutation of 0..n-1.

    Static n (<= MAX_BF_CUSTOMERS) keeps the selection loop unrolled.
    Each step picks the d-th smallest unused element by indexing into a
    sorted list of available ids, then deletes it with a roll+select
    shift. An earlier formulation tracked a `used` bool mask and picked
    via argmax(cumsum(~used) rank == d); XLA:TPU miscompiles that
    bool-cumsum/argmax/scatter chain at wide vmap batches (measured: 85%
    of rows decode with repeated elements at batch 8192 on v5e, while
    CPU is correct at every width) — the gather/roll form avoids the
    fragile pattern entirely and is equivalence-tested against the host
    decode on-device (tests/test_bf_local_search.py).
    """
    facts = [math.factorial(k) for k in range(n)]
    avail = jnp.arange(n, dtype=jnp.int32)  # unused ids, ascending
    pos = jnp.arange(n, dtype=jnp.int32)
    out = []
    rem = idx
    for i in range(n):
        f = facts[n - 1 - i]
        d = (rem // f).astype(jnp.int32)
        rem = rem % f
        out.append(avail[d])
        # delete element d: shift the tail left by one
        shifted = jnp.roll(avail, -1)
        avail = jnp.where(pos >= d, shifted, avail)
    return jnp.stack(out).astype(jnp.int32)


def _min_step(score_fn, n_perms: int):
    """One fixed-size enumeration batch folded into the running best —
    the ONE reduction step behind both the single-shot scan and the
    deadline-chunked driver (indices past n_perms score inf, so partial
    final batches and overshooting chunks are both harmless)."""

    def step(carry, b):
        best_idx, best_val = carry
        idx = b * _BATCH + jnp.arange(_BATCH)
        valid = idx < n_perms
        scores = jnp.where(valid, score_fn(idx), jnp.inf)
        j = jnp.argmin(scores)
        better = scores[j] < best_val
        return (
            jnp.where(better, idx[j], best_idx),
            jnp.where(better, scores[j], best_val),
        ), None

    return step


def _enumerate_min(n_perms: int, score_fn, n: int):
    """Scan over fixed-size index batches; returns (best_idx, best_score).

    score_fn: i32[B] perm-indices -> f32[B] scores (BIG for padding).
    """
    n_batches = (n_perms + _BATCH - 1) // _BATCH
    (best_idx, best_val), _ = jax.lax.scan(
        _min_step(score_fn, n_perms),
        (jnp.int32(0), jnp.float32(jnp.inf)),
        jnp.arange(n_batches),
    )
    return best_idx, best_val


def _check_size(inst: Instance):
    n = inst.n_customers
    if n > MAX_BF_CUSTOMERS:
        raise ValueError(
            f"brute force is exact enumeration; {n} customers exceeds the "
            f"{MAX_BF_CUSTOMERS}-customer bound ({math.factorial(n):,} orders)"
        )
    return n


def _giant_of(idx, inst: Instance, n: int):
    perm = _perm_from_index(idx, n) + 1
    zeros = jnp.zeros(inst.n_vehicles, dtype=jnp.int32)
    return jnp.concatenate([jnp.zeros(1, jnp.int32), perm, zeros])


def _score_fn(kind: str, n: int, inst: Instance, w: CostWeights):
    """idx-batch scorer for one problem kind — the ONE place enumeration
    pricing is defined, shared by the single-shot jits and the deadline
    chunks so the two paths cannot diverge. 'vrp' picks full-evaluation
    vs optimal-split pricing off static Instance/weights metadata."""
    if kind == "tsp":
        def score(idx_batch):
            giants = jax.vmap(lambda i: _giant_of(i, inst, n))(idx_batch)
            return jax.vmap(lambda g: total_cost(evaluate_giant(g, inst), w))(giants)

        return score
    # Orders score by pure optimal-split distance only when that IS the
    # objective; time windows or a makespan weight need the full giant
    # evaluation (static metadata, so each variant compiles once).
    full = inst.has_tw or inst.time_dependent or w.use_makespan

    def perm_of(idx):
        return _perm_from_index(idx, n) + 1

    if full:
        def score(idx_batch):
            giants = jax.vmap(lambda i: greedy_split_giant(perm_of(i), inst))(idx_batch)
            return jax.vmap(lambda g: total_cost(evaluate_giant(g, inst), w))(giants)
    else:
        def score(idx_batch):
            perms = jax.vmap(perm_of)(idx_batch)
            return jax.vmap(lambda p: optimal_split_cost(p, inst))(perms)

    return score


@lru_cache(maxsize=MAX_BF_CUSTOMERS + 1)
def _tsp_bf_run_fn(n: int):
    """Build (and cache) the jitted enumeration; the compile caches
    across solves (a per-call jit(lambda) would recompile per request).
    n is bounded by MAX_BF_CUSTOMERS, so the cache covers every size."""

    @jax.jit
    def run(inst, w):
        return _enumerate_min(math.factorial(n), _score_fn("tsp", n, inst, w), n)

    return run


@lru_cache(maxsize=2 * (MAX_BF_CUSTOMERS + 1))
def _bf_chunk_fn(n: int, kind: str):
    """One jitted chunk of _CHUNK_BATCHES enumeration batches from a
    dynamic batch offset — the deadline-aware twin of the single-shot
    run fns. Chunks compose to exactly the single-shot reduction
    (indices past n! score inf), so the host can check the wall clock
    between chunks like every other solver's blocked driver."""

    @jax.jit
    def run(carry, start_b, inst, w):
        step = _min_step(_score_fn(kind, n, inst, w), math.factorial(n))
        carry, _ = jax.lax.scan(
            step, carry, start_b + jnp.arange(_CHUNK_BATCHES)
        )
        return carry

    return run


def _enumerate_deadline(n: int, kind: str, inst: Instance, w, deadline_s: float):
    """Host-clock-checked enumeration: returns (best_idx, orders_scored,
    exhausted). At least one chunk always runs, so the result is the
    best over >= ~262k orders (or the whole space when smaller); when
    the deadline cuts enumeration short the result is best-so-far, NOT
    exact — the caller reports the scored count via SolveResult.evals.

    Under VRPMS_PIPELINE (default on) the chunk loop is depth-1
    pipelined like common.run_blocked: chunk k+1 dispatches before
    chunk k's reduction is synced, so the deadline/cancel check reacts
    within at most one in-flight chunk. The carry chains through
    asynchronously and every launched chunk is drained, so the result
    equals the serial loop's over the same scored prefix."""
    from vrpms_tpu.obs.progress import cancel_requested
    from vrpms_tpu.solvers.common import pipeline_enabled

    n_perms = math.factorial(n)
    n_batches = (n_perms + _BATCH - 1) // _BATCH
    carry = (jnp.int32(0), jnp.float32(jnp.inf))
    run = _bf_chunk_fn(n, kind)
    t0 = time.monotonic()
    b = 0
    if not pipeline_enabled():
        while b < n_batches:
            carry = run(carry, jnp.int32(b), inst, w)
            jax.block_until_ready(carry[1])
            b += _CHUNK_BATCHES
            # chunk-granular cooperative cancel, same seam as the
            # deadline (a cancelled enumeration is best-effort, never
            # exact)
            if time.monotonic() - t0 >= deadline_s or cancel_requested():
                break
        scored = min(b * _BATCH, n_perms)
        return carry[0], scored, scored >= n_perms
    prev = None  # the in-flight chunk's reduction to sync on
    while b < n_batches:
        carry = run(carry, jnp.int32(b), inst, w)
        b += _CHUNK_BATCHES
        if prev is not None:
            jax.block_until_ready(prev)
            # clock/cancel observed on the last SYNCED chunk while the
            # one just launched computes — reaction defers by ≤1 chunk,
            # which the final drain below always completes and counts
            if time.monotonic() - t0 >= deadline_s or cancel_requested():
                break
        prev = carry[1]
    jax.block_until_ready(carry[1])
    scored = min(b * _BATCH, n_perms)
    return carry[0], scored, scored >= n_perms


def solve_tsp_bf(
    inst: Instance,
    weights: CostWeights | None = None,
    deadline_s: float | None = None,
) -> SolveResult:
    """Exact TSP by full enumeration (single vehicle assumed).

    With `deadline_s` the enumeration runs in host-clock-checked chunks
    and may stop early with the best order seen so far (SolveResult.evals
    reports how many orders were actually scored) — the same best-effort
    deadline contract as every iterative solver.
    """
    n = _check_size(inst)
    w = weights or CostWeights.make()
    n_perms = math.factorial(n)
    length = giant_length(n, inst.n_vehicles)

    if deadline_s is None:
        best_idx, _ = _tsp_bf_run_fn(n)(inst, w)
        scored = n_perms
    else:
        best_idx, scored, _ = _enumerate_deadline(n, "tsp", inst, w, deadline_s)
    giant = _giant_of(best_idx, inst, n)
    assert giant.shape == (length,)
    bd = evaluate_giant(giant, inst)
    return SolveResult(giant, total_cost(bd, w), bd, jnp.int32(scored))


@lru_cache(maxsize=MAX_BF_CUSTOMERS + 1)
def _vrp_bf_run_fn(n: int):
    """Build (and cache) the jitted enumeration (see _tsp_bf_run_fn).
    The timed-vs-plain dispatch keys off static Instance metadata, so
    each variant compiles once."""

    @jax.jit
    def run(inst, w):
        return _enumerate_min(math.factorial(n), _score_fn("vrp", n, inst, w), n)

    return run


def solve_vrp_bf(
    inst: Instance,
    weights: CostWeights | None = None,
    deadline_s: float | None = None,
) -> SolveResult:
    """Exact CVRP: every customer order priced by its optimal split.

    Heterogeneous fleets are exact too: the split DP applies per-vehicle
    capacities in vehicle order (core.split.optimal_split_cost), and
    enumerating ALL orders covers every assignment of route spans to
    vehicles (the DP's "stay" transition lets any vehicle go empty).
    Time windows and makespan-priced objectives fall back to enumerating
    orders and evaluating the greedy-split giant — exact over that split
    space, matching the solver fitness paths.

    With `deadline_s` the enumeration runs in host-clock-checked chunks
    and may stop early with the best order seen so far (then NOT exact;
    SolveResult.evals reports the orders actually scored).
    """
    n = _check_size(inst)
    w = weights or CostWeights.make()
    n_perms = math.factorial(n)
    full = inst.has_tw or inst.time_dependent or w.use_makespan

    if deadline_s is None:
        best_idx, _ = _vrp_bf_run_fn(n)(inst, w)
        scored = n_perms
    else:
        best_idx, scored, _ = _enumerate_deadline(n, "vrp", inst, w, deadline_s)
    perm = _perm_from_index(best_idx, n) + 1
    if full:
        giant = greedy_split_giant(perm, inst)
    else:
        # A deadline-truncated enumeration can stop before ANY scored
        # order had a capacity-feasible split (tight het fleets): its
        # best_idx then carries an inf score and optimal_split_routes
        # would raise. Fall back to the greedy split of that order — a
        # penalized best-effort result, matching every other solver's
        # deadline contract (ADVICE round 2).
        try:
            routes = optimal_split_routes(perm, inst)
            giant = giant_from_routes(routes, n, inst.n_vehicles)
        except ValueError:
            giant = greedy_split_giant(perm, inst)
    bd = evaluate_giant(giant, inst)
    return SolveResult(giant, total_cost(bd, w), bd, jnp.int32(scored))
