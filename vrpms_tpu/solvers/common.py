"""Shared result container for all solvers.

Every solver — bf, local_search, sa, ga, aco — returns the same
SolveResult so the service layer (the api->solver boundary the reference
prescribes at README.md:31-33 but never wired) is algorithm-agnostic.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import (
    CostBreakdown,
    CostWeights,
    evaluate_giant,
    objective_batch_mode,
    resolve_eval_mode,
    total_cost,
)
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import (
    greedy_split_cost,
    greedy_split_cost_hot_batch,
    greedy_split_giant,
)


class SolveResult(NamedTuple):
    giant: jax.Array          # best giant tour found (core.encoding layout)
    cost: jax.Array           # scalar weighted objective of `giant`
    breakdown: CostBreakdown  # its cost components (distance, penalties, ...)
    evals: jax.Array          # candidate evaluations performed (throughput metric)
    pool: jax.Array | None = None  # optional [K, L] elite tours (best first,
                                   # pool[0] == giant) for multi-start polish


def run_blocked(
    step_block,
    state,
    n_total: int,
    block_size: int,
    deadline_s: float | None,
    sync,
    rate_hint: float | None = None,
    evals_per_iter: float | None = None,
    incumbent=None,
):
    """Deadline-aware composition of jitted iteration blocks — the one
    block-driver loop shared by SA, GA, and ACO (identical granularity
    contract everywhere: the host clock is checked between device-side
    blocks, so a deadline shorter than one block overshoots by that
    block's runtime).

    step_block(state, n_block, start) runs n_block iterations from
    absolute offset `start` (offsets arrive as dynamic scalars inside,
    so composed blocks reproduce the unbounded run exactly); sync(state)
    picks the array to block on for the clock check. Returns
    (state, iterations_done). deadline_s None runs everything as one
    block with no host sync.

    Deadline fidelity (VERDICT round-2 item 6): once at least one block
    has timed, the next block is SHRUNK to what the measured iteration
    rate says still fits the clock — in multiples of 128 so the set of
    compiled block shapes stays tiny (each extra shape is one
    persistent-cacheable compile, ever) — instead of the old run-whole-
    or-skip choice whose overshoot was a full block (~1.3 s at
    production shapes, 13% of a 10 s budget). `rate_hint` (iterations/s
    from a previous same-shape run; solvers cache it) lets even the
    FIRST block fit a short remaining budget — that unshrinkable first
    block of a late-starting ILS round was the residual overshoot. The
    hint is derated 20% so a tunnel-throughput wobble errs toward
    finishing early (the loop self-corrects from measured elapsed).

    `evals_per_iter` feeds the per-request convergence trace
    (vrpms_tpu.obs.trace): when a collector is active, every block
    boundary records (wall, best-of-sync, cumulative evals). With no
    collector — the default — the cost is one ContextVar read, and the
    deadline-free fast path gains no extra device sync.

    The live-progress sink (vrpms_tpu.obs.progress) rides the SAME
    cadence: when one is active, every block boundary also publishes
    the synced best to it, and a cooperative CANCEL flag is honored
    between blocks — the loop stops and the caller returns its
    incumbent. Neither path changes the block decomposition or any
    device computation, so fixed-seed trajectories are bit-identical
    with or without a sink attached.

    `incumbent(state)`, when given, extracts the champion TOUR from the
    loop state (solvers pass it so durable checkpointing can persist a
    resumable incumbent, not just its cost). It is called only when the
    sink's checkpoint handle says a capture is due
    (ProgressSink.want_incumbent — bounded VRPMS_CKPT_MS cadence), so
    the common case costs one attribute read per boundary; like the
    sink itself it only READS the already-synced state and never
    changes the trajectory.
    """
    import time

    from vrpms_tpu.obs.progress import active_sink
    from vrpms_tpu.obs.trace import active_trace

    trace = active_trace()
    sink = active_sink()
    if deadline_s is None:
        if sink is not None and sink.cancelled:
            # cancelled before the single unbounded block launched: the
            # caller's prepared state IS the incumbent. A cancel landing
            # mid-block instead runs the whole budget — there is no
            # boundary left to stop at, and the result is then NOT
            # marked cancelled (sink.note_cancel_seen never fires).
            sink.note_cancel_seen()
            return state, 0
        state = step_block(state, n_total, 0)
        if (trace is not None or sink is not None) and n_total > 0:
            best = sync(state)
            jax.block_until_ready(best)
            if trace is not None:
                trace.record(best, n_total, evals_per_iter)
            if sink is not None:
                sink.record(best, n_total, evals_per_iter)
                _maybe_capture(sink, incumbent, state)
        return state, n_total
    block = max(1, min(n_total, block_size))
    done = 0
    t_start = time.monotonic()
    while done < n_total:
        if sink is not None and sink.cancelled:
            sink.note_cancel_seen()
            break
        nb = min(block, n_total - done)
        elapsed = time.monotonic() - t_start
        remaining_t = deadline_s - elapsed
        rate = (
            done / elapsed
            if done
            else (0.8 * rate_hint if rate_hint else None)
        )
        if rate is not None:
            if remaining_t <= 0 and done:
                break
            fit = int(rate * max(remaining_t, 0.0))
            if fit < nb:
                nb = (fit // 128) * 128
                if nb < 128:
                    if done:
                        break
                    nb = min(128, n_total)  # a call always runs SOMETHING
        elif nb > 128:
            # No rate known at all (fresh shape, empty cache): open with
            # one small block to MEASURE instead of committing a whole
            # block blind — a full 512-sweep block against a 1 s budget
            # was the residual first-solve overshoot (VERDICT round 3).
            # Costs at most 3 extra host syncs on generous deadlines;
            # the measured rate fits every later block.
            nb = 128
        state = step_block(state, nb, done)
        best = sync(state)
        jax.block_until_ready(best)
        done += nb
        if trace is not None:
            trace.record(best, nb, evals_per_iter)
        if sink is not None:
            sink.record(best, nb, evals_per_iter)
            _maybe_capture(sink, incumbent, state)
        if time.monotonic() - t_start >= deadline_s:
            break
    return state, done


def _maybe_capture(sink, incumbent, state) -> None:
    """Offer the champion tour to the sink's durable-checkpoint handle
    when a capture is due (see run_blocked's `incumbent` contract).
    Batched fanouts and shard rollups carry no capture protocol — the
    getattr guard makes them (and plain sinks with no handle) free."""
    if incumbent is None:
        return
    want = getattr(sink, "want_incumbent", None)
    if want is None or not want():
        return
    try:
        sink.offer_incumbent(incumbent(state))
    except Exception:
        pass  # capture must never kill the device loop


def seed_objective(giant, inst: Instance, w: CostWeights | None = None) -> float:
    """Exact scalar objective of a seed tour — the ONE pricing that
    continuation-budget decisions use (sa.continuation_params estimates
    the re-entry temperature from it), so the schedule a warm re-solve
    continues with is derived from the same objective the solver
    anneals. One device dispatch; host float out."""
    from vrpms_tpu.core.cost import exact_cost

    _, cost = exact_cost(giant, inst, w or CostWeights.make())
    return float(cost)


def solve_info(res: SolveResult, unvisited: list | None = None) -> dict:
    """Reference-shaped solve summary: {tour, total_time, unvisited, date}.

    The reference's solver entry returns exactly these keys with
    placeholder values (reference src/solver.py:18-27: a random depot-
    wrapped shuffle, constant total_time, empty unvisited, dated via
    src/utilities/helper.py). Here they are real: the winning giant tour
    flattened to one depot-wrapped node list, the summed route durations,
    and the customers excluded from this solve (the dynamic re-solve
    inputs — SURVEY.md §5 checkpoint/resume).
    """
    from vrpms_tpu.core.encoding import routes_from_giant
    from vrpms_tpu.utils import current_date

    tour = [0]
    for route in routes_from_giant(res.giant):
        tour.extend(route)
        tour.append(0)
    return {
        "tour": tour,
        "total_time": float(jnp.asarray(res.breakdown.duration_sum)),
        "unvisited": list(unvisited or []),
        "date": current_date(),
    }


def perm_fitness_fn(
    inst: Instance,
    w: CostWeights,
    fleet_penalty: float = 1_000.0,
    mode: str = "auto",
):
    """Batched fitness for permutation genomes (GA population, ACO ants).

    Plain CVRP: greedy split distance + penalty per route over the fleet
    bound — via the gather-free one-hot/pointer-doubling formulation on
    accelerators (core.split.greedy_split_cost_hot_batch), the scan
    formulation on CPU. Timed instances (TW or time-dependent
    durations): full giant-tour evaluation so waiting/lateness are
    priced.
    """
    # Timed instances, makespan-priced objectives, and heterogeneous
    # fleets need the full giant-tour evaluation (the split-distance
    # shortcuts price none of those; per-vehicle capacities require the
    # positional giant pricing)
    full_eval = (
        inst.has_tw or inst.time_dependent or w.use_makespan or inst.het_fleet
    )
    v = inst.n_vehicles
    hot = resolve_eval_mode(mode) != "gather"

    def fit_timed(perm):
        giant = greedy_split_giant(perm, inst)
        return total_cost(evaluate_giant(giant, inst), w)

    def fit_plain(perm):
        cost, n_routes = greedy_split_cost(perm, inst)
        overflow = jnp.maximum(n_routes - v, 0).astype(jnp.float32)
        return cost + fleet_penalty * overflow

    if full_eval:
        if hot:
            # Split each genome, then evaluate the giants through the
            # gather-free batched objective (which prices TW + makespan)
            # instead of per-genome gather evaluation.
            def batch_full(perms):
                giants = jax.vmap(lambda p: greedy_split_giant(p, inst))(perms)
                return objective_batch_mode(giants, inst, w, mode)

            return batch_full
        return jax.vmap(fit_timed)
    if hot:
        def batch(perms):
            cost, n_routes = greedy_split_cost_hot_batch(perms, inst)
            overflow = jnp.maximum(n_routes - v, 0.0)
            return cost + fleet_penalty * overflow

        return batch
    return jax.vmap(fit_plain)
