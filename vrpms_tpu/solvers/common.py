"""Shared result container + the one block-driver loop for all solvers.

Every solver — bf, local_search, sa, ga, aco — returns the same
SolveResult so the service layer (the api->solver boundary the reference
prescribes at README.md:31-33 but never wired) is algorithm-agnostic.

This module also owns the deadline-aware block driver (`run_blocked`)
every iterative solver composes its jitted blocks through, the
measured-rate hint cache that lets a first block open fitted instead of
probing, and the donation/pipelining helpers the chunked drivers share
(see run_blocked's docstring for the VRPMS_PIPELINE contract).
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import (
    CostBreakdown,
    CostWeights,
    evaluate_giant,
    objective_batch_mode,
    resolve_eval_mode,
    total_cost,
)
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import (
    greedy_split_cost,
    greedy_split_cost_hot_batch,
    greedy_split_giant,
)


class SolveResult(NamedTuple):
    giant: jax.Array          # best giant tour found (core.encoding layout)
    cost: jax.Array           # scalar weighted objective of `giant`
    breakdown: CostBreakdown  # its cost components (distance, penalties, ...)
    evals: jax.Array          # candidate evaluations performed (throughput metric)
    pool: jax.Array | None = None  # optional [K, L] elite tours (best first,
                                   # pool[0] == giant) for multi-start polish


# ---------------------------------------------------------------------------
# pipelining + donation helpers (ISSUE 19)
# ---------------------------------------------------------------------------


def pipeline_enabled() -> bool:
    """The VRPMS_PIPELINE master switch (default on). Read per call so
    tests and embedders can toggle at runtime; `off` restores the
    serial driver loop exactly, including its per-block sync points."""
    from vrpms_tpu import config

    return config.enabled("VRPMS_PIPELINE")


@lru_cache(maxsize=1)
def donation_enabled() -> bool:
    """Whether block jits donate their loop-state buffers. Only on
    accelerators: XLA:CPU ignores donation (and jax warns per donated
    call), and CPU-side tests rely on entry arrays staying readable.
    Cached — the backend is fixed for the life of the process."""
    return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")


def maybe_donate_jit(fn):
    """jit a solver block body with its loop state (argument 0) donated
    on accelerators, so chained blocks update state in place instead of
    double-buffering the chain/population arrays — the pipelined driver
    otherwise holds two full copies of the loop state while block k+1
    computes. A plain jit on CPU (donation is a no-op there)."""
    if donation_enabled():
        return jax.jit(fn, donate_argnums=(0,))
    return jax.jit(fn)


def donate_safe_state(state):
    """Deep-copy a solver's ENTRY loop state when donation is active.

    Two hazards make the copy necessary exactly once, at loop entry:
    caller-owned seed arrays (warm-start pools, cached tours) must
    survive the first block's donation, and the solvers' aliased state
    tuples — SA's (giants, costs, giants, costs) — must donate four
    DISTINCT buffers, not the same one twice. Identity (free) on CPU."""
    if not donation_enabled():
        return state
    return jax.tree.map(jnp.copy, state)


@lru_cache(maxsize=1)
def _scalar_min_fn():
    """Jitted best-of-batch reduction: the pipelined driver syncs on
    this one device-side scalar per block boundary instead of pulling
    the full per-chain best array to host just to take its min."""
    return jax.jit(jnp.min)


def _scalar_best(best):
    """Reduce a sync payload to its scalar min on device; pass odd
    payloads (host scalars, already-reduced values) through unchanged —
    the record paths accept either."""
    try:
        return _scalar_min_fn()(best)
    except Exception:
        return best


def _now() -> float:
    import time

    return time.perf_counter()


def _timed_sync(timer, best) -> None:
    """Block on a sync payload, attributing the blocked wall time to the
    flight timer as device wait when one is installed (ISSUE 20). With
    no timer this is exactly the bare block_until_ready the driver
    always did — the analytics-off path adds zero work."""
    if timer is None:
        jax.block_until_ready(best)
        return
    t0 = _now()
    jax.block_until_ready(best)
    timer.note_wait(_now() - t0)


# ---------------------------------------------------------------------------
# measured-rate hint cache (shared by SA/GA/ACO and the batched launch)
# ---------------------------------------------------------------------------

# (solver, shape...) -> measured iterations/s of the last deadline-
# bounded run; run_blocked's first-block fit hint. Persisted alongside
# the XLA compile cache: a FRESH process otherwise starts hint-less and
# its first tight-deadline solve opens with a blind probe block (or,
# pre-hint, overshot by a whole unshrunk block — measured: the cold
# 30 s budget-series point ran 51 s while the warmed bench family holds
# 10 s budgets to ~5%). Generalized out of solvers.sa so GA/ACO and
# warmup seed the same cache (ISSUE 19 satellite).
_SWEEP_RATE: dict = {}
_RATE_LOADED = False


def _rate_cache_path():
    import os

    from vrpms_tpu import config

    return config.get("VRPMS_RATE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "vrpms_tpu_sweep_rates.json"
    )


def rate_get(key) -> float | None:
    global _RATE_LOADED
    if not _RATE_LOADED:
        _RATE_LOADED = True
        import json

        try:
            with open(_rate_cache_path()) as f:
                for k, v in json.load(f).items():
                    _SWEEP_RATE.setdefault(k, float(v))
        except (OSError, ValueError):
            pass
    return _SWEEP_RATE.get("|".join(map(str, key)))


def rate_put(key, rate: float) -> None:
    _SWEEP_RATE["|".join(map(str, key))] = float(rate)
    import json
    import os

    path = _rate_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_SWEEP_RATE, f)
        os.replace(tmp, path)
    except OSError:  # best-effort: a hint cache must never fail a solve
        pass


def run_blocked(
    step_block,
    state,
    n_total: int,
    block_size: int,
    deadline_s: float | None,
    sync,
    rate_hint: float | None = None,
    evals_per_iter: float | None = None,
    incumbent=None,
):
    """Deadline-aware composition of jitted iteration blocks — the one
    block-driver loop shared by SA, GA, and ACO (identical granularity
    contract everywhere: the host clock is checked between device-side
    blocks, so a deadline shorter than one block overshoots by that
    block's runtime).

    step_block(state, n_block, start) runs n_block iterations from
    absolute offset `start` (offsets arrive as dynamic scalars inside,
    so composed blocks reproduce the unbounded run exactly); sync(state)
    picks the array to block on for the clock check. Returns
    (state, iterations_done). deadline_s None runs everything as one
    block with no host sync.

    Deadline fidelity (VERDICT round-2 item 6): once at least one block
    has timed, the next block is SHRUNK to what the measured iteration
    rate says still fits the clock — in multiples of 128 so the set of
    compiled block shapes stays tiny (each extra shape is one
    persistent-cacheable compile, ever) — instead of the old run-whole-
    or-skip choice whose overshoot was a full block (~1.3 s at
    production shapes, 13% of a 10 s budget). `rate_hint` (iterations/s
    from a previous same-shape run; solvers cache it) lets even the
    FIRST block fit a short remaining budget — that unshrinkable first
    block of a late-starting ILS round was the residual overshoot. The
    hint is derated 20% so a tunnel-throughput wobble errs toward
    finishing early (the loop self-corrects from measured elapsed).

    `evals_per_iter` feeds the per-request convergence trace
    (vrpms_tpu.obs.trace): when a collector is active, every block
    boundary records (wall, best-of-sync, cumulative evals). With no
    collector — the default — the cost is one ContextVar read, and the
    deadline-free fast path gains no extra device sync.

    The live-progress sink (vrpms_tpu.obs.progress) rides the SAME
    cadence: when one is active, every block boundary also publishes
    the synced best to it, and a cooperative CANCEL flag is honored
    between blocks — the loop stops and the caller returns its
    incumbent. Neither path changes the block decomposition or any
    device computation, so fixed-seed trajectories are bit-identical
    with or without a sink attached.

    `incumbent(state)`, when given, extracts the champion TOUR from the
    loop state (solvers pass it so durable checkpointing can persist a
    resumable incumbent, not just its cost). It is called only when the
    sink's checkpoint handle says a capture is due
    (ProgressSink.want_incumbent — bounded VRPMS_CKPT_MS cadence), so
    the common case costs one attribute read per boundary; like the
    sink itself it only READS the already-synced state and never
    changes the trajectory.

    Pipelining (VRPMS_PIPELINE, default on): the timed driver is
    depth-1 pipelined over JAX async dispatch — block k+1 is LAUNCHED
    before block k's sync, so the host processes block k's results
    (trace record, sink publish, checkpoint capture, cancel flag,
    rate/deadline math) while k+1 computes on device. The device
    computation sequence is unchanged (same step_block calls, offsets,
    shapes — blocks compose exactly), so fixed-seed trajectories are
    bit-identical with pipelining on or off; what changes is reaction
    latency: cancel, the deadline, and checkpoint cadence are observed
    at launch gates, deferring each by AT MOST the one in-flight block
    (the fit-shrink prices launched-but-unsynced iterations into what
    still fits the clock). The per-boundary transfer also shrinks to a
    device-side scalar min of sync(state) — the full array crosses only
    for sinks that declare `needs_array` (the batched fanout) or when
    an incumbent capture is actually due. VRPMS_PIPELINE=off restores
    the serial loop exactly, including its sync points.
    """
    from vrpms_tpu.obs.analytics import current_timer
    from vrpms_tpu.obs.progress import active_sink
    from vrpms_tpu.obs.trace import active_trace

    trace = active_trace()
    sink = active_sink()
    timer = current_timer()  # flight-record timing; None = zero cost
    pipelined = pipeline_enabled()
    # a sink that consumes per-row bests (the batched fanout) opts out
    # of the device-side scalar reduction; an unknown sink without the
    # attribute conservatively keeps the full array
    needs_array = sink is not None and getattr(sink, "needs_array", True)
    if deadline_s is None:
        if sink is not None and sink.cancelled:
            # cancelled before the single unbounded block launched: the
            # caller's prepared state IS the incumbent. A cancel landing
            # mid-block instead runs the whole budget — there is no
            # boundary left to stop at, and the result is then NOT
            # marked cancelled (sink.note_cancel_seen never fires).
            sink.note_cancel_seen()
            return state, 0
        state = step_block(state, n_total, 0)
        if (
            trace is not None or sink is not None or timer is not None
        ) and n_total > 0:
            best = sync(state)
            if pipelined and not needs_array:
                best = _scalar_best(best)
            _timed_sync(timer, best)
            t0 = _now() if timer is not None else 0.0
            if trace is not None:
                trace.record(best, n_total, evals_per_iter)
            if sink is not None:
                sink.record(best, n_total, evals_per_iter)
                _maybe_capture(sink, incumbent, state)
            if timer is not None:
                timer.note_host(_now() - t0, overlapped=False)
        return state, n_total
    if not pipelined:
        return _run_serial(
            step_block, state, n_total, block_size, deadline_s, sync,
            rate_hint, evals_per_iter, incumbent, trace, sink, timer,
        )
    return _run_pipelined(
        step_block, state, n_total, block_size, deadline_s, sync,
        rate_hint, evals_per_iter, incumbent, trace, sink, needs_array,
        timer,
    )


def _run_serial(
    step_block, state, n_total, block_size, deadline_s, sync,
    rate_hint, evals_per_iter, incumbent, trace, sink, timer=None,
):
    """The pre-pipeline timed driver, byte-for-byte (VRPMS_PIPELINE=off
    contract): launch, sync, process, then launch again — the device
    idles during every host-side boundary, but every check reacts
    within the block that just finished."""
    import time

    block = max(1, min(n_total, block_size))
    done = 0
    t_start = time.monotonic()
    while done < n_total:
        if sink is not None and sink.cancelled:
            sink.note_cancel_seen()
            break
        nb = min(block, n_total - done)
        elapsed = time.monotonic() - t_start
        remaining_t = deadline_s - elapsed
        rate = (
            done / elapsed
            if done
            else (0.8 * rate_hint if rate_hint else None)
        )
        if rate is not None:
            if remaining_t <= 0 and done:
                break
            fit = int(rate * max(remaining_t, 0.0))
            if fit < nb:
                nb = (fit // 128) * 128
                if nb < 128:
                    if done:
                        break
                    nb = min(128, n_total)  # a call always runs SOMETHING
        elif nb > 128:
            # No rate known at all (fresh shape, empty cache): open with
            # one small block to MEASURE instead of committing a whole
            # block blind — a full 512-sweep block against a 1 s budget
            # was the residual first-solve overshoot (VERDICT round 3).
            # Costs at most 3 extra host syncs on generous deadlines;
            # the measured rate fits every later block.
            nb = 128
        state = step_block(state, nb, done)
        best = sync(state)
        _timed_sync(timer, best)
        done += nb
        t0 = _now() if timer is not None else 0.0
        if trace is not None:
            trace.record(best, nb, evals_per_iter)
        if sink is not None:
            sink.record(best, nb, evals_per_iter)
            _maybe_capture(sink, incumbent, state)
        if timer is not None:
            # serial boundaries never overlap device compute: the next
            # block launches only after this bookkeeping finishes
            timer.note_host(_now() - t0, overlapped=False)
        if time.monotonic() - t_start >= deadline_s:
            break
    return state, done


def _fit_block(
    block, n_total, launched, done, t_start, t_sync, deadline_s, rate_hint,
):
    """Next-block sizing for the pipelined driver — the serial loop's
    fit-shrink logic with in-flight work priced in: the measured rate
    comes from iterations already SYNCED (done over the wall clock at
    the last sync), and iterations launched-but-unsynced are subtracted
    from what the remaining clock still fits. Returns 0 to stop (the
    deadline math says nothing more fits and something already ran)."""
    import time

    nb = min(block, n_total - launched)
    remaining_t = deadline_s - (time.monotonic() - t_start)
    rate = None
    if done:
        measured = t_sync - t_start
        if measured > 0:
            rate = done / measured
    elif rate_hint:
        rate = 0.8 * rate_hint
    if rate is not None:
        if remaining_t <= 0 and (done or launched):
            return 0
        fit = int(rate * max(remaining_t, 0.0)) - (launched - done)
        if fit < nb:
            nb = (fit // 128) * 128
            if nb < 128:
                if done or launched:
                    return 0
                nb = min(128, n_total)  # a call always runs SOMETHING
    elif nb > 128:
        # no rate known: open with a small probe block to MEASURE (the
        # serial opener's contract; under pipelining a second probe can
        # launch before the first syncs — the decomposition differs but
        # the composed trajectory does not)
        nb = 128
    return nb


def _run_pipelined(
    step_block, state, n_total, block_size, deadline_s, sync,
    rate_hint, evals_per_iter, incumbent, trace, sink, needs_array,
    timer=None,
):
    """Depth-1 pipelined timed driver (see run_blocked's contract).

    Loop invariant: at most ONE launched-but-unprocessed block exists
    (`prev`). Each turn first launches the next block — so the device
    stays busy while the host works — then syncs and processes the
    PREVIOUS block's results while the new one computes. Cancel and the
    deadline are observable only at launch gates, so reaction defers by
    at most the one in-flight block (which is always drained and
    recorded before return: `done` counts every launched block).

    Donation interplay: launching block k+1 donates block k's state
    buffers, so everything processing needs — the synced best (scalar,
    or a copy of the full array for fanout sinks) and, when a capture
    is due, the incumbent tour — is extracted at launch time, before
    the next launch can invalidate it. Without donation (CPU) the
    incumbent is extracted at processing time instead, preserving the
    serial capture cadence exactly.
    """
    import time

    block = max(1, min(n_total, block_size))
    launched = 0  # iterations dispatched to the device
    done = 0      # iterations synced and processed
    t_start = time.monotonic()
    t_sync = [t_start]  # wall clock of the last processed sync
    done_box = [0]
    donated = donation_enabled()

    def process(blk, overlapped=False):
        nb_p, best_p, state_p, inc_p = blk
        _timed_sync(timer, best_p)
        t_sync[0] = time.monotonic()
        done_box[0] += nb_p
        t0 = _now() if timer is not None else 0.0
        if trace is not None:
            trace.record(best_p, nb_p, evals_per_iter)
        if sink is not None:
            sink.record(best_p, nb_p, evals_per_iter)
            if not donated:
                _maybe_capture(sink, incumbent, state_p)
            elif inc_p is not None:
                try:
                    sink.offer_incumbent(inc_p)
                except Exception:
                    pass  # capture must never kill the device loop
        if timer is not None:
            # overlapped=True only when another block is already in
            # flight behind this sync — that host work hides under
            # device compute; the drains (opener, stop re-fit, final)
            # run with an idle device
            timer.note_host(_now() - t0, overlapped=overlapped)

    prev = None  # in-flight block: (nb, best, state, incumbent|None)
    while True:
        done = done_box[0]
        if (
            prev is not None
            and launched < n_total
            and not done
            and not rate_hint
        ):
            # No rate known and the measuring block is still in flight:
            # DRAIN it before sizing the next launch, exactly like the
            # serial opener — pipelining engages from the second
            # boundary on, and the launch sequence (sizes + offsets)
            # matches the serial loop's whenever the fit never shrinks,
            # which is what keeps fixed-seed runs bit-identical across
            # modes (the presampled move streams are drawn per block).
            process(prev)
            prev = None
            done = done_box[0]
        cur = None
        if launched < n_total:
            stop = False
            if sink is not None and sink.cancelled:
                sink.note_cancel_seen()
                stop = True
            elif launched and time.monotonic() - t_start >= deadline_s:
                stop = True
            if not stop:
                nb = _fit_block(
                    block, n_total, launched, done,
                    t_start, t_sync[0], deadline_s, rate_hint,
                )
                if nb == 0 and prev is not None and not done:
                    # The stop verdict rests on the derated HINT — no
                    # block has synced yet. The serial loop can never
                    # stop unmeasured (it breaks only `if done`), and a
                    # stale hint from a compile-polluted run can under-
                    # state the true rate by orders of magnitude, which
                    # would end the solve at a fraction of its budget.
                    # Drain the in-flight block and re-fit on the
                    # MEASURED rate before accepting the stop.
                    process(prev)
                    prev = None
                    done = done_box[0]
                    nb = _fit_block(
                        block, n_total, launched, done,
                        t_start, t_sync[0], deadline_s, rate_hint,
                    )
                if nb > 0:
                    new_state = step_block(state, nb, launched)
                    best = sync(new_state)
                    if not needs_array:
                        best = _scalar_best(best)
                    elif donated:
                        # the NEXT launch donates new_state's buffers;
                        # keep an independent copy of the full array
                        best = jnp.copy(best)
                    inc = None
                    if donated and incumbent is not None and sink is not None:
                        # pre-extract the champion tour while the state
                        # is still valid; the cadence check runs one
                        # block early, but the offer still lands at this
                        # block's processing
                        want = getattr(sink, "want_incumbent", None)
                        try:
                            if want is not None and want():
                                inc = incumbent(new_state)
                        except Exception:
                            inc = None  # capture must never kill the loop
                    cur = (nb, best, new_state, inc)
                    state = new_state
                    launched += nb
        if prev is not None:
            process(prev, overlapped=cur is not None)
        prev = cur
        if prev is None:
            break
    return state, done_box[0]


def _maybe_capture(sink, incumbent, state) -> None:
    """Offer the champion tour to the sink's durable-checkpoint handle
    when a capture is due (see run_blocked's `incumbent` contract).
    Batched fanouts and shard rollups carry no capture protocol — the
    getattr guard makes them (and plain sinks with no handle) free."""
    if incumbent is None:
        return
    want = getattr(sink, "want_incumbent", None)
    if want is None or not want():
        return
    try:
        sink.offer_incumbent(incumbent(state))
    except Exception:
        pass  # capture must never kill the device loop


def seed_objective(giant, inst: Instance, w: CostWeights | None = None) -> float:
    """Exact scalar objective of a seed tour — the ONE pricing that
    continuation-budget decisions use (sa.continuation_params estimates
    the re-entry temperature from it), so the schedule a warm re-solve
    continues with is derived from the same objective the solver
    anneals. One device dispatch; host float out."""
    from vrpms_tpu.core.cost import exact_cost

    _, cost = exact_cost(giant, inst, w or CostWeights.make())
    return float(cost)


def solve_info(res: SolveResult, unvisited: list | None = None) -> dict:
    """Reference-shaped solve summary: {tour, total_time, unvisited, date}.

    The reference's solver entry returns exactly these keys with
    placeholder values (reference src/solver.py:18-27: a random depot-
    wrapped shuffle, constant total_time, empty unvisited, dated via
    src/utilities/helper.py). Here they are real: the winning giant tour
    flattened to one depot-wrapped node list, the summed route durations,
    and the customers excluded from this solve (the dynamic re-solve
    inputs — SURVEY.md §5 checkpoint/resume).
    """
    from vrpms_tpu.core.encoding import routes_from_giant
    from vrpms_tpu.utils import current_date

    tour = [0]
    for route in routes_from_giant(res.giant):
        tour.extend(route)
        tour.append(0)
    return {
        "tour": tour,
        "total_time": float(jnp.asarray(res.breakdown.duration_sum)),
        "unvisited": list(unvisited or []),
        "date": current_date(),
    }


def perm_fitness_fn(
    inst: Instance,
    w: CostWeights,
    fleet_penalty: float = 1_000.0,
    mode: str = "auto",
):
    """Batched fitness for permutation genomes (GA population, ACO ants).

    Plain CVRP: greedy split distance + penalty per route over the fleet
    bound — via the gather-free one-hot/pointer-doubling formulation on
    accelerators (core.split.greedy_split_cost_hot_batch), the scan
    formulation on CPU. Timed instances (TW or time-dependent
    durations): full giant-tour evaluation so waiting/lateness are
    priced.
    """
    # Timed instances, makespan-priced objectives, and heterogeneous
    # fleets need the full giant-tour evaluation (the split-distance
    # shortcuts price none of those; per-vehicle capacities require the
    # positional giant pricing)
    full_eval = (
        inst.has_tw or inst.time_dependent or w.use_makespan or inst.het_fleet
    )
    v = inst.n_vehicles
    hot = resolve_eval_mode(mode) != "gather"

    def fit_timed(perm):
        giant = greedy_split_giant(perm, inst)
        return total_cost(evaluate_giant(giant, inst), w)

    def fit_plain(perm):
        cost, n_routes = greedy_split_cost(perm, inst)
        overflow = jnp.maximum(n_routes - v, 0).astype(jnp.float32)
        return cost + fleet_penalty * overflow

    if full_eval:
        if hot:
            # Split each genome, then evaluate the giants through the
            # gather-free batched objective (which prices TW + makespan)
            # instead of per-genome gather evaluation.
            def batch_full(perms):
                giants = jax.vmap(lambda p: greedy_split_giant(p, inst))(perms)
                return objective_batch_mode(giants, inst, w, mode)

            return batch_full
        return jax.vmap(fit_timed)
    if hot:
        def batch(perms):
            cost, n_routes = greedy_split_cost_hot_batch(perms, inst)
            overflow = jnp.maximum(n_routes - v, 0.0)
            return cost + fleet_penalty * overflow

        return batch
    return jax.vmap(fit_plain)
