"""Ant colony optimization with a dense on-device pheromone matrix.

Fills the reference's ACO endpoints (`# TODO: Run algorithm`, reference
api/vrp/aco/index.py:40-45, api/tsp/aco/index.py). The design leans on
what TPUs are good at (SURVEY.md §7 step 6): the pheromone state is a
dense f32[N, N] matrix, every construction step is a batched categorical
sample over all N nodes at once (Gumbel-argmax over masked log-scores,
so sampling is a vectorised reduction, not a host-side roulette wheel),
and all A ants advance in lockstep through one `lax.scan` of n steps.

Update rule is MMAS-flavoured: evaporation + deposit along the best
ant's split route edges (depot hops included), with tau clipping to
[tau_min, tau_max] to keep exploration alive.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.solvers.common import SolveResult, perm_fitness_fn


@dataclasses.dataclass(frozen=True)
class ACOParams:
    n_ants: int = 128
    n_iters: int = 200
    alpha: float = 1.0        # pheromone exponent
    beta: float = 2.5         # heuristic (1/duration) exponent
    rho: float = 0.1          # evaporation rate
    fleet_penalty: float = 1_000.0
    knn_k: int = 16           # candidate-list width for construction;
                              # 0 = sample over all unvisited nodes


def _construct_orders(key, tau, eta, n_ants: int, mode: str = "auto", knn_mask=None):
    """All ants build customer orders in lockstep.

    Step k: score[a, c] = alpha*log tau[cur_a, c] + beta*log eta[cur_a, c]
    over unvisited customers, plus Gumbel noise -> argmax is a sample from
    the ACO construction distribution. The per-step row lookup and the
    visited-set update run as one-hot matmul / mask ops on accelerators
    (gathers and scatters lower to scalar loops on TPU); the one-hot of
    the current node is reused from the previous step's argmax.

    With `knn_mask` ([N, N] 0/1, knn_mask[u, v] = 1 iff v is one of u's
    K nearest), sampling restricts to the current node's candidate list
    — the classic construction speed/quality lever (most good next hops
    are geometric neighbors) — falling back to all unvisited nodes for
    ants whose whole candidate list is already visited.
    """
    from vrpms_tpu.core.cost import resolve_eval_mode

    n_nodes = tau.shape[0]
    log_score = jnp.log(jnp.maximum(tau, 1e-30)) + jnp.log(
        jnp.maximum(eta, 1e-30)
    )
    hot = resolve_eval_mode(mode) != "gather"

    def pick(scores, allowed, visited, k):
        gumbel = jax.random.gumbel(jax.random.fold_in(key, k), (n_ants, n_nodes))
        noisy = scores + gumbel
        open_ = ~visited
        if allowed is not None:
            cand = allowed & open_
            # fall back to the full unvisited set when the list is spent
            has = cand.any(axis=1, keepdims=True)
            cand = jnp.where(has, cand, open_)
        else:
            cand = open_
        return jnp.argmax(jnp.where(cand, noisy, -jnp.inf), axis=1).astype(
            jnp.int32
        )

    visited0 = jnp.zeros((n_ants, n_nodes), dtype=bool).at[:, 0].set(True)
    if hot:
        def step(carry, k):
            cur_oh, visited = carry
            scores = jnp.einsum(
                "an,nm->am",
                cur_oh.astype(jnp.bfloat16),
                log_score.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            allowed = None
            if knn_mask is not None:
                allowed = (
                    jnp.einsum(
                        "an,nm->am",
                        cur_oh.astype(jnp.bfloat16),
                        knn_mask.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    )
                    > 0.5
                )
            nxt = pick(scores, allowed, visited, k)
            nxt_oh = nxt[:, None] == jnp.arange(n_nodes)[None, :]
            return (nxt_oh.astype(jnp.float32), visited | nxt_oh), nxt

        init = (jnp.zeros((n_ants, n_nodes)).at[:, 0].set(1.0), visited0)
    else:
        def step(carry, k):
            cur, visited = carry
            allowed = knn_mask[cur] > 0.5 if knn_mask is not None else None
            nxt = pick(log_score[cur], allowed, visited, k)
            visited = visited.at[jnp.arange(n_ants), nxt].set(True)
            return (nxt, visited), nxt

        init = (jnp.zeros(n_ants, dtype=jnp.int32), visited0)
    _, orders = jax.lax.scan(step, init, jnp.arange(n_nodes - 1))
    return orders.T  # [A, n]


def _deposit_edges(giant):
    return giant[:-1], giant[1:]


@lru_cache(maxsize=32)
def _aco_block_fn(params: ACOParams, n_block: int):
    """Build (and cache) one jitted block of n_block colony iterations
    (see sa._sa_block_fn's rationale: cross-request compile reuse with
    bounded retention; blocks compose so a deadline-driven solve can
    check the host clock between device-side blocks). Callers pass
    params with `n_iters` normalized to 0 — the block never reads it —
    so requests differing only in iteration budget share one compile."""

    @jax.jit
    def run(state, key, inst, w, start_it, knn_mask):
        n_nodes = inst.n_nodes
        fitness = perm_fitness_fn(inst, w, params.fleet_penalty)
        d = inst.durations[0]
        eta = (1.0 / jnp.maximum(d, 1e-6)) ** params.beta
        alpha = params.alpha
        rho = params.rho

        def iteration(state, it):
            tau, best_perm, best_fit = state
            k_it = jax.random.fold_in(key, it)
            orders = _construct_orders(
                k_it, tau ** alpha, eta, params.n_ants, knn_mask=knn_mask
            )
            fits = fitness(orders)
            champ = jnp.argmin(fits)
            it_best_perm, it_best_fit = orders[champ], fits[champ]
            better = it_best_fit < best_fit
            best_perm = jnp.where(better, it_best_perm, best_perm)
            best_fit = jnp.where(better, it_best_fit, best_fit)
            # Evaporate, then deposit along the iteration-best ant's
            # actual split route (depot hops included) scaled by quality.
            giant = greedy_split_giant(it_best_perm, inst)
            src, dst = _deposit_edges(giant)
            amount = 1.0 / jnp.maximum(it_best_fit, 1e-6)
            tau = (1.0 - rho) * tau
            tau = tau.at[src, dst].add(amount)
            # MMAS-style trail limits keep exploration alive.
            tau_max = 1.0 / (rho * jnp.maximum(best_fit, 1e-6))
            tau_min = tau_max / (2.0 * n_nodes)
            tau = jnp.clip(tau, tau_min, tau_max)
            return (tau, best_perm, best_fit), None

        state, _ = jax.lax.scan(
            iteration, state, start_it + jnp.arange(n_block)
        )
        return state

    return run


@lru_cache(maxsize=8)
def _aco_init_fn(params: ACOParams):
    """Jitted colony-state init (tau0 scale + incumbent evaluation)."""

    @jax.jit
    def init(inst, w):
        n = inst.n_customers
        fitness = perm_fitness_fn(inst, w, params.fleet_penalty)
        d = inst.durations[0]
        # Rough NN-scale init: tau0 = 1 / (n * mean-duration); exact
        # value is irrelevant once MMAS clipping engages.
        tau0 = 1.0 / (n * jnp.maximum(jnp.mean(d), 1e-6))
        tau = jnp.full((inst.n_nodes, inst.n_nodes), tau0)
        best_perm = jnp.arange(1, n + 1, dtype=jnp.int32)
        return tau, best_perm, fitness(best_perm[None])[0]

    return init


def solve_aco(
    inst: Instance,
    key: jax.Array | int = 0,
    params: ACOParams = ACOParams(),
    weights: CostWeights | None = None,
    deadline_s: float | None = None,
) -> SolveResult:
    """MMAS colony search; with `deadline_s` the colony runs in fixed
    16-iteration device blocks under common.run_blocked's granularity
    contract."""
    from vrpms_tpu.solvers.common import run_blocked

    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)

    # normalize everything the traced block never reads out of the
    # compile key (knn_k only shapes the dynamic knn_mask argument)
    block_params = dataclasses.replace(params, n_iters=0, knn_k=0)
    state = _aco_init_fn(block_params)(inst, w)
    knn_mask = None
    if params.knn_k > 0:
        import numpy as np

        from vrpms_tpu.moves import knn_table

        tbl = np.asarray(knn_table(inst.durations[0], params.knn_k))
        mask = np.zeros((inst.n_nodes, inst.n_nodes), dtype=bool)
        mask[np.arange(inst.n_nodes)[:, None], tbl] = True
        knn_mask = jnp.asarray(mask)

    def step_block(st, nb, start):
        return _aco_block_fn(block_params, nb)(
            st, key, inst, w, jnp.int32(start), knn_mask
        )

    state, done = run_blocked(
        step_block, state, params.n_iters, 16, deadline_s, lambda st: st[2]
    )

    best_perm = state[1]
    giant = greedy_split_giant(best_perm, inst)
    bd = evaluate_giant(giant, inst)
    return SolveResult(
        giant,
        total_cost(bd, w),
        bd,
        jnp.int32(params.n_ants * done),
    )
