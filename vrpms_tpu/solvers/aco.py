"""Ant colony optimization with a dense on-device pheromone matrix.

Fills the reference's ACO endpoints (`# TODO: Run algorithm`, reference
api/vrp/aco/index.py:40-45, api/tsp/aco/index.py). The design leans on
what TPUs are good at (SURVEY.md §7 step 6): the pheromone state is a
dense f32[N, N] matrix, every construction step is a batched categorical
sample over all N nodes at once (Gumbel-argmax over masked log-scores,
so sampling is a vectorised reduction, not a host-side roulette wheel),
and all A ants advance in lockstep through one `lax.scan` of n steps.

Update rule is MMAS-flavoured: evaporation + deposit along the best
ant's split route edges (depot hops included), with tau clipping to
[tau_min, tau_max] to keep exploration alive.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, exact_cost
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.solvers.common import (
    SolveResult,
    donate_safe_state,
    maybe_donate_jit,
    perm_fitness_fn,
    rate_get,
    rate_put,
)


@dataclasses.dataclass(frozen=True)
class ACOParams:
    n_ants: int = 128
    n_iters: int = 200
    alpha: float = 1.0        # pheromone exponent
    beta: float = 2.5         # heuristic (1/duration) exponent
    rho: float = 0.15         # evaporation rate (0.15 with the MMAS
                              # clip measured best on the bench seed;
                              # 0.1 was the round-2 default)
    fleet_penalty: float = 1_000.0
    knn_k: int = 16           # candidate-list width for construction;
                              # 0 = sample over all unvisited nodes
    gb_every: int = 3         # every gb_every-th deposit follows the
                              # GLOBAL best instead of the iteration
                              # best — the classic MMAS alternation
                              # (intensify around the incumbent without
                              # freezing exploration); 0 = always
                              # iteration-best (the round-2 behavior)
    deposit_polish_sweeps: int = 2
                              # delta-polish sweeps applied to the
                              # deposit tour before its edges hit the
                              # trails: ants learn POLISHED edges, not
                              # raw construction noise; 0 = off


def _construct_orders(
    key, tau, eta, n_ants: int, mode: str = "auto", knn_mask=None, n_real=None
):
    """All ants build customer orders in lockstep.

    Step k: score[a, c] = alpha*log tau[cur_a, c] + beta*log eta[cur_a, c]
    over unvisited customers, plus Gumbel noise -> argmax is a sample from
    the ACO construction distribution. The per-step row lookup and the
    visited-set update run as one-hot matmul / mask ops on accelerators
    (gathers and scatters lower to scalar loops on TPU); the one-hot of
    the current node is reused from the previous step's argmax.

    With `knn_mask` ([N, N] 0/1, knn_mask[u, v] = 1 iff v is one of u's
    K nearest), sampling restricts to the current node's candidate list
    — the classic construction speed/quality lever (most good next hops
    are geometric neighbors) — falling back to all unvisited nodes for
    ants whose whole candidate list is already visited.

    Tier-padded instances (`n_real` traced): phantom nodes start out
    marked visited, so ants only ever construct over the real set; once
    every real customer is placed the remaining steps emit depot zeros
    (the all-masked argmax fallback), which the split prices as empty
    separators — cost-neutral tail filler, exactly like the phantoms
    the genome-level operators park there.
    """
    from vrpms_tpu.core.cost import resolve_eval_mode

    n_nodes = tau.shape[0]
    log_score = jnp.log(jnp.maximum(tau, 1e-30)) + jnp.log(
        jnp.maximum(eta, 1e-30)
    )
    hot = resolve_eval_mode(mode) != "gather"

    def pick(scores, allowed, visited, k):
        gumbel = jax.random.gumbel(jax.random.fold_in(key, k), (n_ants, n_nodes))
        noisy = scores + gumbel
        open_ = ~visited
        if allowed is not None:
            cand = allowed & open_
            # fall back to the full unvisited set when the list is spent
            has = cand.any(axis=1, keepdims=True)
            cand = jnp.where(has, cand, open_)
        else:
            cand = open_
        return jnp.argmax(jnp.where(cand, noisy, -jnp.inf), axis=1).astype(
            jnp.int32
        )

    visited0 = jnp.zeros((n_ants, n_nodes), dtype=bool).at[:, 0].set(True)
    if n_real is not None:
        visited0 = visited0 | (jnp.arange(n_nodes) >= n_real)[None, :]
    if hot:
        def step(carry, k):
            cur_oh, visited = carry
            scores = jnp.einsum(
                "an,nm->am",
                cur_oh.astype(jnp.bfloat16),
                log_score.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            allowed = None
            if knn_mask is not None:
                allowed = (
                    jnp.einsum(
                        "an,nm->am",
                        cur_oh.astype(jnp.bfloat16),
                        knn_mask.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32,
                    )
                    > 0.5
                )
            nxt = pick(scores, allowed, visited, k)
            nxt_oh = nxt[:, None] == jnp.arange(n_nodes)[None, :]
            return (nxt_oh.astype(jnp.float32), visited | nxt_oh), nxt

        init = (jnp.zeros((n_ants, n_nodes)).at[:, 0].set(1.0), visited0)
    else:
        def step(carry, k):
            cur, visited = carry
            allowed = knn_mask[cur] > 0.5 if knn_mask is not None else None
            nxt = pick(log_score[cur], allowed, visited, k)
            visited = visited.at[jnp.arange(n_ants), nxt].set(True)
            return (nxt, visited), nxt

        init = (jnp.zeros(n_ants, dtype=jnp.int32), visited0)
    _, orders = jax.lax.scan(step, init, jnp.arange(n_nodes - 1))
    return orders.T  # [A, n]


def _deposit_edges(giant):
    return giant[:-1], giant[1:]


def deposit(tau, giant, amount, hot: bool):
    """tau + amount along the giant tour's edges (multiplicity counted).

    Hot path: the scatter `tau.at[src, dst].add` lowers to a serial
    scalar loop on TPU; the same update is the rank-L outer-product
    accumulation  tau += amount * src_ohT @ dst_oh  — one MXU einsum.
    One-hot counts are integers <= L (exact in bf16 for L <= 256;
    onehot_dtype widens beyond), so both paths add exactly the same
    multiset of edges, including repeated (0, 0) hops of unused
    vehicles.
    """
    src, dst = _deposit_edges(giant)
    if not hot:
        return tau.at[src, dst].add(amount)
    from vrpms_tpu.core.cost import _onehot, onehot_dtype

    n = tau.shape[0]
    dt = onehot_dtype(max(n, giant.shape[0]))
    src_oh = _onehot(src, n, dt)
    dst_oh = _onehot(dst, n, dt)
    counts = jnp.einsum(
        "kn,km->nm", src_oh, dst_oh, preferred_element_type=jnp.float32
    )
    return tau + amount * counts


def _merge_pool(pool_perms, pool_fits, orders, fits):
    """Fold an iteration's ant orders into the running top-K elite pool
    (best first, deduplicated by fitness equality is NOT attempted —
    distinct basins matter more than distinct costs)."""
    all_perms = jnp.concatenate([pool_perms, orders])
    all_fits = jnp.concatenate([pool_fits, fits])
    order = jnp.argsort(all_fits)[: pool_fits.shape[0]]
    return all_perms[order], all_fits[order]


def aco_iteration(state, it, key, inst, w, params: ACOParams, knn_mask, hot: bool):
    """One colony iteration — construct, evaluate, deposit, clip.

    Exposed standalone (like sa.sa_chain_step) so the single-device
    block fn and the island-model driver run the exact same step.
    State: (tau, best_perm, best_fit, pool_perms, pool_fits); the pool
    arrays may be zero-length (K=0) when no elite pool is requested.
    """
    fitness = perm_fitness_fn(inst, w, params.fleet_penalty)
    n_nodes = inst.n_nodes
    d = inst.durations[0]
    eta = (1.0 / jnp.maximum(d, 1e-6)) ** params.beta

    tau, best_perm, best_fit, pool_perms, pool_fits = state
    k_it = jax.random.fold_in(key, it)
    orders = _construct_orders(
        k_it, tau ** params.alpha, eta, params.n_ants, knn_mask=knn_mask,
        n_real=inst.n_real,
    )
    fits = fitness(orders)
    champ = jnp.argmin(fits)
    it_best_perm, it_best_fit = orders[champ], fits[champ]
    better = it_best_fit < best_fit
    best_perm = jnp.where(better, it_best_perm, best_perm)
    best_fit = jnp.where(better, it_best_fit, best_fit)
    if pool_perms.shape[0]:
        pool_perms, pool_fits = _merge_pool(pool_perms, pool_fits, orders, fits)
    # Evaporate, then deposit along the deposit tour's actual split
    # route (depot hops included) scaled by quality. The deposit tour
    # alternates iteration-best / global-best (gb_every) and is
    # delta-polished first (deposit_polish_sweeps) so the trails learn
    # improved edges — both measured on the n=100 bench seed: 19547
    # (round 2, raw iteration-best) -> at/below the GA's 19089.
    if params.gb_every > 0:
        use_gb = (it % params.gb_every) == (params.gb_every - 1)
        dep_perm = jnp.where(use_gb, best_perm, it_best_perm)
        dep_fit = jnp.where(use_gb, best_fit, it_best_fit)
    else:
        dep_perm, dep_fit = it_best_perm, it_best_fit
    giant = greedy_split_giant(dep_perm, inst)
    amount = 1.0 / jnp.maximum(dep_fit, 1e-6)
    if params.deposit_polish_sweeps > 0:
        from vrpms_tpu.solvers.delta_ls import delta_polish_batch

        g2, c2, _ = delta_polish_batch(
            giant[None], inst, w,
            max_sweeps=params.deposit_polish_sweeps, top_k=4,
        )
        giant = g2[0]
        amount = 1.0 / jnp.maximum(c2[0], 1e-6)
    tau = deposit((1.0 - params.rho) * tau, giant, amount, hot)
    # MMAS-style trail limits keep exploration alive.
    tau_max = 1.0 / (params.rho * jnp.maximum(best_fit, 1e-6))
    tau_min = tau_max / (2.0 * n_nodes)
    tau = jnp.clip(tau, tau_min, tau_max)
    return (tau, best_perm, best_fit, pool_perms, pool_fits)


@lru_cache(maxsize=32)
def _aco_block_fn(params: ACOParams, n_block: int):
    """Build (and cache) one jitted block of n_block colony iterations
    (see sa._sa_block_fn's rationale: cross-request compile reuse with
    bounded retention; blocks compose so a deadline-driven solve can
    check the host clock between device-side blocks). Callers pass
    params with `n_iters` normalized to 0 — the block never reads it —
    so requests differing only in iteration budget share one compile.
    On accelerators the loop state (arg 0) is DONATED — see
    sa._sa_block_fn; callers enter through donate_safe_state."""
    from vrpms_tpu.core.cost import resolve_eval_mode

    @maybe_donate_jit
    def run(state, key, inst, w, start_it, knn_mask):
        hot = resolve_eval_mode("auto") != "gather"

        def iteration(st, it):
            return aco_iteration(st, it, key, inst, w, params, knn_mask, hot), None

        state, _ = jax.lax.scan(
            iteration, state, start_it + jnp.arange(n_block)
        )
        return state

    return run


#: pheromone pre-deposit multipliers (of tau0) for a warm seed's route
#: edges: plain warm starts get a light bias; CONTINUATION seeds (an
#: already-annealed tour of a neighboring instance — the dynamic
#: re-solve and boundary re-opt paths) pre-deposit hard enough that the
#: colony starts near-converged on the seed tour and spends its budget
#: refining it, the ACO analogue of sa.continuation_params
WARM_DEPOSIT = 2.0
CONTINUATION_DEPOSIT = 6.0


@lru_cache(maxsize=16)
def _aco_init_fn(params: ACOParams, pool: int, warm: bool = False,
                 deposit_scale: float = WARM_DEPOSIT):
    """Jitted colony-state init (tau0 scale + incumbent evaluation).

    `init_perm` is the starting incumbent — identity order by default,
    or (warm=True) a warm-start seed: it is evaluated as best-so-far
    (so the solve can never return worse than the checkpoint), and a
    WARM seed's split route additionally receives a deposit_scale x
    tau0 pheromone head start, biasing early construction toward the
    known-good edges without freezing exploration (MMAS clipping
    re-engages immediately). Cold solves keep the classic uniform
    pheromone init — the identity incumbent is arbitrary and must not
    steer construction. `pool` > 0 allocates the top-K elite pool
    (seeded with the incumbent; empty slots at +inf).
    """
    from vrpms_tpu.core.cost import resolve_eval_mode

    @jax.jit
    def init(inst, w, init_perm):
        from vrpms_tpu.core.instance import mean_duration

        n = inst.real_nodes - 1  # real customer count (traced if padded)
        fitness = perm_fitness_fn(inst, w, params.fleet_penalty)
        d = inst.durations[0]
        hot = resolve_eval_mode("auto") != "gather"
        # Rough NN-scale init: tau0 = 1 / (n * mean-duration); exact
        # value is irrelevant once MMAS clipping engages. Masked on
        # padded instances so the scale tracks the real problem.
        tau0 = 1.0 / (n * jnp.maximum(mean_duration(inst), 1e-6))
        tau = jnp.full((inst.n_nodes, inst.n_nodes), tau0)
        if warm:
            tau = deposit(
                tau,
                greedy_split_giant(init_perm, inst),
                deposit_scale * tau0,
                hot,
            )
        fit0 = fitness(init_perm[None])[0]
        pool_perms = jnp.tile(init_perm[None], (pool, 1))
        pool_fits = jnp.full((pool,), jnp.inf).at[:1].set(fit0)
        return tau, init_perm, fit0, pool_perms, pool_fits

    return init


def solve_aco(
    inst: Instance,
    key: jax.Array | int = 0,
    params: ACOParams = ACOParams(),
    weights: CostWeights | None = None,
    deadline_s: float | None = None,
    init_perm: jax.Array | None = None,
    pool: int = 0,
    continuation: bool = False,
) -> SolveResult:
    """MMAS colony search; with `deadline_s` the colony runs in fixed
    16-iteration device blocks under common.run_blocked's granularity
    contract.

    `init_perm` warm-starts the colony (incumbent + pheromone head
    start, see _aco_init_fn) — the solve never returns worse than the
    seed; `continuation` (a seed from an explicit re-solve source)
    raises the pre-deposit to CONTINUATION_DEPOSIT so the colony
    refines the seed tour instead of re-exploring from a light bias.
    `pool` > 0 additionally returns the top-`pool` ant orders seen
    across all iterations as split giants (SolveResult.pool, best
    first) — the multi-start polish hook every other solver exposes.
    """
    from vrpms_tpu.solvers.common import run_blocked

    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)

    # normalize everything the traced block never reads out of the
    # compile key (knn_k only shapes the dynamic knn_mask argument)
    block_params = dataclasses.replace(params, n_iters=0, knn_k=0)
    warm = init_perm is not None
    if init_perm is None:
        init_perm = jnp.arange(1, inst.n_customers + 1, dtype=jnp.int32)
    scale = CONTINUATION_DEPOSIT if (warm and continuation) else WARM_DEPOSIT
    # donate_safe_state: distinct buffers for the donated colony state
    # on accelerators (the init fn's pool slots tile the incumbent);
    # identity on CPU
    state = donate_safe_state(
        _aco_init_fn(block_params, pool, warm, scale)(inst, w, init_perm)
    )
    knn_mask = aco_knn_mask(inst, params.knn_k)

    def step_block(st, nb, start):
        return _aco_block_fn(block_params, nb)(
            st, key, inst, w, jnp.int32(start), knn_mask
        )

    # measured colony iterations/s per shape — same first-block fit
    # hint seam as SA/GA (warmup or a prior solve seeds it)
    rate_key = ("aco", params.n_ants, inst.n_nodes, pool)
    import time as _time

    t_run = _time.monotonic()
    state, done = run_blocked(
        step_block, state, params.n_iters, 16, deadline_s, lambda st: st[2],
        rate_hint=rate_get(rate_key), evals_per_iter=params.n_ants,
        # durable-checkpoint capture: the colony's global-best perm
        # split to a giant (only when the sink's checkpoint cadence is
        # due)
        incumbent=lambda st: greedy_split_giant(st[1], inst),
    )
    if deadline_s is not None and done:
        el = _time.monotonic() - t_run
        if el > 0.05:
            rate_put(rate_key, done / el)

    _, best_perm, _, pool_perms, pool_fits = state
    giant = greedy_split_giant(best_perm, inst)
    bd, cost = exact_cost(giant, inst, w)
    if warm:
        giant, bd, cost = warm_floor(giant, bd, cost, init_perm, inst, w)
    elite = None
    if pool > 0:
        from vrpms_tpu.core.cost import exact_cost_batch

        elite = jax.vmap(lambda p: greedy_split_giant(p, inst))(pool_perms)
        # The colony ranks by its fitness (unbounded split + per-route
        # fleet penalty), which can disagree with the true bounded-fleet
        # objective; re-rank the small pool EXACTLY and let an exactly-
        # better elite displace the fitness champion — the caller must
        # never see a champion that exact-prices worse than its pool.
        ecosts = exact_cost_batch(elite, inst, w)
        order = jnp.argsort(ecosts)
        elite = elite[order]
        if float(ecosts[order[0]]) < float(cost):
            giant = elite[0]
            bd, cost = exact_cost(giant, inst, w)
    return SolveResult(
        giant,
        cost,
        bd,
        jnp.int32(params.n_ants * done),
        elite,
    )


def warm_floor(giant, bd, cost, init_perm, inst: Instance, w):
    """Never return worse than a warm seed IN THE EXACT OBJECTIVE — the
    one keep-best guard shared by solve_aco and solve_aco_islands (the
    colony fitness's fleet-overflow penalty can disagree with the
    crammed-giant capacity pricing, so the comparison must be exact)."""
    seed_giant = greedy_split_giant(init_perm, inst)
    bd_s, cost_s = exact_cost(seed_giant, inst, w)
    if float(cost_s) < float(cost):
        return seed_giant, bd_s, cost_s
    return giant, bd, cost


def aco_knn_mask(inst: Instance, knn_k: int):
    """[N, N] candidate-list mask for construction (None when off)."""
    if knn_k <= 0:
        return None
    import numpy as np

    from vrpms_tpu.moves import knn_table

    tbl = np.asarray(knn_table(inst.durations[0], knn_k))
    mask = np.zeros((inst.n_nodes, inst.n_nodes), dtype=bool)
    mask[np.arange(inst.n_nodes)[:, None], tbl] = True
    return jnp.asarray(mask)
