"""Genetic algorithm over permutation genomes, fully vectorised.

Fills the reference's GA endpoints — its richest contract: the VRP GA
is the only endpoint with algorithm parameters (`multiThreaded`,
`randomPermutationCount`, `iterationCount`, reference api/parameters.py:
18-23) and the only one with CORS preflight. Parameter mapping here:
`randomPermutationCount` -> population size (a population IS a set of
random permutations), `iterationCount` -> generations, `multiThreaded`
-> accepted and ignored (the population axis is always data-parallel on
TPU; SURVEY.md §2.3).

Genome = customer permutation; fitness = greedy capacity split
(core.split) on plain CVRP, or full giant-tour evaluation when time
windows / time-dependence require it. Every operator is index
arithmetic so one generation is a handful of vmapped gathers:

  * tournament selection — random [P, k] index draws, argmin by fitness;
  * order crossover (OX) — child keeps p1's cut segment, fills the rest
    with p2's order via a stable argsort compaction (no host loops);
  * mutation — segment reversal / rotation on the genome.

The generation loop is one `lax.scan`; islands across devices are layered
on by vrpms_tpu.mesh (ring elite migration), not inside this module.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import (
    EXACT,
    CostWeights,
    _onehot,
    exact_cost,
    onehot_dtype,
    resolve_eval_mode,
)
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.moves.moves import (
    _segment_src_map,
    apply_src_map,
    reverse_segment,
    rotate_segment,
)
from vrpms_tpu.solvers.common import (
    SolveResult,
    donate_safe_state,
    maybe_donate_jit,
    perm_fitness_fn,
    rate_get,
    rate_put,
)


@dataclasses.dataclass(frozen=True)
class GAParams:
    population: int = 256       # reference: randomPermutationCount
    generations: int = 500      # reference: iterationCount
    tournament: int = 4
    crossover_rate: float = 0.9
    mutation_rate: float = 0.3
    elites: int = 16
    fleet_penalty: float = 1_000.0  # per route beyond the fleet bound
    init: str = "nn"  # "nn": perturbed nearest-neighbor genomes; "random"
    immigrants: int = 8  # per generation, replace this many of the worst
                         # children with ruin-and-recreate perturbations
                         # of the champion (solvers.perturb) — injects
                         # ILS-style restarts into the population.
                         # Measured (synth n100, pop 512, 100 gen, one
                         # v5e): 18.5-19.0k vs 19.1-19.9k without, at no
                         # extra wall. Clamped so elites + at least one
                         # bred child survive; 0 disables


def immigrants_for(params: GAParams, pop: int, n: int) -> int:
    """Immigrants actually injected per generation — THE one clamp
    (elites + at least one bred child survive; tiny instances skip the
    ruin entirely), shared by ga_generation and the evals accounting."""
    if n < 4:
        return 0
    return max(0, min(params.immigrants, pop - params.elites - 1))


def _random_perms(key, pop: int, n: int) -> jax.Array:
    base = jnp.arange(1, n + 1, dtype=jnp.int32)
    return jax.vmap(lambda k: jax.random.permutation(k, base))(
        jax.random.split(key, pop)
    )


@lru_cache(maxsize=8)
def _random_padded_perms_fn(pop: int, n: int):
    """Uniform random genomes for tier-padded instances: the REAL
    prefix [0, n_real-1) shuffled, phantoms fixed at the tail (the
    genome invariant every masked operator preserves)."""

    @jax.jit
    def fn(key, inst):
        base = jnp.arange(1, n + 1, dtype=jnp.int32)
        nrc = inst.n_real - 1  # real customer count, traced
        pos = jnp.arange(n)
        movable = pos < nrc

        def one(k):
            u = jax.random.uniform(k, (n,))
            order = jnp.argsort(jnp.where(movable, u, jnp.inf))
            src = jnp.where(movable, order, pos)
            return base[src]

        return jax.vmap(one)(jax.random.split(key, pop))

    return fn


def _random_padded_perms(key, pop: int, inst) -> jax.Array:
    return _random_padded_perms_fn(pop, inst.n_customers)(key, inst)


def initial_perms(
    key: jax.Array, pop: int, inst: Instance, params: GAParams, mode: str
) -> jax.Array:
    """Starting population per GAParams.init.

    "nn": the nearest-neighbor customer order cloned per genome and
    decorrelated by a few segment moves — measured 45% better best cost
    than a random population at an identical 100-generation budget
    (synth n=100, pop 512); crossover/mutation resupply diversity.
    "random": uniform random permutations.
    """
    n_real_perm = inst.perm_limit
    if params.init == "random":
        if inst.n_real is not None:
            return _random_padded_perms(key, pop, inst)
        return _random_perms(key, pop, inst.n_customers)
    if params.init != "nn":
        raise ValueError(f"GAParams.init must be 'nn' or 'random', got {params.init!r}")

    return perturbed_perm_clones(
        key, pop, _nn_perm_fn()(inst), mode, n_real_perm=n_real_perm
    )


@lru_cache(maxsize=8)
def _nn_perm_fn():
    """Jitted NN construction (one device program — see sa._nn_seed_fn;
    eager dispatch latency through a tunneled TPU is the cold-solve
    bottleneck, not compute)."""
    from vrpms_tpu.solvers.local_search import nearest_neighbor_perm

    return jax.jit(nearest_neighbor_perm)


@lru_cache(maxsize=32)
def _perturb_perms_fn(pop: int, mode: str, n_moves: int):
    """Jitted clone-and-decorrelate for permutations (the GA twin of
    sa._perturb_fn, cached per shape/mode for the same dispatch-latency
    reason)."""

    @jax.jit
    def fn(key, perm, lim):
        n = perm.shape[0]
        perms = jnp.tile(perm[None], (pop, 1))
        for _ in range(n_moves):
            key, k_pos, k_type = jax.random.split(key, 3)
            ij = jax.random.randint(k_pos, (pop, 2), 0, lim)
            lo = jnp.minimum(ij[:, 0], ij[:, 1])[:, None]
            hi = jnp.maximum(ij[:, 0], ij[:, 1])[:, None]
            mt = jax.random.randint(k_type, (pop, 1), 0, 2)
            src = _segment_src_map(lo, hi, mt, jnp.ones_like(mt), n)
            perms = apply_src_map(perms, src, mode=mode)
        return perms.at[0].set(perm)

    return fn


def perturbed_perm_clones(
    key: jax.Array, pop: int, perm: jax.Array, mode: str, n_moves: int = 6,
    n_real_perm=None,
) -> jax.Array:
    """One genome cloned per population slot, decorrelated by a few
    segment moves — the population recipe for any constructive or warm
    seed (the GA twin of sa.perturbed_clones). Slot 0 stays EXACTLY the
    seed so best tracking can never return worse than the seed.
    `n_real_perm` (traced real customer count) confines the moves to a
    padded genome's real prefix."""
    lim = perm.shape[0] if n_real_perm is None else n_real_perm
    return _perturb_perms_fn(pop, mode, n_moves)(key, perm, jnp.int32(lim))


def continuation_perm_ramp(
    key: jax.Array, pop: int, perm: jax.Array, mode: str, n_real_perm=None,
) -> jax.Array:
    """Seeded-population RAMP for CONTINUATION re-solves — the GA twin
    of sa.continuation_params. A continuation seed is an already-
    annealed tour of a neighboring instance, so the flat 6-move
    decorrelation of perturbed_perm_clones destroys more of it than a
    small delta warrants; the ramp instead grades perturbation strength
    across the population: a quarter stays within ~2 moves of the seed
    (exploitation — slot 0 exactly the seed), half at the standard 6
    (the crossover mixing pool), and the last quarter at 18 (the
    diversity tail a converged seed would otherwise lose, standing in
    for cold immigrants without abandoning the seed's basin)."""
    light = max(1, pop // 4)
    heavy = max(0, pop // 4)
    mid = max(0, pop - light - heavy)
    lim = perm.shape[0] if n_real_perm is None else n_real_perm
    k1, k2, k3 = jax.random.split(key, 3)
    parts = [_perturb_perms_fn(light, mode, 2)(k1, perm, jnp.int32(lim))]
    # _perturb_perms_fn pins ITS slot 0 to the exact seed; only the
    # light group may keep that anchor — the mid/heavy groups oversample
    # by one and drop it, or every group would waste a slot on a
    # duplicate of the seed
    if mid:
        parts.append(
            _perturb_perms_fn(mid + 1, mode, 6)(k2, perm, jnp.int32(lim))[1:]
        )
    if heavy:
        parts.append(
            _perturb_perms_fn(heavy + 1, mode, 18)(
                k3, perm, jnp.int32(lim)
            )[1:]
        )
    return jnp.concatenate(parts, axis=0)


def order_crossover(
    p1: jax.Array, p2: jax.Array, key: jax.Array, lim=None
) -> jax.Array:
    """OX: keep p1[i..j], fill remaining slots with p2's order.

    `lim` (traced) bounds the cut to a padded genome's real prefix;
    phantom genes — always at both parents' tails, never inside the
    segment — are all "kept" from p2, so the stable compaction returns
    them to the tail of the child and the invariant survives crossover.
    """
    n = p1.shape[0]
    ij = jax.random.randint(key, (2,), 0, n if lim is None else lim)
    i, j = jnp.minimum(ij[0], ij[1]), jnp.maximum(ij[0], ij[1])
    pos = jnp.arange(n)
    in_seg = (pos >= i) & (pos <= j)
    # Mark genome values inside the kept segment (ids are 1..n; slot 0 is
    # a scatter dump for masked-out positions).
    in_seg_val = (
        jnp.zeros(n + 1, dtype=bool)
        .at[jnp.where(in_seg, p1, 0)]
        .set(True)
        .at[0]
        .set(False)
    )
    keep = ~in_seg_val[p2]
    compact = p2[jnp.argsort(~keep, stable=True)]  # kept elements, in p2 order
    rank = jnp.cumsum(~in_seg) - 1
    return jnp.where(in_seg, p1, compact[rank]).astype(jnp.int32)


def order_crossover_hot(
    p1: jax.Array, p2: jax.Array, key: jax.Array, lim=None
) -> jax.Array:
    """Batched gather-free OX for (P, n) parents (the accelerator path).

    Same semantics as order_crossover, reformulated so nothing gathers,
    scatters, or sorts (all three lower poorly on TPU): segment
    membership, the p2-order compaction of the remaining genes, and the
    final fill are one-hot einsums; ranks come from cumsums. Genome
    values are <= n and one-hot count sums are <= n, so onehot_dtype
    keeps every contraction exact.
    """
    pop, n = p1.shape
    dt = onehot_dtype(n + 1)
    ij = jax.random.randint(key, (pop, 2), 0, n if lim is None else lim)
    i = jnp.minimum(ij[:, 0], ij[:, 1])[:, None]
    j = jnp.maximum(ij[:, 0], ij[:, 1])[:, None]
    pos = jnp.arange(n)[None, :]
    in_seg = (pos >= i) & (pos <= j)  # (P, n)

    oh1 = _onehot(p1, n + 1, dt)  # (P, n, n+1) over gene values
    oh2 = _onehot(p2, n + 1, dt)
    # member[p, v] = 1 iff value v sits inside p1's kept segment
    member = jnp.einsum(
        "pk,pkv->pv", in_seg.astype(dt), oh1, preferred_element_type=dt
    )
    keep = 1.0 - jnp.einsum(
        "pkv,pv->pk", oh2, member, preferred_element_type=jnp.float32
    )  # (P, n): p2 genes not already in the segment
    # Compact kept p2 genes, preserving order: rank by prefix count.
    rank = jnp.cumsum(keep, axis=1) - keep  # exclusive prefix, f32 ints
    rank_idx = jnp.where(keep > 0.5, rank, n).astype(jnp.int32)
    oh_rank = _onehot(rank_idx, n + 1, dt)
    compact = jnp.einsum(
        "pkr,pk->pr", oh_rank, (p2 * keep).astype(jnp.float32),
        preferred_element_type=jnp.float32, precision=EXACT,
    )[:, :n]  # (P, n) values; slot n dumped
    # Fill positions outside the segment with compact[...] in order.
    fill_rank = (jnp.cumsum(~in_seg, axis=1) - 1).astype(jnp.int32)
    oh_fill = _onehot(jnp.clip(fill_rank, 0, n - 1), n, dt)
    fill = jnp.einsum(
        "pkr,pr->pk", oh_fill, compact,
        preferred_element_type=jnp.float32, precision=EXACT,
    )
    return jnp.where(in_seg, p1, jnp.round(fill).astype(p1.dtype))


def mutate(perm: jax.Array, key: jax.Array, rate: float, lim=None) -> jax.Array:
    n = perm.shape[0]
    k_do, k_pos, k_type = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (2,), 0, n if lim is None else lim)
    i, j = jnp.minimum(ij[0], ij[1]), jnp.maximum(ij[0], ij[1])
    mutated = jax.lax.switch(
        jax.random.randint(k_type, (), 0, 2),
        [
            lambda p: reverse_segment(p, i, j),
            lambda p: rotate_segment(p, i, j, 1),
        ],
        perm,
    )
    do = jax.random.uniform(k_do) < rate
    return jnp.where(do, mutated, perm)


def mutate_batch(perms, key, rate: float, mode: str, lim=None) -> jax.Array:
    """Batched segment mutation: one reverse/rotate per genome, applied
    through the mode-aware src-map machinery (one-hot apply on TPU)."""
    pop, n = perms.shape
    k_do, k_pos, k_type = jax.random.split(key, 3)
    ij = jax.random.randint(k_pos, (pop, 2), 0, n if lim is None else lim)
    lo = jnp.minimum(ij[:, 0], ij[:, 1])[:, None]
    hi = jnp.maximum(ij[:, 0], ij[:, 1])[:, None]
    mt = jax.random.randint(k_type, (pop, 1), 0, 2)  # reverse / rotate-1
    src = _segment_src_map(lo, hi, mt, jnp.ones_like(mt), n)
    mutated = apply_src_map(perms, src, mode=mode)
    do = jax.random.uniform(k_do, (pop, 1)) < rate
    return jnp.where(do, mutated, perms)


def ga_generation(
    perms, fits, key, gen, fitness, params: GAParams, mode="gather", d=None,
    n_real_perm=None,
):
    """One generation: selection -> OX -> mutation -> elitism
    [-> immigrants].

    Standalone so the island driver (vrpms_tpu.mesh) can wrap it with
    migration while reusing the identical update rule. `mode` picks the
    gather (CPU) or one-hot (accelerator) formulation of selection,
    crossover, and mutation — both implement the same operators. `d`
    (durations[0]) enables the immigrant step when params.immigrants>0.
    `n_real_perm` (traced real customer count; Instance.n_real - 1)
    confines crossover cuts and mutation windows to a tier-padded
    genome's real prefix, keeping phantom genes parked at the tail.
    """
    pop = perms.shape[0]
    lim = n_real_perm  # None on unpadded instances (static full range)
    hot = mode in ("onehot", "pallas")
    k_gen = jax.random.fold_in(key, gen)
    k_t1, k_t2, k_cx, k_cxdo, k_mut = jax.random.split(k_gen, 5)

    if hot:
        # Exactness never needs pop in the bound: the draw/winner
        # one-hots only ever accumulate 0/1 values, and fits/perms
        # contractions accumulate in f32 — so gene values (<= n) set
        # the dtype and populations > 256 keep bf16 MXU throughput.
        dt = onehot_dtype(perms.shape[1] + 1)

        def tournament(k):
            draws = jax.random.randint(k, (pop, params.tournament), 0, pop)
            oh_d = _onehot(draws, pop, dt)  # (P, T, P)
            drawn_fits = jnp.einsum(
                "ptq,q->pt", oh_d, fits, preferred_element_type=jnp.float32
            )
            pick = jnp.argmin(drawn_fits, axis=1)
            oh_pick = _onehot(pick, params.tournament, dt)
            winner_oh = jnp.einsum(
                "pt,ptq->pq", oh_pick, oh_d, preferred_element_type=dt
            )
            rows = jnp.einsum(
                "pq,qk->pk",
                winner_oh,
                perms.astype(jnp.float32),
                preferred_element_type=jnp.float32,
                precision=EXACT,
            )
            return jnp.round(rows).astype(perms.dtype)

        pa = tournament(k_t1)
        pb = tournament(k_t2)
        children = order_crossover_hot(pa, pb, k_cx, lim)
    else:
        def tournament(k):
            draws = jax.random.randint(k, (pop, params.tournament), 0, pop)
            return draws[jnp.arange(pop), jnp.argmin(fits[draws], axis=1)]

        pa = perms[tournament(k_t1)]
        pb = perms[tournament(k_t2)]
        children = jax.vmap(order_crossover, in_axes=(0, 0, 0, None))(
            pa, pb, jax.random.split(k_cx, pop), lim
        )
    do_cx = jax.random.uniform(k_cxdo, (pop,)) < params.crossover_rate
    children = jnp.where(do_cx[:, None], children, pa)
    if hot:
        children = mutate_batch(children, k_mut, params.mutation_rate, mode, lim)
    else:
        children = jax.vmap(mutate, in_axes=(0, 0, None, None))(
            children, jax.random.split(k_mut, pop), params.mutation_rate, lim
        )
    # Elitism: overwrite the first E children with the current best E.
    elite_idx = jnp.argsort(fits)[: params.elites]
    children = children.at[: params.elites].set(perms[elite_idx])
    new_fits = fitness(children)
    imm_n = immigrants_for(params, pop, perms.shape[1])
    # tier-padded genomes skip the immigrant step: the ruin-and-recreate
    # cluster size is a STATIC shape (top_k) and cannot track the traced
    # real size; masked crossover/mutation still resupply diversity
    if n_real_perm is not None:
        imm_n = 0
    if imm_n > 0 and d is not None:
        # replace the worst children with ruin-and-recreate variants of
        # the generation champion — structurally fresh, high-quality
        # blood every generation (the GA analog of the ILS reseed)
        from vrpms_tpu.solvers.perturb import ruin_recreate_perms

        # base the immigrants on a RANDOM top-8 member, not always the
        # champion: champion-only immigration crowds the population
        # into one basin (measured: post-polish quality regressed)
        k_imm, k_base = jax.random.split(jax.random.fold_in(k_gen, 7))
        order = jnp.argsort(new_fits)
        base = children[order[jax.random.randint(k_base, (), 0, min(8, pop))]]
        imm = ruin_recreate_perms(k_imm, base, imm_n, d)
        worst = order[-imm_n:]
        children = children.at[worst].set(imm)
        new_fits = new_fits.at[worst].set(fitness(imm))
    return children, new_fits


@lru_cache(maxsize=32)
def _ga_block_fn(params: GAParams, n_block: int, mode: str):
    """Build (and cache) one jitted block of n_block generations.

    Hoisted to module level so the compile caches across solves (an
    inner @jax.jit closure would recompile on every service request);
    bounded lru_cache so request-controlled GAParams can't pin compiled
    executables without limit. GAParams is frozen, hence hashable.
    `mode` is the resolved eval mode (gather on CPU, one-hot family on
    accelerators) applied to both operators and fitness.

    Blocks compose exactly like sa._sa_block_fn's: the generation index
    offset arrives as a dynamic scalar, so a deadline-driven solve runs
    several blocks with host clock checks in between while an unbounded
    solve runs the whole budget as one block. Callers pass params with
    `generations` normalized to 0 (the block body never reads it), so
    requests differing only in iteration budget share one compile.

    On accelerators the loop state (arg 0) is DONATED — see
    sa._sa_block_fn; callers enter through donate_safe_state.
    """

    @maybe_donate_jit
    def run(state, key, inst, w, start_gen):
        fitness = perm_fitness_fn(inst, w, params.fleet_penalty, mode=mode)
        nrp = inst.perm_limit

        def step(state, gen):
            perms, fits, best_p, best_f = state
            perms, fits = ga_generation(
                perms, fits, key, gen, fitness, params, mode,
                d=inst.durations[0], n_real_perm=nrp,
            )
            champ = jnp.argmin(fits)
            better = fits[champ] < best_f
            best_p = jnp.where(better, perms[champ], best_p)
            best_f = jnp.where(better, fits[champ], best_f)
            return (perms, fits, best_p, best_f), None

        state, _ = jax.lax.scan(step, state, start_gen + jnp.arange(n_block))
        return state

    return run


@lru_cache(maxsize=32)
def _ga_init_fn(params: GAParams, mode: str):
    """Jitted initial population evaluation (kept compiled like blocks)."""

    @jax.jit
    def init(perms, inst, w):
        return perm_fitness_fn(inst, w, params.fleet_penalty, mode=mode)(perms)

    return init


def solve_ga(
    inst: Instance,
    key: jax.Array | int = 0,
    params: GAParams = GAParams(),
    weights: CostWeights | None = None,
    init_perms: jax.Array | None = None,
    mode: str = "auto",
    deadline_s: float | None = None,
    pool: int = 0,
) -> SolveResult:
    """Vectorised GA; returns the best genome's split route plan.

    With `deadline_s`, generations run in fixed 32-generation device
    blocks under common.run_blocked's granularity contract. `pool` > 0
    additionally returns the champion plus the final population's top
    genomes as split giants (SolveResult.pool, best first) for
    multi-start polish.
    """
    from vrpms_tpu.solvers.common import run_blocked

    w = weights or CostWeights.make()
    if isinstance(key, int):
        key = jax.random.key(key)
    pop = params.population
    mode = resolve_eval_mode(mode)
    k_init, k_run = jax.random.split(key)
    if init_perms is None:
        perms0 = initial_perms(k_init, pop, inst, params, mode)
    else:
        perms0 = init_perms

    # The iteration budget lives outside the compile key: blocks never
    # read it, so requests differing only in generations share compiles.
    block_params = dataclasses.replace(params, generations=0)
    fits0 = _ga_init_fn(block_params, mode)(perms0, inst, w)
    champ0 = jnp.argmin(fits0)
    # donate_safe_state: caller-owned init_perms must survive the first
    # block's donation on accelerators; identity on CPU
    state = donate_safe_state((perms0, fits0, perms0[champ0], fits0[champ0]))

    def step_block(st, nb, start):
        return _ga_block_fn(block_params, nb, mode)(
            st, k_run, inst, w, jnp.int32(start)
        )

    # genome + immigrant evaluations per generation (also the evals
    # accounting below — the trace and the stat must agree); padded
    # instances run without immigrants (see ga_generation)
    gen_evals = perms0.shape[0] + (
        0
        if inst.n_real is not None
        else immigrants_for(params, perms0.shape[0], inst.n_customers)
    )
    # measured generations/s per shape, fed back as run_blocked's
    # first-block fit hint — a known same-tier rate (warmup or a prior
    # solve) lets the first block open fitted instead of probing blind
    rate_key = ("ga", perms0.shape[0], perms0.shape[1], mode)
    import time as _time

    t_run = _time.monotonic()
    state, done = run_blocked(
        step_block, state, params.generations, 32, deadline_s,
        lambda st: st[3], rate_hint=rate_get(rate_key),
        evals_per_iter=gen_evals,
        # durable-checkpoint capture: the best-so-far genome split to a
        # giant (only when the sink's checkpoint cadence is due)
        incumbent=lambda st: greedy_split_giant(st[2], inst),
    )
    if deadline_s is not None and done:
        el = _time.monotonic() - t_run
        if el > 0.05:
            rate_put(rate_key, done / el)

    perms, fits, best_perm, _ = state
    giant = greedy_split_giant(best_perm, inst)
    bd, cost = exact_cost(giant, inst, w)
    elite = None
    if pool > 0:
        # Elitism keeps the champion genome in the final population, so
        # naively prepending it would duplicate pool[0] and waste a
        # multi-start slot; skip the population's copy when present.
        import numpy as np

        order = jnp.argsort(fits)
        if perms.shape[0] and np.array_equal(
            np.asarray(perms[order[0]]), np.asarray(best_perm)
        ):
            order = order[1:]
        order = order[: min(pool - 1, order.shape[0])]
        elite = jnp.concatenate(
            [
                giant[None],
                jax.vmap(lambda p: greedy_split_giant(p, inst))(perms[order]),
            ]
        )
    return SolveResult(
        giant,
        cost,
        bd,
        # evals from the actual population (init_perms may differ),
        # plus the immigrant evaluations each generation performs
        jnp.int32(gen_evals * done),
        elite,
    )
