"""Ruin-and-recreate perturbation: the ILS reseed that actually jumps.

The round-1 reseed cloned the incumbent and applied a few random moves
(sa.perturbed_clones) — local wiggles that mostly land in the same
basin. Classic ILS results (and our own measurements below) favor
spatial ruin-and-recreate: remove a geographically coherent cluster of
customers, then greedily reinsert each at its cheapest position. The
rebuilt tours are structurally different yet high-quality starts.

TPU shape discipline: everything is fixed-shape and batched over B
chains —

  * ruin: per chain, pick a random seed customer and remove its
    `k_remove` nearest customers (top-k over the duration row — a
    vectorised reduction, no host loop);
  * compact: keep the survivors in incumbent order via one stable
    argsort over (removed, position);
  * recreate: `k_remove` insertion steps; each step scores EVERY gap of
    every chain at once (three [B, m+1] duration lookups) and splices
    by index arithmetic (no dynamic shapes — the sequence buffer stays
    [B, n] with a static valid length per step).

Insertion deltas treat the customer order as a depot-anchored path
(route boundaries are re-derived by the greedy split afterwards) — the
standard giant-tour approximation.

Cites: reference api/vrp/sa/index.py:40 (the SA/ILS slot this feeds);
ruin-and-recreate is the Schrimpf et al. / SISR family of perturbations,
re-derived here in batched fixed-shape form.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.split import greedy_split_giant


def _ruin_recreate_one_batch(key, perm, batch: int, d, k_remove: int,
                             n_real=None):
    """[batch, n] perturbed customer orders from ONE incumbent perm.

    d is the [N, N] duration matrix (slice 0). Every row is perturbed;
    the keep-best guarantee (chain 0 == exact incumbent giant) lives in
    ONE place, _rr_giants_fn's final overwrite.

    Tier-padded instances (`n_real` traced): the incumbent perm carries
    its phantom genes at the tail; seeds draw from the real prefix,
    phantom columns are masked out of the ruin, and insertion gaps are
    confined to the real region — so phantoms stay parked at the tail
    and, for a fixed key, the real-prefix trajectory matches what the
    unpadded perm would do wherever the random shapes allow.
    """
    n = perm.shape[0]
    k_seed, k_order, k_jit = jax.random.split(key, 3)
    nrc = None if n_real is None else n_real - 1  # real customer count

    # --- ruin: per-chain seed customer + its k nearest customers -----
    seeds = jax.random.randint(k_seed, (batch,), 0, n if nrc is None else nrc)
    seed_nodes = perm[seeds]  # node ids
    rows = d[seed_nodes][:, 1:]  # distances to customers 1..n (B, n)
    # jitter breaks ties so chains ruin different clusters even from
    # identical seeds
    rows = rows * (1.0 + 0.1 * jax.random.uniform(k_jit, rows.shape))
    if n_real is not None:
        # phantoms (depot-alias distances) must never be "ruined"
        rows = jnp.where(
            (jnp.arange(1, n + 1) >= n_real)[None, :], jnp.inf, rows
        )
    # the seed itself is distance 0 -> always removed; take k nearest
    _, rm_idx = jax.lax.top_k(-rows, k_remove)  # customer ids - 1
    removed_nodes = rm_idx + 1  # (B, k)

    # --- compact survivors in incumbent order ------------------------
    perm_b = jnp.tile(perm[None], (batch, 1))  # (B, n)
    is_removed = (
        perm_b[:, :, None] == removed_nodes[:, None, :]
    ).any(-1)  # (B, n)
    # stable sort: survivors (0) before removed (1), original order kept
    order = jnp.argsort(is_removed.astype(jnp.int32), axis=1, stable=True)
    seq = jnp.take_along_axis(perm_b, order, axis=1)  # (B, n)
    # removal order for reinsertion: the removed customers, shuffled
    # identically cheaply via a per-chain random roll
    rolls = jax.random.randint(k_order, (batch, 1), 0, k_remove)
    pos_k = (jnp.arange(k_remove)[None, :] + rolls) % k_remove
    to_insert = jnp.take_along_axis(removed_nodes, pos_k, axis=1)

    # --- recreate: greedy cheapest-gap insertion, one step per removal
    m0 = n - k_remove
    pos = jnp.arange(n)

    def insert_step(seq, t):
        m = m0 + t  # static per unrolled step
        c = to_insert[:, t]  # (B,)
        valid = pos[None, : m + 1]
        a = jnp.where(
            valid == 0,
            0,
            jnp.take_along_axis(
                seq, jnp.maximum(valid - 1, 0), axis=1
            ),
        )  # predecessor node of gap j (depot for j == 0)
        b = jnp.where(
            valid == m, 0, jnp.take_along_axis(seq, jnp.minimum(valid, m - 1), axis=1)
        )  # successor node of gap j (depot for j == m)
        delta = d[a, c[:, None]] + d[c[:, None], b] - d[a, b]
        if nrc is not None:
            # gaps beyond the real survivors (i.e. inside the phantom
            # tail) are off limits; real survivor count this step is
            # nrc - k_remove + t
            delta = jnp.where(
                valid <= (nrc - k_remove + t), delta, jnp.inf
            )
        j = jnp.argmin(delta, axis=1)  # (B,) best gap
        shift = pos[None, :] > j[:, None]  # positions after j shift right
        at = pos[None, :] == j[:, None]
        prev = jnp.concatenate(
            [jnp.zeros((seq.shape[0], 1), seq.dtype), seq[:, :-1]], axis=1
        )
        seq = jnp.where(at, c[:, None], jnp.where(shift, prev, seq))
        return seq, None

    # python-unrolled over the (small, static) k_remove steps so each
    # step's valid length m is a static shape
    for t in range(k_remove):
        seq, _ = insert_step(seq, t)
    return seq


def default_k_remove(n: int) -> int:
    """The ONE ruin cluster-size heuristic (n = customer count)."""
    return min(max(2, min(24, n // 8)), n - 1)


def ruin_recreate_perms(
    key: jax.Array, perm: jax.Array, batch: int, d, k_remove: int | None = None,
    n_real=None,
) -> jax.Array:
    """[batch, n] perturbed customer orders from one incumbent perm —
    the perm-level entry (GA immigrants); every row is perturbed."""
    n = perm.shape[0]
    if k_remove is None:
        k_remove = default_k_remove(n)
    k_remove = max(1, min(int(k_remove), n - 1))  # explicit values clamp too
    return _ruin_recreate_one_batch(key, perm, batch, d, k_remove, n_real)


def ruin_recreate_clones(
    key: jax.Array,
    batch: int,
    giant: jax.Array,
    inst: Instance,
    k_remove: int | None = None,
) -> jax.Array:
    """[batch, L] giant tours: the incumbent giant's customer order,
    ruin-and-recreate perturbed per chain, re-split greedily. Chain 0 is
    the exact incumbent (keep-best guarantee). One jitted program.
    """
    # the cluster size is a STATIC shape (top_k), so it comes from the
    # CONCRETE real size; the handful of distinct values (default_k_remove
    # quantizes hard) bounds the extra compiles per tier
    n = inst.n_customers if inst.n_real is None else int(inst.n_real) - 1
    if k_remove is None:
        k_remove = default_k_remove(n)
    k_remove = max(1, min(int(k_remove), n - 1))  # explicit values clamp too
    return _rr_giants_fn(batch, k_remove)(key, giant, inst)


@lru_cache(maxsize=32)
def _rr_giants_fn(batch: int, k_remove: int):
    @jax.jit
    def fn(key, giant, inst):
        perm = _perm_of_giant(giant, inst.n_customers, inst.n_real)
        seqs = _ruin_recreate_one_batch(
            key, perm, batch, inst.durations[0], k_remove, inst.n_real
        )
        out = jax.vmap(lambda p: greedy_split_giant(p, inst))(seqs)
        # chain 0 keeps the incumbent GIANT byte-exact — a greedy
        # re-split of its order could lose an annealed separator
        # placement (TW/makespan/het instances), breaking keep-best
        return out.at[0].set(giant)

    return fn


def _perm_of_giant(giant: jax.Array, n: int, n_real=None) -> jax.Array:
    """Customer order of a giant tour, fixed shape [n]: real customers
    in tour order first, then (tier-padded) the phantoms, then the
    zeros are dropped by the [:n] cut — one stable three-way sort, so a
    phantom standing in for an interior separator still lands at the
    genome tail where the masked ruin expects it."""
    if n_real is None:
        key = (giant == 0).astype(jnp.int32)
    else:
        key = jnp.where(
            giant == 0, 2, jnp.where(giant >= n_real, 1, 0)
        ).astype(jnp.int32)
    order = jnp.argsort(key, axis=0, stable=True)
    return giant[order][:n]
