"""Delta-evaluated steepest-descent local search — the fast polish.

The full-evaluation steepest descent (solvers.local_search) re-costs every
candidate tour, O(L) each, so one sweep of the O(L^2) neighborhood is
O(L^3) — fine for the 50-node ladder slice, hopeless as a polish step on
X-n200-scale champions. This module evaluates the SAME neighborhood
(2-opt reversals, or-opt segment relocations of length 1-3, swaps — the
move set SURVEY.md §2.2 requires) in O(L^2) per sweep via classic delta
formulas, reshaped for the MXU:

  * the permuted duration matrix P[a, b] = d[g_a, g_b] is two one-hot
    matmuls (onehot(g) @ d @ onehot(g)^T) — no gathers on TPU;
  * every move's DISTANCE delta is elementwise arithmetic over shifted
    views of P and cumulative leg sums — exact even for asymmetric
    matrices (a reversed segment re-costs its interior legs from the
    transpose diagonal's cumsum);
  * CAPACITY deltas ride along (cap_delta_tables): exact for every
    load-shifting move family with a closed form — inter-route segment
    relocations, separator relocations (route merge/split/boundary
    shift), customer swaps, and separator-spanning reversals — and a
    can't-win penalty for the rest. Distance-only ranking dies on
    tight instances: every top slot is a capacity-busting merge;
  * time-window / makespan / time-of-day effects stay unmodeled, so the
    top-K predicted moves per tour are re-evaluated with the exact
    penalized objective and only true improvements are accepted.
    Correctness never depends on the delta being complete — it is a
    proposal ranking; acceptance is exact.

Batched over tours (polish a whole champion set at once) and jittable:
sweeps run under `lax.while_loop` with an early exit once no tour
improves. This is the reference's missing local-search core (its stub
shuffles randomly, reference src/solver.py:18-27) built as dense linear
algebra instead of nested loops.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import (
    CostWeights,
    exact_cost,
    objective_batch_mode,
    onehot_dtype,
    resolve_eval_mode,
    _onehot,
    _rid_batch,
)
from vrpms_tpu.core.encoding import separators
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.moves.moves import _segment_src_map, apply_src_map
from vrpms_tpu.solvers.common import SolveResult

# Table order (axis 1 of move_delta_tables): the t in a flat move index.
#   0: 2-opt reverse [i, j]
#   1: swap i, j (non-adjacent; adjacent swaps ARE reversals)
#   2/3/4: or-opt relocate segment [i, i+s-1], s = 1/2/3, to after j
#   5/6:   or-opt relocate REVERSED segment, s = 2/3 (s = 1 flips to
#          itself); the classic second or-opt orientation
#   7:     2-opt* suffix exchange — route of i and route of j (a later
#          route) trade their suffixes after i resp. j, orientation
#          preserved; the classic inter-route tail move
N_TABLES = 8
_INF = jnp.float32(jnp.inf)
BIGF = 1e18  # sentinel for "no separator to the right" scans


def _permuted_matrix(giants: jax.Array, inst: Instance, mode: str) -> jax.Array:
    """P[b, a, c] = durations[0][g_a, g_c] for each tour in the batch.

    'gather' indexes directly (CPU); otherwise two one-hot contractions
    keep the build on the MXU with the hot paths' precision (bf16-rounded
    matrix for instances with <= 256 nodes, exactly like core.cost).
    """
    d = inst.durations[0]
    if mode == "gather":
        return d[giants[:, :, None], giants[:, None, :]]
    n = inst.n_nodes
    dt = onehot_dtype(max(giants.shape[1], n))
    oh = _onehot(giants, n, dt)  # (B, L, N)
    rows = jnp.einsum("bln,nm->blm", oh, d.astype(dt), preferred_element_type=dt)
    return jnp.einsum("blm,bkm->blk", rows, oh, preferred_element_type=jnp.float32)


def _shift(a: jax.Array, di: int, dj: int) -> jax.Array:
    """out[b, i, j] = a[b, i + di, j + dj]; wrapped entries are masked by
    every consumer's validity mask, so plain rolls suffice."""
    return jnp.roll(a, shift=(-di, -dj), axis=(1, 2))


def move_delta_tables(giants: jax.Array, inst: Instance, mode: str = "auto") -> jax.Array:
    """[B, N_TABLES, L, L] distance deltas; +inf marks invalid slots.

    Entry [b, t, i, j] is the EXACT change in total leg distance (of the
    mode's rounded matrix, slice 0) when move (t, i, j) is applied to
    tour b — see decode_move for the move each slot denotes.
    """
    mode = resolve_eval_mode(mode)
    b, length = giants.shape
    nr = inst.n_real
    # last movable position: tier-padded tours confine every window to
    # the real prefix (the tail's phantom/zero filler must stay put)
    last = (length - 2) if nr is None else (inst.n_real + inst.v_real - 2)
    p = _permuted_matrix(giants, inst, mode)

    # Leg vectors over positions, padded to length L (out-of-range = 0).
    fwd = jnp.diagonal(p, offset=1, axis1=1, axis2=2)   # P[k, k+1]
    bwd = jnp.diagonal(p, offset=-1, axis1=1, axis2=2)  # P[k+1, k]
    zcol = jnp.zeros((b, 1), jnp.float32)
    fwd_at = jnp.concatenate([fwd, zcol], axis=1)       # [B, L]
    # Prefix sums: F[k] = sum of fwd legs 0..k-1, so ranges are diffs.
    cum_f = jnp.concatenate([zcol, jnp.cumsum(fwd, axis=1)], axis=1)
    cum_b = jnp.concatenate([zcol, jnp.cumsum(bwd, axis=1)], axis=1)

    def row(vec):  # value varies along i
        return vec[:, :, None]

    def col(vec):  # value varies along j
        return vec[:, None, :]

    def rshift(vec, k):  # out[i] = vec[i + k]
        return jnp.roll(vec, -k, axis=1)

    i_idx = jnp.arange(length)[None, :, None]
    j_idx = jnp.arange(length)[None, None, :]
    interior_i = (i_idx >= 1) & (i_idx <= last)
    interior_j = (j_idx >= 1) & (j_idx <= last)

    fwd_im1 = row(rshift(fwd_at, -1))
    fwd_i = row(fwd_at)
    fwd_jm1 = col(rshift(fwd_at, -1))
    fwd_j = col(fwd_at)

    # --- 2-opt reverse [i, j] ------------------------------------------
    # new legs (i-1 -> j), reversed interior, (i -> j+1)
    interior_flip = (col(cum_b) - row(cum_b)) - (col(cum_f) - row(cum_f))
    rev = (
        _shift(p, -1, 0)            # P[i-1, j]
        + _shift(p, 0, 1)           # P[i, j+1]
        - fwd_im1
        - fwd_j
        + interior_flip
    )
    rev = jnp.where(interior_i & interior_j & (i_idx < j_idx), rev, _INF)

    # --- swap i, j (j >= i + 2) ----------------------------------------
    pt = jnp.swapaxes(p, 1, 2)  # pt[i, j] = P[j, i]
    swp = (
        _shift(p, -1, 0)            # P[i-1, j]
        + _shift(pt, 1, 0)          # P[j, i+1]
        + _shift(pt, 0, -1)         # P[j-1, i]
        + _shift(p, 0, 1)           # P[i, j+1]
        - fwd_im1 - fwd_i - fwd_jm1 - fwd_j
    )
    swp = jnp.where(interior_i & interior_j & (j_idx >= i_idx + 2), swp, _INF)

    # --- or-opt relocate [i, i+s-1] to after j, both orientations ------
    tables = [rev, swp]
    flip_tables = []
    cf, cb = cum_f[:, :length], cum_b[:, :length]
    for s in (1, 2, 3):
        # closing leg P[i-1, i+s] = the (s+1)-offset diagonal at i-1
        dg = jnp.diagonal(p, offset=s + 1, axis1=1, axis2=2)
        dg = jnp.concatenate(
            [dg, jnp.zeros((b, length - dg.shape[1]), jnp.float32)], axis=1
        )
        removal = fwd_im1 + row(rshift(fwd_at, s - 1)) - row(rshift(dg, -1))
        insertion = (
            pt                        # P[j, i]
            + _shift(p, s - 1, 1)     # P[i+s-1, j+1]
            - fwd_j
        )
        seg_ok = interior_i & (i_idx + s - 1 <= last)
        # j outside [i-1, i+s-1]; j = 0 (insert right after the start
        # depot) is valid, j = L-1 is not (no leg leaves the last depot).
        j_ok = (j_idx <= last) & ((j_idx <= i_idx - 2) | (j_idx >= i_idx + s))
        rel = jnp.where(seg_ok & j_ok, insertion - removal, _INF)
        tables.append(rel)
        if s >= 2:
            # Reversed insertion: (j -> i+s-1), flipped interior legs,
            # (i -> j+1). The segment's interior travels backwards, so
            # its fwd legs are re-costed from the bwd cumsum (exact on
            # asymmetric matrices, like the 2-opt interior term).
            interior = row((rshift(cb, s - 1) - cb) - (rshift(cf, s - 1) - cf))
            ins_flip = (
                _shift(pt, s - 1, 0)  # P[j, i+s-1]
                + _shift(p, 0, 1)     # P[i, j+1]
                - fwd_j
                + interior
            )
            flip_tables.append(
                jnp.where(seg_ok & j_ok, ins_flip - removal, _INF)
            )

    # --- 2-opt*: routes of i and j (a later route) trade suffixes ------
    # Suffix of position k = everything after k up to k's route-closing
    # separator. New legs: (i -> j+1), (B-tail -> i's old close),
    # (j -> i+1), (A-tail -> j's old close); an empty donor suffix
    # degenerates to a direct close. Orientation is preserved, so no
    # interior re-costing — this is the inter-route tail move the
    # window-based families above cannot express.
    rid = _rid_batch(giants, nr)
    nz_after, at_idx, suf_len = _suffix_structure(giants, nr)
    nz_clip = jnp.clip(nz_after, 0, length - 1)
    if mode == "gather":
        # direct O(L^2) indexing on CPU; the one-hot matmuls below would
        # be O(L^3) dense contractions — catastrophic off the MXU
        fwd_tail = jnp.take_along_axis(fwd_at, at_idx, axis=1)
        p_close = jnp.take_along_axis(p, nz_clip[:, :, None], axis=2)[:, :, 0]
        pr = jnp.take_along_axis(
            p, jnp.broadcast_to(at_idx[:, :, None], p.shape), axis=1
        )
        y = jnp.take_along_axis(
            pr, jnp.broadcast_to(nz_clip[:, None, :], p.shape), axis=2
        )
    else:
        at_oh = _onehot(at_idx, length, jnp.float32)
        nz_oh = _onehot(nz_clip, length, jnp.float32)
        fwd_tail = _select_by_pos(at_oh, fwd_at, mode)
        # P[k, nz_after[k]]: the direct-close leg from k
        p_close = jnp.einsum(
            "bkm,bkm->bk", p, nz_oh, preferred_element_type=jnp.float32
        )
        # Y[b, x, y] = P[at_idx[x], nz_after[y]]: both tail->close legs
        pr = jnp.einsum("bxr,brc->bxc", at_oh, p, preferred_element_type=jnp.float32)
        y = jnp.einsum("bxc,byc->bxy", pr, nz_oh, preferred_element_type=jnp.float32)

    a_empty = row(suf_len == 0)
    b_empty = col(suf_len == 0)
    added_a = jnp.where(
        b_empty, row(p_close), _shift(p, 0, 1) + jnp.swapaxes(y, 1, 2)
    )
    added_b = jnp.where(a_empty, col(p_close), _shift(pt, 1, 0) + y)
    removed_a = fwd_i + jnp.where(a_empty, 0.0, row(fwd_tail))
    removed_b = fwd_j + jnp.where(b_empty, 0.0, col(fwd_tail))
    star_ok = (
        (col(rid) > row(rid))
        & (i_idx <= last)
        & (j_idx <= last)
        & ~(a_empty & b_empty)
    )
    star = jnp.where(star_ok, added_a + added_b - removed_a - removed_b, _INF)

    return jnp.stack(tables + flip_tables + [star], axis=1)


def _suffix_structure(giants: jax.Array, n_real=None):
    """(nz_after, at_idx, suf_len): per position, the index of the next
    separator strictly after it, the index of its route-suffix tail, and
    that suffix's length (0 when the next position is a separator).
    Phantom ids >= n_real are separators on tier-padded tours.
    Entries at L-1 are wrapped garbage; consumers mask them."""
    b, length = giants.shape
    idx = jnp.arange(length, dtype=jnp.int32)[None, :]
    masked = jnp.where(separators(giants, n_real), idx, length)
    nz_geq = jnp.flip(
        jax.lax.cummin(jnp.flip(masked, axis=1), axis=1), axis=1
    )
    nz_after = jnp.roll(nz_geq, -1, axis=1)
    at_idx = jnp.clip(nz_after - 1, 0, length - 1)
    suf_len = nz_after - idx - 1
    return nz_after, at_idx, jnp.broadcast_to(suf_len, (b, length))


def _select_by_pos(pos_oh: jax.Array, vec: jax.Array, mode: str, idx=None):
    """vec[rid[b, k]] per position, as one-hot contraction off-CPU."""
    if mode == "gather":
        return vec[idx] if vec.ndim == 1 else jnp.take_along_axis(vec, idx, axis=1)
    if vec.ndim == 1:
        return jnp.einsum("blv,v->bl", pos_oh, vec, preferred_element_type=jnp.float32)
    return jnp.einsum("blv,bv->bl", pos_oh, vec, preferred_element_type=jnp.float32)


def cap_delta_tables(giants: jax.Array, inst: Instance, mode: str = "auto") -> jax.Array:
    """[B, N_TABLES, L, L] capacity-excess deltas, same move slots.

    Without this term, distance-only ranking collapses on tight-capacity
    instances: the best distance deltas are all capacity-busting
    inter-route moves, and every true improvement drowns below the top-K
    horizon (measured on synth CVRP: polish accepted zero moves from an
    NN seed). Coverage, per move family:

      * intra-route moves: exactly 0 — no load shifts;
      * relocation of a separator-free segment between routes: exact;
      * relocation of a lone separator: exact — merges its two routes
        and splits (or boundary-shifts) the receiving route;
      * swap of two customers in different routes: exact;
      * 2-opt reversal spanning separators: exact for homogeneous
        capacities — interior sub-routes keep their load MULTISET (their
        excess sum is invariant), so only the two edge routes change:
        the window-head chunk [i, z1-1] and window-tail chunk [z2+1, j]
        trade places (z1/z2 = first/last separator in the window);
      * the rest (multi-node segments containing separators; swaps
        involving a separator) have no tractable closed form — they get
        a penalty exceeding any real excess change, so they only surface
        when capacity is unpriced (w.cap = 0 keeps them distance-ranked,
        since the caller scales this table by w.cap).

    Separator moves renumber the routes in between, so a HETEROGENEOUS
    fleet makes those entries heuristic (the exact recheck still guards
    acceptance); per-route capacities stay exact for customer-only moves.
    """
    mode = resolve_eval_mode(mode)
    b, length = giants.shape
    v = inst.n_vehicles
    is_zero = separators(giants, inst.n_real)
    rid = _rid_batch(giants, inst.n_real)
    rid_c = jnp.clip(rid, 0, v - 1)
    rid_oh = _onehot(rid_c, v, jnp.float32)
    if mode == "gather":
        dem_at = inst.demands[giants]
    else:
        dt = onehot_dtype(inst.n_nodes)
        dem_at = jnp.einsum(
            "bln,n->bl",
            _onehot(giants, inst.n_nodes, dt),
            inst.demands,
            preferred_element_type=jnp.float32,
        )
    load = jnp.einsum("blv,bl->bv", rid_oh, dem_at, preferred_element_type=jnp.float32)
    load_at = _select_by_pos(rid_oh, load, mode, rid_c)
    cap_at = _select_by_pos(rid_oh, inst.capacities, mode, rid_c)
    exc_at = jnp.maximum(load_at - cap_at, 0.0)

    zcol = jnp.zeros((b, 1), jnp.float32)
    cum_dem = jnp.concatenate([zcol, jnp.cumsum(dem_at, axis=1)], axis=1)
    cum_zero = jnp.concatenate(
        [zcol, jnp.cumsum(is_zero.astype(jnp.float32), axis=1)], axis=1
    )

    def row(vec):
        return vec[:, :, None]

    def col(vec):
        return vec[:, None, :]

    diff_route = row(rid) != col(rid)
    # unmodeled slots cost more than any real excess change can gain
    unmodeled = jnp.sum(inst.demands) * 2.0 + 1.0

    d_inc = cum_dem[:, 1:]  # demand of positions 0..k, inclusive
    open_d = jax.lax.cummax(jnp.where(is_zero, d_inc, -1.0), axis=1)
    prefix = d_inc - open_d  # in-route load up to each position
    # demand from each position to its route's closing separator
    close_d = jnp.flip(
        jax.lax.cummin(
            jnp.flip(jnp.where(is_zero, d_inc, jnp.float32(BIGF)), axis=1), axis=1
        ),
        axis=1,
    )
    suffix = close_d - cum_dem[:, :length]

    # --- 2-opt reversal: edge chunks trade routes ----------------------
    # Start-edge route = rid[i-1] (owner of the leg entering the window),
    # end-edge route = rid[j]; exact whenever the window holds >= 1
    # separator (otherwise intra-route: exactly 0).
    load_in = jnp.roll(load_at, 1, axis=1)
    cap_in = jnp.roll(cap_at, 1, axis=1)
    exc_in = jnp.roll(exc_at, 1, axis=1)
    qa, qb = row(suffix), col(prefix)  # head chunk out, tail chunk in
    has_zero = (col(cum_zero[:, 1:]) - row(cum_zero[:, :length])) >= 1.0
    rev = (
        jnp.maximum(row(load_in) - qa + qb - row(cap_in), 0.0) - row(exc_in)
        + jnp.maximum(col(load_at) - qb + qa - col(cap_at), 0.0) - col(exc_at)
    )
    rev = jnp.where(has_zero, rev, 0.0)

    # --- swap of two customers between different routes ----------------
    qi, qj = row(dem_at), col(dem_at)
    swp = (
        jnp.maximum(row(load_at) - qi + qj - row(cap_at), 0.0) - row(exc_at)
        + jnp.maximum(col(load_at) - qj + qi - col(cap_at), 0.0) - col(exc_at)
    )
    swp = jnp.where(diff_route, swp, 0.0)
    swp = jnp.where(row(is_zero) | col(is_zero), unmodeled, swp)

    tables = [rev, swp]

    # Relocating a lone SEPARATOR (s = 1, g[i] = 0) merges the two routes
    # around it and splits (or boundary-shifts) the route receiving it —
    # the fleet-rebalancing move.
    rid_prev = jnp.clip(rid - 1, 0, v - 1)
    prev_oh = _onehot(rid_prev, v, jnp.float32)
    load_prev = _select_by_pos(prev_oh, load, mode, rid_prev)
    cap_prev = _select_by_pos(prev_oh, inst.capacities, mode, rid_prev)
    exc_prev = jnp.maximum(load_prev - cap_prev, 0.0)
    load_m = load_prev + load_at  # merged load of routes r-1 and r
    merge_term = jnp.maximum(load_m - cap_prev, 0.0) - exc_prev - exc_at
    split_term = (
        jnp.maximum(prefix - cap_at, 0.0)
        + jnp.maximum(load_at - prefix - cap_at, 0.0)
        - exc_at
    )
    # Insertion back into the merged pair (q = r-1: before the removed
    # zero; q = r: after it) is a boundary SHIFT: the merged route
    # re-splits at j, with the in-merged-route prefix extended by route
    # r-1's full load when j lies in route r.
    into_r = col(rid) == row(rid)
    boundary = into_r | (col(rid) == row(rid) - 1)
    p_m = col(prefix) + jnp.where(into_r, row(load_prev), 0.0)
    shift_delta = (
        jnp.maximum(p_m - row(cap_prev), 0.0)
        + jnp.maximum(row(load_m) - p_m - row(cap_at), 0.0)
        - row(exc_prev)
        - row(exc_at)
    )
    sep1 = jnp.where(
        row(is_zero),
        jnp.where(boundary, shift_delta, row(merge_term) + col(split_term)),
        0.0,
    )

    # relocation of a separator-free segment [i, i+s-1] to after j;
    # load shifts are orientation-blind, so the reversed-relocation
    # tables (s = 2, 3) reuse the same entries
    flip_tables = []
    for s in (1, 2, 3):
        q_seg = jnp.roll(cum_dem, -s, axis=1)[:, :length] - cum_dem[:, :length]
        pure = (
            jnp.roll(cum_zero, -s, axis=1)[:, :length] - cum_zero[:, :length]
        ) == 0.0
        src_term = (
            jnp.maximum(row(load_at) - row(q_seg) - row(cap_at), 0.0)
            - row(exc_at)
        )
        dst_term = (
            jnp.maximum(col(load_at) + row(q_seg) - col(cap_at), 0.0)
            - col(exc_at)
        )
        rel = jnp.where(diff_route & row(pure), src_term + dst_term, 0.0)
        if s == 1:
            rel = rel + sep1  # disjoint: `pure` excludes zero segments
        else:
            rel = jnp.where(row(pure), rel, unmodeled)
            flip_tables.append(rel)
        tables.append(rel)

    # 2-opt* suffix exchange: each route keeps its vehicle slot (the
    # separator ORDER is preserved), so the load swap is exact even for
    # heterogeneous fleets. suffix[k] counts demand from k to its route
    # close, so rolling by one gives the demand strictly AFTER k (a
    # separator's "after" is the whole route it opens).
    suf_after = jnp.roll(suffix, -1, axis=1)
    star_a = (
        jnp.maximum(
            row(load_at) - row(suf_after) + col(suf_after) - row(cap_at), 0.0
        )
        - row(exc_at)
    )
    star_b = (
        jnp.maximum(
            col(load_at) - col(suf_after) + row(suf_after) - col(cap_at), 0.0
        )
        - col(exc_at)
    )
    star = jnp.where(col(rid) > row(rid), star_a + star_b, 0.0)

    return jnp.stack(tables + flip_tables + [star], axis=1)


def decode_move(t: jax.Array, i: jax.Array, j: jax.Array):
    """Table slot (t <= 4) -> (move_type, lo, hi, m) for
    moves._segment_src_map.

    Reverse and swap map directly; a relocation is a rotation of the
    window between the segment and its insertion point (forward: rotate
    [i, j] left by s; backward: rotate [j+1, i+s-1] left by i-j-1).
    Reversed relocations (t >= 5) are not rotations — move_src_map
    builds their permutation directly.
    """
    s = t - 1  # segment length for relocation tables
    forward = j >= i + s
    mt = jnp.where(t == 0, 0, jnp.where(t == 1, 2, 1))
    lo = jnp.where(t <= 1, i, jnp.where(forward, i, j + 1))
    hi = jnp.where(t <= 1, j, jnp.where(forward, j, i + s - 1))
    m = jnp.where(t <= 1, 1, jnp.where(forward, s, i - j - 1))
    return mt, lo, hi, m


def move_src_map(
    t, i, j, length: int, giants: jax.Array | None = None, n_real=None
) -> jax.Array:
    """(M,) table slots -> (M, L) gather maps applying each move.

    The single apply path for every table (the sweep and the tests use
    exactly this, so the formulas and the application can never drift):
    t <= 4 routes through moves._segment_src_map; t = 5/6 (reversed
    relocation) and t = 7 (2-opt* suffix exchange) write their
    permutations directly. t = 7 depends on where each tour's
    separators sit, so `giants` ([M, L], row-aligned with the slots) is
    required when any slot uses it.
    """
    shape = lambda a: jnp.asarray(a, jnp.int32).reshape(-1, 1)
    t, i, j = shape(t), shape(i), shape(j)
    mt, lo, hi, m = decode_move(t, i, j)
    base = _segment_src_map(lo, hi, mt, m, length)

    s = t - 3  # segment length for the reversed-relocation tables
    k = jnp.arange(length, dtype=jnp.int32)[None, :]
    # forward (j >= i+s): window [i, j] = shifted tail, then flipped seg
    src_f = jnp.where(
        (k >= i) & (k <= j - s),
        k + s,
        jnp.where((k > j - s) & (k <= j), i + (j - k), k),
    )
    # backward (j <= i-2): window [j+1, i+s-1] = flipped seg, then shift
    src_b = jnp.where(
        (k >= j + 1) & (k <= j + s),
        i + (j + s - k),
        jnp.where((k > j + s) & (k <= i + s - 1), k - s, k),
    )
    src_flip = jnp.where(j >= i + s, src_f, src_b)
    out = jnp.where(t >= 5, src_flip, base)
    if giants is None:
        # t == 7 NEEDS the tours (separator positions); without them the
        # t >= 5 branch above would silently apply a wrong-but-valid
        # permutation that does not match the scored delta. Concrete
        # misuse fails loudly; traced values can't be inspected.
        try:
            has_star = bool((t == 7).any())
        except jax.errors.ConcretizationTypeError:
            has_star = False
        if has_star:
            raise ValueError("move_src_map: t == 7 (2-opt*) requires giants=")
        return out

    # 2-opt* suffix exchange: [0..i] ++ Bsuf ++ [zA..j] ++ Asuf ++ rest,
    # where Asuf/Bsuf are the (possibly empty) suffixes of i's and j's
    # routes and zA closes i's route. The middle block (zA..j) shifts by
    # the suffix-length difference; both suffixes keep orientation.
    nz_after, _, _ = _suffix_structure(giants, n_real)
    za = jnp.take_along_axis(nz_after, jnp.clip(i, 0, length - 1), axis=1)
    zb = jnp.take_along_axis(nz_after, jnp.clip(j, 0, length - 1), axis=1)
    la = za - i - 1
    lb = zb - j - 1
    src_star = jnp.where(
        (k > i) & (k <= i + lb),
        k + (j - i),
        jnp.where(
            (k > i + lb) & (k <= j + lb - la),
            k + (la - lb),
            jnp.where(
                (k > j + lb - la) & (k <= j + lb),
                k + (i - j + la - lb),
                k,
            ),
        ),
    )
    return jnp.where(t == 7, src_star, out)


def _sweep(giants, costs, inst, w, mode, top_k):
    """One steepest-descent sweep: rank all moves by delta, exactly
    re-evaluate each tour's top-K, accept each tour's best improvement."""
    b, length = giants.shape
    deltas = move_delta_tables(giants, inst, mode)
    if inst.n_vehicles > 1:  # single-route (TSP) moves never shift load
        deltas = deltas + w.cap * cap_delta_tables(giants, inst, mode)
    deltas = deltas.reshape(b, -1)
    scores, idx = jax.lax.top_k(-deltas, top_k)  # best = most negative delta
    valid = jnp.isfinite(scores)

    t = idx // (length * length)
    rem = idx % (length * length)
    i, j = rem // length, rem % length
    # invalid slots (masked +inf deltas) become identity swaps
    one = jnp.ones((), jnp.int32)
    t = jnp.where(valid, t, 1)  # table 1 = swap; lo == hi is identity
    i = jnp.where(valid, i, one)
    j = jnp.where(valid, j, one)
    rep = jnp.repeat(giants, top_k, axis=0)
    src = move_src_map(t, i, j, length, giants=rep, n_real=inst.n_real)
    cands = apply_src_map(rep, src, mode=mode).reshape(b, top_k, length)
    cand_costs = objective_batch_mode(
        cands.reshape(b * top_k, length), inst, w, mode
    ).reshape(b, top_k)
    cand_costs = jnp.where(valid, cand_costs, _INF)

    k_best = jnp.argmin(cand_costs, axis=1)
    best_cost = jnp.take_along_axis(cand_costs, k_best[:, None], axis=1)[:, 0]
    best_tour = jnp.take_along_axis(
        cands, k_best[:, None, None], axis=1
    )[:, 0, :]
    better = best_cost < costs - 1e-6
    giants = jnp.where(better[:, None], best_tour, giants)
    costs = jnp.where(better, best_cost, costs)
    return giants, costs, better.any()


@lru_cache(maxsize=32)
def _polish_fn(max_sweeps: int, top_k: int, mode: str):
    """Build (and cache) the jitted polish loop; compile reuse across
    requests with bounded retention (see sa._sa_block_fn's rationale)."""

    @jax.jit
    def run(giants, inst, w):
        costs = objective_batch_mode(giants, inst, w, mode)

        def cond(state):
            _, _, improved, sweeps = state
            return improved & (sweeps < max_sweeps)

        def body(state):
            giants, costs, _, sweeps = state
            giants, costs, improved = _sweep(giants, costs, inst, w, mode, top_k)
            return giants, costs, improved, sweeps + 1

        giants, costs, _, sweeps = jax.lax.while_loop(
            cond, body, (giants, costs, jnp.bool_(True), jnp.int32(0))
        )
        return giants, costs, sweeps

    return run


def delta_polish_batch(
    giants: jax.Array,
    inst: Instance,
    weights: CostWeights | None = None,
    mode: str = "auto",
    max_sweeps: int = 128,
    top_k: int = 8,
):
    """Polish a [B, L] batch of tours to delta-neighborhood local optima.

    Returns (giants, costs, evals): improved tours, their penalized
    objectives (in `mode` precision), and the number of exact candidate
    evaluations spent.
    """
    w = weights or CostWeights.make()
    mode = resolve_eval_mode(mode)
    giants, costs, sweeps = _polish_fn(max_sweeps, top_k, mode)(giants, inst, w)
    evals = sweeps * giants.shape[0] * top_k  # counts the final no-improve sweep
    return giants, costs, evals


def delta_polish(
    giant: jax.Array,
    inst: Instance,
    weights: CostWeights | None = None,
    mode: str = "auto",
    max_sweeps: int = 128,
    top_k: int = 8,
) -> SolveResult:
    """Polish one tour; the post-solver champion improver."""
    w = weights or CostWeights.make()
    giants, _, evals = delta_polish_batch(
        giant[None], inst, w, mode=mode, max_sweeps=max_sweeps, top_k=top_k
    )
    g = giants[0]
    bd, cost = exact_cost(g, inst, w)
    return SolveResult(g, cost, bd, jnp.int32(evals))
