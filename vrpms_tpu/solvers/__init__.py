from vrpms_tpu.solvers.common import SolveResult, perm_fitness_fn, solve_info
from vrpms_tpu.solvers.bf import solve_tsp_bf, solve_vrp_bf
from vrpms_tpu.solvers.local_search import (
    nearest_neighbor_perm,
    local_search,
    solve_nn_2opt,
)
from vrpms_tpu.solvers.exact import solve_tsp_exact
from vrpms_tpu.solvers.delta_ls import (
    delta_polish,
    delta_polish_batch,
    move_delta_tables,
)
from vrpms_tpu.solvers.sa import SAParams, solve_sa
from vrpms_tpu.solvers.ils import ILSParams, solve_ils
from vrpms_tpu.solvers.ga import GAParams, solve_ga
from vrpms_tpu.solvers.aco import ACOParams, solve_aco
