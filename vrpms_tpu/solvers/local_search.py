"""Construction + steepest-descent local search (the NN + 2-opt slice).

BASELINE.md config 1 is "TSP 50-node nearest-neighbor + 2-opt". On TPU
the whole neighborhood is evaluated at once: all O(L^2) candidate moves
(2-opt reversals, or-opt rotations, swaps) are materialised as a vmapped
batch of index-transformed tours, fully evaluated by the cost kernel,
and the best one applied — a `lax.while_loop` of dense sweeps instead of
the reference-era nested Python loops that never got written (the stub
at reference src/solver.py:18-27 shuffles randomly).

Works on any giant tour, so it doubles as the polish step after SA/GA/ACO
and as a VRP improver (moves across separators reassign vehicles).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import giant_length
from vrpms_tpu.core.instance import Instance
from vrpms_tpu.moves import reverse_segment, rotate_segment, swap_positions
from vrpms_tpu.solvers.common import SolveResult


def nearest_neighbor_perm(inst: Instance, start_time: float = 0.0) -> jax.Array:
    """Greedy nearest-neighbor customer order from the depot.

    Ranks by the duration slice active at `start_time` (a cheap static
    heuristic; exact time propagation happens in the cost kernel).
    """
    slice_idx = int(start_time // inst.slice_minutes) % inst.n_slices
    d = inst.durations[slice_idx]
    n = inst.n_customers
    # tier-padded instances: phantom columns (depot aliases) are pushed
    # behind every real customer, so the construction visits the real
    # set in exactly the unpadded order and parks phantoms at the tail
    # (the canonical padded layout the masked moves rely on)
    phantom_pen = None
    if inst.n_real is not None:
        phantom_pen = jnp.where(
            jnp.arange(1, inst.n_nodes) >= inst.n_real, 1e17, 0.0
        )

    def step(carry, _):
        cur, visited = carry
        dist = jnp.where(visited[1:], jnp.inf, d[cur, 1:])
        if phantom_pen is not None:
            dist = dist + phantom_pen
        nxt = jnp.argmin(dist).astype(jnp.int32) + 1
        return (nxt, visited.at[nxt].set(True)), nxt

    visited0 = jnp.zeros(inst.n_nodes, dtype=jnp.bool_).at[0].set(True)
    _, order = jax.lax.scan(step, (jnp.int32(0), visited0), None, length=n)
    return order


def _candidate_moves(length: int):
    """Static enumeration of (move_type, i, j) over interior positions.

    move_type 0: reverse [i, j]   (2-opt)      — i < j
    move_type 1: rotate [i, j] by 1 (or-opt)   — i < j
    move_type 2: swap i, j                     — i < j
    """
    idx = jnp.arange(1, length - 1)
    i, j = jnp.meshgrid(idx, idx, indexing="ij")
    mask = (i < j).reshape(-1)
    i, j = i.reshape(-1), j.reshape(-1)
    types = []
    for t in range(3):
        types.append(jnp.stack([jnp.full_like(i, t), i, j], axis=1))
    cands = jnp.concatenate(types, axis=0)
    return cands, jnp.concatenate([mask] * 3)


def _apply_move(giant, move):
    t, i, j = move[0], move[1], move[2]
    return jax.lax.switch(
        t,
        [
            lambda g: reverse_segment(g, i, j),
            lambda g: rotate_segment(g, i, j, 1),
            lambda g: swap_positions(g, i, j),
        ],
        giant,
    )


@lru_cache(maxsize=32)
def _ls_run_fn(max_sweeps: int):
    """Build (and cache) the jitted steepest descent; compile caches
    across calls with bounded retention (see sa._sa_block_fn rationale)."""

    @jax.jit
    def run(giant, inst, w):
        return _ls_body(giant, inst, w, max_sweeps)

    return run


def _ls_body(giant, inst, w, max_sweeps):
    length = giant.shape[0]
    cands, valid = _candidate_moves(length)
    n_cands = cands.shape[0]

    def score_all(g):
        moved = jax.vmap(lambda m: _apply_move(g, m))(cands)
        costs = jax.vmap(lambda x: total_cost(evaluate_giant(x, inst), w))(moved)
        return moved, jnp.where(valid, costs, jnp.inf)

    def cond(state):
        _, cur_cost, improved, sweeps, _ = state
        return improved & (sweeps < max_sweeps)

    def body(state):
        g, cur_cost, _, sweeps, evals = state
        moved, costs = score_all(g)
        k = jnp.argmin(costs)
        better = costs[k] < cur_cost - 1e-6
        g = jnp.where(better, moved[k], g)
        cur_cost = jnp.where(better, costs[k], cur_cost)
        return g, cur_cost, better, sweeps + 1, evals + n_cands

    c0 = total_cost(evaluate_giant(giant, inst), w)
    state = (giant, c0, jnp.bool_(True), jnp.int32(0), jnp.int32(0))
    g, c, _, _, evals = jax.lax.while_loop(cond, body, state)
    return g, c, evals


def local_search(
    giant: jax.Array,
    inst: Instance,
    weights: CostWeights | None = None,
    max_sweeps: int = 256,
) -> SolveResult:
    """Steepest-descent to a local optimum of the full move neighborhood."""
    w = weights or CostWeights.make()
    g, c, evals = _ls_run_fn(max_sweeps)(giant, inst, w)
    bd = evaluate_giant(g, inst)
    return SolveResult(g, c, bd, evals)


def solve_nn_2opt(
    inst: Instance, weights: CostWeights | None = None, max_sweeps: int = 256
) -> SolveResult:
    """Config-1 pipeline: nearest-neighbor construction, then steepest
    descent. For VRP the NN order is wrapped by the greedy capacity split
    before improvement."""
    from vrpms_tpu.core.split import greedy_split_giant

    order = nearest_neighbor_perm(inst)
    if inst.n_vehicles == 1:
        zero = jnp.zeros(1, dtype=jnp.int32)
        giant = jnp.concatenate([zero, order, zero])
        assert giant.shape == (giant_length(inst.n_customers, 1),)
    else:
        giant = greedy_split_giant(order, inst)
    return local_search(giant, inst, weights, max_sweeps)
