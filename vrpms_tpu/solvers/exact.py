"""Held-Karp exact TSP: dynamic programming over customer subsets.

The reference pins `gurobipy==10.0.3` in requirements.txt:2 without ever
importing it — the one signal of an intended exact/MILP solver path beyond
brute force. This module supplies that path TPU-natively: the Held-Karp
O(2^n n^2) subset DP runs as a single `lax.scan` over subset masks (each
mask only depends on strictly smaller masks, so ascending order is a valid
schedule), with the per-mask transition a dense (n, n) min-plus product on
the VPU. That lifts the exact-TSP bound from brute force's 10 customers
(10! ~ 3.6M orders) to 16 (2^16 x 16 DP states).

Asymmetric duration matrices are handled naturally (the DP walks directed
legs). Time windows / time-dependence are not — callers with timed
instances use brute force (solvers.bf) below its bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import giant_from_routes
from vrpms_tpu.core.instance import BIG, Instance
from vrpms_tpu.solvers.common import SolveResult

MAX_EXACT_CUSTOMERS = 16


def _check(inst: Instance) -> int:
    n = inst.n_customers
    if n > MAX_EXACT_CUSTOMERS:
        raise ValueError(
            f"Held-Karp is exact subset DP; {n} customers exceeds the "
            f"{MAX_EXACT_CUSTOMERS}-customer bound (2^{n} x {n} states)"
        )
    if inst.has_tw or inst.time_dependent:
        raise ValueError(
            "Held-Karp does not support time windows or time-dependent "
            "durations; use brute force below its bound"
        )
    return n


def _held_karp_table(d: jax.Array, n: int) -> jax.Array:
    """dp[mask, j] = min cost of depot -> (visit exactly the customers in
    mask) -> customer j, for j in mask. Returns the full [2^n, n] table."""
    bit = jnp.int32(1) << jnp.arange(n, dtype=jnp.int32)  # [n]
    d_c = d[1:, 1:]  # customer->customer legs, [n, n]
    d_0 = d[0, 1:]  # depot->customer legs, [n]

    def step(dp, mask):
        in_mask = (mask & bit) != 0  # [n] j in mask?
        single = (mask & (mask - 1)) == 0  # popcount == 1
        prev_mask = mask & ~bit  # [n] mask \ {j}
        prev_rows = dp[prev_mask]  # [n, n]: dp[mask\{j}, k]
        # k must be in mask\{j}: invalid entries are BIG already, but the
        # row for prev_mask == 0 is the (unused) all-BIG row 0.
        cand = prev_rows + d_c.T  # [n(j), n(k)]: dp[...,k] + d[k, j]
        best = jnp.min(cand, axis=1)  # [n] over k
        val = jnp.where(single, d_0, best)
        val = jnp.where(in_mask, val, BIG)
        dp = dp.at[mask].set(val)
        return dp, None

    dp0 = jnp.full((1 << n, n), BIG, dtype=jnp.float32)
    masks = jnp.arange(1, 1 << n, dtype=jnp.int32)
    dp, _ = jax.lax.scan(step, dp0, masks)
    return dp


_hk_table_jit = jax.jit(_held_karp_table, static_argnums=1)


def solve_tsp_exact(inst: Instance, weights: CostWeights | None = None) -> SolveResult:
    """Exact TSP via Held-Karp; fills the reference's BF/exact hole for
    11..16 customers where enumeration (solvers.bf) is infeasible."""
    n = _check(inst)
    w = weights or CostWeights.make()
    d = inst.durations[0]

    dp = _hk_table_jit(d, n)

    # Host-side backtrack (tiny: n steps over a 4 MB table at n == 16).
    dp_h = np.asarray(dp)
    d_h = np.asarray(d)
    full = (1 << n) - 1
    closing = dp_h[full] + d_h[1:, 0]
    j = int(np.argmin(closing))
    order = [j]
    mask = full
    for _ in range(n - 1):
        pm = mask & ~(1 << j)
        k = int(np.argmin(dp_h[pm] + d_h[1:, 1 + j]))
        order.append(k)
        mask, j = pm, k
    order.reverse()  # depot -> order[0] -> ... -> order[-1] -> depot

    giant = giant_from_routes([[c + 1 for c in order]], n, inst.n_vehicles)
    bd = evaluate_giant(giant, inst)
    return SolveResult(giant, total_cost(bd, w), bd, jnp.int32((1 << n) * n))
