"""Exact solvers: Held-Karp TSP DP and branch-and-bound CVRP.

The reference pins `gurobipy==10.0.3` in requirements.txt:2 without ever
importing it — the one signal of an intended exact/MILP solver path beyond
brute force. This module supplies that path:

* Held-Karp O(2^n n^2) subset DP for TSP, run as a single `lax.scan` over
  subset masks (each mask only depends on strictly smaller masks, so
  ascending order is a valid schedule), with the per-mask transition a
  dense (n, n) min-plus product on the VPU. That lifts the exact-TSP bound
  from brute force's 10 customers (10! ~ 3.6M orders) to 16.

* `solve_cvrp_bnb` — depth-first branch-and-bound over route construction
  for CVRP to n ≈ 32 (VERDICT round-2 item 3). This one is deliberately
  HOST-side numpy/scipy: the search tree is irregular, data-dependent
  control flow — the worst possible shape for XLA — while each node's
  work is a tiny assignment problem. The TPU's job in the exact path is
  producing the incumbent (ILS), which is what makes the pruning bite.

Asymmetric duration matrices are handled naturally (both walk directed
legs). Time windows / time-dependence are not — callers with timed
instances use brute force (solvers.bf) below its bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import giant_from_routes
from vrpms_tpu.core.instance import BIG, Instance
from vrpms_tpu.solvers.common import SolveResult

MAX_EXACT_CUSTOMERS = 16


def _check(inst: Instance) -> int:
    n = inst.n_customers
    if n > MAX_EXACT_CUSTOMERS:
        raise ValueError(
            f"Held-Karp is exact subset DP; {n} customers exceeds the "
            f"{MAX_EXACT_CUSTOMERS}-customer bound (2^{n} x {n} states)"
        )
    if inst.has_tw or inst.time_dependent:
        raise ValueError(
            "Held-Karp does not support time windows or time-dependent "
            "durations; use brute force below its bound"
        )
    return n


def _held_karp_table(d: jax.Array, n: int) -> jax.Array:
    """dp[mask, j] = min cost of depot -> (visit exactly the customers in
    mask) -> customer j, for j in mask. Returns the full [2^n, n] table."""
    bit = jnp.int32(1) << jnp.arange(n, dtype=jnp.int32)  # [n]
    d_c = d[1:, 1:]  # customer->customer legs, [n, n]
    d_0 = d[0, 1:]  # depot->customer legs, [n]

    def step(dp, mask):
        in_mask = (mask & bit) != 0  # [n] j in mask?
        single = (mask & (mask - 1)) == 0  # popcount == 1
        prev_mask = mask & ~bit  # [n] mask \ {j}
        prev_rows = dp[prev_mask]  # [n, n]: dp[mask\{j}, k]
        # k must be in mask\{j}: invalid entries are BIG already, but the
        # row for prev_mask == 0 is the (unused) all-BIG row 0.
        cand = prev_rows + d_c.T  # [n(j), n(k)]: dp[...,k] + d[k, j]
        best = jnp.min(cand, axis=1)  # [n] over k
        val = jnp.where(single, d_0, best)
        val = jnp.where(in_mask, val, BIG)
        dp = dp.at[mask].set(val)
        return dp, None

    dp0 = jnp.full((1 << n, n), BIG, dtype=jnp.float32)
    masks = jnp.arange(1, 1 << n, dtype=jnp.int32)
    dp, _ = jax.lax.scan(step, dp0, masks)
    return dp


_hk_table_jit = jax.jit(_held_karp_table, static_argnums=1)


def solve_tsp_exact(inst: Instance, weights: CostWeights | None = None) -> SolveResult:
    """Exact TSP via Held-Karp; fills the reference's BF/exact hole for
    11..16 customers where enumeration (solvers.bf) is infeasible."""
    n = _check(inst)
    w = weights or CostWeights.make()
    d = inst.durations[0]

    dp = _hk_table_jit(d, n)

    # Host-side backtrack (tiny: n steps over a 4 MB table at n == 16).
    dp_h = np.asarray(dp)
    d_h = np.asarray(d)
    full = (1 << n) - 1
    closing = dp_h[full] + d_h[1:, 0]
    j = int(np.argmin(closing))
    order = [j]
    mask = full
    for _ in range(n - 1):
        pm = mask & ~(1 << j)
        k = int(np.argmin(dp_h[pm] + d_h[1:, 1 + j]))
        order.append(k)
        mask, j = pm, k
    order.reverse()  # depot -> order[0] -> ... -> order[-1] -> depot

    giant = giant_from_routes([[c + 1 for c in order]], n, inst.n_vehicles)
    bd = evaluate_giant(giant, inst)
    return SolveResult(giant, total_cost(bd, w), bd, jnp.int32((1 << n) * n))


# ---------------------------------------------------------------------------
# Branch-and-bound exact CVRP
# ---------------------------------------------------------------------------

MAX_BNB_CUSTOMERS = 34


class InfeasibleError(ValueError):
    """No capacity-feasible solution exists for the instance — distinct
    from precondition ValueErrors so dispatchers can fall back to a
    penalized best-effort result ONLY for true infeasibility."""


def _bnb_check(inst: Instance) -> tuple[int, float]:
    n = inst.n_customers
    if n > MAX_BNB_CUSTOMERS:
        raise ValueError(
            f"branch-and-bound is practical to ~{MAX_BNB_CUSTOMERS} "
            f"customers; got {n}"
        )
    if inst.has_tw or inst.time_dependent:
        raise ValueError("branch-and-bound does not support TW/TD instances")
    caps = np.asarray(inst.capacities, dtype=np.float64)
    if np.unique(caps).size > 1:
        raise ValueError("branch-and-bound requires a uniform fleet")
    return n, float(caps[0])


def solve_cvrp_bnb(
    inst: Instance,
    weights: CostWeights | None = None,
    time_limit_s: float | None = None,
    incumbent_routes: list[list[int]] | None = None,
    incumbent_cost: float | None = None,
    use_native: bool = True,
    n_threads: int = 0,
):
    """Exact CVRP by DFS branch-and-bound -> (SolveResult, proven, stats).

    Search space: routes are built one at a time, depot-out to depot-in.
    Two symmetries are broken exactly:
      * route order — routes open in strictly increasing order of their
        first customer, so each PARTITION into oriented routes is
        enumerated once;
      * direction — for symmetric matrices a closed route with >= 2
        customers must satisfy first < last (each orientation pair
        appears once).

    Pruning, cheapest test first:
      1. capacity feasibility: demand left must fit in the open route's
         slack plus (fleet left) x capacity;
      2. out/in-arc sum bound: every remaining node emits exactly one arc
         (and every remaining customer absorbs exactly one) — sum of
         per-node cheapest legal arcs, both directions, max of the two;
      3. assignment-problem relaxation (scipy Hungarian) on the residual
         digraph with one depot-out row per unused vehicle and matching
         depot-in columns (depot-out -> depot-in = 0 models idle
         vehicles), the classic Fischetti-Toth AP bound;
      4. dominance: a Pareto memo per (unvisited-set, last-node,
         open-route-first) of (cost, slack, vehicles-left) triples — a
         state beaten on all three coordinates cannot lead anywhere its
         dominator cannot.

    The incumbent seeds the pruning: callers hand the ILS champion in
    (routes as customer-index lists); without one the bound starts at the
    greedy depot-star. `proven` is True iff the tree was exhausted inside
    the time limit — then the returned cost IS the optimum under the
    distance objective.
    """
    import time as _time

    n, cap = _bnb_check(inst)
    w = weights or CostWeights.make()
    d = np.asarray(inst.durations[0], dtype=np.float64)
    dem = np.asarray(inst.demands, dtype=np.float64)[1:]  # per customer
    V = inst.n_vehicles
    symmetric = bool(np.allclose(d, d.T))
    INF = float("inf")

    best_cost = INF if incumbent_cost is None else float(incumbent_cost) + 1e-9
    best_routes: list[list[int]] | None = (
        None if incumbent_routes is None else [list(r) for r in incumbent_routes]
    )
    # `certified` tracks whether the routes we HOLD achieve the pruning
    # bound. It goes false only in the cost-without-routes case (the
    # caller's bound prunes below anything we can return) and comes back
    # true the moment the search finds its own solution — `proven` must
    # never be claimed for a returned solution that merely survived
    # someone else's bound (a ladder ub rounded below the true optimum
    # would otherwise stamp the NN fallback as a "proven optimum").
    certified = incumbent_routes is not None or incumbent_cost is None
    if best_routes is None:
        # nearest-neighbor-with-capacity fallback so a deadline hit can
        # always return SOMETHING feasible (first-fit by proximity; a
        # failed packing just leaves pruning cold). Only its COST is
        # trusted for pruning when it actually beats the caller's bound.
        routes_nn, unv = [], set(range(1, n + 1))
        while unv and len(routes_nn) < V:
            r, load, p = [], 0.0, 0
            while True:
                fits = [j for j in unv if dem[j - 1] + load <= cap + 1e-9]
                if not fits:
                    break
                j = min(fits, key=lambda j: d[p, j])
                r.append(j)
                unv.discard(j)
                load += dem[j - 1]
                p = j
            if not r:
                break
            routes_nn.append(r)
        if not unv:
            nn_cost = sum(
                d[0, r[0]] + sum(d[a, b] for a, b in zip(r, r[1:])) + d[r[-1], 0]
                for r in routes_nn
            )
            best_routes = routes_nn
            if nn_cost < best_cost:
                best_cost = float(nn_cost) + 1e-9
                certified = True

    deadline = None if time_limit_s is None else _time.monotonic() + time_limit_s
    stats = {"nodes": 0, "ap_calls": 0, "proven": False}
    memo: dict[tuple[int, int, int], list[tuple[float, float, int]]] = {}

    cust = np.arange(1, n + 1)

    # Root Lagrangian artifacts: the CMT q-route ascent (Polyak-stepped
    # against the incumbent) fixes multipliers, then the q-path completion
    # tables turn every node's bound into one vector-min over the open
    # route's residual capacity — capacity-aware where the AP bound is
    # blind (measured on E-n22-k4: AP alone exceeded 8M nodes without
    # closing; the q-completion bound closes it in seconds).
    from vrpms_tpu.io.bounds import cmt_qroute_ascent, qpath_completion_tables

    asc_iters = 80 if time_limit_s is None else min(80, max(5, int(time_limit_s * 10)))
    # the ng sharpening pass costs seconds of native DP (plus a one-time
    # g++ build); only afford it when the budget is generous (ADVICE r4)
    afford_ng = time_limit_s is None or time_limit_s >= 10.0
    asc = cmt_qroute_ascent(
        inst, iters=asc_iters,
        ub=None if not np.isfinite(best_cost) else best_cost,
        ng_sharpen=afford_ng,
    )
    qtab = None
    if asc is not None:
        tabs = qpath_completion_tables(
            inst, asc["lam"], ng_tables=asc.get("ng_tables"),
            build_ng=afford_ng,
        )
        if tabs is not None:
            R_tab, Psi = tabs
            lam = asc["lam"]
            dem_s = asc["dem_s"]  # per customer, scaled ints
            cap_s = asc["cap_s"]
            total_s = asc["total_s"]
            r_rows = Psi.shape[0] - 1
            qtab = True
    if not qtab:
        lam = np.zeros(n)
        dem_s = dem.astype(np.float64)
        cap_s = cap
        total_s = float(dem.sum())
        r_rows = 0
    root_stats = {"qroute_bound": None if asc is None else asc["bound"]}
    stats.update(root_stats)
    stats["engine"] = "python"

    # The native (C++) DFS walks the identical tree ~100x faster — the
    # Python walker below sustains ~10-20k nodes/s, the compiled one
    # millions; n=32 proofs take 10^7+ nodes. Python remains both the
    # no-toolchain fallback and the cross-check oracle
    # (tests/test_exact.py::TestBranchAndBound::test_native_matches_python,
    # which forces use_native=False on one side).
    if qtab and use_native:
        from vrpms_tpu.native import bnb_solve_native

        remaining = (
            None if deadline is None else max(0.2, deadline - _time.monotonic())
        )
        out = bnb_solve_native(
            d, dem_s, lam, R_tab, Psi, cap_s, total_s, V,
            best_cost, remaining, symmetric, n_threads=n_threads,
        )
        if out is not None:
            routes_n, cost_n, nodes_n, proven_n = out
            stats["nodes"] = nodes_n
            stats["engine"] = "native"
            if routes_n is not None and cost_n < best_cost:
                best_routes, best_cost = routes_n, cost_n
                certified = True
            if best_routes is None:
                raise InfeasibleError("no capacity-feasible solution found")
            stats["proven"] = bool(proven_n and certified)
            giant = giant_from_routes(best_routes, n, V)
            bd = evaluate_giant(giant, inst)
            res = SolveResult(giant, total_cost(bd, w), bd, jnp.int32(min(nodes_n, 2**31 - 1)))
            return res, stats["proven"], stats

    def ap_bound(S: np.ndarray, p: int, m: int) -> float:
        """AP relaxation of completing the tour: rows = {p} u S u m depot-
        outs, cols = S u (m+1) depot-ins. Only the non-integer-demand
        fallback path runs this, so scipy stays an optional dependency
        (imported here, not at solve entry)."""
        from scipy.optimize import linear_sum_assignment

        stats["ap_calls"] += 1
        s = len(S)
        size = 1 + s + m
        M = np.full((size, s + m + 1), INF)
        M[0, :s] = d[p, S]
        M[0, s:] = d[p, 0]
        M[1 : 1 + s, :s] = d[np.ix_(S, S)]
        M[np.arange(1, 1 + s), np.arange(s)] = INF  # no self-loops
        M[1 : 1 + s, s:] = d[S, 0][:, None]
        if m:
            M[1 + s :, :s] = d[0, S][None, :]
            M[1 + s :, s:] = 0.0  # idle vehicle: depot-out -> depot-in
        r, c = linear_sum_assignment(M)
        return float(M[r, c].sum())

    def cheap_bound(S: np.ndarray, p: int, m: int) -> float:
        """Max of the out-arc-sum and in-arc-sum relaxations (vector ops
        only, no Hungarian): every node in {p} u S emits exactly one arc
        into S u {0}; every customer in S absorbs exactly one from
        {p} u S u (depot if m > 0)."""
        sub = d[np.ix_(S, S)].copy()
        np.fill_diagonal(sub, INF)
        out = np.minimum(sub.min(axis=1) if len(S) > 1 else INF, d[S, 0]).sum()
        out += min(d[p, S].min(), d[p, 0])
        inn = sub.min(axis=0) if len(S) > 1 else np.full(len(S), INF)
        inn = np.minimum(inn, d[p, S])
        if m:
            inn = np.minimum(inn, d[0, S])
        return float(max(out, inn.sum()))

    # Children are walked cheapest-extension-first: good incumbents early
    # make the bounds bite sooner. All capacity arithmetic runs in the
    # gcd-scaled integers of the q-tables when they exist (exact), else
    # in raw floats with tolerances.
    def dfs(unvis, p, first, slack, m, cost, sum_lam, routes, route):
        nonlocal best_cost, best_routes, certified
        stats["nodes"] += 1
        if deadline is not None and stats["nodes"] % 2048 == 0:
            if _time.monotonic() > deadline:
                raise TimeoutError
        S = cust[[(unvis >> (j - 1)) & 1 == 1 for j in cust]]
        if len(S) == 0:
            if symmetric and len(route) >= 2 and route[0] > route[-1]:
                return  # non-canonical orientation
            total = cost + d[p, 0]
            if total < best_cost - 1e-12:
                best_cost = total
                best_routes = [list(r) for r in routes] + [list(route)]
                certified = True
            return
        dem_left = dem_s[S - 1].sum()
        if dem_left > slack + m * cap_s + (0 if qtab else 1e-9):
            return
        if qtab:
            # completion = finish the open route from p with q1 more units
            # (q-path table) + at most m fresh routes over the rest (combo
            # table); minus the remaining customers' multiplier mass
            hi = int(min(slack, dem_left))
            vals = R_tab[: hi + 1, p - 1] + Psi[min(m, r_rows), dem_left - hi : dem_left + 1][::-1]
            qb = cost + vals.min() - sum_lam
            if qb >= best_cost - 1e-9:
                return
        else:
            if cost + cheap_bound(S, p, m) >= best_cost - 1e-9:
                return
            if cost + ap_bound(S, p, m) >= best_cost - 1e-9:
                return
        key = (unvis, p, first)
        ent = memo.get(key)
        if ent is not None:
            for c0, sl0, m0 in ent:
                if c0 <= cost + 1e-12 and sl0 >= slack - 1e-12 and m0 >= m:
                    return
        else:
            ent = memo[key] = []
        ent[:] = [e for e in ent if not (cost <= e[0] and slack >= e[1] and m >= e[2])]
        if len(ent) < 8:
            ent.append((cost, slack, m))

        # children: extend within the open route ...
        tol = 0 if qtab else 1e-9
        ext = S[dem_s[S - 1] <= slack + tol]
        order = np.argsort(d[p, ext], kind="stable") if len(ext) else []
        children = [
            (float(d[p, j]), int(j), False) for j in (ext[order] if len(ext) else ())
        ]
        # ... or close it (canonical orientation only) and open the next
        # with a strictly larger first customer
        if m >= 1 and not (symmetric and len(route) >= 2 and route[0] > route[-1]):
            starts = S[(S > first) & (dem_s[S - 1] <= cap_s + tol)]
            close = d[p, 0]
            children += [(float(close + d[0, f]), int(f), True) for f in starts]
            children.sort(key=lambda t: t[0])
        for step_cost, j, opens in children:
            if cost + step_cost >= best_cost - 1e-9:
                continue
            bit = 1 << (j - 1)
            if opens:
                routes.append(list(route))
                route[:] = [j]
                dfs(
                    unvis & ~bit, j, j, cap_s - dem_s[j - 1], m - 1,
                    cost + step_cost, sum_lam - lam[j - 1], routes, route,
                )
                route[:] = routes.pop()
            else:
                route.append(j)
                dfs(
                    unvis & ~bit, j, first, slack - dem_s[j - 1], m,
                    cost + step_cost, sum_lam - lam[j - 1], routes, route,
                )
                route.pop()

    full = (1 << n) - 1
    lam_total = float(lam.sum())
    try:
        # root: branch on the first route's first customer (all of them —
        # route ordering only constrains LATER routes)
        roots = [int(f) for f in cust[dem_s <= cap_s]]
        roots.sort(key=lambda f: d[0, f])
        if len(roots) < n:
            raise TimeoutError  # some customer exceeds capacity: infeasible
        for f in roots:
            bit = 1 << (f - 1)
            if d[0, f] >= best_cost:
                continue
            dfs(
                full & ~bit, f, f, cap_s - dem_s[f - 1], V - 1,
                float(d[0, f]), lam_total - lam[f - 1], [], [f],
            )
        stats["proven"] = certified
    except TimeoutError:
        pass

    if best_routes is None:
        raise InfeasibleError("no capacity-feasible solution found")
    giant = giant_from_routes(best_routes, n, V)
    bd = evaluate_giant(giant, inst)
    res = SolveResult(giant, total_cost(bd, w), bd, jnp.int32(stats["nodes"]))
    return res, bool(stats["proven"]), stats
