"""Live solve progress: anytime incumbent snapshots + cooperative cancel.

The solvers are anytime metaheuristics whose deadline drivers already
return to the host between device-side scan blocks (solvers.common.
run_blocked — the same cadence the BlockTrace collector records at).
This module is the seam that publishes that cadence LIVE, while the
solve is still running, instead of only in the post-hoc stats:

  * ProgressSink — a thread-safe mailbox one job owns. The solver
    thread `record()`s the synced best at each block boundary; any
    number of reader threads (`GET /api/jobs/{id}` polls, the SSE
    stream) take `snapshot()`/`wait_progress()` without ever touching
    the device. Snapshots are published only when the incumbent
    IMPROVES, so the stream is quiet exactly when the solver is, and
    the published bestCost is monotone non-increasing by construction.
  * ProgressFanout — the micro-batched launch's adapter: one vmapped
    SA launch carries K jobs, the fanout splits the per-instance best
    rows to K per-job sinks (service.jobs._run_batched installs it).
  * cooperative cancellation — `cancel()` flips a flag the deadline
    drivers check between blocks (run_blocked, the delta launch loop,
    the ILS round loop); the solve stops at the next boundary and
    returns its incumbent instead of burning the rest of its budget.

Like the BlockTrace, the sink rides a ContextVar: with none active the
solver hot path pays one ContextVar read per block, and with
VRPMS_PROGRESS=off the service never installs one — solver
trajectories are bit-identical to the pre-progress contract either way
(recording only READS the already-synced best; it never changes the
block decomposition or any device computation).

Nothing here imports jax or the service: the concrete instruments
(vrpms_progress_events_total, vrpms_incumbent_gap) are wired in by
service.obs through `set_observer`, the tiers/set_tier_observer
pattern.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time

from vrpms_tpu import config
from vrpms_tpu.obs import spans

#: published (improving) snapshots kept for the terminal convergence
#: profile — the record persisted with the job must stay bounded
MAX_PROFILE_SNAPSHOTS = 256


def enabled() -> bool:
    """The VRPMS_PROGRESS master switch (default on). Read per call so
    tests and embedders can toggle at runtime."""
    return config.enabled("VRPMS_PROGRESS")


# observer seam: service.obs wires the Prometheus instruments in;
# fn(sink, snapshot) is called once per PUBLISHED snapshot
_observer = None


def set_observer(fn) -> None:
    global _observer
    _observer = fn


class ProgressSink:
    """One job's live incumbent mailbox (see module docstring).

    `lower_bound`, when given, is the instance's best cheap applicable
    lower bound (io.bounds.quick_lower_bound) — every snapshot carries
    `gap` = (bestCost - LB) / LB against it, the certified-style
    optimality-gap ceiling a dispatch client sheds budget on.
    """

    def __init__(self, job_id: str | None = None, problem: str | None = None,
                 algorithm: str | None = None,
                 lower_bound: float | None = None):
        self.job_id = job_id
        self.problem = problem
        self.algorithm = algorithm
        self.lower_bound = (
            float(lower_bound)
            if lower_bound is not None and lower_bound > 0
            else None
        )
        self._lock = threading.Lock()
        self._new = threading.Condition(self._lock)
        self._t0 = time.perf_counter()
        self._evals = 0.0  # guarded-by: _lock
        self._block = 0  # guarded-by: _lock
        self._latest: dict | None = None  # guarded-by: _lock
        self._profile: list[dict] = []  # guarded-by: _lock
        self._profile_truncated = False  # guarded-by: _lock
        self.seq = 0  # guarded-by: _lock (bumped per published snapshot + close)
        self.closed = False  # guarded-by: _lock
        self.status: str | None = None   # terminal: done|failed|...
        self._cancel = False
        self._ack = False  # a driver stopped FOR the cancel
        # durable-checkpoint capture handle (service.checkpoint): when
        # attached, the solver seam offers the champion tour to it at a
        # bounded cadence (want_incumbent/offer_incumbent below).
        # Opaque here — this module stays store-free; None (the
        # default, and VRPMS_CKPT=off) costs one attribute read per
        # block boundary and nothing else.
        self.ckpt = None

    #: the pipelined driver reduces best-of-batch to ONE device-side
    #: scalar before transfer; a plain sink only needs that min, so it
    #: opts in to the cheap path (the fanout below overrides: it splits
    #: per-row bests and must see the full array)
    needs_array = False

    # -- solver side (device-owning thread) ---------------------------------
    def record(self, best, iters: int, evals_per_iter: float | None) -> None:
        """Block-boundary report — same contract as BlockTrace.record:
        `best` is whatever the deadline loop synced on (already
        block_until_ready'd) — a pre-reduced device scalar or host
        float under the pipelined driver, the full per-chain best array
        from the serial loop — and its min is the incumbent cost.
        Publishes a snapshot only when the incumbent improves (or on
        the first block); telemetry failures never fail the solve."""
        import numpy as np

        with self._lock:
            self._evals += float(iters) * float(
                evals_per_iter if evals_per_iter is not None else 1.0
            )
            self._block += 1
        try:
            # host floats (and 0-d scalars) skip the array round trip —
            # the common per-boundary case once the driver pre-reduces
            best_cost = (
                float(best)
                if isinstance(best, (int, float))
                else float(np.min(np.asarray(best)))
            )
        except Exception:
            return  # keep eval accounting, skip the unreadable entry
        with self._new:
            if (
                self._latest is not None
                and best_cost >= self._latest["bestCost"] - 1e-9
            ):
                return
            snap = {
                "block": self._block,
                "wallMs": round((time.perf_counter() - self._t0) * 1e3, 2),
                "bestCost": best_cost,
                "gap": (
                    None
                    if self.lower_bound is None
                    else round(
                        (best_cost - self.lower_bound) / self.lower_bound, 6
                    )
                ),
                "evals": int(self._evals),
            }
            self._latest = snap
            if len(self._profile) < MAX_PROFILE_SNAPSHOTS:
                self._profile.append(snap)
            else:
                self._profile_truncated = True
            self.seq += 1
            self._new.notify_all()
        # the snapshot joins the request's span waterfall too (no-op
        # without an active span — one ContextVar read); distinct from
        # the includeStats-only "block" events of the BlockTrace cadence
        spans.add_event("progress", **{k: v for k, v in snap.items()})
        obs = _observer
        if obs is not None:
            try:
                obs(self, snap)
            except Exception:
                pass  # telemetry must never kill the device loop

    def close(self, status: str | None = None) -> None:
        """Terminal transition: wake every stream waiter for good."""
        with self._new:
            if self.closed:
                return
            self.closed = True
            self.status = status
            self.seq += 1
            self._new.notify_all()

    # -- durable-checkpoint capture (crash-resumable solves) ----------------
    def seed_incumbent(self, cost: float, evals: int = 0) -> None:
        """Pre-publish a RESUMED attempt's inherited incumbent (the
        predecessor's checkpoint) as the block-0 snapshot: the stream
        opens at the checkpoint cost, and the improves-only filter then
        guarantees the first live-published incumbent is never worse
        than the checkpoint — the resume contract. No-op once anything
        was published."""
        with self._new:
            if self._latest is not None:
                return
            snap = {
                "block": 0,
                "wallMs": 0.0,
                "bestCost": float(cost),
                "gap": (
                    None
                    if self.lower_bound is None
                    else round(
                        (float(cost) - self.lower_bound) / self.lower_bound,
                        6,
                    )
                ),
                "evals": int(evals),
                "resumed": True,
            }
            self._latest = snap
            self._profile.append(snap)
            self.seq += 1
            self._new.notify_all()
        obs = _observer
        if obs is not None:
            try:
                obs(self, snap)
            except Exception:
                pass

    def want_incumbent(self) -> bool:
        """Should the solver seam extract + offer the champion tour at
        this block boundary? True only when a checkpoint handle is
        attached AND its cadence says a capture is due — the handle
        owns the interval/improvement bookkeeping, so the hot path
        pays one attribute read when checkpointing is off."""
        h = self.ckpt
        if h is None:
            return False
        try:
            return h.due(self)
        except Exception:
            return False  # a broken handle must never stop the solve

    def offer_incumbent(self, giant) -> None:
        """Hand the champion tour (the synced best state's giant, a
        device or host array) to the checkpoint handle. Best-effort:
        decode + store write happen on the checkpointer's background
        thread, never here."""
        h = self.ckpt
        if h is None:
            return
        try:
            h.offer(self, giant)
        except Exception:
            pass  # capture must never kill the device loop

    # -- cancellation --------------------------------------------------------
    def cancel(self) -> None:
        """Request a cooperative stop: the deadline drivers check this
        between device blocks and return their incumbent."""
        with self._new:
            self._cancel = True
            self._new.notify_all()

    @property
    def cancelled(self) -> bool:
        return self._cancel

    def note_cancel_seen(self) -> None:
        """A driver observed the cancel at a boundary and STOPPED —
        only then may the result honestly be marked `cancelled`: a
        single-block (deadline-free) solve has no boundary left to
        check and runs its full budget, which is not a cut-short run."""
        self._ack = True

    @property
    def cancel_acknowledged(self) -> bool:
        return self._ack

    # -- reader side (HTTP threads) -----------------------------------------
    def snapshot(self) -> dict | None:
        """Latest published incumbent snapshot (a copy), or None."""
        with self._lock:
            return None if self._latest is None else dict(self._latest)

    def wait_progress(self, seen_seq: int, timeout: float):
        """Park until the sink moves past `seen_seq` (a new snapshot or
        the terminal close) or `timeout` elapses. Returns
        (seq, snapshot|None, closed)."""
        deadline = time.monotonic() + timeout
        with self._new:
            while self.seq == seen_seq and not self.closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._new.wait(remaining)
            snap = None if self._latest is None else dict(self._latest)
            return self.seq, snap, self.closed

    def profile(self) -> dict | None:
        """Terminal convergence profile for the persisted job record:
        every published (improving) snapshot, bounded."""
        with self._lock:
            if not self._profile:
                return None
            out = {
                "blocks": self._block,
                "improvements": [dict(s) for s in self._profile],
            }
            if self.lower_bound is not None:
                out["lowerBound"] = self.lower_bound
            if self._profile_truncated:
                out["truncated"] = True
            return out


class ProgressFanout:
    """Per-job sinks behind one batched launch's contextvar slot.

    The batched SA launch syncs a [K, B] per-instance best array;
    `record` splits row i to sink i (None entries — jobs without
    progress — are skipped). `cancelled` only when EVERY participating
    sink is cancelled: one job's cancel must not kill its batch-mates'
    solve (a cancelled batched job simply gets its incumbent when the
    launch ends)."""

    #: the fanout splits per-instance ROWS to member sinks, so the
    #: pipelined driver must keep the full [K, B] sync array for it —
    #: a scalar min across the batch would leak job A's cost to job B
    needs_array = True

    def __init__(self, sinks: list):
        self._sinks = list(sinks)

    def record(self, best, iters: int, evals_per_iter: float | None) -> None:
        import numpy as np

        try:
            rows = np.asarray(best)
        except Exception:
            return
        if rows.ndim == 0 or rows.shape[0] < len(self._sinks):
            return
        per = (
            None
            if evals_per_iter is None
            else float(evals_per_iter) / max(1, rows.shape[0])
        )
        for i, sink in enumerate(self._sinks):
            if sink is not None:
                sink.record(rows[i], iters, per)

    @property
    def cancelled(self) -> bool:
        live = [s for s in self._sinks if s is not None]
        return bool(live) and all(s.cancelled for s in live)

    def note_cancel_seen(self) -> None:
        for s in self._sinks:
            if s is not None and s.cancelled:
                s.note_cancel_seen()


_active: contextvars.ContextVar = contextvars.ContextVar(
    "vrpms_progress_sink", default=None
)


def active_sink():
    """The sink (or fanout) the current solve installed, if any — the
    only call the solver hot path makes."""
    return _active.get()


def cancel_requested() -> bool:
    """Between-blocks cancellation check for drivers layered above
    run_blocked (the delta launch loop, the ILS round loop, chunked
    enumeration). A True answer means the caller is about to STOP, so
    it doubles as the acknowledgement that makes `cancelled: true`
    honest (see ProgressSink.note_cancel_seen)."""
    sink = _active.get()
    if sink is None or not sink.cancelled:
        return False
    sink.note_cancel_seen()
    return True


@contextlib.contextmanager
def attach(sink):
    """Install a sink (or fanout) for the duration of a solve; a None
    sink yields without installing, so callers need no branch."""
    if sink is None:
        yield None
        return
    token = _active.set(sink)
    try:
        yield sink
    finally:
        _active.reset(token)


@contextlib.contextmanager
def masked():
    """HIDE the active sink for the duration of a nested auxiliary
    solve — e.g. the decomposition's boundary re-opt, whose tiny
    band-instance costs must not publish into the enclosing job's
    incumbent stream (they would beat the full-instance sum and stick,
    the improves-only filter discarding every honest later total). The
    auxiliary solve also skips cooperative-cancel checks while masked;
    callers bound it with a deadline instead."""
    token = _active.set(None)
    try:
        yield
    finally:
        _active.reset(token)
