"""Per-QoS-class SLO tracking: deadline-met objective, burn-rate windows.

The QoS layer (sched.qos) stamps every job with a class and an absolute
EDF deadline; the job observer already knows, at each terminal
transition, whether the deadline was met. This module turns those
booleans into the standard SRE alerting shape: a deadline-met SLO with
a target (VRPMS_SLO_TARGET, default 99%) and TWO burn-rate windows —
fast (5 min, pages on sharp regressions) and slow (1 h, catches slow
bleeds) — per class.

    burn rate = (observed miss fraction over the window)
                / (allowed miss budget, 1 - target)

A burn rate of 1.0 means the class is consuming exactly its error
budget; >1 means the budget exhausts early. Exported as
vrpms_slo_burn_rate{qos,window} gauges (service.obs refreshes at scrape
time) and as the `slo` block on /api/debug/fleet.

Bounded and stdlib-only: per-class outcome deques cap at MAX_OUTCOMES
(oldest evicted — at that point the slow window is saturated with
fresher evidence anyway). The clock is injectable for window-arithmetic
tests. Like every obs subsystem, nothing here runs unless the service
wiring calls in — VRPMS_ANALYTICS off never builds a tracker.
"""

from __future__ import annotations

import threading
import time

from vrpms_tpu import config

#: (name, seconds) — the fast window pages, the slow window trends
WINDOWS = (("fast", 300.0), ("slow", 3600.0))

#: per-class outcome cap; beyond it the oldest outcomes age out of the
#: deque before they age out of the slow window (bounded memory wins)
MAX_OUTCOMES = 4096


def slo_target() -> float:
    """The deadline-met objective, clamped to a meaningful (0, 1)."""
    t = float(config.get("VRPMS_SLO_TARGET"))
    return min(max(t, 0.0), 0.9999)


class SloTracker:
    """Per-QoS-class sliding-window deadline-met accounting."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        # class -> deque[(ts, met: bool)], appended at terminal
        # transitions, pruned lazily per read
        self._outcomes: dict = {}  # guarded-by: _lock

    def note(self, qos_class: str, met: bool) -> None:
        """One terminal job outcome: was its deadline met? Jobs with no
        deadline count as met — an unbounded request cannot miss."""
        cls = str(qos_class or "standard")
        now = self._clock()
        with self._lock:
            dq = self._outcomes.setdefault(cls, [])
            dq.append((now, bool(met)))
            if len(dq) > MAX_OUTCOMES:
                del dq[: len(dq) - MAX_OUTCOMES]

    def _window_stats(self, dq: list, now: float, span_s: float):
        cutoff = now - span_s
        total = met = 0
        for ts, ok in reversed(dq):
            if ts < cutoff:
                break
            total += 1
            met += 1 if ok else 0
        return total, met

    def burn_rates(self) -> dict:
        """{class: {window: {burnRate, total, met}}} over the live
        windows; classes with no outcomes are absent. An empty window
        burns 0 (no evidence is not a violation)."""
        now = self._clock()
        budget = max(1.0 - slo_target(), 1e-4)
        out: dict = {}
        with self._lock:
            items = {c: list(dq) for c, dq in self._outcomes.items()}
        for cls, dq in items.items():
            per = {}
            for name, span_s in WINDOWS:
                total, met = self._window_stats(dq, now, span_s)
                miss_frac = 0.0 if total == 0 else (total - met) / total
                per[name] = {
                    "burnRate": round(miss_frac / budget, 4),
                    "total": total,
                    "met": met,
                }
            out[cls] = per
        return out

    def fleet_block(self) -> dict:
        """The `slo` block for /api/debug/fleet."""
        return {
            "objective": "deadline-met",
            "target": slo_target(),
            "windows": {name: span for name, span in WINDOWS},
            "classes": self.burn_rates(),
        }


_lock = threading.Lock()
_tracker: SloTracker | None = None  # guarded-by: _lock


def get_tracker() -> SloTracker:
    global _tracker
    with _lock:
        if _tracker is None:
            _tracker = SloTracker()
        return _tracker


def note(qos_class: str, met: bool) -> None:
    """Record one terminal outcome (no-op tracker build is cheap; the
    caller gates on VRPMS_ANALYTICS so off-mode never reaches here)."""
    get_tracker().note(qos_class, met)


def burn_rates() -> dict:
    with _lock:
        t = _tracker
    return t.burn_rates() if t is not None else {}


def fleet_block() -> dict:
    return get_tracker().fleet_block()


def reset_tracker() -> None:
    global _tracker
    with _lock:
        _tracker = None
