"""Solver convergence telemetry: per-block (wall, best-cost, evals).

The SA/GA/ACO/ILS deadline loops (solvers.common.run_blocked and the
delta drivers layered on it) already return to the host between
device-side scan blocks — exactly the cadence an operator wants a
convergence trace at, and the ONE place it can be recorded with zero
jit-graph changes. A collector is installed per-request via ContextVar
(only when the request asks for stats), so with none active the cost in
the solver loop is a single ContextVar read per block.

Each entry is cumulative at the block boundary:

    {"wallMs": ms since the collector opened,
     "bestCost": best objective seen so far (solver's tracking basis),
     "evals": candidate evaluations performed so far}

`convergence_summary` derives the two headline numbers from a trace:
time-to-first-improvement (first block whose best beats the opening
block's) and first-block vs steady-state cost per evaluation — the
compile/dispatch overhead a warmed service should have amortised away.
"""

from __future__ import annotations

import contextlib
import contextvars
import time

from vrpms_tpu.obs import spans

MAX_TRACE_BLOCKS = 512  # a runaway many-block solve must not grow an
                        # unbounded response; the summary still counts
                        # every block via `evals`


class BlockTrace:
    __slots__ = ("blocks", "truncated", "_t0", "_evals")

    def __init__(self):
        self.blocks: list = []
        self.truncated = False
        self._t0 = time.perf_counter()
        self._evals = 0.0

    def record(self, best, iters: int, evals_per_iter: float | None) -> None:
        """Append one block-boundary entry. `best` is whatever the
        solver's deadline loop synced on — a pre-reduced device scalar
        or host float under the pipelined driver (VRPMS_PIPELINE), or
        the full array (per-chain bests, a champion fitness, ...) from
        the serial loop; its min is the best cost. It has been
        block_until_ready'd by the caller, so reading it is a transfer,
        not a wait. `evals_per_iter` None counts raw iterations."""
        import numpy as np

        self._evals += float(iters) * float(
            evals_per_iter if evals_per_iter is not None else 1.0
        )
        if len(self.blocks) >= MAX_TRACE_BLOCKS:
            self.truncated = True
            return
        try:
            # host floats (and 0-d scalars) skip the array round trip
            best_cost = (
                float(best)
                if isinstance(best, (int, float))
                else float(np.min(np.asarray(best)))
            )
        except Exception:
            # telemetry must never fail a solve: e.g. a multi-process
            # mesh's globally-sharded best array isn't fully addressable
            # from this host — skip the entry, keep the eval accounting
            return
        entry = {
            "wallMs": round((time.perf_counter() - self._t0) * 1e3, 2),
            "bestCost": best_cost,
            "evals": int(self._evals),
        }
        self.blocks.append(entry)
        # feed the same cadence into the request's span tree (no-op
        # without an active span — one ContextVar read): the waterfall
        # shows per-block solver progress inside the solve span
        spans.add_event("block", **entry)


_active: contextvars.ContextVar = contextvars.ContextVar(
    "vrpms_block_trace", default=None
)


def active_trace() -> BlockTrace | None:
    """The collector the current request installed, if any — the only
    call the solver hot path makes."""
    return _active.get()


@contextlib.contextmanager
def collect_blocks(enabled: bool = True):
    """Install a BlockTrace for the duration of a solve; yields it (or
    None when disabled, so callers need no branch)."""
    if not enabled:
        yield None
        return
    trace = BlockTrace()
    token = _active.set(trace)
    try:
        yield trace
    finally:
        _active.reset(token)


def convergence_summary(blocks: list) -> dict | None:
    """Headline numbers from a block trace (None on an empty trace).

    timeToFirstImprovementMs: wallMs of the first block whose bestCost
        beats the opening block's (None if nothing after block 0
        improved — including single-block traces).
    firstBlockMs / msPerKEvalFirstBlock: the opening block, which pays
        any residual compile/dispatch cost.
    msPerKEvalSteady: the remaining blocks' marginal rate; the ratio to
        the first block's is the cold-start overhead factor.
    """
    if not blocks:
        return None
    first = blocks[0]
    out = {
        "blocks": len(blocks),
        "firstBlockMs": first["wallMs"],
        "timeToFirstImprovementMs": None,
    }
    for entry in blocks[1:]:
        if entry["bestCost"] < first["bestCost"] - 1e-9:
            out["timeToFirstImprovementMs"] = entry["wallMs"]
            break
    if first["evals"] > 0:
        out["msPerKEvalFirstBlock"] = round(
            first["wallMs"] / first["evals"] * 1e3, 4
        )
    last = blocks[-1]
    d_evals = last["evals"] - first["evals"]
    if d_evals > 0:
        out["msPerKEvalSteady"] = round(
            (last["wallMs"] - first["wallMs"]) / d_evals * 1e3, 4
        )
    return out
