"""Solve analytics: per-solve flight records, durably exported.

Every efficiency and quality signal the stack computes during a solve
— device-vs-host time split and overlap ratio (the pipelined driver's
per-block timings), padding occupancy over the tier shape, micro-batch
fill, evals/sec, compile seconds, cache outcome, gap vs the quick
lower bound, and the primal-integral quality score over the progress
profile — used to die with the response. This module is the durable
half: the service's finish seams assemble one compact *flight record*
per completed solve and `offer` it here; a bounded queue + background
flusher batch-writes records through the store's flight seam
(store.base.put_flight_records — one row per (job_id, replica)), and a
bounded local ring keeps the newest records for the federated
GET /api/debug/analytics rollup and the per-job timeline's closing
"solve economics" event.

Capture rides a ContextVar `FlightTimer` the service installs around a
solve ONLY when VRPMS_ANALYTICS is on: the solver drivers
(solvers.common.run_blocked, sched.batch.solve_sa_batch) read it once
and, with none active, pay a single ContextVar read — fixed-seed
responses stay byte-identical with the switch off, the contract every
obs subsystem honors.

Failure policy mirrors the trace exporter (obs.export): queue overflow
drops the OLDEST record (counted `dropped`), store failures count
`failed` (single-attempt, fail-open), successes count `ok` — every
record accounted exactly once via the observer seam
(vrpms_analytics_total{outcome}).

The regression sentinel compares rolling per-(tier, algorithm) EWMAs
of gap and evals/sec against a committed baseline snapshot
(benchmarks/records/analytics_baseline.json; absent = inert) and flags
drift as a structured `analytics.regression` log event plus a counter
tick — quality archaeology becomes a dashboard alert.

Stdlib-only, like the rest of vrpms_tpu.obs: the store is reached
through an injected factory, defaulting to a lazy `store.get_database`
import on the flusher thread.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import threading
import time

from vrpms_tpu import config
from vrpms_tpu.obs.logging import log_event

#: hard bound on one flight-record row's serialized document — records
#: are compact by construction, so an oversized one (a runaway profile)
#: drops its `profile` block, then drops entirely
MAX_ROW_BYTES = 32768

#: newest flight records kept in-process for the local half of the
#: federated rollup and the timeline's economics event
RECENT_CAP = 256

OK, DROPPED, FAILED = "ok", "dropped", "failed"


def enabled() -> bool:
    return config.enabled("VRPMS_ANALYTICS")


# ---------------------------------------------------------------------------
# FlightTimer: the solver-side capture slot
# ---------------------------------------------------------------------------


class FlightTimer:
    """Per-solve accumulator the solver drivers write into.

    Installed on a ContextVar by the service ONLY when analytics is on;
    the drivers read `current_timer()` once per solve and skip every
    timing call when it is None. Single-threaded by construction: one
    solve owns one timer on one device-owning thread, so plain
    attribute adds suffice.

      * wait_s    — host seconds spent blocked in block_until_ready
                    (the device-side share of the wall clock);
      * overlap_s — host bookkeeping seconds that ran WHILE another
                    block was in flight on device (the pipelined
                    driver's hidden host work);
      * host_s    — host bookkeeping seconds NOT overlapped (serial
                    drains, the deadline-free path);
      * blocks    — device dispatches observed;
      * batch_members/batch_padded — the vmapped launch's real member
                    count and its power-of-two padded size
                    (sched.batch.solve_sa_batch fills these).
    """

    __slots__ = (
        "wait_s", "overlap_s", "host_s", "blocks",
        "batch_members", "batch_padded",
    )

    def __init__(self):
        self.wait_s = 0.0
        self.overlap_s = 0.0
        self.host_s = 0.0
        self.blocks = 0
        self.batch_members = None
        self.batch_padded = None

    def note_wait(self, seconds: float) -> None:
        self.wait_s += seconds
        self.blocks += 1

    def note_host(self, seconds: float, overlapped: bool) -> None:
        if overlapped:
            self.overlap_s += seconds
        else:
            self.host_s += seconds

    def overlap_ratio(self) -> float | None:
        """Fraction of observed host bookkeeping hidden behind device
        compute; None when no bookkeeping was timed (nothing to
        overlap — e.g. the deadline-free single-block path)."""
        total = self.overlap_s + self.host_s
        if total <= 0.0:
            return None
        return self.overlap_s / total


_active: contextvars.ContextVar = contextvars.ContextVar(
    "vrpms_flight_timer", default=None
)


def current_timer() -> FlightTimer | None:
    """The solve's flight timer, if the service installed one — the
    only call the solver hot path makes."""
    return _active.get()


@contextlib.contextmanager
def flight(timer: FlightTimer | None):
    """Install a timer for the duration of a solve; None yields without
    installing, so callers need no branch."""
    if timer is None:
        yield None
        return
    token = _active.set(timer)
    try:
        yield timer
    finally:
        _active.reset(token)


# ---------------------------------------------------------------------------
# Quality scores
# ---------------------------------------------------------------------------


def primal_integral(profile: dict | None) -> float | None:
    """Time-normalized primal integral over a progress profile
    (obs.progress.ProgressSink.profile()): the average optimality gap
    held over the solve's observed wall clock — 0 is ideal (the final
    incumbent found instantly), larger means quality arrived late.

    The gap is a step function: each improvement's gap holds from its
    wallMs to the next improvement's. The first snapshot's gap is
    charged from t=0 (the pre-incumbent span has no better bound), and
    the last holds to the final snapshot's wallMs. None when the
    profile is absent or carries no gaps (no lower bound)."""
    if not profile:
        return None
    imps = [
        s for s in profile.get("improvements", ())
        if s.get("gap") is not None and s.get("wallMs") is not None
    ]
    if not imps:
        return None
    end = float(imps[-1]["wallMs"])
    if end <= 0.0:
        return round(max(0.0, float(imps[-1]["gap"])), 6)
    area = 0.0
    prev_t = 0.0
    prev_gap = max(0.0, float(imps[0]["gap"]))
    for snap in imps:
        t = float(snap["wallMs"])
        area += prev_gap * max(0.0, t - prev_t)
        prev_t = t
        prev_gap = max(0.0, float(snap["gap"]))
    return round(area / end, 6)


# ---------------------------------------------------------------------------
# Seams: metrics observers, store factory
# ---------------------------------------------------------------------------

_observer = None


def set_observer(fn) -> None:
    """fn(outcome: str, n_records: int) — service.obs wires the
    vrpms_analytics_total counter in."""
    global _observer
    _observer = fn


def _notify(outcome: str, n: int) -> None:
    if n and _observer is not None:
        try:
            _observer(outcome, n)
        except Exception:
            pass  # telemetry about telemetry must never break either


_record_observer = None


def set_record_observer(fn) -> None:
    """fn(doc: dict) — called once per offered flight record;
    service.obs feeds the occupancy/fill/overlap histograms (with the
    trace-id exemplar) from it."""
    global _record_observer
    _record_observer = fn


def replica_identity() -> str:
    """This process's identity on exported rows — the trace exporter's,
    so flight rows and trace rows agree."""
    from vrpms_tpu.obs import export

    return export.replica_identity()


_store_factory = None


def set_store_factory(fn) -> None:
    """fn() -> a store.base.Database (anything with put_flight_records).
    Tests and benchmarks inject shims here; None restores the default
    (the configured store, resolved lazily on the flusher thread)."""
    global _store_factory
    _store_factory = fn


def _store():
    if _store_factory is not None:
        return _store_factory()
    from store import get_database

    return get_database("vrp", None)


# ---------------------------------------------------------------------------
# Serialization: one bounded row per (job, replica)
# ---------------------------------------------------------------------------


def serialize_record(doc: dict) -> dict | None:
    """The store row for one flight record. Enforces the row byte bound
    by shedding the `profile` block first; None means even the compact
    core is oversized (caller counts the record dropped)."""
    doc = dict(doc)
    for strip in (None, "profile"):
        if strip is not None:
            if strip not in doc:
                continue
            doc.pop(strip, None)
            doc["truncated"] = True
        try:
            size = len(json.dumps(doc))
        except (TypeError, ValueError):
            return None  # unserializable value snuck in: drop
        if size <= MAX_ROW_BYTES:
            return {
                "job_id": str(doc.get("jobId")),
                "replica": str(doc.get("replica")),
                "finished_at": float(doc.get("finishedAt") or 0.0),
                "tier": doc.get("tier"),
                "algorithm": doc.get("algorithm"),
                "doc": doc,
            }
    return None


# ---------------------------------------------------------------------------
# The exporter: bounded queue + background batch flusher
# ---------------------------------------------------------------------------


class AnalyticsExporter:
    """Bounded hand-off between the finish seams and the store — the
    TraceExporter design (obs.export) applied to flight records.

    `offer` is the solve-path half: one lock/append (plus an eviction
    pop when full); serialization and store I/O happen on the flusher
    thread. The flusher drains up to `batch` records per round into ONE
    put_flight_records call, then idles `flush_s` (a fresh offer wakes
    it immediately)."""

    def __init__(self, queue_cap: int = 256, batch: int = 16,
                 flush_s: float = 0.05):
        self.queue_cap = max(1, int(queue_cap))
        self.batch = max(1, int(batch))
        self.flush_s = max(0.001, float(flush_s))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()  # guarded-by: _lock
        self._busy = False  # guarded-by: _lock
        self._halt = False  # guarded-by: _lock
        self._warned = False  # guarded-by: _lock
        # flusher-thread-only store handle, reused across rounds and
        # keyed by the active selector so env flips rebuild it; dropped
        # after any failed write so a broken client is never pinned
        self._db = None
        self._db_key = None
        self._thread = threading.Thread(
            target=self._run, name="vrpms-analytics", daemon=True
        )
        self._thread.start()

    # -- solve-path side ----------------------------------------------------
    def offer(self, doc: dict) -> None:
        dropped = False
        with self._lock:
            if self._halt:
                return
            self._queue.append(doc)
            if len(self._queue) > self.queue_cap:
                # drop the OLDEST record, keep the newest evidence
                self._queue.popleft()
                dropped = True
            self._cond.notify()
        if dropped:
            self._note_drop()

    def _note_drop(self) -> None:
        _notify(DROPPED, 1)
        with self._lock:
            warned, self._warned = self._warned, True
        if not warned:
            # one structured event per backlog episode, not per drop
            log_event(
                "analytics.dropping",
                level="warn",
                queue=self.queue_cap,
                hint="raise VRPMS_ANALYTICS_QUEUE or check store "
                "latency; flight records are being dropped",
            )

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- flusher side -------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._halt:
                    self._cond.wait(self.flush_s)
                    if not self._queue and not self._halt:
                        # idle tick: clear the backlog-warn latch so a
                        # NEW backlog episode logs again
                        self._warned = False
                if self._halt and not self._queue:
                    return
                batch = [
                    self._queue.popleft()
                    for _ in range(min(self.batch, len(self._queue)))
                ]
                self._busy = True
            try:
                self._flush(batch)
            finally:
                with self._lock:
                    self._busy = False
                    self._cond.notify_all()

    def _flush(self, batch: list) -> None:
        rows, dropped = [], 0
        for doc in batch:
            try:
                row = serialize_record(doc)
            except Exception:
                row = None
            if row is None:
                dropped += 1
                continue
            rows.append(row)
        if dropped:
            _notify(DROPPED, dropped)
        if not rows:
            return
        try:
            wrote = self._resolve_store().put_flight_records(rows)
        except Exception:
            wrote = False  # a factory/store constructor failure
        if not wrote:
            self._db = None  # fresh client next round
        _notify(OK if wrote else FAILED, len(rows))

    def _resolve_store(self):
        """The flusher's cached store handle (flusher thread only)."""
        key = (
            _store_factory,
            config.raw("VRPMS_STORE"),
            config.get("SUPABASE_URL"),
        )
        if self._db is None or self._db_key != key:
            self._db = _store()
            self._db_key = key
        return self._db

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until the queue is drained and no batch is in flight
        (tests / benchmarks / shutdown); False on timeout."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._queue or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.05))
        return True

    def stop(self, drain_s: float = 2.0) -> None:
        self.flush(timeout=drain_s)
        with self._lock:
            self._halt = True
            self._cond.notify_all()
        self._thread.join(timeout=drain_s + 1.0)


# ---------------------------------------------------------------------------
# Process singleton + the local recent ring
# ---------------------------------------------------------------------------

_exporter_lock = threading.Lock()
_exporter: AnalyticsExporter | None = None  # guarded-by: _exporter_lock

_recent_lock = threading.Lock()
_recent: collections.deque = collections.deque(
    maxlen=RECENT_CAP
)  # guarded-by: _recent_lock


def get_exporter() -> AnalyticsExporter:
    global _exporter
    with _exporter_lock:
        if _exporter is None:
            _exporter = AnalyticsExporter(
                queue_cap=config.get("VRPMS_ANALYTICS_QUEUE"),
                batch=16,
                flush_s=config.get("VRPMS_ANALYTICS_FLUSH_MS") / 1e3,
            )
        return _exporter


def offer(doc: dict) -> None:
    """The finish-seam hook: one completed solve's flight record. With
    the switch off this is ONE env read. The local ring and the metric
    observer see every offered record even when the durable write later
    fails — the process-local half must survive store outages."""
    if not enabled():
        return
    if not doc or not doc.get("jobId"):
        return
    with _recent_lock:
        _recent.append(doc)
    obs = _record_observer
    if obs is not None:
        try:
            obs(doc)
        except Exception:
            pass  # instruments must never fail a solve
    get_sentinel().note(doc)
    get_exporter().offer(doc)


def recent_records() -> list:
    """Newest-first copy of the local flight-record ring."""
    with _recent_lock:
        return list(reversed(_recent))


def recent_for_job(job_id: str) -> dict | None:
    """This replica's flight record for a job, if still in the ring."""
    with _recent_lock:
        for doc in reversed(_recent):
            if doc.get("jobId") == job_id:
                return dict(doc)
    return None


def queue_depth() -> int:
    """Exporter backlog for the scrape-time gauge (0 when no exporter
    was ever built — scraping must not build one)."""
    with _exporter_lock:
        exp = _exporter
    return exp.depth() if exp is not None else 0


def flush(timeout: float = 10.0) -> bool:
    """Drain the exporter if one exists (tests/benchmarks/shutdown)."""
    with _exporter_lock:
        exp = _exporter
    return exp.flush(timeout) if exp is not None else True


def reset_analytics() -> None:
    """Stop and forget the exporter, ring, and sentinel state (tests;
    knobs re-read on rebuild)."""
    global _exporter, _sentinel
    with _exporter_lock:
        exp, _exporter = _exporter, None
    if exp is not None:
        exp.stop(drain_s=0.5)
    with _recent_lock:
        _recent.clear()
    with _sentinel_lock:
        _sentinel = None


# ---------------------------------------------------------------------------
# Regression sentinel: rolling quality vs the committed baseline
# ---------------------------------------------------------------------------

#: committed baseline snapshot the sentinel compares against; absent =
#: the sentinel is inert (fresh checkouts flag nothing)
BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "benchmarks", "records", "analytics_baseline.json",
)

_regression_observer = None


def set_regression_observer(fn) -> None:
    """fn(kind: str) — service.obs wires the
    vrpms_analytics_regressions_total counter in."""
    global _regression_observer
    _regression_observer = fn


class RegressionSentinel:
    """Rolling per-(tier, algorithm) EWMAs of gap and evals/sec,
    compared against the committed baseline on every record. Drift
    beyond the baseline's tolerance — after `minSamples` records for
    that key — emits ONE `analytics.regression` structured event per
    episode (the latch clears when the EWMA recovers) and ticks the
    regression counter per flagged record."""

    ALPHA = 0.2

    def __init__(self, baseline: dict | None = None):
        if baseline is None:
            baseline = self._load()
        self._baseline = (baseline or {}).get("tiers", {})
        tol = (baseline or {}).get("tolerance", {})
        self._tol_gap = float(tol.get("gap", 0.25))
        self._tol_rate = float(tol.get("evalsPerSec", 0.25))
        self._min_samples = int((baseline or {}).get("minSamples", 5))
        self._lock = threading.Lock()
        self._ewma: dict = {}  # guarded-by: _lock
        self._flagged: set = set()  # guarded-by: _lock

    @staticmethod
    def _load() -> dict | None:
        try:
            with open(BASELINE_PATH) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def note(self, doc: dict) -> None:
        if not self._baseline:
            return
        key = f"{doc.get('tier')}|{doc.get('algorithm')}"
        base = self._baseline.get(key)
        if base is None:
            return
        drifts = []
        with self._lock:
            state = self._ewma.setdefault(key, {"n": 0})
            state["n"] += 1
            for metric, tol, worse_is in (
                ("gap", self._tol_gap, "higher"),
                ("evalsPerSec", self._tol_rate, "lower"),
            ):
                val = doc.get(metric)
                if val is None or base.get(metric) is None:
                    continue
                prev = state.get(metric)
                ew = (
                    float(val) if prev is None
                    else (1 - self.ALPHA) * prev + self.ALPHA * float(val)
                )
                state[metric] = ew
                if state["n"] < self._min_samples:
                    continue
                ref = float(base[metric])
                if worse_is == "higher":
                    drifted = ew > ref + tol * max(abs(ref), 1e-9)
                else:
                    drifted = ew < ref * (1 - tol)
                episode = (key, metric)
                if drifted:
                    first = episode not in self._flagged
                    self._flagged.add(episode)
                    drifts.append((metric, ew, ref, first))
                else:
                    self._flagged.discard(episode)
        for metric, ew, ref, first in drifts:
            obs = _regression_observer
            if obs is not None:
                try:
                    obs(metric)
                except Exception:
                    pass
            if first:
                log_event(
                    "analytics.regression",
                    level="warn",
                    key=key,
                    metric=metric,
                    rolling=round(ew, 6),
                    baseline=ref,
                    hint="rolling solve quality/efficiency drifted past "
                    "the committed baseline; compare recent deploys",
                )

    def snapshot(self) -> dict:
        """Current EWMAs + flagged episodes (the debug endpoint)."""
        with self._lock:
            return {
                "keys": {
                    k: {m: round(v, 6) for m, v in st.items()}
                    for k, st in self._ewma.items()
                },
                "flagged": sorted(
                    f"{k}:{m}" for k, m in self._flagged
                ),
                "baselineKeys": sorted(self._baseline),
            }


_sentinel_lock = threading.Lock()
_sentinel: RegressionSentinel | None = None  # guarded-by: _sentinel_lock


def get_sentinel() -> RegressionSentinel:
    global _sentinel
    with _sentinel_lock:
        if _sentinel is None:
            _sentinel = RegressionSentinel()
        return _sentinel
