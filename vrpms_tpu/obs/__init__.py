"""Stdlib-only observability spine: metrics, structured logs, traces.

Four deliberately independent pieces (SURVEY.md §5 "failure detection"
made first-class):

  registry — thread-safe Counter/Gauge/Histogram instruments plus
             Prometheus text exposition (the service's GET /metrics),
             with per-bucket trace-id exemplars;
  logging  — one-JSON-object-per-line event logger with a request-id
             contextvar so every log line of a request correlates;
  trace    — a contextvar block-trace collector the solver deadline
             loops report (wall-clock, best-cost, evals) into with zero
             jit-graph changes;
  spans    — Dapper-style per-request span tracing: W3C traceparent
             in/out, explicit context propagation across the
             scheduler's thread hops, a bounded ring of completed
             traces, and slow-trace auto-capture.

Nothing here imports jax or the solver stack: the service layer owns
the concrete instruments (service.obs) and the solvers only ever call
`active_trace()` — absent a collector, that is one ContextVar read.
"""

from vrpms_tpu.obs import progress, spans
from vrpms_tpu.obs.logging import (
    current_request_id,
    log_event,
    new_request_id,
    reset_request_id,
    set_log_stream,
    set_request_id,
)
from vrpms_tpu.obs.registry import Counter, Gauge, Histogram, Registry
from vrpms_tpu.obs.trace import (
    BlockTrace,
    active_trace,
    collect_blocks,
    convergence_summary,
)

__all__ = [
    "BlockTrace",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "active_trace",
    "collect_blocks",
    "convergence_summary",
    "current_request_id",
    "log_event",
    "new_request_id",
    "progress",
    "reset_request_id",
    "set_log_stream",
    "set_request_id",
    "spans",
]
