"""Thread-safe metric instruments + Prometheus text exposition.

The service router is a ThreadingHTTPServer, so every instrument must
tolerate concurrent writers; each labelled child carries its own lock
and the registry serialises child creation. A `Registry(enabled=False)`
turns every record call into a single attribute check — the no-op
baseline benchmarks/obs_overhead.py measures the hot path against.

Exposition follows the Prometheus text format (version 0.0.4): HELP and
TYPE comment lines, `name{label="value"} value` samples, histograms as
cumulative `_bucket{le=...}` series plus `_sum`/`_count`. Rendering
takes a point-in-time snapshot under the per-child locks, so a scrape
concurrent with a solve never sees a half-updated histogram.

Histograms additionally carry **exemplars** (OpenMetrics syntax,
`... # {trace_id="..."} value`): `observe(v, trace_id=...)` remembers
the WORST observation landing in each bucket since the last scrape, so
a dashboard's p99 spike links straight to the trace that caused it
(GET /api/debug/traces/{traceId}). Exemplars are only legal in the
OpenMetrics exposition — a classic text-format parser errors on the
`#` where it expects an optional timestamp and the WHOLE scrape fails
— so `render(openmetrics=True)` emits them (with OpenMetrics family
naming: counters' `_total` suffix stripped from HELP/TYPE, `untyped`
-> `unknown`) and drains them, while the default classic render leaves
them untouched for the next OpenMetrics scrape. The service's
/metrics negotiates via the Accept header (service.obs).
"""

from __future__ import annotations

import math
import threading

_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0,
)


def _escape_label(value) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _sample(name: str, labels: dict, value: float) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in labels.items()
        )
        return f"{name}{{{body}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"


class _Instrument:
    """Shared labels/children plumbing for the three instrument kinds."""

    kind = "untyped"

    def __init__(self, registry: Registry, name: str, help: str,  # noqa: A002
                 labels: tuple = ()):
        self._registry = registry
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._children: dict = {}  # guarded-by: _lock
        if not self.label_names:
            # the unlabeled instrument IS its own single child
            self._children[()] = self._make_child()

    def labels(self, **kv):
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, got "
                f"{tuple(kv)}"
            )
        key = tuple(str(kv[n]) for n in self.label_names)
        # lock-free fast path: dict read is atomic under the GIL and a
        # miss falls through to the locked setdefault
        child = self._children.get(key)  # vrpms-lint: disable=lock-discipline (double-checked fast path; locked setdefault below arbitrates)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make_child())
        return child

    def _default_child(self):
        # the () child is created in __init__ and never replaced, so the
        # unlabeled hot path skips the lock entirely
        return self._children[()]  # vrpms-lint: disable=lock-discipline (immutable after __init__; hot-path read)

    def _snapshot(self) -> list:
        with self._lock:
            items = list(self._children.items())
        return items

    def render(self, openmetrics: bool = False) -> list:
        family, kind = self.name, self.kind
        if openmetrics:
            # OpenMetrics names the counter FAMILY without the _total
            # suffix (samples keep it) and calls untyped "unknown"
            if kind == "counter" and family.endswith("_total"):
                family = family[: -len("_total")]
            kind = "unknown" if kind == "untyped" else kind
        lines = [
            f"# HELP {family} {self.help}",
            f"# TYPE {family} {kind}",
        ]
        for key, child in self._snapshot():
            labels = dict(zip(self.label_names, key))
            lines.extend(child.render(self.name, labels, openmetrics))
        return lines


class _CounterChild:
    __slots__ = ("_lock", "_value", "_enabled")

    def __init__(self, enabled_ref):
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: _lock
        self._enabled = enabled_ref

    def inc(self, amount: float = 1.0):
        if not self._enabled():
            return
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self, name: str, labels: dict,
               openmetrics: bool = False) -> list:
        return [_sample(name, labels, self.value)]


class Counter(_Instrument):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(lambda: self._registry.enabled)

    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild:
    __slots__ = ("_lock", "_value", "_enabled")

    def __init__(self, enabled_ref):
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: _lock
        self._enabled = enabled_ref

    def set(self, value: float):
        if not self._enabled():
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        if not self._enabled():
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self, name: str, labels: dict,
               openmetrics: bool = False) -> list:
        return [_sample(name, labels, self.value)]


class Gauge(_Instrument):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(lambda: self._registry.enabled)

    def set(self, value: float):
        self._default_child().set(value)

    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count",
                 "_enabled", "_exemplars")

    def __init__(self, buckets: tuple, enabled_ref):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * len(buckets)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._enabled = enabled_ref
        # per-bucket (trace_id, value): the worst observation that
        # landed in the bucket since the last render (scrape) drained it
        self._exemplars: dict = {}  # guarded-by: _lock

    def observe(self, value: float, trace_id: str | None = None):
        if not self._enabled():
            return
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, ub in enumerate(self._buckets):
                if value <= ub:
                    self._counts[i] += 1
                    if trace_id is not None:
                        worst = self._exemplars.get(i)
                        if worst is None or value > worst[1]:
                            self._exemplars[i] = (trace_id, value)
                    break

    def render(self, name: str, labels: dict,
               openmetrics: bool = False) -> list:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            if openmetrics:
                # drained only when actually emitted: a classic scrape
                # must not silently discard the window's exemplars
                exemplars, self._exemplars = self._exemplars, {}
            else:
                exemplars = {}
        lines = []
        cum = 0
        for i, (ub, c) in enumerate(zip(self._buckets, counts)):
            cum += c
            le = dict(labels)
            le["le"] = _format_value(ub)
            line = _sample(f"{name}_bucket", le, cum)
            ex = exemplars.get(i)
            if ex is not None:
                # OpenMetrics exemplar: the trace to pull up for this
                # bucket's worst observation of the scrape window
                line += (
                    f' # {{trace_id="{_escape_label(ex[0])}"}} '
                    f"{_format_value(ex[1])}"
                )
            lines.append(line)
        lines.append(_sample(f"{name}_sum", labels, s))
        lines.append(_sample(f"{name}_count", labels, total))
        return lines


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, registry, name, help, labels=(),  # noqa: A002
                 buckets=_LATENCY_BUCKETS):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        if bs[-1] != math.inf:
            bs = bs + (math.inf,)
        self.buckets = bs
        super().__init__(registry, name, help, labels)

    def _make_child(self):
        return _HistogramChild(self.buckets, lambda: self._registry.enabled)

    def observe(self, value: float, trace_id: str | None = None):
        self._default_child().observe(value, trace_id)


class Registry:
    """Instrument factory + exposition. One per process in practice
    (service.obs.REGISTRY); tests and the overhead benchmark construct
    their own. `enabled=False` makes every record call a no-op while
    keeping render() working (all-zero output)."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._instruments: dict = {}  # guarded-by: _lock

    def _register(self, instrument):
        with self._lock:
            if instrument.name in self._instruments:
                raise ValueError(
                    f"metric {instrument.name!r} already registered"
                )
            self._instruments[instrument.name] = instrument
        return instrument

    def counter(self, name: str, help: str, labels: tuple = ()) -> Counter:  # noqa: A002
        return self._register(Counter(self, name, help, labels))

    def gauge(self, name: str, help: str, labels: tuple = ()) -> Gauge:  # noqa: A002
        return self._register(Gauge(self, name, help, labels))

    def histogram(self, name: str, help: str, labels: tuple = (),  # noqa: A002
                  buckets=_LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram(self, name, help, labels, buckets))

    def render(self, openmetrics: bool = False) -> str:
        """The exposition body. `openmetrics=True` emits exemplars
        (draining them) with OpenMetrics family naming and the
        mandatory `# EOF` terminator; the default classic text format
        (0.0.4) is exemplar-free — classic parsers reject them."""
        with self._lock:
            instruments = list(self._instruments.values())
        lines = []
        for inst in instruments:
            lines.extend(inst.render(openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"
