"""Structured JSON logging: one object per line, request-correlated.

Every line is a single JSON object on stderr with at least `ts` (epoch
seconds), `event` (dotted name, e.g. "http.request"), and — whenever a
request context is active — `requestId`. The request id rides a
ContextVar set by the HTTP layer (service.handler_base), so anything
logged from inside a solve (solver exceptions, warm-start accounting)
correlates with the request's own access line without threading an id
through every call signature. ThreadingHTTPServer gives each request
its own thread, and ContextVars are per-thread, so concurrent requests
never cross-contaminate.

`VRPMS_LOG=off` silences the logger entirely (benchmarks measuring the
hot path without I/O); `set_log_stream` redirects it (tests).
"""

from __future__ import annotations

import contextvars
import json
import sys
import threading
import time
import uuid

from vrpms_tpu import config

_write_lock = threading.Lock()
_stream = None  # None -> sys.stderr at call time (tests may rebind stderr)

_request_id: contextvars.ContextVar = contextvars.ContextVar(
    "vrpms_request_id", default=None
)


def new_request_id() -> str:
    """12-hex-char id: short enough to read in a log line, random enough
    that a collision within one service's retention window is noise."""
    return uuid.uuid4().hex[:12]


def set_request_id(rid: str):
    """Bind `rid` to the current context; returns the reset token."""
    return _request_id.set(rid)


def reset_request_id(token) -> None:
    _request_id.reset(token)


def current_request_id() -> str | None:
    return _request_id.get()


def set_log_stream(stream):
    """Redirect log output (None restores stderr); returns the previous
    setting."""
    global _stream
    prev = _stream
    _stream = stream
    return prev


def log_event(event: str, **fields) -> None:
    """Emit one structured line. None-valued fields are dropped; the
    active request id is attached unless the caller passes its own."""
    if not config.enabled("VRPMS_LOG"):
        return
    record = {"ts": round(time.time(), 3), "event": event}
    rid = fields.pop("requestId", None) or _request_id.get()
    if rid is not None:
        record["requestId"] = rid
    record.update((k, v) for k, v in fields.items() if v is not None)
    line = json.dumps(record, default=str)
    stream = _stream if _stream is not None else sys.stderr
    with _write_lock:
        try:
            stream.write(line + "\n")
            stream.flush()
        except (OSError, ValueError):
            pass  # a closed/broken log stream must never fail a request
