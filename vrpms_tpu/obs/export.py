"""Best-effort durable trace export: the fleet half of span tracing.

The completed-trace ring (obs.spans) is process-local, so under
VRPMS_QUEUE=store a job submitted on replica A and executed on replica
B has its spans split across two rings no single debug read can see.
This module closes that gap WITHOUT touching the request path's cost
model: when VRPMS_TRACE_EXPORT is on, `Trace.finish` hands the
completed trace to a bounded in-process queue (one deque append), and
a background flusher batch-writes serialized span trees through the
store's trace seam (store.base put_trace_spans — one row per
(trace_id, replica), so replicas never clobber each other's half of a
cross-replica trace). The federated debug surfaces (service.debug)
merge those rows back with the local ring.

Failure policy — an export outage drops spans, never blocks or fails
a solve:

  * queue full    -> the OLDEST queued trace is dropped (keep the
                     newest evidence) and counted `dropped`;
  * oversized doc -> events are trimmed, then attributes; a doc still
                     over the row bound is dropped (counted `dropped`);
  * store failure -> the batch's spans count `failed` (single-attempt,
                     fail-open — store.resilient gives trace writes the
                     solution cache's inverted policy: no retries, no
                     journal, shared breaker);
  * success       -> the batch's spans count `ok`.

Every span offered is accounted exactly once across those outcomes
(vrpms_trace_export_total{outcome} via the observer seam, plus the
queue-depth gauge service.obs scrapes), so "are we losing telemetry"
is a dashboard question, not an archaeology project.

Knobs (vrpms_tpu.config): VRPMS_TRACE_EXPORT (off by default — local
serving keeps the PR-5 process-local contract byte-identical),
VRPMS_TRACE_EXPORT_QUEUE / _BATCH / _FLUSH_MS. Knobs are read when the
exporter singleton is built; tests use `reset_exporter()` after
changing them.

Stdlib-only, like the rest of vrpms_tpu.obs: the store is reached
through an injected factory (service wiring / tests), defaulting to a
lazy `store.get_database` import on the flusher thread — never at
import time, so the one-way obs -> (nothing) import rule holds.
"""

from __future__ import annotations

import collections
import json
import threading
import uuid

from vrpms_tpu import config
from vrpms_tpu.obs.logging import log_event

#: hard bound on one exported row's serialized document — a runaway
#: trace must degrade (events first, then attributes) or drop, never
#: write an unbounded jsonb row
MAX_ROW_BYTES = 262144

OK, DROPPED, FAILED = "ok", "dropped", "failed"


def enabled() -> bool:
    return config.enabled("VRPMS_TRACE_EXPORT")


# ---------------------------------------------------------------------------
# Seams: metrics observer, replica identity, store factory
# ---------------------------------------------------------------------------

_observer = None


def set_observer(fn) -> None:
    """fn(outcome: str, n_spans: int) — service.obs wires the
    vrpms_trace_export_total counter in (the set_cache_observer
    pattern: this package stays free of service imports)."""
    global _observer
    _observer = fn


def _notify(outcome: str, n: int) -> None:
    if n and _observer is not None:
        try:
            _observer(outcome, n)
        except Exception:
            pass  # telemetry about telemetry must never break either


_replica_provider = None
_generated_replica: str | None = None


def set_replica_provider(fn) -> None:
    """fn() -> str — service.jobs wires its replica_id() in so exported
    rows and /api/ready agree on this process's identity."""
    global _replica_provider
    _replica_provider = fn


def replica_identity() -> str:
    if _replica_provider is not None:
        try:
            rid = _replica_provider()
            if rid:
                return str(rid)
        except Exception:
            pass
    global _generated_replica
    if _generated_replica is None:
        _generated_replica = (
            config.get("VRPMS_REPLICA_ID")
            or f"replica-{uuid.uuid4().hex[:8]}"
        )
    return _generated_replica


_store_factory = None


def set_store_factory(fn) -> None:
    """fn() -> a store.base.Database (anything with put_trace_spans).
    Tests and benchmarks inject shims here; None restores the default
    (the configured store, resolved lazily on the flusher thread)."""
    global _store_factory
    _store_factory = fn


def _store():
    if _store_factory is not None:
        return _store_factory()
    from store import get_database

    return get_database("vrp", None)


# ---------------------------------------------------------------------------
# Serialization: one bounded row per (trace, replica)
# ---------------------------------------------------------------------------


def serialize_trace(trace, replica: str) -> dict | None:
    """The store row for one completed trace as THIS replica saw it.
    Enforces the row byte bound by degrading gracefully — span events
    go first, then attributes; None means even the skeleton is too big
    (caller counts the spans dropped)."""
    doc = trace.to_dict()
    doc["replica"] = replica
    root = doc["spans"][0]["name"] if doc["spans"] else None
    for strip in (None, "events", "attributes"):
        if strip is not None:
            stripped = False
            for span in doc["spans"]:
                if strip in span:
                    span.pop(strip, None)
                    stripped = True
            if stripped:
                doc["truncated"] = True
            else:
                continue  # nothing left to strip at this level
        try:
            size = len(json.dumps(doc))
        except (TypeError, ValueError):
            return None  # unserializable attribute snuck in: drop
        if size <= MAX_ROW_BYTES:
            return {
                "trace_id": trace.trace_id,
                "replica": replica,
                "started_at": trace.start_ts,
                "duration_ms": doc["durationMs"],
                "status": doc["status"],
                "root": root,
                "spans": len(doc["spans"]),
                "doc": doc,
            }
    return None


# ---------------------------------------------------------------------------
# The exporter: bounded queue + background batch flusher
# ---------------------------------------------------------------------------


class TraceExporter:
    """Bounded hand-off between `Trace.finish` and the store.

    `offer` is the request-path half: one lock/append (plus an eviction
    pop when full) — serialization and store I/O happen on the flusher
    thread. The flusher drains up to `batch` traces per round into ONE
    put_trace_spans call, then idles `flush_s` (a fresh offer wakes it
    immediately)."""

    def __init__(self, queue_cap: int = 256, batch: int = 16,
                 flush_s: float = 0.05):
        self.queue_cap = max(1, int(queue_cap))
        self.batch = max(1, int(batch))
        self.flush_s = max(0.001, float(flush_s))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()  # guarded-by: _lock
        self._busy = False  # guarded-by: _lock
        self._halt = False  # guarded-by: _lock
        self._warned = False  # guarded-by: _lock
        # flusher-thread-only store handle, reused across rounds (a
        # hosted-store client per batch would pay construction + a new
        # session every ~flush_s); keyed by the active selector so env
        # flips (tests, live re-config) rebuild it, and dropped after
        # any failed write so a broken client is never pinned
        self._db = None
        self._db_key = None
        self._thread = threading.Thread(
            target=self._run, name="vrpms-trace-export", daemon=True
        )
        self._thread.start()

    # -- request-path side --------------------------------------------------
    def offer(self, trace) -> None:
        dropped = None
        with self._lock:
            if self._halt:
                return
            self._queue.append(trace)
            if len(self._queue) > self.queue_cap:
                # drop the OLDEST evidence, keep the newest; the
                # counter makes the loss visible
                dropped = self._queue.popleft()
            self._cond.notify()
        if dropped is not None:
            self._note_drop(dropped)

    def _note_drop(self, trace) -> None:
        _notify(DROPPED, self._span_count(trace))
        with self._lock:
            warned, self._warned = self._warned, True
        if not warned:
            # one structured event per backlog episode, not per drop
            log_event(
                "trace_export.dropping",
                level="warn",
                queue=self.queue_cap,
                hint="raise VRPMS_TRACE_EXPORT_QUEUE or check store "
                "latency; spans are being dropped",
            )

    @staticmethod
    def _span_count(trace) -> int:
        try:
            with trace._lock:
                return len(trace.spans)
        except Exception:
            return 1

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- flusher side -------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._halt:
                    self._cond.wait(self.flush_s)
                    if not self._queue and not self._halt:
                        # idle tick: clear the backlog-warn latch so a
                        # NEW backlog episode logs again
                        self._warned = False
                if self._halt and not self._queue:
                    return
                batch = [
                    self._queue.popleft()
                    for _ in range(min(self.batch, len(self._queue)))
                ]
                self._busy = True
            try:
                self._flush(batch)
            finally:
                with self._lock:
                    self._busy = False
                    self._cond.notify_all()

    def _flush(self, batch: list) -> None:
        rid_default = replica_identity()
        rows, ok_spans, dropped = [], 0, 0
        for trace in batch:
            n = self._span_count(trace)
            row = None
            try:
                rid = getattr(trace, "export_replica", None) or rid_default
                row = serialize_trace(trace, rid)
            except Exception:
                row = None
            if row is None:
                dropped += n
                continue
            rows.append(row)
            ok_spans += n
        if dropped:
            _notify(DROPPED, dropped)
        if not rows:
            return
        try:
            wrote = self._resolve_store().put_trace_spans(rows)
        except Exception:
            wrote = False  # a factory/store constructor failure
        if not wrote:
            self._db = None  # fresh client next round
        _notify(OK if wrote else FAILED, ok_spans)

    def _resolve_store(self):
        """The flusher's cached store handle (flusher thread only)."""
        # the factory OBJECT rides the key (identity equality; holding
        # the reference also keeps a replaced factory from aliasing)
        key = (
            _store_factory,
            config.raw("VRPMS_STORE"),
            config.get("SUPABASE_URL"),
        )
        if self._db is None or self._db_key != key:
            self._db = _store()
            self._db_key = key
        return self._db

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until the queue is drained and no batch is in flight
        (tests / benchmarks / shutdown); False on timeout."""
        import time

        deadline = time.monotonic() + timeout
        with self._lock:
            while self._queue or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.05))
        return True

    def stop(self, drain_s: float = 2.0) -> None:
        self.flush(timeout=drain_s)
        with self._lock:
            self._halt = True
            self._cond.notify_all()
        self._thread.join(timeout=drain_s + 1.0)


# ---------------------------------------------------------------------------
# Process singleton + the Trace.finish hook
# ---------------------------------------------------------------------------

_exporter_lock = threading.Lock()
_exporter: TraceExporter | None = None  # guarded-by: _exporter_lock


def get_exporter() -> TraceExporter:
    global _exporter
    with _exporter_lock:
        if _exporter is None:
            _exporter = TraceExporter(
                queue_cap=config.get("VRPMS_TRACE_EXPORT_QUEUE"),
                batch=config.get("VRPMS_TRACE_EXPORT_BATCH"),
                flush_s=config.get("VRPMS_TRACE_EXPORT_FLUSH_MS") / 1e3,
            )
        return _exporter


def offer(trace) -> None:
    """The spans.Trace.finish hook: hand a completed trace to the
    exporter. With the switch off this is ONE env read — the always-on
    hot-path contract every obs hook honors."""
    if not enabled():
        return
    if not trace.spans:
        return  # an empty trace carries no evidence (the ring rule)
    get_exporter().offer(trace)


def queue_depth() -> int:
    """Exporter backlog for the scrape-time gauge (0 when no exporter
    was ever built — scraping must not build one)."""
    with _exporter_lock:
        exp = _exporter
    return exp.depth() if exp is not None else 0


def flush(timeout: float = 10.0) -> bool:
    """Drain the exporter if one exists (tests/benchmarks/shutdown)."""
    with _exporter_lock:
        exp = _exporter
    return exp.flush(timeout) if exp is not None else True


def reset_exporter() -> None:
    """Stop and forget the exporter (tests; knobs re-read on rebuild)."""
    global _exporter
    with _exporter_lock:
        exp, _exporter = _exporter, None
    if exp is not None:
        exp.stop(drain_s=0.5)
