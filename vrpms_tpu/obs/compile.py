"""XLA compile observability: count and time every backend compile.

jax.monitoring emits a `/jax/core/compile/backend_compile_duration`
event for every XLA compilation this process performs (cache hits —
in-process jit cache or the persistent disk cache — emit nothing), so
listening to it gives an exact distinct-compile counter and a compile-
seconds histogram source with zero instrumentation in the solver code.

This module owns only the jax-facing aggregation (stdlib + jax
monitoring; no service imports). The service layer (service.obs) wires
`on_compile` into its Prometheus registry, and the tier layer's
includeStats path snapshots before/after a solve to attach a `compile`
block when a request actually paid one.
"""

from __future__ import annotations

import threading

_COMPILE_KEY = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_count = 0
_seconds = 0.0
_installed = False
_callbacks: list = []
# per-thread tallies: XLA compiles run synchronously on the dispatching
# thread, so a thread-local snapshot attributes compiles to the solve
# that actually paid them (a background tier warmup or a concurrent
# request must not leak into another request's stats.compile block)
_local = threading.local()


def _listener(key: str, duration: float, **_kw) -> None:
    global _count, _seconds
    if key != _COMPILE_KEY:
        return
    with _lock:
        _count += 1
        _seconds += float(duration)
        callbacks = tuple(_callbacks)
    _local.count = getattr(_local, "count", 0) + 1
    _local.seconds = getattr(_local, "seconds", 0.0) + float(duration)
    for cb in callbacks:
        try:
            cb(float(duration))
        except Exception:
            pass


def install() -> None:
    """Register the jax.monitoring listener (idempotent, best-effort —
    observability must never break a solve)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_listener)
    except Exception:
        pass


def on_compile(cb) -> None:
    """Register cb(duration_s) for every backend compile; installs the
    listener on first use."""
    install()
    with _lock:
        _callbacks.append(cb)


def snapshot() -> tuple[int, float]:
    """(total compiles, total compile seconds) so far this process."""
    with _lock:
        return _count, _seconds


def snapshot_local() -> tuple[int, float]:
    """(compiles, compile seconds) paid by the CALLING THREAD — the
    per-request attribution source (see _local)."""
    return getattr(_local, "count", 0), getattr(_local, "seconds", 0.0)
