"""Dapper-style per-request span tracing (the model OpenTelemetry
standardizes; Sigelman et al., 2010).

Since the async scheduler (PR 2) a request's life crosses four threads
— HTTP handler -> admission queue -> micro-batcher -> device worker ->
store I/O — and aggregate histograms cannot say WHERE a slow request
spent its time. This module records that: every request owns a Trace (a
thread-safe per-trace span collector), code brackets its work in named
Spans (trace_id / span_id / parent, start, duration, attributes,
events), and context rides two ContextVars that the scheduler
re-activates explicitly on the worker side of every thread hop (the
Job carries its Trace + parent Span through queue.push/pop/
take_matching — see vrpms_tpu.sched.queue.Job and service.jobs).

Surfaces (wired by the service layer):
  * W3C `traceparent` accepted on requests and emitted on responses;
    `traceId` echoed in every envelope;
  * `stats.spans` — the request's latency waterfall under includeStats;
  * GET /api/debug/traces[/{traceId}] — a bounded in-memory ring of
    recently completed traces;
  * histogram exemplars (obs.registry) carry the worst trace id per
    latency bucket;
  * traces slower than VRPMS_TRACE_SLOW_MS log a `trace.slow` event
    with the full waterfall — tail-latency evidence on disk before
    anyone asks.

Env knobs: VRPMS_TRACING (on|off, default on), VRPMS_TRACE_RING (ring
capacity, default 128), VRPMS_TRACE_SLOW_MS (default 5000).

Stdlib-only, like the rest of vrpms_tpu.obs: no jax, no service
imports. With tracing off — or simply no active trace — `span()` is one
ContextVar read.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
import time
import uuid

from vrpms_tpu import config
from vrpms_tpu.obs import export as trace_export
from vrpms_tpu.obs.logging import log_event

#: hard caps so a runaway request can never grow an unbounded trace
MAX_SPANS_PER_TRACE = 256
MAX_EVENTS_PER_SPAN = 64
#: anything longer than a legal traceparent (55 chars) plus slack is
#: rejected outright — never parsed, never echoed
MAX_TRACEPARENT_LEN = 128

#: The span-name registry: every LITERAL span name the codebase starts.
#: Dashboards, the waterfall tests, and trace tooling key on these; the
#: static analyzer (rule `contract-span-name`) flags any spans.span()/
#: trace.span()/span_at() literal that is missing here, so a new span
#: is a deliberate, greppable addition instead of silent cardinality.
#: (Dynamic names — the per-route HTTP root span — are out of scope.)
KNOWN_SPAN_NAMES = frozenset({
    "parse",            # request-body parse + validation
    "prepare",          # instance build / tier pad / cache lookup
    "resolve",          # warm-start seed resolution (service.cache)
    "resolve.delta",    # request-delta application (core.delta)
    "queue.wait",       # retroactive admission-queue wait
    "solve",            # one job's solver run (worker side)
    "decompose",        # giant-instance clustering + shard planning
    "stitch",           # shard-route merge + boundary repair
    "solver.solve",     # the device solve inside a request
    "solver.polish",    # post-solve local-search polish
    "finish",           # decode + response assembly
    "dist.execute",     # distributed-queue claim-side execution
    "dist.claim_batch",  # how this job's store claim was assembled
    "qos.shed",         # a request shed by QoS policy (class + reason)
    "ckpt.write",       # one durable checkpoint write (background)
    "ckpt.resume",      # a requeued attempt seeded from a checkpoint
    "sub.generation",   # one standing-subscription re-solve launch
    "fleet.scalein",    # scale-in victim selection + drain dispatch
    "read.federate",    # checkpoint-sourced incumbent overlay (non-owner)
    "read.relay",       # live-progress relay from the owning replica
    "store.read",       # table reads on the request path
    "store.persist",    # solution/warm-start persistence
    "store.persist_job",  # terminal job-record persistence
    "store.cache",      # solution-cache lookup/store
    "store.resilient",  # one guarded (retry/breaker) store call
})


def tracing_enabled() -> bool:
    return config.enabled("VRPMS_TRACING")


def slow_threshold_ms() -> float:
    return config.get("VRPMS_TRACE_SLOW_MS")


def new_trace_id() -> str:
    """32 lowercase hex chars (W3C trace-id width)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """16 lowercase hex chars (W3C parent-id width)."""
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------------------
# W3C traceparent
# ---------------------------------------------------------------------------


def _is_hex(s: str) -> bool:
    return all(c in "0123456789abcdef" for c in s)


def parse_traceparent(header) -> tuple[str | None, str | None]:
    """(trace_id, parent_span_id) from a W3C traceparent header, or
    (None, None) for anything malformed — a bad header means a FRESH
    trace, never an error (the contract the edge cases test pins:
    malformed version/ids, all-zero ids, oversized headers)."""
    if not header or not isinstance(header, str):
        return None, None
    header = header.strip()
    if len(header) > MAX_TRACEPARENT_LEN:
        return None, None
    parts = header.split("-")
    if len(parts) < 4:
        return None, None
    version, trace_id, parent_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None, None
    if version == "00" and len(parts) != 4:
        return None, None
    if len(trace_id) != 32 or not _is_hex(trace_id):
        return None, None
    if len(parent_id) != 16 or not _is_hex(parent_id):
        return None, None
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None, None
    if len(parts[3]) != 2 or not _is_hex(parts[3]):
        return None, None
    return trace_id, parent_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    """The header a response (or downstream call) should carry; sampled
    flag always 01 — if we have a trace id at all, we recorded."""
    return f"00-{trace_id}-{span_id}-01"


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------


class Span:
    """One named, timed operation inside a Trace.

    Mutations (set/event/end) are cheap and lock the owning trace only
    for event appends; a span may be annotated after `end` (the solve
    path attaches compile attribution once the delta is known).
    """

    __slots__ = (
        "name", "span_id", "parent_id", "start_mono", "start_ts",
        "duration_ms", "status", "attributes", "events", "_trace",
    )

    def __init__(self, trace, name: str, parent_id: str | None,
                 start_mono: float | None = None):
        self._trace = trace
        self.name = name
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start_mono = (
            time.monotonic() if start_mono is None else start_mono
        )
        self.start_ts = time.time()
        self.duration_ms: float | None = None
        self.status = "ok"
        self.attributes: dict = {}
        self.events: list = []

    def set(self, **attrs) -> None:
        """Attach attributes (None values dropped, like log_event)."""
        self.attributes.update(
            (k, v) for k, v in attrs.items() if v is not None
        )

    def event(self, name: str, **attrs) -> None:
        """Append a point-in-time event; bounded per span."""
        if len(self.events) >= MAX_EVENTS_PER_SPAN:
            self._trace.truncated = True
            return
        ev = {
            "name": name,
            "offsetMs": round(
                (time.monotonic() - self._trace.start_mono) * 1e3, 2
            ),
        }
        ev.update((k, v) for k, v in attrs.items() if v is not None)
        self.events.append(ev)

    def end(self, status: str | None = None) -> None:
        """First end wins (a requeued job's abandoned attempt may race
        its own watchdog bookkeeping)."""
        if self.duration_ms is None:
            self.duration_ms = round(
                (time.monotonic() - self.start_mono) * 1e3, 3
            )
        if status is not None:
            self.status = status

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startMs": round(
                (self.start_mono - self._trace.start_mono) * 1e3, 3
            ),
            "durationMs": self.duration_ms,
            "status": self.status,
        }
        if self.attributes:
            d["attributes"] = dict(self.attributes)
        if self.events:
            d["events"] = list(self.events)
        return d


class Trace:
    """Thread-safe per-trace span collector.

    One per request; crosses threads by reference (the Job carries it),
    so every append locks. `deferred` marks traces whose completion the
    HTTP thread hands to the scheduler worker (async jobs: the 202 goes
    out long before the solve ends)."""

    def __init__(self, trace_id: str | None = None,
                 remote_parent_id: str | None = None):
        self.trace_id = trace_id or new_trace_id()
        self.remote_parent_id = remote_parent_id
        #: which replica's spans these are, for the durable exporter's
        #: (trace_id, replica) row key — None means the process default
        #: (obs.export.replica_identity). The distributed claim path
        #: stamps the leasing replica's id so a submit-side trace and
        #: an execute-side trace sharing one trace_id never clobber
        #: each other's exported row.
        self.export_replica: str | None = None
        self.start_mono = time.monotonic()
        self.start_ts = time.time()
        self.spans: list[Span] = []  # guarded-by: _lock
        self.truncated = False
        self.status = "ok"
        self.deferred = False
        self._finished = False  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- span creation ------------------------------------------------------
    def span(self, name: str, parent_id: str | None = None,
             start_mono: float | None = None) -> Span:
        """Create (and register) a span. Over the cap the span is still
        returned — callers never branch — but not retained."""
        if parent_id is None:
            parent_id = self.remote_parent_id
        s = Span(self, name, parent_id, start_mono=start_mono)
        with self._lock:
            if len(self.spans) < MAX_SPANS_PER_TRACE:
                self.spans.append(s)
            else:
                self.truncated = True
        return s

    def span_at(self, name: str, parent_id: str | None,
                start_mono: float, duration_s: float, **attrs) -> Span:
        """Retroactive completed span — how the worker records the
        queue wait it can only measure once the job pops."""
        s = self.span(name, parent_id=parent_id, start_mono=start_mono)
        s.duration_ms = round(max(duration_s, 0.0) * 1e3, 3)
        if attrs:
            s.set(**attrs)
        return s

    def root(self) -> Span | None:
        with self._lock:
            return self.spans[0] if self.spans else None

    # -- completion ---------------------------------------------------------
    def duration_ms(self) -> float:
        """Trace start to the latest span end seen (open spans count up
        to 'now' — a finished trace has none on the request path)."""
        end = 0.0
        with self._lock:
            spans = list(self.spans)
        for s in spans:
            off = (s.start_mono - self.start_mono) * 1e3
            end = max(
                end,
                off + (
                    s.duration_ms
                    if s.duration_ms is not None
                    else (time.monotonic() - s.start_mono) * 1e3
                ),
            )
        return round(end, 3)

    def finish(self, status: str | None = None) -> None:
        """Idempotent terminal step: push to the completed-trace ring,
        and log the full waterfall if the trace breached the slow bar
        (VRPMS_TRACE_SLOW_MS)."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
        if status is not None:
            self.status = status
        dur = self.duration_ms()
        _ring_push(self)
        # durable export (VRPMS_TRACE_EXPORT; off = one env read): the
        # completed trace is handed to a bounded background flusher so
        # the fleet debug surfaces can federate it across replicas
        trace_export.offer(self)
        if dur >= slow_threshold_ms():
            log_event(
                "trace.slow",
                traceId=self.trace_id,
                durationMs=dur,
                status=self.status,
                spans=self.waterfall(),
            )

    @property
    def finished(self) -> bool:
        with self._lock:
            return self._finished

    # -- export -------------------------------------------------------------
    def waterfall(self) -> list[dict]:
        """The latency waterfall: spans as dicts, by start offset."""
        with self._lock:
            spans = list(self.spans)
        return [s.to_dict() for s in sorted(spans, key=lambda s: s.start_mono)]

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "startedAt": self.start_ts,
            "durationMs": self.duration_ms(),
            "status": self.status,
            "truncated": self.truncated,
            "remoteParent": self.remote_parent_id,
            "spans": self.waterfall(),
        }

    def summary(self) -> dict:
        root = self.root()
        with self._lock:
            n_spans = len(self.spans)
        return {
            "traceId": self.trace_id,
            "startedAt": self.start_ts,
            "durationMs": self.duration_ms(),
            "status": self.status,
            "root": root.name if root is not None else None,
            "spans": n_spans,
        }


# ---------------------------------------------------------------------------
# Context propagation
# ---------------------------------------------------------------------------

_trace_var: contextvars.ContextVar = contextvars.ContextVar(
    "vrpms_trace", default=None
)
_span_var: contextvars.ContextVar = contextvars.ContextVar(
    "vrpms_span", default=None
)


def current_trace() -> Trace | None:
    return _trace_var.get()


def current_span() -> Span | None:
    return _span_var.get()


def current_trace_id() -> str | None:
    """The active trace's id — the histogram-exemplar source (one
    ContextVar read; None with no trace active)."""
    t = _trace_var.get()
    return None if t is None else t.trace_id


def start_trace(traceparent: str | None = None) -> Trace | None:
    """Begin a trace for one request. Adopts the incoming W3C context
    when valid (same trace_id, spans parent under the remote span);
    anything malformed starts fresh. None when tracing is off."""
    if not tracing_enabled():
        return None
    trace_id, parent_id = parse_traceparent(traceparent)
    return Trace(trace_id=trace_id, remote_parent_id=parent_id)


def activate(trace: Trace | None, span: Span | None = None):
    """Bind (trace, span) to the current context — the worker-side hop:
    the runner re-activates each job's carried context before touching
    solver code. Returns an opaque token pair for `deactivate`."""
    return (_trace_var.set(trace), _span_var.set(span))


def deactivate(tokens) -> None:
    t_tok, s_tok = tokens
    _trace_var.reset(t_tok)
    _span_var.reset(s_tok)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Bracket the enclosed work in a child span of the current context.

    No active trace -> yields None at the cost of one ContextVar read
    (the always-on hot-path contract, same as active_trace()). An
    escaping exception marks the span status=error (and re-raises)."""
    trace = _trace_var.get()
    if trace is None:
        yield None
        return
    parent = _span_var.get()
    s = trace.span(
        name, parent_id=parent.span_id if parent is not None else None
    )
    if attrs:
        s.set(**attrs)
    token = _span_var.set(s)
    try:
        yield s
    except BaseException as e:
        s.set(error=f"{type(e).__name__}: {e}")
        s.end(status="error")
        raise
    finally:
        _span_var.reset(token)
        s.end()


def add_event(name: str, **attrs) -> None:
    """Attach an event to the current span, if any (the BlockTrace
    cadence feeds per-block solver events through this)."""
    s = _span_var.get()
    if s is not None:
        s.event(name, **attrs)


# ---------------------------------------------------------------------------
# Completed-trace ring
# ---------------------------------------------------------------------------

def _ring_capacity_env() -> int:
    """VRPMS_TRACE_RING, defaulting (not crashing) on junk — a typo'd
    knob must degrade to the default, same as slow_threshold_ms."""
    return max(1, config.get("VRPMS_TRACE_RING"))


_ring_lock = threading.Lock()
_ring: collections.deque = collections.deque(  # guarded-by: _ring_lock
    maxlen=_ring_capacity_env()
)


def _ring_push(trace: Trace) -> None:
    if not trace.spans:
        return  # an empty trace carries no evidence
    with _ring_lock:
        _ring.append(trace)


def ring_size() -> int:
    with _ring_lock:
        return len(_ring)


def ring_capacity() -> int:
    with _ring_lock:
        return _ring.maxlen or 0


def ring_get(trace_id: str) -> Trace | None:
    with _ring_lock:
        for t in reversed(_ring):
            if t.trace_id == trace_id:
                return t
    return None


def ring_snapshot(min_duration_ms: float = 0.0, status: str | None = None,
                  limit: int = 50) -> list[dict]:
    """Newest-first summaries of recently completed traces, filterable
    by minimum duration and status (the /api/debug/traces contract)."""
    with _ring_lock:
        traces = list(_ring)
    out = []
    for t in reversed(traces):
        if status is not None and t.status != status:
            continue
        if t.duration_ms() < min_duration_ms:
            continue
        out.append(t.summary())
        if len(out) >= max(1, limit):
            break
    return out


def reset_ring(capacity: int | None = None) -> None:
    """Drop every retained trace (tests; ops escape hatch). `capacity`
    re-sizes the ring — otherwise VRPMS_TRACE_RING is re-read so tests
    that tweak the env see it applied."""
    global _ring
    if capacity is None:
        capacity = _ring_capacity_env()
    with _ring_lock:
        _ring = collections.deque(maxlen=max(1, capacity))
