"""vrpms_tpu — a TPU-native Vehicle Routing / Traveling Salesman framework.

A from-scratch JAX/XLA implementation of the capability surface of the
reference VRP microservice (metehkaya/vrpms): the {vrp, tsp} x {ga, sa,
aco, bf} solver matrix behind its 9 HTTP endpoints (reference anchors:
/root/reference/api/vrp/*/index.py, api/tsp/*/index.py), with the solver
core the reference left as stubs (reference src/solver.py:18-27) realised
as jit/vmap/shard_map-compiled metaheuristic search.

Layout:
  core/     problem representation, cost kernels, penalties, split
  moves/    neighborhood moves as batched index transforms
  solvers/  bf, local_search, sa, ga, aco — compiled search loops
  mesh/     island-model parallelism over a jax.sharding.Mesh
  kernels/  Pallas TPU kernels for the hot route-evaluation path
  io/       instance loaders (CVRPLIB, Solomon, JSON) + schemas
  native/   C++ components (exact oracle, parsers) via ctypes
"""

__version__ = "0.1.0"
