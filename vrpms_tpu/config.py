"""Typed registry of every VRPMS_* environment variable.

Nine PRs grew ~49 scattered ``os.environ.get`` sites, each re-deriving
its own parse-and-default logic (three private ``_env_float`` copies,
four spellings of the on/off switch). This module is the one place a
knob is declared — name, type, default, doc — and the one place it is
read. The static analyzer (vrpms_tpu.analysis, rule ``config-env-read``)
flags any direct environ read outside this file, and rule
``config-doc-sync`` checks every registered name is documented in
README.md, so the registry, the code, and the docs cannot drift.

Reads go through :func:`get` (typed), :func:`raw` (the uninterpreted
string, for knobs with bespoke grammars like VRPMS_TIERS or
VRPMS_STORE=faulty:<plan>), and :func:`enabled` (switches). All are
read per call — tests and embedders toggle env vars at runtime and the
service re-reads most knobs per request, so nothing is cached here.

Parsing is forgiving by policy: a junk value for an int/float knob
falls back to the declared default (the behavior the resilience layer's
``_env_*`` helpers already had — a typo'd knob must degrade, not crash
a request). Validation with real failure semantics (a malformed
VRPMS_TIERS is a boot error) stays with the owning parser; those knobs
are registered as kind="str" and parsed at the call site.

Switches accept one spelling everywhere: any of ``off``, ``0``,
``false``, ``no`` (case-insensitive, surrounding whitespace ignored)
disables; anything else — including unset, for default-on switches —
enables.

Stdlib-only and import-light on purpose: everything (stores, solvers,
the obs layer, the analyzer itself) imports this module, so it must
never import jax, the service, or any sibling package.
"""

from __future__ import annotations

import dataclasses
import os

_OFF_VALUES = ("off", "0", "false", "no")


@dataclasses.dataclass(frozen=True)
class Var:
    """One registered environment variable."""

    name: str
    kind: str  # "str" | "int" | "float" | "switch"
    default: object
    doc: str


def _v(name: str, kind: str, default, doc: str) -> Var:
    return Var(name=name, kind=kind, default=default, doc=doc)


#: Every environment variable the system reads, by name. Order is the
#: order the README table documents them in.
REGISTRY: dict[str, Var] = {
    v.name: v
    for v in (
        # -- store selection + resilience ------------------------------
        _v("VRPMS_STORE", "str", None,
           "Backend: memory | supabase | faulty:<plan>. Unset: supabase "
           "when SUPABASE_URL is set, else memory."),
        _v("VRPMS_FIXTURES", "str", None,
           "JSON fixture file seeding the memory store on first read."),
        _v("VRPMS_RESILIENCE", "str", "auto",
           "Wrap the store in the resilience layer: on | off | auto "
           "(auto wraps supabase and faulty)."),
        _v("VRPMS_STORE_DEADLINE_S", "float", 5.0,
           "Per-store-call deadline in seconds (0 = unbounded)."),
        _v("VRPMS_STORE_RETRIES", "int", 2,
           "Read retries after the first attempt."),
        _v("VRPMS_STORE_BACKOFF_S", "float", 0.05,
           "Base of the jittered exponential retry backoff."),
        _v("VRPMS_STORE_POOL", "int", 8,
           "Shared store-call thread-pool size."),
        _v("VRPMS_STORE_CACHE", "int", 256,
           "Degraded-mode last-known-rows cache entry cap."),
        _v("VRPMS_STORE_JOURNAL", "int", 512,
           "Degraded-mode write-replay journal entry cap."),
        _v("VRPMS_CB_FAILURES", "int", 5,
           "Consecutive failures that open the store circuit breaker."),
        _v("VRPMS_CB_RESET_S", "float", 30.0,
           "Open-circuit seconds before one half-open probe is let in."),
        _v("SUPABASE_URL", "str", "",
           "Supabase project URL (also selects the supabase store when "
           "VRPMS_STORE is unset)."),
        _v("SUPABASE_KEY", "str", "",
           "Supabase anon/service key for the hosted store."),
        # -- solution cache --------------------------------------------
        _v("VRPMS_CACHE", "str", "",
           "Content-addressed solution cache: off disables, an integer "
           "sets the in-memory LRU entry cap, unset/other = on with the "
           "default cap (512)."),
        _v("VRPMS_CACHE_NEAR", "int", 4,
           "Max Hamming distance a near-hit warm seed may bridge "
           "(0 disables near seeding)."),
        # -- scheduler + async jobs ------------------------------------
        _v("VRPMS_SCHED", "switch", True,
           "Async solve scheduler (off = solve inline on the HTTP "
           "thread)."),
        _v("VRPMS_SCHED_QUEUE", "int", 64,
           "Bounded admission queue depth per backend."),
        _v("VRPMS_SCHED_WINDOW_MS", "float", 10.0,
           "Micro-batch gather window in milliseconds."),
        _v("VRPMS_SCHED_MAX_BATCH", "int", 16,
           "Max same-bucket jobs merged into one batched launch."),
        _v("VRPMS_SCHED_WATCHDOG_MS", "float", 500.0,
           "Worker watchdog check interval (0 disables supervision)."),
        _v("VRPMS_SCHED_WEDGE_GRACE_S", "float", 10.0,
           "Grace past a batch's summed budget before a worker counts "
           "as wedged; size above the slowest legitimate cold compile."),
        _v("VRPMS_READY_RESTART_WINDOW_S", "float", 60.0,
           "How long after a worker restart /api/ready stays degraded."),
        _v("VRPMS_STREAM_TIMEOUT_S", "float", 600.0,
           "Max lifetime of one GET /api/jobs/{id}/stream connection."),
        _v("VRPMS_RESOLVE_WAIT_S", "float", 30.0,
           "How long POST /api/jobs/{id}/resolve waits for the "
           "predecessor's terminal record before answering 409."),
        # -- QoS scheduling + fairness ---------------------------------
        _v("VRPMS_QOS", "switch", True,
           "Deadline/class-aware QoS scheduling (priority classes, EDF "
           "claim ordering, selective shed, tenant quotas); off "
           "restores plain FIFO queues and pre-QoS responses."),
        _v("VRPMS_QOS_SHED_STANDARD", "float", 1.0,
           "Fraction of the admission bound standard-class submits may "
           "fill before they shed with 429; the default (1.0, the full "
           "bound) keeps default-class admission identical to the "
           "pre-QoS contract — lower it to reserve headroom for "
           "interactive traffic (interactive always gets the full "
           "bound)."),
        _v("VRPMS_QOS_SHED_BATCH", "float", 0.5,
           "Fraction of the admission bound batch-class submits may "
           "fill before they shed — the class that absorbs overload "
           "first."),
        _v("VRPMS_QOS_TENANT_QUOTA", "int", 0,
           "Max active jobs one authenticated tenant may hold across "
           "the replica fleet (auth-scoped; anonymous requests are "
           "exempt); 0 disables quotas."),
        # -- distributed queue + replicas ------------------------------
        _v("VRPMS_QUEUE", "str", "local",
           "Job queue: local (in-process) or store|shared|dist (the "
           "store-backed distributed queue)."),
        _v("VRPMS_QUEUE_STEAL", "switch", True,
           "Steal off-arc work when this replica's own arcs are empty."),
        _v("VRPMS_QUEUE_POLL_MS", "float", 50.0,
           "Replica claim-loop poll interval in milliseconds."),
        _v("VRPMS_QUEUE_MAX_INFLIGHT", "int", 16,
           "Max leases one replica holds at once."),
        _v("VRPMS_CLAIM_BATCH", "int", 0,
           "Max same-ring-token entries one store claim may lease "
           "together (claim-K micro-batching); 0 = auto-size each "
           "claim to local admission headroom, 1 = single-claim."),
        _v("VRPMS_DEPTH_MEMO_MS", "float", 250.0,
           "Shared-queue depth memo TTL for the 429/readiness paths "
           "(bounded staleness instead of a store round trip per "
           "request); 0 reads the store every time."),
        _v("VRPMS_READ_TTL_MS", "float", 250.0,
           "Job-read cache TTL on the distributed queue: N watchers "
           "polling one job cost one store read per TTL instead of N "
           "(terminal records, checkpoint overlays, owner lookups); "
           "0 reads the store every time. Local-queue mode never "
           "caches."),
        _v("VRPMS_READ_RELAY", "switch", True,
           "Federated reads on the distributed queue: a non-owning "
           "replica answering GET /api/jobs/{id} (or its SSE stream) "
           "overlays the latest checkpoint-sourced incumbent — marked "
           "incumbentSource/staleMs — and relays live progress from "
           "the owning replica found in the heartbeat registry. Off = "
           "byte-identical pre-federation responses."),
        _v("VRPMS_REPLICA_ID", "str", None,
           "Stable replica identity (set to the pod/host name so "
           "restarts keep their ring arcs); unset generates one."),
        _v("VRPMS_REPLICA_DRAIN_S", "float", 5.0,
           "Graceful-stop window for in-flight leases at shutdown."),
        _v("VRPMS_DRAIN_GRACE_S", "float", 10.0,
           "Graceful-drain window (POST /api/admin/drain and SIGTERM): "
           "in-flight jobs get this long to finish before they are "
           "checkpointed and nacked back to the shared queue for a "
           "peer to resume."),
        # -- crash-resumable solves ------------------------------------
        _v("VRPMS_CKPT", "switch", True,
           "Durable solve checkpoints: a background checkpointer "
           "persists each async job's latest incumbent (and each "
           "completed decomposition shard) so lease reclaims, watchdog "
           "requeues, and drained replicas resume instead of "
           "re-solving from zero. Off = byte-identical pre-checkpoint "
           "behavior; requires VRPMS_PROGRESS (capture rides the "
           "progress sink)."),
        _v("VRPMS_CKPT_MS", "float", 2000.0,
           "Minimum interval between checkpoint captures of one job's "
           "incumbent (bounded cadence: solves shorter than this never "
           "pay a checkpoint write)."),
        # -- standing subscriptions ------------------------------------
        _v("VRPMS_SUBS", "switch", True,
           "Standing subscriptions: POST /api/subscriptions creates a "
           "durable re-solve-on-change entity that launches a warm-"
           "seeded generation per coalesced delta burst (or on its "
           "resolveEvery cadence), with lineage in records and trace "
           "roots. Off = the subscription routes 404 and every pre-"
           "subscription response stays byte-identical."),
        _v("VRPMS_SUB_DEBOUNCE_MS", "float", 250.0,
           "Delta debounce window per subscription: a burst of deltas "
           "arriving within this window coalesces into ONE re-solve "
           "generation (counted in vrpms_sub_coalesced_total); 0 "
           "launches a generation per delta."),
        _v("VRPMS_SUB_MAX_PER_TENANT", "int", 0,
           "Max standing subscriptions one tenant may hold (QoS "
           "fairness for the control plane, next to the per-tenant "
           "job quota); 0 = unlimited."),
        # -- elastic fleet autoscaling ---------------------------------
        _v("VRPMS_AUTOSCALE", "switch", True,
           "Elastic-fleet controller: publishes the desired replica "
           "count (vrpms_fleet_desired_replicas gauge + the autoscale "
           "block on /api/debug/fleet) from shared backlog x per-class "
           "drain rate vs deadline headroom, enables POST "
           "/api/admin/scalein victim selection, and pre-warms tiers a "
           "replica inherits on ring membership churn. Off = no "
           "controller runs and every pre-autoscale response stays "
           "byte-identical."),
        _v("VRPMS_AUTOSCALE_MIN", "int", 1,
           "Floor of the desired-replica recommendation."),
        _v("VRPMS_AUTOSCALE_MAX", "int", 0,
           "Ceiling of the desired-replica recommendation; 0 = "
           "unbounded."),
        _v("VRPMS_AUTOSCALE_HEADROOM_S", "float", 30.0,
           "Deadline headroom the fleet must drain the backlog within: "
           "desired = ceil(backlog work-seconds / (headroom x per-"
           "replica inflight)). Lower = more aggressive scale-up."),
        _v("VRPMS_AUTOSCALE_COOLDOWN_S", "float", 60.0,
           "How long a scale-DOWN signal must persist before the "
           "recommendation drops (scale-up is immediate — deadlines "
           "are at stake)."),
        _v("VRPMS_AUTOSCALE_HYSTERESIS", "float", 0.25,
           "Slack fraction a smaller fleet must keep before scale-down "
           "is eligible: the backlog must fit in (1 - hysteresis) of "
           "the smaller fleet's capacity, so a boundary wiggle never "
           "flaps the signal."),
        _v("VRPMS_RING_VNODES", "int", 64,
           "Virtual nodes per replica on the consistent-hash ring."),
        _v("VRPMS_LEASE_S", "float", 15.0,
           "Queue lease duration; renewed at half-life while solving."),
        _v("VRPMS_HEARTBEAT_S", "float", 5.0,
           "Replica membership heartbeat interval (TTL is 3 beats)."),
        _v("VRPMS_RECLAIM_S", "float", 1.0,
           "Expired-lease reclaim scan interval."),
        # -- giant-instance decomposition ------------------------------
        _v("VRPMS_DECOMP", "str", "auto",
           "Giant-instance decompose-solve-stitch path for VRP SA "
           "requests ABOVE the tier ladder top: off disables, auto/on "
           "engage (a no-op for any instance that fits one tier, so "
           "responses below the ceiling stay byte-identical)."),
        _v("VRPMS_DECOMP_TIER", "int", 0,
           "Target shard NODE tier for decomposed solves; 0 = auto "
           "(the largest ladder tier <= 256). Shards pad to one common "
           "tier so they merge into vmapped batched launches."),
        _v("VRPMS_DECOMP_BOUNDARY", "float", 1.25,
           "Frontier ratio of the boundary re-opt band: a customer "
           "joins the band when its distance to the nearest OTHER "
           "shard center is within this factor of the distance to its "
           "own shard's center."),
        # -- observability ---------------------------------------------
        _v("VRPMS_LOG", "switch", True,
           "Structured JSON event log (off silences it)."),
        _v("VRPMS_TRACING", "switch", True,
           "Dapper-style request tracing + traceparent propagation."),
        _v("VRPMS_TRACE_RING", "int", 128,
           "Completed-trace debug ring capacity (/api/debug/traces)."),
        _v("VRPMS_TRACE_SLOW_MS", "float", 5000.0,
           "Traces at least this slow auto-log their full waterfall."),
        _v("VRPMS_PROGRESS", "switch", True,
           "Live incumbent progress + cooperative cancellation."),
        _v("VRPMS_TRACE_EXPORT", "switch", False,
           "Durable trace export: completed traces batch-write to the "
           "store's trace_spans seam so GET /api/debug/traces federates "
           "across replicas. Off by default locally; turn on for "
           "store-backed (VRPMS_QUEUE=store) deployments."),
        _v("VRPMS_TRACE_EXPORT_QUEUE", "int", 256,
           "Bounded export queue: completed traces awaiting the "
           "background flusher; overflow DROPS the oldest spans "
           "(counted vrpms_trace_export_total{outcome=dropped}), never "
           "blocks a request."),
        _v("VRPMS_TRACE_EXPORT_BATCH", "int", 16,
           "Max traces one flusher round batch-writes per store call."),
        _v("VRPMS_TRACE_EXPORT_FLUSH_MS", "float", 50.0,
           "Idle wait between exporter flush rounds in milliseconds "
           "(a non-empty queue flushes immediately)."),
        _v("VRPMS_ANALYTICS", "switch", False,
           "Solve analytics: every completed solve emits a flight "
           "record (device/host split, padding + batch occupancy, "
           "evals/sec, cache outcome, gap, primal integral) exported "
           "through the store's flight_records seam, rolled up on "
           "GET /api/debug/analytics, with per-QoS-class SLO burn "
           "rates and the regression sentinel. Off (the default) = "
           "byte-identical responses."),
        _v("VRPMS_ANALYTICS_QUEUE", "int", 256,
           "Bounded flight-record export queue; overflow DROPS the "
           "oldest record (counted "
           "vrpms_analytics_total{outcome=dropped}), never blocks a "
           "solve."),
        _v("VRPMS_ANALYTICS_FLUSH_MS", "float", 50.0,
           "Idle wait between analytics flusher rounds in milliseconds "
           "(a non-empty queue flushes immediately)."),
        _v("VRPMS_SLO_TARGET", "float", 0.99,
           "Deadline-met SLO objective per QoS class: the burn rate is "
           "the observed miss fraction over each window divided by the "
           "allowed miss budget (1 - target)."),
        _v("VRPMS_ILS_TRACE", "str", None,
           "Truthy: print ILS round-by-round trace lines to stderr."),
        # -- solver + compile knobs ------------------------------------
        _v("VRPMS_PIPELINE", "switch", True,
           "Depth-1 pipelined block dispatch in the solver deadline "
           "drivers: block k+1 launches while block k's results are "
           "processed on host, so cancel/deadline/checkpoint react "
           "within at most one in-flight block. Off restores the "
           "serial loop exactly, including its sync points."),
        _v("VRPMS_TIERS", "str", "",
           "Shape-tier ladder spec (see core.tiers.parse_tiers; 'off' "
           "disables padding; malformed values are a boot error)."),
        _v("VRPMS_WARMUP", "str", "",
           "Startup warmup: 'tiers'/'auto' warms the owned tier ladder "
           "in the background, or explicit 'NxV[xT]' shapes."),
        _v("VRPMS_COMPILE_CACHE", "str", None,
           "Persistent XLA compile cache dir; off|0|none disables; "
           "unset uses ~/.cache/vrpms_tpu/xla."),
        _v("VRPMS_CERT_CACHE", "str", "",
           "B&B certificate cache dir; 0 disables; unset uses "
           "~/.cache/vrpms_tpu_certs."),
        _v("VRPMS_RATE_CACHE", "str", None,
           "Sweep-rate calibration cache file; unset uses "
           "~/.cache/vrpms_tpu_sweep_rates.json."),
        _v("VRPMS_DELTA_INTERPRET", "str", None,
           "Truthy (any non-empty value): run Pallas delta kernels in "
           "interpret mode (tests)."),
    )
}


def _var(name: str) -> Var:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a registered environment variable; add it "
            "to vrpms_tpu.config.REGISTRY (and README.md) first"
        ) from None


def raw(name: str) -> str | None:
    """The uninterpreted environment value (None when unset), for knobs
    whose grammar lives with their owning parser. The name must still
    be registered — typos fail loudly."""
    return os.environ.get(_var(name).name)


def _as_switch(value: str | None, default) -> bool:
    if value is None:
        return bool(default)
    return value.strip().lower() not in _OFF_VALUES


def get(name: str):
    """The typed value of `name`: str/int/float per the registry, bool
    for switches. Junk int/float values fall back to the default."""
    var = _var(name)
    value = os.environ.get(var.name)
    if var.kind == "switch":
        return _as_switch(value, var.default)
    if value is None:
        return var.default
    if var.kind == "int":
        try:
            return int(value)
        except ValueError:
            return var.default
    if var.kind == "float":
        try:
            return float(value)
        except ValueError:
            return var.default
    return value


def enabled(name: str) -> bool:
    """Switch read, asserting the registry agrees `name` IS a switch."""
    var = _var(name)
    if var.kind != "switch":
        raise TypeError(f"{name} is kind={var.kind!r}, not a switch")
    return _as_switch(os.environ.get(var.name), var.default)


def iter_vars():
    """Registered vars in documentation order (the README table)."""
    return list(REGISTRY.values())


def markdown_table() -> str:
    """The generated README config table (kept in sync by the
    ``config-doc-sync`` analyzer rule + tests/test_analysis.py)."""
    lines = [
        "| Variable | Type | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for var in iter_vars():
        if var.kind == "switch":
            default = "on" if var.default else "off"
        elif var.default is None:
            default = "(unset)"
        elif var.default == "":
            default = '""'
        else:
            default = f"`{var.default}`"
        lines.append(
            f"| `{var.name}` | {var.kind} | {default} | {var.doc} |"
        )
    return "\n".join(lines)
