// Native DFS core for the exact CVRP branch-and-bound.
//
// Same search as vrpms_tpu/solvers/exact.py::solve_cvrp_bnb's Python DFS
// (route-by-route construction, first-customer route ordering, canonical
// orientation for symmetric matrices, Pareto dominance memo, q-route
// completion bound) — reimplemented in C++ because the node engine is the
// whole ballgame: the Python walker sustains ~10-20k nodes/s while n=32
// proofs need 10^7-10^9 nodes. The Lagrangian tables (R, Psi, lam) are
// computed once in numpy (io/bounds.py) and passed in read-only; this file
// owns only the hot tree walk. Built as a shared library and driven via
// ctypes (no pybind11 in the image).
//
// Contract notes mirrored from the Python twin:
//  * routes open in strictly increasing order of their first customer;
//  * for symmetric matrices a closed route with >= 2 customers must have
//    first < last (one orientation per route);
//  * bound: cost + min_{q1 <= min(slack, dl)} R[q1][p] + Psi[m][dl - q1]
//           - sum_{j unvisited} lam[j]        (capacity-aware, exact LB);
//  * dominance: per (unvisited-set, last, open-route-first) a Pareto set
//    of (cost, slack, vehicles-left) — beaten on all three => prune.

#include <cstdint>
#include <cstring>
#include <ctime>
#include <unordered_map>
#include <vector>

namespace {

struct Ctx {
  int n;                // customers
  int V;
  int64_t cap;          // scaled capacity
  const double* d;      // (n+1)^2
  const int64_t* dem;   // n, customer j demand at dem[j-1]
  const double* lam;    // n
  const double* R;      // (cap+1) x n
  const double* Psi;    // (V+1) x (total+1)
  int64_t total;
  int psi_rows;         // actual Psi row count = min(V, n)+1 (clamp m)
  bool symmetric;
  double best_cost;
  int64_t nodes;
  int64_t node_budget;  // deadline check cadence
  double deadline;      // CLOCK_MONOTONIC seconds; <0 => none
  bool timed_out;
  // best solution: customer sequence with route breaks
  std::vector<int> best_seq;   // route-major customers, -1 between routes
  std::vector<int> cur_stack;  // same layout while walking
  struct Dom { double cost; int64_t slack; int m; };
  std::unordered_map<uint64_t, std::vector<Dom>> memo;
  size_t memo_cap = 0;  // max stored entries: billion-node searches must
                        // not eat the host (measured: an uncapped memo on
                        // a 1.26B-node A-n32-k5 run grew into the GBs and
                        // took the machine into OOM territory)
  size_t memo_size = 0;
};

inline double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + 1e-9 * double(ts.tv_nsec);
}

inline double dd(const Ctx& c, int a, int b) {
  return c.d[a * (c.n + 1) + b];
}

struct Child { double step; int j; bool opens; };

void dfs(Ctx& c, uint64_t unvis, int p, int first, int64_t slack, int m,
         double cost, double sum_lam, int64_t dem_left) {
  if (c.timed_out) return;
  if (++c.nodes >= c.node_budget) {
    c.node_budget = c.nodes + 8192;
    if (c.deadline >= 0 && now_s() > c.deadline) { c.timed_out = true; return; }
  }
  if (unvis == 0) {
    // canonical orientation: first < last for symmetric multi-customer routes
    if (c.symmetric && p != first && first > p) return;
    double total_cost = cost + dd(c, p, 0);
    if (total_cost < c.best_cost - 1e-12) {
      c.best_cost = total_cost;
      c.best_seq = c.cur_stack;
    }
    return;
  }
  if (dem_left > slack + int64_t(m) * c.cap) return;
  // q-route completion bound
  {
    int64_t hi = slack < dem_left ? slack : dem_left;
    int mrow = m < c.psi_rows - 1 ? m : c.psi_rows - 1;
    const double* Rp = c.R;             // R[q][p-1]
    const double* Pm = c.Psi + size_t(mrow) * size_t(c.total + 1);
    double bound = 1e300;
    for (int64_t q1 = 0; q1 <= hi; ++q1) {
      double v = Rp[size_t(q1) * size_t(c.n) + size_t(p - 1)] + Pm[dem_left - q1];
      if (v < bound) bound = v;
    }
    if (cost + bound - sum_lam >= c.best_cost - 1e-9) return;
  }
  // dominance memo (bounded: stop inserting past memo_cap — lookups keep
  // working on what exists, correctness never depends on the memo)
  {
    uint64_t key = unvis | (uint64_t(p) << 36) | (uint64_t(first) << 44);
    auto it = c.memo.find(key);
    if (it != c.memo.end()) {
      auto& ent = it->second;
      for (const auto& e : ent)
        if (e.cost <= cost + 1e-12 && e.slack >= slack && e.m >= m) return;
      size_t w = 0;
      for (size_t i = 0; i < ent.size(); ++i)
        if (!(cost <= ent[i].cost && slack >= ent[i].slack && m >= ent[i].m))
          ent[w++] = ent[i];
      c.memo_size -= ent.size() - w;
      ent.resize(w);
      if (ent.size() < 8 && c.memo_size < c.memo_cap) {
        ent.push_back({cost, slack, m});
        ++c.memo_size;
      }
    } else if (c.memo_size < c.memo_cap) {
      c.memo[key].push_back({cost, slack, m});
      ++c.memo_size;
    }
  }
  // children, cheapest first
  Child kids[80];
  int nk = 0;
  uint64_t rest = unvis;
  while (rest) {
    int j = __builtin_ctzll(rest) + 1;
    rest &= rest - 1;
    if (c.dem[j - 1] <= slack)
      kids[nk++] = {dd(c, p, j), j, false};
  }
  bool can_close =
      m >= 1 && !(c.symmetric && p != first && first > p);
  if (can_close) {
    double close = dd(c, p, 0);
    rest = unvis;
    while (rest) {
      int f = __builtin_ctzll(rest) + 1;
      rest &= rest - 1;
      if (f > first && c.dem[f - 1] <= c.cap)
        kids[nk++] = {close + dd(c, 0, f), f, true};
    }
  }
  // insertion sort by step cost (nk <= ~2n, small)
  for (int i = 1; i < nk; ++i) {
    Child x = kids[i];
    int k = i - 1;
    while (k >= 0 && kids[k].step > x.step) { kids[k + 1] = kids[k]; --k; }
    kids[k + 1] = x;
  }
  for (int i = 0; i < nk; ++i) {
    if (c.timed_out) return;
    double ncost = cost + kids[i].step;
    if (ncost >= c.best_cost - 1e-9) continue;
    int j = kids[i].j;
    uint64_t bit = 1ull << (j - 1);
    if (kids[i].opens) {
      c.cur_stack.push_back(-1);
      c.cur_stack.push_back(j);
      dfs(c, unvis & ~bit, j, j, c.cap - c.dem[j - 1], m - 1, ncost,
          sum_lam - c.lam[j - 1], dem_left - c.dem[j - 1]);
      c.cur_stack.pop_back();
      c.cur_stack.pop_back();
    } else {
      c.cur_stack.push_back(j);
      dfs(c, unvis & ~bit, j, first, slack - c.dem[j - 1], m, ncost,
          sum_lam - c.lam[j - 1], dem_left - c.dem[j - 1]);
      c.cur_stack.pop_back();
    }
  }
}

}  // namespace

extern "C" int bnb_solve(
    int n, int V, int64_t cap_s,
    const double* d, const int64_t* dem_s, const double* lam,
    const double* R, const double* Psi, int psi_rows, int64_t total_s,
    double best_cost_in, double time_limit_s, int symmetric,
    // outputs
    int* out_seq,        // size n + V: customers with -1 route breaks
    int* out_seq_len,
    double* out_cost,
    int64_t* out_nodes,
    int* out_proven) {
  if (n < 1 || n > 34) return -1;
  Ctx c;
  c.n = n; c.V = V; c.cap = cap_s; c.d = d; c.dem = dem_s; c.lam = lam;
  c.R = R; c.Psi = Psi; c.total = total_s; c.psi_rows = psi_rows;
  c.symmetric = symmetric != 0;
  c.best_cost = best_cost_in;
  c.nodes = 0; c.node_budget = 8192;
  c.memo_cap = 30'000'000;  // ~1.5 GB worst case, plenty for the hit rate
  c.deadline = time_limit_s > 0 ? now_s() + time_limit_s : -1.0;
  c.timed_out = false;
  c.cur_stack.reserve(n + V + 2);

  double lam_total = 0;
  int64_t dem_total = 0;
  for (int j = 0; j < n; ++j) { lam_total += lam[j]; dem_total += dem_s[j]; }

  // root: every capacity-feasible first customer, nearest first
  std::vector<std::pair<double, int>> roots;
  for (int f = 1; f <= n; ++f) {
    if (dem_s[f - 1] > cap_s) { *out_proven = 0; *out_cost = 1e300;
      *out_seq_len = 0; *out_nodes = 0; return 1; }  // infeasible customer
    roots.push_back({dd(c, 0, f), f});
  }
  for (size_t i = 1; i < roots.size(); ++i) {  // insertion sort
    auto x = roots[i]; size_t k = i;
    while (k > 0 && roots[k - 1].first > x.first) { roots[k] = roots[k - 1]; --k; }
    roots[k] = x;
  }
  uint64_t full = (n == 64) ? ~0ull : ((1ull << n) - 1);
  for (auto& rf : roots) {
    if (c.timed_out) break;
    int f = rf.second;
    if (rf.first >= c.best_cost) continue;
    c.cur_stack.clear();
    c.cur_stack.push_back(f);
    dfs(c, full & ~(1ull << (f - 1)), f, f, cap_s - dem_s[f - 1], V - 1,
        rf.first, lam_total - lam[f - 1], dem_total - dem_s[f - 1]);
  }

  *out_nodes = c.nodes;
  *out_proven = c.timed_out ? 0 : 1;
  *out_cost = c.best_cost;
  int len = int(c.best_seq.size());
  if (len > n + V) len = n + V;
  for (int i = 0; i < len; ++i) out_seq[i] = c.best_seq[i];
  *out_seq_len = len;
  return 0;
}
