// Native DFS core for the exact CVRP branch-and-bound.
//
// Same search as vrpms_tpu/solvers/exact.py::solve_cvrp_bnb's Python DFS
// (route-by-route construction, first-customer route ordering, canonical
// orientation for symmetric matrices, Pareto dominance memo, q-route
// completion bound) — reimplemented in C++ because the node engine is the
// whole ballgame: the Python walker sustains ~10-20k nodes/s while n=32
// proofs need 10^7-10^9 nodes. The Lagrangian tables (R, Psi, lam) are
// computed once in numpy (io/bounds.py) and passed in read-only; this file
// owns only the hot tree walk. Built as a shared library and driven via
// ctypes (no pybind11 in the image).
//
// Parallel search (round 4): the DFS forest under (first customer, second
// branch) splitting is embarrassingly parallel — workers pull depth-2
// subtree tasks from a shared cheapest-first queue and share one atomic
// incumbent (each worker refreshes its local bound from it per node, and
// publishes improvements under a mutex). Each worker owns a private
// dominance memo: cross-thread dominance sharing would need locking on the
// hottest structure, and the memo is a pruning accelerator, not a
// correctness requirement. n_threads <= 0 means hardware_concurrency; 1
// runs the exact sequential walk (no queue, no atomics on the hot path
// beyond a relaxed load). The host this was built on exposes ONE core, so
// the parallel speedup is validated structurally (identical results across
// thread counts), not by wall-clock here.
//
// Contract notes mirrored from the Python twin:
//  * routes open in strictly increasing order of their first customer;
//  * for symmetric matrices a closed route with >= 2 customers must have
//    first < last (one orientation per route);
//  * bound: cost + min_{q1 <= min(slack, dl)} R[q1][p] + Psi[m][dl - q1]
//           - sum_{j unvisited} lam[j]        (capacity-aware, exact LB);
//  * dominance: per (unvisited-set, last, open-route-first) a Pareto set
//    of (cost, slack, vehicles-left) — beaten on all three => prune.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Shared {
  std::atomic<double> best_cost;
  std::atomic<bool> timed_out;
  std::mutex mu;                // guards best_seq + best_cost publication
  std::vector<int> best_seq;    // route-major customers, -1 between routes
};

struct Ctx {
  int n;                // customers
  int V;
  int64_t cap;          // scaled capacity
  const double* d;      // (n+1)^2
  const int64_t* dem;   // n, customer j demand at dem[j-1]
  const double* lam;    // n
  const double* R;      // (cap+1) x n
  const double* Psi;    // (V+1) x (total+1)
  int64_t total;
  int psi_rows;         // actual Psi row count = min(V, n)+1 (clamp m)
  bool symmetric;
  double best_cost;     // local mirror of shared->best_cost
  Shared* shared;
  int64_t nodes;
  int64_t node_budget;  // deadline check cadence
  double deadline;      // CLOCK_MONOTONIC seconds; <0 => none
  bool timed_out;
  std::vector<int> cur_stack;  // route-major walk state
  struct Dom { double cost; int64_t slack; int m; };
  std::unordered_map<uint64_t, std::vector<Dom>> memo;
  size_t memo_cap = 0;  // max stored entries: billion-node searches must
                        // not eat the host (measured: an uncapped memo on
                        // a 1.26B-node A-n32-k5 run grew into the GBs and
                        // took the machine into OOM territory)
  size_t memo_size = 0;
};

inline double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + 1e-9 * double(ts.tv_nsec);
}

inline double dd(const Ctx& c, int a, int b) {
  return c.d[a * (c.n + 1) + b];
}

struct Child { double step; int j; bool opens; };

void dfs(Ctx& c, uint64_t unvis, int p, int first, int64_t slack, int m,
         double cost, double sum_lam, int64_t dem_left) {
  if (c.timed_out) return;
  // pull the freshest incumbent (relaxed: monotone decreasing, a stale
  // read only costs pruning power, never correctness)
  {
    double gb = c.shared->best_cost.load(std::memory_order_relaxed);
    if (gb < c.best_cost) c.best_cost = gb;
  }
  if (++c.nodes >= c.node_budget) {
    c.node_budget = c.nodes + 8192;
    if (c.shared->timed_out.load(std::memory_order_relaxed)) {
      c.timed_out = true;
      return;
    }
    if (c.deadline >= 0 && now_s() > c.deadline) {
      c.timed_out = true;
      c.shared->timed_out.store(true, std::memory_order_relaxed);
      return;
    }
  }
  if (unvis == 0) {
    // canonical orientation: first < last for symmetric multi-customer routes
    if (c.symmetric && p != first && first > p) return;
    double total_cost = cost + dd(c, p, 0);
    if (total_cost < c.best_cost - 1e-12) {
      std::lock_guard<std::mutex> lk(c.shared->mu);
      if (total_cost <
          c.shared->best_cost.load(std::memory_order_relaxed) - 1e-12) {
        c.shared->best_cost.store(total_cost, std::memory_order_relaxed);
        c.shared->best_seq = c.cur_stack;
      }
      c.best_cost = c.shared->best_cost.load(std::memory_order_relaxed);
    }
    return;
  }
  if (dem_left > slack + int64_t(m) * c.cap) return;
  // q-route completion bound
  {
    int64_t hi = slack < dem_left ? slack : dem_left;
    int mrow = m < c.psi_rows - 1 ? m : c.psi_rows - 1;
    const double* Rp = c.R;             // R[q][p-1]
    const double* Pm = c.Psi + size_t(mrow) * size_t(c.total + 1);
    double bound = 1e300;
    for (int64_t q1 = 0; q1 <= hi; ++q1) {
      double v = Rp[size_t(q1) * size_t(c.n) + size_t(p - 1)] + Pm[dem_left - q1];
      if (v < bound) bound = v;
    }
    if (cost + bound - sum_lam >= c.best_cost - 1e-9) return;
  }
  // dominance memo (bounded: stop inserting past memo_cap — lookups keep
  // working on what exists, correctness never depends on the memo)
  {
    uint64_t key = unvis | (uint64_t(p) << 36) | (uint64_t(first) << 44);
    auto it = c.memo.find(key);
    if (it != c.memo.end()) {
      auto& ent = it->second;
      for (const auto& e : ent)
        if (e.cost <= cost + 1e-12 && e.slack >= slack && e.m >= m) return;
      size_t w = 0;
      for (size_t i = 0; i < ent.size(); ++i)
        if (!(cost <= ent[i].cost && slack >= ent[i].slack && m >= ent[i].m))
          ent[w++] = ent[i];
      c.memo_size -= ent.size() - w;
      ent.resize(w);
      if (ent.size() < 8 && c.memo_size < c.memo_cap) {
        ent.push_back({cost, slack, m});
        ++c.memo_size;
      }
    } else if (c.memo_size < c.memo_cap) {
      c.memo[key].push_back({cost, slack, m});
      ++c.memo_size;
    }
  }
  // children, cheapest first
  Child kids[80];
  int nk = 0;
  uint64_t rest = unvis;
  while (rest) {
    int j = __builtin_ctzll(rest) + 1;
    rest &= rest - 1;
    if (c.dem[j - 1] <= slack)
      kids[nk++] = {dd(c, p, j), j, false};
  }
  bool can_close =
      m >= 1 && !(c.symmetric && p != first && first > p);
  if (can_close) {
    double close = dd(c, p, 0);
    rest = unvis;
    while (rest) {
      int f = __builtin_ctzll(rest) + 1;
      rest &= rest - 1;
      if (f > first && c.dem[f - 1] <= c.cap)
        kids[nk++] = {close + dd(c, 0, f), f, true};
    }
  }
  // insertion sort by step cost (nk <= ~2n, small)
  for (int i = 1; i < nk; ++i) {
    Child x = kids[i];
    int k = i - 1;
    while (k >= 0 && kids[k].step > x.step) { kids[k + 1] = kids[k]; --k; }
    kids[k + 1] = x;
  }
  for (int i = 0; i < nk; ++i) {
    if (c.timed_out) return;
    double ncost = cost + kids[i].step;
    if (ncost >= c.best_cost - 1e-9) continue;
    int j = kids[i].j;
    uint64_t bit = 1ull << (j - 1);
    if (kids[i].opens) {
      c.cur_stack.push_back(-1);
      c.cur_stack.push_back(j);
      dfs(c, unvis & ~bit, j, j, c.cap - c.dem[j - 1], m - 1, ncost,
          sum_lam - c.lam[j - 1], dem_left - c.dem[j - 1]);
      c.cur_stack.pop_back();
      c.cur_stack.pop_back();
    } else {
      c.cur_stack.push_back(j);
      dfs(c, unvis & ~bit, j, first, slack - c.dem[j - 1], m, ncost,
          sum_lam - c.lam[j - 1], dem_left - c.dem[j - 1]);
      c.cur_stack.pop_back();
    }
  }
}

// A depth-<=2 subtree root: the state after choosing the first route's
// first customer f (and optionally one more branch), plus the stack
// prefix that reproduces it for solution reconstruction.
struct Task {
  double key;      // cheapest-first ordering (cumulative cost)
  uint64_t unvis;
  int p, first, m;
  int64_t slack, dem_left;
  double cost, sum_lam;
  std::vector<int> prefix;
};

}  // namespace

extern "C" int bnb_solve(
    int n, int V, int64_t cap_s,
    const double* d, const int64_t* dem_s, const double* lam,
    const double* R, const double* Psi, int psi_rows, int64_t total_s,
    double best_cost_in, double time_limit_s, int symmetric,
    int n_threads,
    // outputs
    int* out_seq,        // size n + V: customers with -1 route breaks
    int* out_seq_len,
    double* out_cost,
    int64_t* out_nodes,
    int* out_proven) {
  if (n < 1 || n > 34) return -1;
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? int(hc) : 1;
  }

  Shared shared;
  shared.best_cost.store(best_cost_in, std::memory_order_relaxed);
  shared.timed_out.store(false, std::memory_order_relaxed);
  double deadline = time_limit_s > 0 ? now_s() + time_limit_s : -1.0;

  double lam_total = 0;
  int64_t dem_total = 0;
  for (int j = 0; j < n; ++j) { lam_total += lam[j]; dem_total += dem_s[j]; }
  for (int f = 1; f <= n; ++f) {
    if (dem_s[f - 1] > cap_s) {  // infeasible customer: nothing to search
      *out_proven = 0; *out_cost = 1e300; *out_seq_len = 0; *out_nodes = 0;
      return 1;
    }
  }
  uint64_t full = (n == 64) ? ~0ull : ((1ull << n) - 1);

  auto make_ctx = [&](Ctx& c, size_t memo_cap) {
    c.n = n; c.V = V; c.cap = cap_s; c.d = d; c.dem = dem_s; c.lam = lam;
    c.R = R; c.Psi = Psi; c.total = total_s; c.psi_rows = psi_rows;
    c.symmetric = symmetric != 0;
    c.best_cost = shared.best_cost.load(std::memory_order_relaxed);
    c.shared = &shared;
    c.nodes = 0; c.node_budget = 8192;
    c.memo_cap = memo_cap;
    c.deadline = deadline;
    c.timed_out = false;
    c.cur_stack.reserve(n + V + 2);
  };
  // ~1.5 GB worst case total across workers, same envelope as before
  size_t memo_cap_total = 30'000'000;

  // Depth-1 root states (one per feasible first customer, cheapest first).
  std::vector<Task> roots;
  for (int f = 1; f <= n; ++f) {
    Task t;
    t.key = d[0 * (n + 1) + f];
    t.unvis = full & ~(1ull << (f - 1));
    t.p = f; t.first = f; t.m = V - 1;
    t.slack = cap_s - dem_s[f - 1];
    t.dem_left = dem_total - dem_s[f - 1];
    t.cost = t.key;
    t.sum_lam = lam_total - lam[f - 1];
    t.prefix = {f};
    roots.push_back(std::move(t));
  }
  std::sort(roots.begin(), roots.end(),
            [](const Task& a, const Task& b) { return a.key < b.key; });

  int64_t total_nodes = 0;
  bool any_timeout = false;

  if (n_threads == 1) {
    // sequential path: walk the roots directly (identical to the
    // pre-parallel engine)
    Ctx c;
    make_ctx(c, memo_cap_total);
    for (auto& t : roots) {
      if (c.timed_out) break;
      if (t.cost >= c.best_cost) continue;
      c.cur_stack = t.prefix;
      dfs(c, t.unvis, t.p, t.first, t.slack, t.m, t.cost, t.sum_lam,
          t.dem_left);
    }
    total_nodes = c.nodes;
    any_timeout = c.timed_out;
  } else {
    // Expand roots one level for balance: the cheapest-first root often
    // owns most of the tree, so tasks are (first, second-branch) pairs.
    std::vector<Task> tasks;
    for (auto& t : roots) {
      if (n == 1) { tasks.push_back(t); continue; }
      uint64_t rest = t.unvis;
      while (rest) {
        int j = __builtin_ctzll(rest) + 1;
        rest &= rest - 1;
        if (dem_s[j - 1] <= t.slack) {  // extend the open route
          Task u = t;
          u.key = t.cost + d[t.p * (n + 1) + j];
          u.cost = u.key;
          u.unvis = t.unvis & ~(1ull << (j - 1));
          u.p = j;
          u.slack = t.slack - dem_s[j - 1];
          u.sum_lam = t.sum_lam - lam[j - 1];
          u.dem_left = t.dem_left - dem_s[j - 1];
          u.prefix.push_back(j);
          tasks.push_back(std::move(u));
        }
        if (t.m >= 1 && j > t.first) {  // close + open route at j
          Task u = t;
          u.key = t.cost + d[t.p * (n + 1) + 0] + d[0 * (n + 1) + j];
          u.cost = u.key;
          u.unvis = t.unvis & ~(1ull << (j - 1));
          u.p = j; u.first = j; u.m = t.m - 1;
          u.slack = cap_s - dem_s[j - 1];
          u.sum_lam = t.sum_lam - lam[j - 1];
          u.dem_left = t.dem_left - dem_s[j - 1];
          u.prefix.push_back(-1);
          u.prefix.push_back(j);
          tasks.push_back(std::move(u));
        }
      }
    }
    std::sort(tasks.begin(), tasks.end(),
              [](const Task& a, const Task& b) { return a.key < b.key; });

    std::atomic<size_t> next{0};
    std::atomic<int64_t> nodes_sum{0};
    std::atomic<bool> timeout_any{false};
    size_t per_memo = memo_cap_total / size_t(n_threads);
    auto worker = [&]() {
      Ctx c;
      make_ctx(c, per_memo);
      for (;;) {
        size_t idx = next.fetch_add(1, std::memory_order_relaxed);
        if (idx >= tasks.size()) break;
        if (shared.timed_out.load(std::memory_order_relaxed)) {
          c.timed_out = true;
          break;
        }
        const Task& t = tasks[idx];
        double gb = shared.best_cost.load(std::memory_order_relaxed);
        if (gb < c.best_cost) c.best_cost = gb;
        if (t.cost >= c.best_cost) continue;
        c.cur_stack = t.prefix;
        c.timed_out = false;
        dfs(c, t.unvis, t.p, t.first, t.slack, t.m, t.cost, t.sum_lam,
            t.dem_left);
        if (c.timed_out) break;
      }
      nodes_sum.fetch_add(c.nodes, std::memory_order_relaxed);
      if (c.timed_out) timeout_any.store(true, std::memory_order_relaxed);
    };
    std::vector<std::thread> pool;
    for (int w = 1; w < n_threads; ++w) pool.emplace_back(worker);
    worker();
    for (auto& th : pool) th.join();
    total_nodes = nodes_sum.load();
    any_timeout = timeout_any.load() ||
                  shared.timed_out.load(std::memory_order_relaxed);
  }

  *out_nodes = total_nodes;
  *out_proven = any_timeout ? 0 : 1;
  *out_cost = shared.best_cost.load(std::memory_order_relaxed);
  int len = int(shared.best_seq.size());
  if (len > n + V) len = n + V;
  for (int i = 0; i < len; ++i) out_seq[i] = shared.best_seq[i];
  *out_seq_len = len;
  return 0;
}
