// ng-route relaxation tables for the CVRP lower bound / branch-and-bound.
//
// The q-route machinery in io/bounds.py relaxes route elementarity down
// to 2-cycle elimination: walks may revisit a customer after one
// intermediate hop, which is most of why the X-n200 certificate sat at
// 16-18% (VERDICT round-3 item 4). The ng-route relaxation
// (Baldacci-Mingozzi-Roberti) is strictly finer-grained: every walk
// state carries a MEMORY — the subset of recently-visited customers
// still remembered — and a customer may be revisited only after it has
// been forgotten (dropped by a hop whose neighbor set does not contain
// it). With neighbor sets NG(i) = {i and its g-1 nearest customers},
// elementary routes remain feasible trajectories, so the DP value is a
// valid lower bound, and cheap local cycles (the ones that dominate the
// 2-cycle table) are excluded because nearby customers remember each
// other.
//
// State: B[q][i][M] = min cost of a walk that STARTS at customer i
// (i already visited; collecting nothing for i), collects exactly q
// more scaled demand units from entered customers (each entered j pays
// d[.,j] + lam[j]), and ends at the depot. M is a bitmask over NG(i)'s
// positions (i's own bit always set). Transition (pull form):
//
//   B[q][i][M] = min over customers j with dem_j <= q and j not in M:
//                d[i][j] + lam[j] + B[q - dem_j][j][proj_j(M) | bit_j]
//   B[0][i][M] = d[i][0]
//
// where proj_j(M) re-expresses M's node-set intersected with NG(j) in
// NG(j)'s bit positions (a precomputed per-(i, j) bit remap). Exactly-q
// semantics match the 2-cycle tables, so the outputs are drop-in:
//
//   R[q][i]    = B[q][i][{i}]   (completion table for the B&B pruner —
//                the true completion path from i is elementary, hence a
//                feasible trajectory from memory {i})
//   route_q[q] = min_j d[0][j] + lam[j] + B[q - dem_j][j][{j}]
//                (closed penalized q-routes for the combo/Psi DP)
//
// Neither table dominates the 2-cycle one pointwise (an ng walk may
// 2-cycle through a customer OUTSIDE the neighbor sets), so the Python
// side takes the elementwise MAX of both — each is a valid lower bound.
//
// Complexity: (cap_s+1) * n * 2^g states, n transitions each — ~300M
// simple ops at the X-n200 scale (g=8), a second or two of single-core
// C++; certificates are offline artifacts and the B&B builds tables
// once at the root. Compiled into the same ctypes-loaded library
// family as bnb.cpp (no pybind11 in the image).

#include <cstddef>
#include <cstdint>
#include <vector>

using std::size_t;

namespace {
constexpr double INF = 1e300;
}

extern "C" int ngroute_tables(
    int n,                 // customers
    const double* d,       // (n+1)^2 row-major
    const int64_t* dem,    // n scaled integer demands (>= 1)
    int64_t cap_s,         // scaled capacity (max walk load)
    const double* lam,     // n penalties (entering j costs lam[j-1])
    const int32_t* ng,     // n x g: NG sets as customer ids (1-based);
                           // ng[i*g + .] MUST contain i+1; pad with 0
    int g,                 // memory width (<= 16)
    // outputs
    double* route_q,       // cap_s + 1
    double* R_out) {       // (cap_s + 1) x n, row-major R[q*n + i]
  if (n < 1 || g < 1 || g > 16 || cap_s < 0) return -1;
  const int np1 = n + 1;
  const int masks = 1 << g;
  // size guard INSIDE the library (ADVICE r4): the Python wrapper's
  // _ng_budget_ok is advisory; a direct caller with large cap_s/g must
  // get an error code, not a std::bad_alloc escaping extern "C" into
  // ctypes (which aborts the process). 2e9 doubles ~ 16 GB, far above
  // any budget the wrapper admits (600 MB).
  // computed in double: a size_t product would wrap modulo 2^64 for a
  // huge cap_s and slip PAST the guard (code review r5)
  if (double(cap_s) + 1.0 > 2e9 ||
      (double(cap_s) + 1.0) * double(n) * double(masks) > 2e9)
    return -3;
  try {

  // position of customer id u in NG(i), or -1
  std::vector<int8_t> pos_of(size_t(n) * np1, -1);
  std::vector<int8_t> self_pos(n, -1);
  for (int i = 0; i < n; ++i) {
    for (int p = 0; p < g; ++p) {
      int32_t u = ng[size_t(i) * g + p];
      if (u >= 1 && u <= n) {
        pos_of[size_t(i) * np1 + u] = int8_t(p);
        if (u == i + 1) self_pos[i] = int8_t(p);
      }
    }
    if (self_pos[i] < 0) return -2;  // NG(i) must contain i
  }

  // per-(i, j) bit remap: bit p of a mask at i maps to bit bp[...] at j
  // (or drops). Built once; the hot loop ORs over set bits.
  std::vector<int8_t> bp(size_t(n) * n * g, -1);
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < n; ++j) {
      if (i == j) continue;
      int8_t* row = &bp[(size_t(i) * n + j) * g];
      for (int p = 0; p < g; ++p) {
        int32_t u = ng[size_t(i) * g + p];
        if (u >= 1 && u <= n) row[p] = pos_of[size_t(j) * np1 + u];
      }
    }

  // B layers: two full (n x masks) planes would be wrong — dem_j varies,
  // so keep all q layers (the table IS the output's intermediate).
  std::vector<double> B(size_t(cap_s + 1) * n * masks, INF);
  auto idx = [&](int64_t q, int i, int M) {
    return (size_t(q) * n + i) * masks + M;
  };
  for (int i = 0; i < n; ++i) {
    double home = d[size_t(i + 1) * np1 + 0];
    for (int M = 0; M < masks; ++M) B[idx(0, i, M)] = home;
  }

  for (int64_t q = 1; q <= cap_s; ++q) {
    for (int i = 0; i < n; ++i) {
      const double* di = d + size_t(i + 1) * np1;
      const int8_t* pos_i = &pos_of[size_t(i) * np1];
      for (int M = 0; M < masks; ++M) {
        double best = INF;
        for (int j = 0; j < n; ++j) {
          if (j == i) continue;
          int64_t dj = dem[j];
          if (dj > q) continue;
          int8_t pj = pos_i[j + 1];
          if (pj >= 0 && (M >> pj) & 1) continue;  // j still remembered
          // project M onto NG(j), then remember j
          const int8_t* row = &bp[(size_t(i) * n + j) * g];
          int Mj = 1 << self_pos[j];
          int rest = M;
          while (rest) {
            int p = __builtin_ctz(rest);
            rest &= rest - 1;
            int8_t t = row[p];
            if (t >= 0) Mj |= 1 << t;
          }
          double v = di[j + 1] + lam[j] + B[idx(q - dj, j, Mj)];
          if (v < best) best = v;
        }
        B[idx(q, i, M)] = best;
      }
    }
  }

  // outputs
  for (int64_t q = 0; q <= cap_s; ++q)
    for (int i = 0; i < n; ++i)
      R_out[size_t(q) * n + i] = B[idx(q, i, 1 << self_pos[i])];
  for (int64_t q = 0; q <= cap_s; ++q) {
    double best = INF;
    for (int j = 0; j < n; ++j) {
      int64_t dj = dem[j];
      if (dj > q) continue;
      double v = d[0 * np1 + (j + 1)] + lam[j] +
                 B[idx(q - dj, j, 1 << self_pos[j])];
      if (v < best) best = v;
    }
    route_q[q] = best;  // INF when no walk reaches exactly q
  }
  return 0;
  } catch (...) {
    return -3;  // allocation failure — report, never abort the host
  }
}
