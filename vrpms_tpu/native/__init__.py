"""Native (C++) runtime components, loaded via ctypes.

The accelerator compute path is JAX/XLA/Pallas; the pieces that are
irreducibly host-side and irregular — today the exact branch-and-bound's
tree walk (bnb.cpp) — are C++, compiled on first use into this package
directory with the image's g++ (no pybind11 in the image; the ABI is a
flat extern "C" ctypes surface). Everything degrades gracefully: callers
get None when no toolchain is available and fall back to the Python twin.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "bnb.cpp")
_LIB = os.path.join(_DIR, "libbnb.so")
_lib = None
_load_failed = False
_NG_SRC = os.path.join(_DIR, "ngroute.cpp")
_NG_LIB = os.path.join(_DIR, "libngroute.so")
_ng_lib = None
_ng_load_failed = False


def _build(src: str = _SRC, lib: str = _LIB) -> bool:
    cmd = [
        "g++", "-O2", "-march=native", "-pthread", "-shared", "-fPIC",
        "-o", lib, src,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # no g++ / hung
        print(f"vrpms_tpu.native: build unavailable ({e})", file=sys.stderr)
        return False
    if proc.returncode != 0:
        print(
            f"vrpms_tpu.native: g++ failed:\n{proc.stderr[-2000:]}",
            file=sys.stderr,
        )
        return False
    return True


def load_bnb():
    """The compiled B&B library, building it if stale; None if impossible."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    fresh = os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
    if not fresh and not _build():
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError as e:  # pragma: no cover - corrupt artifact
        print(f"vrpms_tpu.native: load failed ({e})", file=sys.stderr)
        _load_failed = True
        return None
    lib.bnb_solve.restype = ctypes.c_int
    lib.bnb_solve.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int64,
        ctypes.c_double, ctypes.c_double, ctypes.c_int,
        ctypes.c_int,  # n_threads (<= 0: hardware concurrency)
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int),
    ]
    _lib = lib
    return lib


def bnb_solve_native(
    d, dem_s, lam, R, Psi, cap_s, total_s, V,
    best_cost, time_limit_s, symmetric, n_threads: int = 0,
):
    """Run the native DFS -> (routes | None, cost, nodes, proven) or None
    when the library cannot be built/loaded. `routes` is None when the
    search found nothing better than `best_cost` (the caller keeps its
    incumbent). n_threads 0 = hardware concurrency (the parallel engine
    splits the forest into depth-2 subtree tasks with a shared atomic
    incumbent); 1 = the sequential walk."""
    lib = load_bnb()
    if lib is None:
        return None
    n = len(dem_s)
    d = np.ascontiguousarray(d, np.float64)
    dem = np.ascontiguousarray(dem_s, np.int64)
    lam = np.ascontiguousarray(lam, np.float64)
    R = np.ascontiguousarray(R, np.float64)
    Psi = np.ascontiguousarray(Psi, np.float64)
    out_seq = np.zeros(n + V + 2, np.int32)
    out_len = ctypes.c_int(0)
    out_cost = ctypes.c_double(0.0)
    out_nodes = ctypes.c_int64(0)
    out_proven = ctypes.c_int(0)
    rc = lib.bnb_solve(
        n, V, int(cap_s), d, dem, lam, R, Psi, int(Psi.shape[0]), int(total_s),
        float(best_cost) if np.isfinite(best_cost) else 1e300,
        -1.0 if time_limit_s is None else float(time_limit_s),
        1 if symmetric else 0,
        int(n_threads),
        out_seq, ctypes.byref(out_len), ctypes.byref(out_cost),
        ctypes.byref(out_nodes), ctypes.byref(out_proven),
    )
    if rc != 0:
        return None
    routes = None
    if out_len.value > 0:
        routes, cur = [], []
        for v in out_seq[: out_len.value]:
            if v == -1:
                routes.append(cur)
                cur = []
            else:
                cur.append(int(v))
        routes.append(cur)
    return routes, float(out_cost.value), int(out_nodes.value), bool(out_proven.value)


def load_ngroute():
    """The compiled ng-route table builder; None when unavailable."""
    global _ng_lib, _ng_load_failed
    if _ng_lib is not None:
        return _ng_lib
    if _ng_load_failed:
        return None
    fresh = os.path.exists(_NG_LIB) and os.path.getmtime(
        _NG_LIB
    ) >= os.path.getmtime(_NG_SRC)
    if not fresh and not _build(_NG_SRC, _NG_LIB):
        _ng_load_failed = True
        return None
    try:
        lib = ctypes.CDLL(_NG_LIB)
    except OSError as e:  # pragma: no cover - corrupt artifact
        print(f"vrpms_tpu.native: ngroute load failed ({e})", file=sys.stderr)
        _ng_load_failed = True
        return None
    lib.ngroute_tables.restype = ctypes.c_int
    lib.ngroute_tables.argtypes = [
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    _ng_lib = lib
    return lib


def ngroute_tables_native(d, dem_s, lam, ng_sets, cap_s):
    """Native ng-route DP -> (route_q[cap_s+1], R[(cap_s+1), n]) or None
    when the library cannot be built/loaded. `ng_sets` is an (n, g)
    int32 array of 1-based customer ids; row i must contain i+1."""
    lib = load_ngroute()
    if lib is None:
        return None
    n = len(dem_s)
    d = np.ascontiguousarray(d, np.float64)
    dem = np.ascontiguousarray(dem_s, np.int64)
    lam = np.ascontiguousarray(lam, np.float64)
    ng = np.ascontiguousarray(ng_sets, np.int32)
    g = ng.shape[1]
    route_q = np.zeros(int(cap_s) + 1, np.float64)
    R = np.zeros((int(cap_s) + 1, n), np.float64)
    rc = lib.ngroute_tables(n, d, dem, int(cap_s), lam, ng, g, route_q, R)
    if rc != 0:
        return None
    return route_q, R
