"""Test/chaos support utilities (stdlib-only, importable anywhere).

Currently: the declarative fault-plan DSL (testing.faults) that drives
the fault-injecting store wrapper (store.faulty) and the chaos
benchmark (benchmarks.chaos_latency). Lives in the library package —
not under tests/ — because the service selects it at runtime via
`VRPMS_STORE=faulty:<plan>`.
"""

from vrpms_tpu.testing.faults import FaultInjector, FaultPlan, StoreFault, parse_plan

__all__ = ["FaultInjector", "FaultPlan", "StoreFault", "parse_plan"]
