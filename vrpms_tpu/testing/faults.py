"""Declarative fault plans: the chaos DSL behind `VRPMS_STORE=faulty:<plan>`.

A plan is a small `;`/`,`-separated token string describing how store
calls should misbehave, so degradation paths can be exercised from
tests, benchmarks, and a live shell without code changes:

    down                         every matched call fails
    fail=3                       the first 3 matched calls fail, then succeed
    rate=0.25                    each matched call fails with probability 0.25
    latency=0.05                 fixed sleep (seconds) before every matched call
    jitter=0.02                  extra uniform [0, jitter) sleep on top
    hang=30                      sleep this long before answering (a "hung"
                                 backend — per-call deadlines must cut it)
    ops=reads|writes|all         which operations the faults apply to (default all)
    seed=7                       RNG seed for rate/jitter (deterministic runs)

Examples: `fail=2;latency=0.01`, `rate=0.3;jitter=0.05;ops=writes`,
`down;ops=reads`, `hang=30`.

Injected failures raise StoreFault — an ordinary Exception, so they
surface through the store seam's normal error envelopes exactly like a
real backend error would. Stdlib-only by design.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

OPS = ("reads", "writes", "all")


class StoreFault(Exception):
    """An injected store failure (the fault plan said so)."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Parsed fault plan; all-zero defaults mean "no faults"."""

    down: bool = False
    fail_n: int = 0
    rate: float = 0.0
    latency_s: float = 0.0
    jitter_s: float = 0.0
    hang_s: float = 0.0
    ops: str = "all"
    seed: int = 0

    def matches(self, op: str) -> bool:
        return self.ops == "all" or self.ops == op + "s"


def parse_plan(text: str | None) -> FaultPlan:
    """Parse the DSL; raises ValueError with the offending token so a
    typo'd VRPMS_STORE=faulty:<plan> fails loudly at store construction
    (an ignored plan would silently test nothing)."""
    fields: dict = {}
    for token in (text or "").replace(",", ";").split(";"):
        token = token.strip()
        if not token:
            continue
        key, sep, value = token.partition("=")
        key = key.strip()
        value = value.strip()
        try:
            if key == "down" and not sep:
                fields["down"] = True
            elif key == "fail":
                fields["fail_n"] = int(value)
            elif key == "rate":
                fields["rate"] = float(value)
                if not 0.0 <= fields["rate"] <= 1.0:
                    raise ValueError(value)
            elif key == "latency":
                fields["latency_s"] = float(value)
            elif key == "jitter":
                fields["jitter_s"] = float(value)
            elif key == "hang":
                fields["hang_s"] = float(value)
            elif key == "ops":
                if value not in OPS:
                    raise ValueError(value)
                fields["ops"] = value
            elif key == "seed":
                fields["seed"] = int(value)
            else:
                raise ValueError(key)
        except ValueError:
            raise ValueError(
                f"bad fault-plan token {token!r} (plan {text!r}); see "
                "vrpms_tpu.testing.faults for the DSL"
            ) from None
        if any(v < 0 for v in fields.values() if isinstance(v, (int, float))
               and not isinstance(v, bool)):
            raise ValueError(
                f"fault-plan token {token!r} must be non-negative (plan {text!r})"
            )
    return FaultPlan(**fields)


class FaultInjector:
    """Applies a FaultPlan to a stream of calls (thread-safe).

    One injector per plan per process (store.faulty keeps the registry)
    so "fail the first N calls" counts across the per-request store
    instances the service constructs.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._seq = 0
        self._rng = random.Random(plan.seed)
        self.calls = 0
        self.faults = 0

    def apply(self, op: str) -> None:
        """Sleep/raise per the plan for one call of kind `op`
        ("read" | "write"); a no-op for unmatched ops."""
        plan = self.plan
        if not plan.matches(op):
            return
        with self._lock:
            seq = self._seq
            self._seq += 1
            self.calls += 1
            roll = self._rng.random()
            jitter = self._rng.random() * plan.jitter_s
        delay = plan.latency_s + jitter + plan.hang_s
        if delay > 0:
            time.sleep(delay)
        if plan.down or seq < plan.fail_n or roll < plan.rate:
            with self._lock:
                self.faults += 1
            raise StoreFault(
                f"injected fault ({op} call #{seq}, plan: down={plan.down} "
                f"fail_n={plan.fail_n} rate={plan.rate})"
            )
