"""Crash-resumable solves (ISSUE 15 acceptance): durable checkpoints,
resume-from-incumbent reclaim, and graceful replica drain.

Layers, bottom up: the store checkpoint seam (put/get/delete keyed by
job id + attempt, fail-open under fault plans), the background
checkpointer's capture/flush/hygiene, VRPMS_CKPT=off fixed-seed
byte-identity, and the cross-replica acceptance gates with REAL solves
— kill-mid-flight resume (attempt=2 under the original trace id, first
published incumbent never worse than the checkpoint, exactly-once
publish), kill-mid-decomposition resuming only unfinished shards, and
graceful drain (checkpoint-and-nack to a peer with no burned attempt,
heartbeat deregistered, drain state on the surfaces).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

import store
import store.memory as mem
from service import checkpoint as ckpt_mod
from service import jobs as jobs_mod
from store.faulty import reset_faults
from store.resilient import reset_resilience
from vrpms_tpu.sched import Replica, Scheduler
from vrpms_tpu.sched.ring import SLOTS, HashRing

SMALL_LADDER = "n=8,16,32;v=1,2,4,8;t=1"


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.setenv("VRPMS_CKPT_MS", "5")
    mem.reset()
    reset_faults()
    reset_resilience()
    ckpt_mod.reset()
    yield
    jobs_mod.shutdown_scheduler()
    ckpt_mod.reset()
    mem.reset()
    reset_faults()
    reset_resilience()


def _wait(cond, timeout=60.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return cond()


def _seed_dataset(key, n, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _solve_content(key, n, seed=1, **over):
    content = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"ckpt-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": 200,
        "populationSize": 8,
    }
    content.update(over)
    return content


# ---------------------------------------------------------------------------
# Store seam units
# ---------------------------------------------------------------------------


class TestCheckpointSeam:
    def test_put_get_latest_attempt_delete(self):
        db = store.get_database("vrp", None)
        assert db.get_checkpoint("j1") is None
        assert db.put_checkpoint("j1", 1, {"cost": 10.0})
        assert db.put_checkpoint("j1", 2, {"cost": 7.0})
        row = db.get_checkpoint("j1")
        assert row["attempt"] == 2 and row["state"]["cost"] == 7.0
        assert db.delete_checkpoint("j1")
        assert db.get_checkpoint("j1") is None

    def test_memory_table_is_bounded(self):
        db = store.get_database("vrp", None)
        cap = mem._InMemoryMixin.MAX_CHECKPOINTS
        for i in range(cap + 10):
            db.put_checkpoint(f"j{i}", 1, {"i": i})
        with mem._lock:
            assert len(mem._tables["checkpoints"]) == cap

    def test_fail_open_under_down_plan(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        monkeypatch.setenv("VRPMS_RESILIENCE", "off")
        db = store.get_database("vrp", None)
        # never raises: a checkpoint store outage must cost nothing
        assert db.put_checkpoint("j1", 1, {"cost": 1.0}) is False
        assert db.get_checkpoint("j1") is None
        assert db.delete_checkpoint("j1") is False

    def test_queue_nack_note_merges_into_payload(self):
        qs = store.get_queue_store()
        qs.enqueue({"id": "e1", "slot": 0, "payload": {"content": {}}})
        entry = qs.claim("r1", lease_s=30.0)
        assert entry["id"] == "e1"
        assert qs.nack("r1", "e1", {"ckpt": True})
        again = qs.claim("r1", lease_s=30.0)
        assert again["payload"]["ckpt"] is True
        assert again["payload"]["content"] == {}
        assert again["attempt"] == 0  # a nack never burns an attempt

    def test_deregister_replica_removes_heartbeat(self):
        qs = store.get_queue_store()
        qs.register_replica("r1", ttl_s=60.0)
        qs.register_replica("r2", ttl_s=60.0)
        qs.deregister_replica("r1")
        assert qs.replicas() == ["r2"]


# ---------------------------------------------------------------------------
# Capture + hygiene on the local async path (real solves)
# ---------------------------------------------------------------------------


class _FakeHandler:
    _request_id = "req-ckpt"
    _trace = None
    _trace_root = None


def _submit_local(content, box):
    """Drive the async submit pipeline headless; fills `box` with the
    (code, body) the handler would have written."""
    saved = jobs_mod._respond

    def capture(handler, code, body):
        box.update(code=code, body=body)

    jobs_mod._respond = capture
    try:
        errors: list = []
        ctx = jobs_mod._parse_content(content, errors)
        assert ctx is not None, errors
        jobs_mod._submit_parsed(_FakeHandler(), ctx)
    finally:
        jobs_mod._respond = saved
    return box


class TestCaptureAndHygiene:
    def test_deadline_solve_writes_then_terminal_deletes(self):
        _seed_dataset("ck9", 9)
        box: dict = {}
        _submit_local(
            _solve_content(
                "ck9", 9, iterationCount=600_000, timeLimit=90.0
            ),
            box,
        )
        assert box["code"] == 202, box
        jid = box["body"]["jobId"]
        db = store.get_database("vrp", None)

        def has_row():
            row = db.get_checkpoint(jid)
            return bool(row and row["state"].get("routes"))

        assert _wait(has_row, timeout=60), "no checkpoint was written"
        row = db.get_checkpoint(jid)
        state = row["state"]
        assert state["problem"] == "vrp" and state["algorithm"] == "sa"
        visited = sorted(c for r in state["routes"] for c in r)
        assert visited == list(range(1, 9))
        assert state["cost"] > 0 and state["elapsedMs"] > 0
        job = jobs_mod.get_live_job(jid)
        assert job is not None and job.wait(timeout=60)
        # terminal hygiene: the rows disappear (background delete)
        assert _wait(lambda: db.get_checkpoint(jid) is None, timeout=10)

    def test_off_means_no_rows_and_no_handle(self, monkeypatch):
        monkeypatch.setenv("VRPMS_CKPT", "off")
        _seed_dataset("ck7", 7)
        box: dict = {}
        _submit_local(
            _solve_content("ck7", 7, iterationCount=400, timeLimit=5.0),
            box,
        )
        jid = box["body"]["jobId"]
        job = jobs_mod.get_live_job(jid)
        assert job is not None
        assert job.sink is None or job.sink.ckpt is None
        assert job.wait(timeout=60)
        with mem._lock:
            assert mem._tables["checkpoints"] == {}

    def test_short_solves_never_pay_a_write(self, monkeypatch):
        # bounded cadence: a solve shorter than VRPMS_CKPT_MS captures
        # nothing — the zero-overhead contract for interactive traffic
        monkeypatch.setenv("VRPMS_CKPT_MS", "600000")
        _seed_dataset("ck7b", 7)
        box: dict = {}
        _submit_local(_solve_content("ck7b", 7, iterationCount=200), box)
        job = jobs_mod.get_live_job(box["body"]["jobId"])
        assert job is not None and job.wait(timeout=60)
        with mem._lock:
            assert mem._tables["checkpoints"] == {}


class TestOffByteIdentity:
    def test_fixed_seed_response_identical_on_and_off(self, monkeypatch):
        # capture only READS the synced state, so VRPMS_CKPT=off and on
        # must produce byte-identical fixed-seed responses (cache off:
        # the second run must SOLVE, not serve the first run's entry)
        monkeypatch.setenv("VRPMS_CACHE", "off")
        monkeypatch.setenv("VRPMS_CKPT_MS", "0")  # capture every block
        _seed_dataset("ckid", 8)
        results = {}
        for mode in ("on", "off"):
            monkeypatch.setenv("VRPMS_CKPT", mode)
            jobs_mod.shutdown_scheduler()
            box: dict = {}
            _submit_local(
                _solve_content("ckid", 8, seed=5, iterationCount=600),
                box,
            )
            job = jobs_mod.get_live_job(box["body"]["jobId"])
            assert job is not None and job.wait(timeout=120)
            assert job.status == "done", job.errors
            results[mode] = json.dumps(job.result, sort_keys=True)
        assert results["on"] == results["off"]


# ---------------------------------------------------------------------------
# Cross-replica resume with REAL solves (the acceptance gates)
# ---------------------------------------------------------------------------


def _service_replica(rid, **kw):
    sched = Scheduler(
        jobs_mod._runner,
        queue_limit=64,
        window_s=0.005,
        max_batch=8,
        on_event=jobs_mod._on_event,
        watchdog_s=0,
    )
    defaults = dict(
        lease_s=1.0, poll_s=0.01, heartbeat_s=0.1, reclaim_s=0.05,
        vnodes=16, steal=False,
    )
    defaults.update(kw)
    rep = Replica(
        store.get_queue_store(),
        rid,
        materialize=lambda e: jobs_mod._materialize_entry(e, rid),
        submit=lambda job: sched.submit(
            job, backend=job.payload.get("backend") or "default"
        ),
        complete=jobs_mod._dist_complete,
        dead=jobs_mod._dist_dead,
        **defaults,
    )
    rep._test_scheduler = sched
    return rep


def _pin_slot(ring, target, start=0):
    return next(
        s for s in range(start, SLOTS, 191) if ring.owner(s) == target
    )


def _entry_for(content, slot, trace_id=None):
    job_id = uuid.uuid4().hex[:16]
    payload = {
        "content": content,
        "requestId": f"req-{job_id[:6]}",
        "problem": "vrp",
        "algorithm": "sa",
    }
    if trace_id is not None:
        payload["traceparent"] = (
            f"00-{trace_id}-{uuid.uuid4().hex[:16]}-01"
        )
    return {
        "id": job_id,
        "slot": slot,
        "bucket": "ckpt-tier",
        "time_limit": content.get("timeLimit"),
        "submitted_at": time.time(),
        "payload": payload,
    }


def _teardown(replicas):
    for rep in replicas:
        rep.kill()
    for rep in replicas:
        rep._test_scheduler.shutdown(timeout=0.5)


class TestResumeReclaim:
    def test_kill_mid_flight_resumes_from_checkpoint(self, monkeypatch):
        """The flagship gate: a replica dies mid-solve at a block
        boundary; the peer reclaims at attempt=2 under the ORIGINAL
        trace id, seeds from the durable checkpoint, and its first
        published incumbent is never worse than the checkpoint cost —
        with exactly-once publication."""
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_CKPT_MS", "5")
        _seed_dataset("ckr9", 9)
        qs = store.get_queue_store()
        victim = _service_replica("victim", lease_s=0.8)
        rescuer = _service_replica("rescuer", lease_s=0.8)
        qs.register_replica("victim", 60.0)
        qs.register_replica("rescuer", 60.0)
        ring = HashRing(["victim", "rescuer"], vnodes=16)
        tid = uuid.uuid4().hex
        # iteration-bound anneal (~seconds) under a GENEROUS wall
        # budget: the budget must survive a slow cold compile on a
        # loaded 1-core box, while the kill window (first checkpoint ->
        # iteration bound) stays seconds wide
        entry = _entry_for(
            _solve_content(
                "ckr9", 9, seed=3,
                iterationCount=600_000, timeLimit=90.0,
            ),
            _pin_slot(ring, "victim"),
            trace_id=tid,
        )
        jid = entry["id"]
        qs.enqueue(entry)
        victim.start()
        rescuer.start()
        db = store.get_database("vrp", None)

        def ckpt_ready():
            row = db.get_checkpoint(jid)
            return bool(row and row["state"].get("routes"))

        try:
            assert _wait(ckpt_ready, timeout=90), "no checkpoint written"
            ckpt_cost = db.get_checkpoint(jid)["state"]["cost"]
            vic_job = jobs_mod.get_live_job(jid)
            victim.kill()
            if vic_job is not None and vic_job.sink is not None:
                # free the single CPU core for the rescuer's resume
                # (the orphaned solve would otherwise burn its budget)
                vic_job.sink.cancel()

            def done():
                rec = db.get_job_seed(jid)
                return rec is not None and rec.get("status") == "done"

            assert _wait(done, timeout=120), db.get_job_seed(jid)
            time.sleep(0.5)  # let any stray duplicate publication land
        finally:
            _teardown([victim, rescuer])
        rec = db.get_job_seed(jid)
        assert rec["status"] == "done"
        assert rec["attempt"] == 2, rec  # the reclaimed generation
        assert rec["traceId"] == tid  # crash continuity: SAME trace
        visited = sorted(
            c for v in rec["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == list(range(1, 9))
        # first published incumbent of attempt 2 is the checkpoint
        # itself (the resume seeds the sink), so it can never be worse
        improvements = rec["progress"]["improvements"]
        assert improvements[0].get("resumed") is True
        assert improvements[0]["bestCost"] == pytest.approx(ckpt_cost)
        costs = [s["bestCost"] for s in improvements]
        assert costs == sorted(costs, reverse=True) or len(costs) == 1
        assert rec["message"]["durationSum"] > 0
        assert qs.depth() == 0  # exactly-once: nothing left behind


class TestResumeDecomposition:
    def test_kill_mid_decomposition_resumes_unfinished_shards(
        self, monkeypatch
    ):
        """A giant decomposed solve dies after completing some shards;
        the peer's attempt=2 restores those from the checkpoint and
        solves ONLY the remaining shards before stitching."""
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        monkeypatch.setenv("VRPMS_SCHED_MAX_BATCH", "1")
        monkeypatch.setenv("VRPMS_CKPT_MS", "1")
        from vrpms_tpu.io.synth import synth_clustered_coords

        n = 61
        coords, demands = synth_clustered_coords(n, 4, seed=3)
        d = np.linalg.norm(coords[:, None] - coords[None, :], axis=-1)
        mem.seed_locations(
            "ckg",
            [
                {"id": i, "demand": float(demands[i]) if i else 0}
                for i in range(n)
            ],
        )
        mem.seed_durations("ckg", d.tolist())
        cap = float(np.ceil(demands.sum() * 1.3 / 6))
        content = {
            "problem": "vrp",
            "algorithm": "sa",
            "solutionName": "ckpt-giant",
            "solutionDescription": "t",
            "locationsKey": "ckg",
            "durationsKey": "ckg",
            "capacities": [cap] * 6,
            "startTimes": [0.0] * 6,
            "ignoredCustomers": [],
            "completedCustomers": [],
            "seed": 7,
            # iteration-bound, NO timeLimit: on this 1-core container
            # the first tier-32 compile alone can eat a wall budget
            # before the reclaim even lands (the remaining-budget
            # semantics are covered by TestResumeReclaim); ~seconds per
            # shard leaves a wide kill window between the two chunks
            "iterationCount": 300_000,
            "populationSize": 16,
        }
        qs = store.get_queue_store()
        victim = _service_replica("victim", lease_s=0.8)
        rescuer = _service_replica("rescuer", lease_s=0.8)
        qs.register_replica("victim", 60.0)
        qs.register_replica("rescuer", 60.0)
        ring = HashRing(["victim", "rescuer"], vnodes=16)
        entry = _entry_for(content, _pin_slot(ring, "victim"))
        entry["bucket"] = None  # decomposed: no ring token
        jid = entry["id"]
        qs.enqueue(entry)
        victim.start()
        rescuer.start()
        db = store.get_database("vrp", None)

        def shard_ckpt():
            row = db.get_checkpoint(jid)
            return bool(row and row["state"].get("shards"))

        try:
            assert _wait(shard_ckpt, timeout=120), "no shard checkpoint"
            n_done = len(db.get_checkpoint(jid)["state"]["shards"])
            vic_job = jobs_mod.get_live_job(jid)
            victim.kill()
            if vic_job is not None and vic_job.sink is not None:
                vic_job.sink.cancel()

            def done():
                rec = db.get_job_seed(jid)
                return rec is not None and rec.get("status") == "done"

            assert _wait(done, timeout=180), db.get_job_seed(jid)
        finally:
            _teardown([victim, rescuer])
        rec = db.get_job_seed(jid)
        assert rec["status"] == "done" and rec["attempt"] == 2, rec
        decomp = rec["message"]["decomposition"]
        assert decomp["resumedShards"] >= 1
        assert decomp["resumedShards"] >= n_done
        visited = sorted(
            c for v in rec["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == list(range(1, n))
        assert qs.depth() == 0


class TestDrain:
    def test_drain_checkpoints_and_nacks_to_peer(self, monkeypatch):
        """Graceful drain: the draining replica stops claiming, flushes
        the job's checkpoint, nacks WITHOUT burning an attempt, marks
        the payload resumable, deregisters its heartbeat — and the peer
        completes the job exactly-once from the checkpoint."""
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_CKPT_MS", "5")
        _seed_dataset("ckd9", 9)
        qs = store.get_queue_store()
        victim = _service_replica("victim", lease_s=5.0)
        qs.register_replica("victim", 60.0)
        ring = HashRing(["victim"], vnodes=16)
        entry = _entry_for(
            _solve_content(
                "ckd9", 9, seed=4,
                iterationCount=600_000, timeLimit=90.0,
            ),
            _pin_slot(ring, "victim"),
        )
        jid = entry["id"]
        qs.enqueue(entry)
        victim.start()
        db = store.get_database("vrp", None)

        def ckpt_ready():
            row = db.get_checkpoint(jid)
            return bool(row and row["state"].get("routes"))

        rescuer = None
        try:
            assert _wait(ckpt_ready, timeout=90), "no checkpoint written"
            nacked = victim.drain(
                grace_s=0.1, requeue=jobs_mod._drain_requeue
            )
            assert nacked == 1
            assert victim.draining
            # heartbeat deregistered immediately, not TTL-expired
            assert "victim" not in qs.replicas()
            # the entry is queued again with NO burned attempt and the
            # resumable marker a claimant probes the checkpoint on
            with mem._lock:
                row = mem._tables["job_queue"][jid]
                assert row["state"] == "queued"
                assert row["attempt"] == 0
                assert row["payload"]["ckpt"] is True
            rescuer = _service_replica("rescuer", lease_s=5.0, steal=True)
            qs.register_replica("rescuer", 60.0)
            rescuer.start()

            def done():
                rec = db.get_job_seed(jid)
                return rec is not None and rec.get("status") == "done"

            assert _wait(done, timeout=120), db.get_job_seed(jid)
            time.sleep(0.5)
        finally:
            _teardown([victim] + ([rescuer] if rescuer else []))
        rec = db.get_job_seed(jid)
        assert rec["status"] == "done"
        # a drain hand-off is voluntary: attempt 1, not a crash reclaim
        assert rec.get("attempt") in (None, 1), rec
        improvements = rec["progress"]["improvements"]
        assert improvements[0].get("resumed") is True
        visited = sorted(
            c for v in rec["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == list(range(1, 9))
        assert qs.depth() == 0

    def test_drain_with_room_lets_jobs_finish_and_ack(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        _seed_dataset("ckd7", 7)
        qs = store.get_queue_store()
        rep = _service_replica("solo", lease_s=5.0)
        qs.register_replica("solo", 60.0)
        ring = HashRing(["solo"], vnodes=16)
        entry = _entry_for(
            _solve_content("ckd7", 7, iterationCount=200),
            _pin_slot(ring, "solo"),
        )
        qs.enqueue(entry)
        rep.start()
        db = store.get_database("vrp", None)
        try:
            assert _wait(
                lambda: (db.get_job_seed(entry["id"]) or {}).get("status")
                == "done"
                or rep.inflight() > 0,
                timeout=60,
            )
            nacked = rep.drain(
                grace_s=60.0, requeue=jobs_mod._drain_requeue
            )
            assert nacked == 0  # everything finished inside the grace
            rec = db.get_job_seed(entry["id"])
            assert rec is not None and rec["status"] == "done"
            assert qs.depth() == 0
        finally:
            _teardown([rep])


class TestDrainHTTP:
    @pytest.fixture()
    def server(self):
        from service.app import serve

        jobs_mod.shutdown_scheduler()
        srv = serve(port=0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{port}"
        srv.shutdown()
        jobs_mod.shutdown_scheduler()

    @staticmethod
    def _get(base, path):
        try:
            with urllib.request.urlopen(base + path, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    @staticmethod
    def _post(base, path, body):
        req = urllib.request.Request(
            base + path,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_drain_endpoint_flips_surfaces_and_sheds_submits(self, server):
        # a solve rebuilds the scheduler (a prior test's shutdown left
        # readiness legitimately 'down' until then) — then: not draining
        _seed_dataset("ckh7", 7)
        status, resp = self._post(
            server, "/api/vrp/sa", _solve_content("ckh7", 7)
        )
        assert status == 200, resp
        status, resp = self._get(server, "/api/ready")
        assert status == 200 and "draining" not in resp
        status, resp = self._post(server, "/api/admin/drain", {})
        assert status == 202 and resp["drain"]["draining"] is True
        # idempotent: a second POST reports, never restarts
        status, resp = self._post(server, "/api/admin/drain", {})
        assert status == 202
        status, resp = self._get(server, "/api/ready")
        assert status == 200
        assert resp["status"] == "degraded" and resp["draining"] is True
        status, resp = self._get(server, "/api/debug/fleet")
        assert status == 200
        assert resp["fleet"]["draining"]["draining"] is True
        # new async submits shed: a draining replica takes nothing new
        status, resp = self._post(
            server, "/api/jobs",
            _solve_content("ckh7", 7),
        )
        assert status == 503, resp
        assert resp["errors"][0]["what"] == "Service unavailable"
        # a rebuilt service (tests, embedders) starts undrained
        jobs_mod.shutdown_scheduler()
        status, resp = self._get(server, "/api/ready")
        assert "draining" not in resp


# ---------------------------------------------------------------------------
# Local watchdog-requeue resume (single process, no shared queue)
# ---------------------------------------------------------------------------


class TestLocalWatchdogResume:
    def test_requeued_job_seeds_from_checkpoint(self, monkeypatch):
        """The in-process half of the resume contract: a watchdog-
        requeued Job (its Prepared survived) applies the checkpoint —
        warm perm, continuation marker, remaining budget."""
        _seed_dataset("ckw9", 9)
        box: dict = {}
        # a LONG iteration bound: the job must still be running when
        # the requeue + resume assertions run (it is cooperatively
        # cancelled at the end, so the test never waits it out)
        _submit_local(
            _solve_content(
                "ckw9", 9, iterationCount=4_000_000, timeLimit=90.0
            ),
            box,
        )
        jid = box["body"]["jobId"]
        db = store.get_database("vrp", None)

        def has_row():
            row = db.get_checkpoint(jid)
            return bool(row and row["state"].get("routes"))

        assert _wait(has_row, timeout=60)
        job = jobs_mod.get_live_job(jid)
        assert job is not None
        state = db.get_checkpoint(jid)["state"]
        # simulate the watchdog's requeue transition, then apply
        assert job.reopen_for_requeue()
        ckpt_mod.apply_local_resume(job)
        prep = job.payload["prep"]
        assert prep.warm is not None
        assert prep.resolve == {
            "seedSource": "checkpoint", "seeded": True,
        }
        assert job.payload["ckpt_elapsed_s"] == pytest.approx(
            state["elapsedMs"] / 1e3
        )
        # the live solve is still burning the old budget; cancel it and
        # let the scheduler wind down in the fixture teardown
        if job.sink is not None:
            job.sink.cancel()
