"""Test bootstrap: force CPU with a virtual 8-device mesh.

The container's sitecustomize registers the TPU PJRT plugin at
interpreter startup and the environment pins JAX_PLATFORMS to it, so env
vars set here are too late for platform selection — but backends
initialise lazily, so `jax.config.update` before the first operation
still wins. XLA_FLAGS *is* read at CPU-backend creation, so the virtual
8-device flag works from here as long as no jax op ran yet.

This is the mesh-without-hardware strategy from SURVEY.md §4: shard_map /
ppermute island logic gets CI coverage with no TPU attached.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
