"""Test bootstrap: force CPU with a virtual 8-device mesh.

The container's sitecustomize registers the TPU PJRT plugin at
interpreter startup and the environment pins JAX_PLATFORMS to it, so env
vars set here are too late for platform selection — but backends
initialise lazily, so `jax.config.update` before the first operation
still wins. XLA_FLAGS *is* read at CPU-backend creation, so the virtual
8-device flag works from here as long as no jax op ran yet.

This is the mesh-without-hardware strategy from SURVEY.md §4: shard_map /
ppermute island logic gets CI coverage with no TPU attached.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True, scope="session")
def _hermetic_rate_cache(tmp_path_factory):
    """Point the persisted sweep-rate hint cache at a per-session
    throwaway file. Hints recorded by PREVIOUS runs on this machine are
    often compile-polluted (a rate measured across a cold jit compile
    understates the true rate by orders of magnitude) and they change
    the deadline drivers' block decomposition — which the job-timing
    tests (busy-worker blockers) and the fixed-seed identity tests all
    depend on. The suite must see the same empty cache CI sees."""
    from vrpms_tpu.solvers import common

    path = tmp_path_factory.mktemp("rates") / "sweep_rates.json"
    prior = os.environ.get("VRPMS_RATE_CACHE")
    os.environ["VRPMS_RATE_CACHE"] = str(path)
    common._SWEEP_RATE.clear()
    common._RATE_LOADED = False
    yield
    if prior is None:
        os.environ.pop("VRPMS_RATE_CACHE", None)
    else:
        os.environ["VRPMS_RATE_CACHE"] = prior


# ---------------------------------------------------------------------------
# quick/slow split: `-m quick` is the sub-2-minute iteration gate (exactness,
# contract, parsing, kernel-equivalence tests); the full suite (~12 min, incl.
# the quality/convergence/end-to-end solves below) remains the round gate.
# Node-id patterns keep the policy in one place at file/class granularity so
# individual test renames don't silently change buckets.
# ---------------------------------------------------------------------------

_SLOW_PATTERNS = (
    # quality/convergence-heavy solver suites
    "test_delta_ls.py",
    "test_islands.py",
    "test_ils.py",
    "test_multihost.py",
    "test_sa.py::TestSA",
    "test_ga_aco.py",
    "test_knn_moves.py::TestKnnQuality",
    "test_pallas_eval.py",
    # multi-second solves inside otherwise-quick suites; for the
    # parametrized equivalence families one representative stays quick
    "test_core_cost.py::TestPropertyVsOracle::test_matches_naive_eval[3",
    "test_core_cost.py::TestPropertyVsOracle::test_matches_naive_eval[1-True]",
    "test_split_hot.py::TestGreedySplitHot::test_matches_scan_split[33-5-21]",
    "test_split_hot.py::TestGreedySplitHot::test_matches_scan_split[19-3-14]",
    "test_split_hot.py::TestGreedySplitHot::test_fitness_fn_hot_matches_gather",
    "test_split_hot.py::TestGreedySplitHot::test_oversize_customer_rides_alone",
    "test_moves_split.py::TestSplit::test_optimal_not_worse_than_greedy",
    "test_moves_split.py::TestSplit::test_greedy_giant_consistent",
    "test_moves_split.py::TestMoves::test_random_move_preserves_validity",
    "test_split_hot.py::TestGaOperatorsHot::test_hot_generation_evolves_and_stays_valid",
    "test_makespan.py::TestMakespanObjective::test_solve_sa_reduces_makespan",
    "test_onehot.py::TestSAOnehotMode",
    "test_io.py::TestSolomon::test_solvable_feasible",
    "test_io.py::TestCVRPLIB::test_solvable",
    "test_bf_local_search.py::TestBruteForce::test_vrp_matches_itertools",
    "test_bf_local_search.py::TestBruteForce::test_vrp_tw_runs_and_beats_random",
    "test_bf_local_search.py::TestBruteForce::test_deadline_none_and_generous_agree",
    "test_bf_local_search.py::TestBruteForce::test_deadline_zero_truncates_but_returns_valid",
    "test_bf_local_search.py::TestLocalSearch",
    "test_bounds.py::TestValidity",
    "test_het_fleet.py::TestHetBF",
    "test_het_fleet.py::TestHetMetaheuristics",
    "test_perturb.py::TestRuinRecreate::test_ils_reseed_ruin_mode_runs",
    # end-to-end HTTP solves (the envelope/contract tests stay quick)
    "test_concurrency.py",
    "test_progress.py::TestStreamHTTP",
    "test_progress.py::TestCancellationHTTP",
    "test_progress.py::TestBatchedProgress",
    "test_progress.py::TestProgressOffContract",
    "test_service.py::TestObservabilitySolve",
    "test_service.py::TestVRPSolve",
    "test_service.py::TestTSPSolve",
    "test_service.py::TestTimedPaths",
    "test_service.py::TestErrorEnvelope::test_non_finite_or_negative_matrix_rejected",
    "test_service.py::TestErrorEnvelope::test_tsp_duplicate_customers_deduped",
    "test_makespan.py::TestServiceMakespan",
    "test_warmstart.py::TestWarmStartHTTP",
    # 3 solves incl. a 500-iteration cache warmer; the rest of the
    # cache suite stays quick (and tier1.yml runs the file in full)
    "test_cache.py::TestNearHit::test_never_loses_to_cold_start",
    # distributed-queue end-to-end layers: real cross-replica solves +
    # the HTTP surface (ring/lease/replica units stay quick; tier1.yml
    # runs the file in full)
    "test_distqueue.py::TestCrossReplicaChaos",
    "test_distqueue.py::TestClaimKCrossReplica",
    "test_distqueue.py::TestServiceDistHTTP",
    # QoS end-to-end HTTP layers: real solves behind blockers (the
    # unit/store/fast-fail layers stay quick; tier1.yml runs the file
    # in full)
    "test_qos.py::TestQosHTTP",
    "test_qos.py::TestQosDistHTTP",
    "test_qos.py::TestQosOffGuard",
    # fleet-observability end-to-end layers: the federated HTTP
    # surfaces, real cross-replica solves, and chaos requests (the
    # exporter/seam units stay quick; tier1.yml runs the file in full)
    "test_trace_export.py::TestFederatedHTTP",
    "test_trace_export.py::TestCrossReplicaFederation",
    "test_trace_export.py::TestExportChaos",
    # crash-resume end-to-end layers: real kill/drain solves across
    # in-process replicas + the HTTP drain surface (the store-seam and
    # hygiene units stay quick; tier1.yml runs the file in full)
    "test_checkpoint.py::TestResumeReclaim",
    "test_checkpoint.py::TestResumeDecomposition",
    "test_checkpoint.py::TestDrain",
    "test_checkpoint.py::TestDrainHTTP",
    "test_checkpoint.py::TestLocalWatchdogResume",
    "test_checkpoint.py::TestCaptureAndHygiene",
    "test_checkpoint.py::TestOffByteIdentity",
    "test_chaos.py::TestCheckpointChaos",
    # dynamic re-solve end-to-end solves (unit/envelope layers stay
    # quick; tier1.yml runs the file in full)
    "test_resolve.py::TestDeltaHTTP",
    "test_resolve.py::TestWarmStartSpec",
    "test_resolve.py::TestResolveEndpoint",
    "test_utils_info.py::TestSolveInfo",
    "test_fixtures.py::TestSolverBand",
    "test_sa_delta.py::TestDeltaStepKernel::test_many_steps_zero_drift_and_valid_tours",
    "test_sa_delta.py::TestSolveSaDelta",
    # TW delta kernel: the always-accept trajectory test stays quick as
    # the representative; the rest are interpret-mode solves
    "test_sa_delta_tw.py::TestTwDeltaKernel::test_metropolis_never_accepts_worse_at_zero_temp",
    "test_sa_delta_tw.py::TestTwDeltaKernel::test_uniform_window_without_knn",
    "test_sa_delta_tw.py::TestSolveSaDeltaTw::test_solve_level_driver",
    # pipelined-dispatch byte-identity pairs: real SA/GA/ACO solves run
    # twice per case (the launch-sequence/deferral units stay quick;
    # tier1.yml runs the file in full)
    "test_pipeline.py::TestByteIdentity",
    # standing-subscription end-to-end layers: real generation solves,
    # SSE replay, crash-resume, and the off-switch byte-identity pair
    # (compose/store/contract/quota/adoption units stay quick;
    # tier1.yml runs the file in full)
    "test_subscriptions.py::TestGenerationsE2E",
    "test_subscriptions.py::TestStreamSSE",
    "test_subscriptions.py::TestResumeHandoff",
    "test_subscriptions.py::TestOffGuard",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        if any(p in item.nodeid for p in _SLOW_PATTERNS):
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.quick)
