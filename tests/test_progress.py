"""Live solve observability (ISSUE 7): incumbent snapshots, SSE
streaming, gap telemetry, cooperative cancellation.

Unit layers (quick): the ProgressSink/ProgressFanout contract —
monotone non-increasing published costs, the gap formula against the
quick lower bound, cancel semantics — plus the solver-seam guarantees:
fixed-seed results are BIT-identical with a sink attached vs not, and
a deadline-bounded solve publishes at block cadence.

End-to-end layers (slow, via conftest patterns; tier1.yml runs the
file in full): the /api/jobs/{id}/stream SSE surface (≥1 intermediate
incumbent before the terminal event, framing, client disconnect
mid-stream), per-job snapshots for micro-batched jobs, DELETE
cancellation returning the incumbent marked cancelled, and the
VRPMS_PROGRESS=off byte-identity contract.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import store.memory as mem
from service import jobs as jobs_mod
from service.app import serve
from vrpms_tpu.core import make_instance
from vrpms_tpu.io.bounds import quick_lower_bound
from vrpms_tpu.obs import progress


# ---------------------------------------------------------------------------
# unit: the sink contract
# ---------------------------------------------------------------------------


class TestProgressSink:
    def test_publishes_only_improvements_and_stays_monotone(self):
        sink = progress.ProgressSink(lower_bound=None)
        sink.record(np.asarray([50.0, 60.0]), 128, 2)
        first = sink.snapshot()
        assert first["bestCost"] == 50.0 and first["block"] == 1
        assert first["evals"] == 256
        sink.record(np.asarray([55.0]), 128, 2)  # worse: not published
        assert sink.snapshot()["block"] == 1
        sink.record(np.asarray([40.0]), 128, 2)  # better: published
        snap = sink.snapshot()
        assert snap["bestCost"] == 40.0 and snap["block"] == 3
        assert snap["evals"] == 3 * 256  # skipped blocks still count
        prof = sink.profile()
        assert prof["blocks"] == 3
        costs = [s["bestCost"] for s in prof["improvements"]]
        assert costs == sorted(costs, reverse=True)

    def test_gap_is_relative_to_lower_bound(self):
        sink = progress.ProgressSink(lower_bound=100.0)
        sink.record(np.asarray([125.0]), 1, None)
        assert sink.snapshot()["gap"] == pytest.approx(0.25)
        unbounded = progress.ProgressSink(lower_bound=None)
        unbounded.record(np.asarray([125.0]), 1, None)
        assert unbounded.snapshot()["gap"] is None

    def test_wait_progress_wakes_on_publish_and_close(self):
        sink = progress.ProgressSink()
        seq, snap, closed = sink.wait_progress(0, timeout=0.01)
        assert seq == 0 and snap is None and not closed
        sink.record(np.asarray([9.0]), 1, None)
        seq, snap, closed = sink.wait_progress(0, timeout=5)
        assert seq == 1 and snap["bestCost"] == 9.0 and not closed
        sink.close("done")
        seq, snap, closed = sink.wait_progress(seq, timeout=5)
        assert closed and sink.status == "done"

    def test_fanout_splits_rows_per_job(self):
        a, b = progress.ProgressSink(), progress.ProgressSink()
        fan = progress.ProgressFanout([a, None, b])
        best = np.asarray([[7.0, 9.0], [1.0, 1.0], [3.0, 5.0]])
        fan.record(best, 512, 6.0)  # 6 evals/iter over 3 rows -> 2 each
        assert a.snapshot()["bestCost"] == 7.0
        assert b.snapshot()["bestCost"] == 3.0
        assert a.snapshot()["evals"] == 1024

    def test_fanout_cancel_requires_every_member(self):
        a, b = progress.ProgressSink(), progress.ProgressSink()
        fan = progress.ProgressFanout([a, b])
        a.cancel()
        assert not fan.cancelled  # one job's DELETE spares batch-mates
        b.cancel()
        assert fan.cancelled
        # acknowledgement fans out to the cancelled members
        fan.note_cancel_seen()
        assert a.cancel_acknowledged and b.cancel_acknowledged

    def test_cancelled_mark_requires_driver_acknowledgement(self):
        # a cancel the driver never got to act on (deadline-free solve
        # already inside its single block) must NOT claim a cut-short
        # run: only a driver break acknowledges
        sink = progress.ProgressSink()
        sink.cancel()
        assert sink.cancelled and not sink.cancel_acknowledged
        with progress.attach(sink):
            assert progress.cancel_requested()  # a driver breaking...
        assert sink.cancel_acknowledged  # ...is the acknowledgement

    def test_attach_contextvar(self):
        assert progress.active_sink() is None
        sink = progress.ProgressSink()
        with progress.attach(sink):
            assert progress.active_sink() is sink
            assert not progress.cancel_requested()
            sink.cancel()
            assert progress.cancel_requested()
        assert progress.active_sink() is None
        with progress.attach(None):
            assert progress.active_sink() is None


# ---------------------------------------------------------------------------
# gap sanity: the quick bound vs the exact oracle (test_bounds-style)
# ---------------------------------------------------------------------------


class TestQuickLowerBound:
    def test_vrp_bound_below_bf_optimum(self, rng):
        from vrpms_tpu.solvers import solve_vrp_bf

        for _ in range(3):
            n = int(rng.integers(5, 8))
            pts = rng.uniform(0, 100, (n + 1, 2))
            d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
            inst = make_instance(
                d, demands=[0] + [2] * n, capacities=[2 * n] * 3
            )
            lb = quick_lower_bound(inst)
            opt = float(solve_vrp_bf(inst).cost)
            assert lb is not None and 0 < lb <= opt + 1e-6

    def test_tsp_bound_below_bf_optimum(self, rng):
        from vrpms_tpu.solvers import solve_tsp_bf

        pts = rng.uniform(0, 100, (7, 2))
        d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
        inst = make_instance(d, n_vehicles=1)
        lb = quick_lower_bound(inst)
        opt = float(solve_tsp_bf(inst).cost)
        assert lb is not None and 0 < lb <= opt + 1e-6

    def test_padded_instance_bound_stays_valid(self, rng):
        # the sink computes its bound on the TIER-PADDED instance the
        # solver actually runs; phantoms are zero-cost depot aliases,
        # so the bound must still sit below the REAL optimum
        from vrpms_tpu.core import tiers
        from vrpms_tpu.solvers import solve_vrp_bf

        pts = rng.uniform(0, 100, (7, 2))
        d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
        inst = make_instance(d, demands=[0] + [2] * 6, capacities=[12.0] * 3)
        opt = float(solve_vrp_bf(inst).cost)
        padded = tiers.maybe_pad(inst)
        lb = quick_lower_bound(padded)
        assert lb is not None and 0 < lb <= opt + 1e-6

    def test_never_raises(self):
        # telemetry bound must answer None, not raise, on junk
        inst = make_instance(np.zeros((2, 2)), n_vehicles=1)
        assert quick_lower_bound(inst) in (None,) or isinstance(
            quick_lower_bound(inst), float
        )


# ---------------------------------------------------------------------------
# solver seam: byte-identity + block cadence + cooperative cancel
# ---------------------------------------------------------------------------


def small_cvrp(seed=5, n=9):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    return make_instance(d, demands=[0] + [2] * (n - 1), capacities=[8.0] * 3)


class TestSolverSeam:
    def test_fixed_seed_results_bit_identical_with_sink(self):
        import jax.numpy as jnp

        from vrpms_tpu.solvers import SAParams, solve_sa

        inst = small_cvrp()
        p = SAParams(n_chains=32, n_iters=600)
        plain = solve_sa(inst, key=7, params=p)
        with progress.attach(progress.ProgressSink(lower_bound=10.0)):
            sunk = solve_sa(inst, key=7, params=p)
        assert bool(jnp.array_equal(plain.giant, sunk.giant))
        assert float(plain.cost) == float(sunk.cost)
        # deadline path too (generous budget: same block decomposition).
        # Identical decompositions need identical RATE-HINT state: the
        # first deadline solve of a shape records a measured rate (and a
        # cold-compile run records a badly understated one), which would
        # let the second solve open fitted instead of probing — so pin
        # both solves to an empty hint table.
        from vrpms_tpu.solvers import common as solver_common

        solver_common._SWEEP_RATE.clear()
        plain_d = solve_sa(inst, key=7, params=p, deadline_s=3600.0)
        sink = progress.ProgressSink(lower_bound=10.0)
        solver_common._SWEEP_RATE.clear()
        with progress.attach(sink):
            sunk_d = solve_sa(inst, key=7, params=p, deadline_s=3600.0)
        assert bool(jnp.array_equal(plain_d.giant, sunk_d.giant))
        assert sink.snapshot() is not None

    def test_deadline_solve_publishes_at_block_cadence(self):
        from vrpms_tpu.solvers import SAParams, solve_sa

        inst = small_cvrp()
        sink = progress.ProgressSink(
            lower_bound=quick_lower_bound(inst)
        )
        with progress.attach(sink):
            solve_sa(
                inst, key=3,
                params=SAParams(n_chains=32, n_iters=1200),
                deadline_s=3600.0,
            )
        prof = sink.profile()
        assert prof is not None and prof["blocks"] >= 2
        snap = sink.snapshot()
        assert snap["gap"] is not None and snap["gap"] >= -1e-6
        # gap consistency with io.bounds: invert the published formula
        implied = snap["bestCost"] / (1.0 + snap["gap"])
        assert implied == pytest.approx(sink.lower_bound, rel=1e-4)

    def test_cancel_between_blocks_returns_incumbent_early(self):
        from vrpms_tpu.core.encoding import is_valid_giant
        from vrpms_tpu.solvers import SAParams, solve_sa

        inst = small_cvrp()
        sink = progress.ProgressSink()
        # cancel as soon as the first snapshot lands
        def cancel_on_first():
            sink.wait_progress(0, timeout=60)
            sink.cancel()

        t = threading.Thread(target=cancel_on_first, daemon=True)
        t.start()
        t0 = time.monotonic()
        with progress.attach(sink):
            res = solve_sa(
                inst, key=3,
                params=SAParams(n_chains=32, n_iters=50_000_000),
                deadline_s=3600.0,
            )
        t.join(timeout=10)
        assert time.monotonic() - t0 < 120  # nowhere near the budget
        assert is_valid_giant(res.giant, 8, 3)
        assert float(res.cost) == pytest.approx(
            sink.snapshot()["bestCost"], rel=1e-3
        )


# ---------------------------------------------------------------------------
# end-to-end HTTP (slow lane; tier1.yml runs these in its own step)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    jobs_mod.shutdown_scheduler()  # fresh scheduler under this env
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


@pytest.fixture(autouse=True)
def seeded():
    mem.reset()
    rng = np.random.default_rng(11)
    pts = rng.uniform(0, 100, size=(7, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "locs7", [{"id": i, "demand": 2 if i else 0} for i in range(7)]
    )
    mem.seed_durations("locs7", d.tolist())
    yield


def request(base, method, path, body=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"} if body else {},
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def job_body(**over):
    body = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": "prog",
        "solutionDescription": "t",
        "locationsKey": "locs7",
        "durationsKey": "locs7",
        "capacities": [14, 14, 14],
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": 2000,
        "populationSize": 16,
    }
    body.update(over)
    return body


def poll_done(base, job_id, timeout=180.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = request(base, "GET", f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


def read_sse(base, job_id, timeout=180.0):
    """Collect (event, payload) pairs until a terminal event."""
    events = []
    req = urllib.request.Request(base + f"/api/jobs/{job_id}/stream")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        assert resp.headers.get("Content-Type", "").startswith(
            "text/event-stream"
        )
        name = None
        for raw in resp:
            line = raw.decode("utf-8").rstrip("\n")
            if line.startswith("event: "):
                name = line[len("event: "):]
            elif line.startswith("data: "):
                events.append((name, json.loads(line[len("data: "):])))
                if name in ("done", "failed", "timeout"):
                    break
    return events


class TestStreamHTTP:
    def test_stream_delivers_intermediate_incumbent_then_done(self, server):
        # budgeted multi-block solve: enough iterations that the
        # deadline loop runs several 512-blocks inside the budget
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=5_000_000, timeLimit=4.0),
        )
        assert status == 202, resp
        events = read_sse(server, resp["jobId"])
        kinds = [k for k, _ in events]
        assert kinds[-1] == "done", kinds
        prog = [p for k, p in events if k == "progress"]
        assert len(prog) >= 1  # ≥1 intermediate incumbent before done
        costs = [p["bestCost"] for p in prog]
        assert costs == sorted(costs, reverse=True)  # monotone
        # every snapshot's gap inverts to the SAME lower bound
        implied = {
            round(p["bestCost"] / (1.0 + p["gap"]), 3)
            for p in prog
            if p.get("gap") is not None
        }
        assert len(implied) <= 1
        record = events[-1][1]
        assert record["status"] == "done"
        assert record["incumbent"]["bestCost"] == pytest.approx(
            costs[-1]
        )
        assert record["message"]["durationSum"] > 0
        assert record["progress"]["blocks"] >= 1

    def test_poll_overlays_live_incumbent_and_persists_profile(self, server):
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=5_000_000, timeLimit=4.0, seed=3),
        )
        assert status == 202, resp
        jid = resp["jobId"]
        saw_running_incumbent = False
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            status, r = request(server, "GET", f"/api/jobs/{jid}")
            job = r["job"]
            if job["status"] in ("done", "failed"):
                break
            if job.get("incumbent") is not None:
                saw_running_incumbent = True
            time.sleep(0.05)
        record = poll_done(server, jid)
        assert record["status"] == "done"
        # the terminal record persists the final incumbent + profile
        assert record.get("incumbent") is not None
        assert record.get("progress", {}).get("blocks", 0) >= 1
        # live overlay is timing-dependent but should virtually always
        # land with a 4 s budget and 50 ms polls
        assert saw_running_incumbent

    def test_stream_of_finished_job_replays_then_terminates(self, server):
        status, resp = request(server, "POST", "/api/jobs", job_body())
        jid = resp["jobId"]
        poll_done(server, jid)
        events = read_sse(server, jid)
        kinds = [k for k, _ in events]
        # replay-first contract holds for store-backed follows too: at
        # most the final incumbent, then the terminal event — and a
        # terminal record is NEVER misreported
        assert kinds[-1] == "done" and set(kinds[:-1]) <= {"progress"}
        assert events[-1][1]["status"] == "done"

    def test_stream_of_unowned_running_record_never_reports_failed(
        self, server
    ):
        # cross-replica view: a record another process owns (no live
        # Job here) that is still RUNNING must never be streamed as
        # `failed` — the handler follows the store until it actually
        # turns terminal, replaying persisted incumbents as they land
        import store

        db = store.get_database("vrp", None)
        db.save_job("foreign01", {
            "id": "foreign01", "status": "running",
            "incumbent": {"block": 2, "wallMs": 5.0, "bestCost": 42.0,
                          "gap": None, "evals": 10},
        })

        def other_replica_finishes():
            time.sleep(3.0)
            db.save_job("foreign01", {
                "id": "foreign01", "status": "done",
                "message": {"ok": True},
                "incumbent": {"block": 3, "wallMs": 9.9, "bestCost": 41.0,
                              "gap": None, "evals": 20},
            })

        threading.Thread(target=other_replica_finishes, daemon=True).start()
        events = read_sse(server, "foreign01", timeout=60)
        kinds = [k for k, _ in events]
        assert "failed" not in kinds
        assert kinds[-1] == "done"
        assert [p["block"] for k, p in events if k == "progress"] == [2, 3]

    def test_stream_unknown_job_404(self, server):
        status, resp = request(
            server, "GET", "/api/jobs/nosuchjob/stream"
        )
        assert status == 404
        assert resp["success"] is False

    def test_client_disconnect_mid_stream_leaves_solve_unharmed(
        self, server
    ):
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=5_000_000, timeLimit=4.0, seed=5),
        )
        assert status == 202, resp
        jid = resp["jobId"]
        host, port = server.replace("http://", "").split(":")
        sock = socket.create_connection((host, int(port)), timeout=10)
        sock.sendall(
            f"GET /api/jobs/{jid}/stream HTTP/1.1\r\n"
            f"Host: {host}\r\nConnection: close\r\n\r\n".encode()
        )
        sock.recv(512)  # response headers started streaming
        sock.close()  # hang up mid-stream
        record = poll_done(server, jid)
        assert record["status"] == "done"  # the solve never noticed
        # and the service still serves: a fresh stream works end to end
        events = read_sse(server, jid)
        assert events[-1][0] == "done"


class TestCancellationHTTP:
    def test_delete_returns_incumbent_marked_cancelled(self, server):
        t0 = time.monotonic()
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=50_000_000, timeLimit=120.0, seed=2),
        )
        assert status == 202, resp
        jid = resp["jobId"]
        # wait for the first published incumbent, then cancel
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            _, r = request(server, "GET", f"/api/jobs/{jid}")
            if r["job"].get("incumbent") or r["job"]["status"] in (
                "done", "failed",
            ):
                break
            time.sleep(0.05)
        status, r = request(server, "DELETE", f"/api/jobs/{jid}")
        assert status == 202 and r["cancelRequested"] is True
        record = poll_done(server, jid)
        assert record["status"] == "done"
        assert record["message"].get("cancelled") is True
        assert record.get("incumbent") is not None
        assert time.monotonic() - t0 < 90  # nowhere near the 120 s budget

    def test_delete_finished_job_is_noop(self, server):
        status, resp = request(server, "POST", "/api/jobs", job_body())
        jid = resp["jobId"]
        poll_done(server, jid)
        status, r = request(server, "DELETE", f"/api/jobs/{jid}")
        assert status == 200 and r["cancelRequested"] is False

    def test_delete_unknown_job_404(self, server):
        status, r = request(server, "DELETE", "/api/jobs/missing")
        assert status == 404


class TestBatchedProgress:
    def test_batched_jobs_get_per_job_snapshots(self, server, monkeypatch):
        import os

        # widen the gather window so the three same-bucket submits
        # reliably merge into one vmapped launch
        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_SCHED_WINDOW_MS", "200")
        ids = []
        for seed in (1, 2, 3):
            status, resp = request(
                server, "POST", "/api/jobs",
                job_body(seed=seed, iterationCount=3000, timeLimit=5.0),
            )
            assert status == 202, resp
            ids.append(resp["jobId"])
        records = [poll_done(server, jid) for jid in ids]
        jobs_mod.shutdown_scheduler()  # restore default window
        assert any((r.get("batchSize") or 1) > 1 for r in records)
        for r in records:
            assert r["status"] == "done", r
            assert r.get("incumbent") is not None
            assert r["incumbent"]["bestCost"] == pytest.approx(
                r["message"]["durationSum"], rel=0.25
            )


class TestProgressOffContract:
    def test_off_restores_pre_progress_records_and_bytes(
        self, server, monkeypatch
    ):
        # cache off: the second identical solve must actually solve
        # (an exact cache hit would serve the first response and mask
        # any solver-trajectory difference)
        monkeypatch.setenv("VRPMS_CACHE", "off")
        body = job_body(seed=9)  # deadline-free: deterministic blocks

        monkeypatch.setenv("VRPMS_PROGRESS", "off")
        status, resp = request(server, "POST", "/api/jobs", body)
        assert status == 202, resp
        rec_off = poll_done(server, resp["jobId"])
        assert "incumbent" not in rec_off
        assert "progress" not in rec_off

        monkeypatch.delenv("VRPMS_PROGRESS", raising=False)
        status, resp = request(server, "POST", "/api/jobs", body)
        rec_on = poll_done(server, resp["jobId"])
        # progress on adds record keys but the SOLVE RESULT is
        # byte-identical for the fixed seed
        assert json.dumps(rec_on["message"], sort_keys=True) == json.dumps(
            rec_off["message"], sort_keys=True
        )

    def test_off_means_no_sink_and_no_cancel(self, server, monkeypatch):
        monkeypatch.setenv("VRPMS_PROGRESS", "off")
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=100_000, timeLimit=5.0, seed=4),
        )
        assert status == 202, resp
        jid = resp["jobId"]
        # a DELETE while running (or queued) answers 409 Not cancellable;
        # if the job already finished, the no-op 200 applies instead
        status, r = request(server, "DELETE", f"/api/jobs/{jid}")
        assert status in (200, 409)
        record = poll_done(server, jid)
        assert record["status"] == "done"
        assert "incumbent" not in record
