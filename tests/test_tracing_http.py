"""End-to-end request tracing over real HTTP (ISSUE 5 acceptance).

A request submitted with a W3C `traceparent` through the async jobs
path returns a `stats.spans` waterfall covering >= 95% of the job's
measured end-to-end wall time with distinct queue-wait / solve / store
spans, and the same trace is retrievable from
GET /api/debug/traces/{traceId}. Plus: traceparent echo on responses,
malformed-header hardening over HTTP (never a 500), request/trace ids
on EVERY error path (400, 404, 429, 503), span continuity across a
worker crash + watchdog requeue, store-retry spans under an injected
fault plan, and a Prometheus-text parse guard for /metrics with
exemplars present.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import store
import store.memory as mem
from service import jobs as jobs_mod
from service.app import serve
from vrpms_tpu.obs import spans

GOOD_TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


@pytest.fixture(autouse=True)
def seeded():
    mem.reset()
    spans.reset_ring()
    rng = np.random.default_rng(7)
    n = 7
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "locs7", [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations("locs7", d.tolist())
    yield


def post(base, path, body, headers=None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(), headers=hdrs,
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def solve_body(**over):
    body = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": "trace-test",
        "solutionDescription": "t",
        "locationsKey": "locs7",
        "durationsKey": "locs7",
        "capacities": [14, 14, 14],
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": 1500,
        "populationSize": 16,
    }
    body.update(over)
    return body


def poll_until_done(base, job_id, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp, _ = get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


def ring_detail(base, trace_id, timeout=5.0):
    """The trace lands in the ring at the job's terminal transition —
    allow the handful of milliseconds between poll and push."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp, _ = get(base, f"/api/debug/traces/{trace_id}")
        if status == 200:
            return resp["trace"]
        time.sleep(0.02)
    raise AssertionError(f"trace {trace_id} never reached the ring")


class TestJobsPathWaterfall:
    def test_traceparent_to_stats_spans_and_debug_ring(self, server):
        status, resp, headers = post(
            server, "/api/jobs",
            solve_body(includeStats=True),
            headers={"traceparent": GOOD_TP},
        )
        assert status == 202, resp
        # the submitted trace id is adopted and echoed: envelope + header
        assert resp["traceId"] == "ab" * 16
        assert resp["requestId"]
        echoed = headers["traceparent"]
        tid, _ = spans.parse_traceparent(echoed)
        assert tid == "ab" * 16

        job = poll_until_done(server, resp["jobId"])
        assert job["status"] == "done", job
        assert job["traceId"] == "ab" * 16

        stats = job["message"]["stats"]
        assert stats["traceId"] == "ab" * 16
        waterfall = stats["spans"]
        names = [s["name"] for s in waterfall]
        # distinct queue-wait / solve / store spans (acceptance)
        assert "queue.wait" in names
        assert "solve" in names
        assert any(n.startswith("store.") for n in names)
        by_name = {s["name"]: s for s in waterfall}
        # >= 95% coverage of the measured end-to-end wall time: the job
        # record's own clocks are the measurement; queue wait + solve
        # are the spans that must account for it
        e2e_ms = (job["finishedAt"] - job["submittedAt"]) * 1e3
        covered = job["queueWaitMs"] + by_name["solve"]["durationMs"]
        assert covered >= 0.95 * e2e_ms, (covered, e2e_ms, names)
        # the solve span carries its scheduler context
        attrs = by_name["solve"]["attributes"]
        assert attrs["batchSize"] >= 1 and attrs["attempt"] == 1
        # the remote header's span id parents the root
        assert waterfall[0]["parentId"] == "cd" * 8

        # the same trace, full tree, from the debug surface
        detail = ring_detail(server, "ab" * 16)
        detail_names = [s["name"] for s in detail["spans"]]
        for required in ("queue.wait", "solve", "solver.solve", "prepare"):
            assert required in detail_names, detail_names
        assert detail["status"] == "ok"
        # and the ring listing can filter it
        status, resp, _ = get(server, "/api/debug/traces?minMs=1")
        assert status == 200
        assert any(t["traceId"] == "ab" * 16 for t in resp["traces"])
        status, resp, _ = get(
            server, "/api/debug/traces?minMs=10000000"
        )
        assert all(t["traceId"] != "ab" * 16 for t in resp["traces"])

    def test_sync_endpoint_stats_spans(self, server):
        status, resp, headers = post(
            server, "/api/vrp/sa", solve_body(includeStats=True),
        )
        assert status == 200, resp
        tid = resp["traceId"]
        assert re.fullmatch(r"[0-9a-f]{32}", tid)
        stats = resp["message"]["stats"]
        names = [s["name"] for s in stats["spans"]]
        assert "queue.wait" in names and "solve" in names
        assert any(n.startswith("store.") for n in names)
        # convergence telemetry joins the span tree as block events
        solve_spans = [s for s in stats["spans"] if s["name"] == "solver.solve"]
        assert solve_spans and any(
            e["name"] == "block" for e in solve_spans[0].get("events", [])
        )
        # sync traces finish at respond time: already retrievable
        detail = ring_detail(server, tid)
        assert detail["traceId"] == tid

    def test_block_events_absent_without_include_stats(self, server):
        status, resp, _ = post(server, "/api/vrp/sa", solve_body())
        assert status == 200, resp
        detail = ring_detail(server, resp["traceId"])
        solver = [s for s in detail["spans"] if s["name"] == "solver.solve"]
        assert solver and not solver[0].get("events")


class TestTraceparentEdgeCasesHTTP:
    @pytest.mark.parametrize(
        "header",
        [
            "garbage",
            "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",
            "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",
            "00-" + "ab" * 2000 + "-" + "cd" * 8 + "-01",
        ],
    )
    def test_malformed_header_gets_fresh_trace_never_500(
        self, server, header
    ):
        status, resp, _ = post(
            server, "/api/jobs", solve_body(iterationCount=200),
            headers={"traceparent": header},
        )
        assert status == 202, resp  # hardening: never a 500
        tid = resp["traceId"]
        assert re.fullmatch(r"[0-9a-f]{32}", tid)
        assert tid != "ab" * 16  # fresh, not adopted
        assert poll_until_done(server, resp["jobId"])["status"] == "done"


class TestErrorEnvelopesCarryIds:
    def test_400_carries_ids(self, server):
        status, resp, _ = post(
            server, "/api/jobs", {"problem": "vrp"},
            headers={"traceparent": GOOD_TP},
        )
        assert status == 400
        assert resp["requestId"] and resp["traceId"] == "ab" * 16

    def test_404_job_poll_carries_ids(self, server):
        status, resp, _ = get(
            server, "/api/jobs/no-such-job",
            headers={"traceparent": GOOD_TP},
        )
        assert status == 404
        assert resp["requestId"] and resp["traceId"] == "ab" * 16

    def test_429_queue_full_carries_ids(self, server):
        import os

        jobs_mod.shutdown_scheduler()
        os.environ["VRPMS_SCHED_QUEUE"] = "1"
        try:
            # blocker occupies the worker, next job fills the 1-slot
            # queue, the one after must shed 429 WITH ids
            status, resp, _ = post(
                server, "/api/jobs",
                solve_body(iterationCount=500_000, populationSize=64,
                           timeLimit=3, seed=9),
            )
            assert status == 202, resp
            time.sleep(0.3)
            status, resp, _ = post(
                server, "/api/jobs", solve_body(seed=10)
            )
            assert status == 202, resp
            status, resp, headers = post(
                server, "/api/jobs", solve_body(seed=11),
                headers={"traceparent": GOOD_TP},
            )
            assert status == 429, resp
            assert resp["requestId"] and resp["traceId"] == "ab" * 16
            assert "Retry-After" in headers
            # the sync endpoints shed with ids too
            status, resp, _ = post(
                server, "/api/vrp/sa", solve_body(seed=12),
                headers={"traceparent": GOOD_TP},
            )
            assert status == 429, resp
            assert resp["requestId"] and resp["traceId"] == "ab" * 16
        finally:
            os.environ.pop("VRPMS_SCHED_QUEUE", None)
            jobs_mod.shutdown_scheduler()

    def test_503_down_carries_ids(self, server):
        # drain the scheduler: readiness reports down until a new
        # submit lazily rebuilds it
        jobs_mod.shutdown_scheduler()
        try:
            status, resp, _ = get(
                server, "/api/ready", headers={"traceparent": GOOD_TP}
            )
            assert status == 503, resp
            assert resp["status"] == "down"
            assert resp["requestId"] and resp["traceId"] == "ab" * 16
            # without a traceparent the 503 still carries the requestId
            status, resp, _ = get(server, "/api/ready")
            assert status == 503
            assert resp["requestId"]
        finally:
            # next submit rebuilds a fresh scheduler for later tests
            status, resp, _ = post(
                server, "/api/jobs", solve_body(iterationCount=100)
            )
            assert status == 202, resp
            poll_until_done(server, resp["jobId"])


class TestCrashContinuity:
    def test_requeued_job_parents_under_the_same_trace(
        self, server, monkeypatch
    ):
        """A worker crash mid-solve + watchdog requeue: the second
        attempt's spans land in the SAME trace — two queue.wait spans
        (the retry marked requeued), a second solve span with
        attempt=2, and the job.requeued lifecycle event on the root."""
        import os

        jobs_mod.shutdown_scheduler()
        monkeypatch.setitem(os.environ, "VRPMS_SCHED_WATCHDOG_MS", "30")
        real = jobs_mod.solve_prepared
        crashed = []

        def crash_once(prep, errors):
            if not crashed:
                crashed.append(1)
                raise SystemExit("induced worker death")  # thread dies
            return real(prep, errors)

        monkeypatch.setattr(jobs_mod, "solve_prepared", crash_once)
        try:
            status, resp, _ = post(
                server, "/api/jobs", solve_body(seed=21),
                headers={"traceparent": GOOD_TP},
            )
            assert status == 202, resp
            job = poll_until_done(server, resp["jobId"])
            assert job["status"] == "done", job
            assert crashed  # the first attempt really died

            detail = ring_detail(server, "ab" * 16)
            names = [s["name"] for s in detail["spans"]]
            waits = [s for s in detail["spans"] if s["name"] == "queue.wait"]
            solves = [s for s in detail["spans"] if s["name"] == "solve"]
            assert len(waits) == 2, names
            assert waits[1]["attributes"].get("requeued") is True
            assert len(solves) == 2, names
            # attempt 1 died mid-span (no duration); attempt 2 finished
            attempts = sorted(
                s["attributes"]["attempt"] for s in solves
            )
            assert attempts == [1, 2]
            done = [s for s in solves if s["attributes"]["attempt"] == 2]
            assert done[0]["durationMs"] is not None
            root = detail["spans"][0]
            assert any(
                e["name"] == "job.requeued" for e in root.get("events", [])
            )
        finally:
            jobs_mod.shutdown_scheduler()


class TestStoreFaultSpans:
    def test_injected_read_faults_record_retry_events(self, monkeypatch):
        """The resilient wrapper's spans carry the retry storm: a
        fail-twice fault plan (vrpms_tpu.testing.faults) produces a
        store span with two retry events and a success on attempt 3."""
        from store.faulty import reset_faults
        from store.resilient import reset_resilience

        reset_faults()
        reset_resilience()
        monkeypatch.setenv("VRPMS_STORE", "faulty:fail=2;ops=reads")
        monkeypatch.setenv("VRPMS_STORE_BACKOFF_S", "0.001")
        trace = spans.Trace()
        tokens = spans.activate(trace, trace.span("root"))
        try:
            db = store.get_database("vrp", None)
            errors: list = []
            db.get_locations_by_id("locs7", errors)
            assert not errors
        finally:
            spans.deactivate(tokens)
            reset_faults()
            reset_resilience()
        store_spans = [
            s for s in trace.waterfall() if s["name"] == "store.resilient"
        ]
        assert store_spans, [s["name"] for s in trace.waterfall()]
        sp = store_spans[0]
        assert sp["attributes"]["op"] == "read"
        assert sp["attributes"]["attempts"] == 3
        retries = [
            e for e in sp.get("events", []) if e["name"] == "store.retry"
        ]
        assert len(retries) == 2

    def test_store_down_serves_degraded_with_fallback_span(
        self, monkeypatch
    ):
        from store.faulty import reset_faults
        from store.resilient import reset_resilience

        reset_faults()
        reset_resilience()
        # warm the fallback cache while healthy, then go down
        monkeypatch.setenv("VRPMS_STORE", "faulty:")
        db = store.get_database("vrp", None)
        errors: list = []
        db.get_locations_by_id("locs7", errors)
        assert not errors
        monkeypatch.setenv("VRPMS_STORE", "faulty:down;ops=reads")
        monkeypatch.setenv("VRPMS_STORE_BACKOFF_S", "0.001")
        trace = spans.Trace()
        tokens = spans.activate(trace, trace.span("root"))
        try:
            db = store.get_database("vrp", None)
            db.get_locations_by_id("locs7", errors)
            assert not errors
            assert db.degraded
        finally:
            spans.deactivate(tokens)
            reset_faults()
            reset_resilience()
        sp = [
            s for s in trace.waterfall() if s["name"] == "store.resilient"
        ][0]
        assert sp["attributes"]["fallback"] == "cache"
        assert sp["attributes"]["degraded"] is True


# the exposition line grammar: `name{labels} value` with an optional
# OpenMetrics exemplar suffix `# {labels} value`; label values are
# quoted strings that may themselves contain braces ("/api/jobs/{id}")
_LABELS = r'\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\}'
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"               # metric name
    rf"({_LABELS})?"                           # optional labels
    r" (-?[0-9.eE+]+|\+Inf|-Inf|NaN)"          # value
    rf"( # {_LABELS} (-?[0-9.eE+]+|\+Inf))?$"  # optional exemplar
)


class TestMetricsParseGuard:
    @staticmethod
    def _parse(text, allow_exemplars):
        seen_types: dict = {}
        exemplars = 0
        for line in text.splitlines():
            if not line or line == "# EOF":
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(" ", 3)
                assert len(parts) >= 3, line
                if parts[1] == "TYPE":
                    assert parts[3] in (
                        "counter", "gauge", "histogram", "untyped",
                        "unknown",
                    ), line
                    seen_types[parts[2]] = parts[3]
                continue
            assert _METRIC_LINE.match(line), f"unparseable line: {line!r}"
            if "# {" in line:
                assert allow_exemplars, f"exemplar in classic text: {line!r}"
                exemplars += 1
                assert 'trace_id="' in line
        return seen_types, exemplars

    def test_negotiated_openmetrics_carries_exemplars(self, server):
        # a traced solve guarantees at least one fresh exemplar
        status, resp, _ = post(
            server, "/api/vrp/sa", solve_body(iterationCount=200),
            headers={"traceparent": GOOD_TP},
        )
        assert status == 200, resp
        req = urllib.request.Request(
            server + "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            text = r.read().decode()
            ctype = r.headers["Content-Type"]
        assert ctype.startswith("application/openmetrics-text")
        assert text.endswith("# EOF\n")  # the mandatory terminator
        seen_types, exemplars = self._parse(text, allow_exemplars=True)
        assert exemplars >= 1, "no exemplar found after a traced solve"
        assert seen_types.get("vrpms_solve_seconds") == "histogram"
        assert seen_types.get("vrpms_build_info") == "gauge"
        assert seen_types.get("vrpms_trace_ring_size") == "gauge"
        # OpenMetrics counter families drop the _total suffix
        assert seen_types.get("vrpms_requests") == "counter"

    def test_classic_scrape_stays_exemplar_free(self, server):
        # classic 0.0.4 parsers reject exemplars — a plain scrape must
        # never see one, even right after a traced solve recorded some
        status, resp, _ = post(
            server, "/api/vrp/sa", solve_body(iterationCount=200),
            headers={"traceparent": GOOD_TP},
        )
        assert status == 200, resp
        with urllib.request.urlopen(server + "/metrics", timeout=30) as r:
            text = r.read().decode()
            ctype = r.headers["Content-Type"]
        assert ctype.startswith("text/plain; version=0.0.4")
        assert "# EOF" not in text
        seen_types, exemplars = self._parse(text, allow_exemplars=False)
        assert exemplars == 0
        assert seen_types.get("vrpms_requests_total") == "counter"
        # and the classic scrape did NOT drain the pending exemplars:
        # the next OpenMetrics scrape still gets them
        req = urllib.request.Request(
            server + "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            om = r.read().decode()
        assert "# {" in om

    def test_build_info_and_ring_gauges(self, server):
        status, resp, _ = post(
            server, "/api/vrp/sa", solve_body(iterationCount=200)
        )
        assert status == 200, resp
        with urllib.request.urlopen(server + "/metrics", timeout=30) as r:
            text = r.read().decode()
        (info_line,) = [
            ln for ln in text.splitlines()
            if ln.startswith("vrpms_build_info{")
        ]
        assert 'version="' in info_line and 'jaxVersion="' in info_line
        assert 'platform="' in info_line
        (ring_line,) = [
            ln for ln in text.splitlines()
            if ln.startswith("vrpms_trace_ring_size ")
        ]
        assert float(ring_line.split()[-1]) >= 1
