"""End-to-end degraded-mode serving over real HTTP (ISSUE 3 acceptance):
with the store forced down by a fault plan, sync solves and async jobs
still answer valid solutions marked `degraded: true`, `/api/ready`
tracks ok -> degraded -> ok, no HTTP thread blocks past the configured
store deadline, and the write journal replays into the recovered store.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import store.memory as mem
from service import jobs as jobs_mod
from service.app import serve
from store.faulty import reset_faults
from store.resilient import reset_resilience

N = 7
KEY = "chaos7"

ENV = {
    "VRPMS_STORE": "faulty:",  # healthy chaos backend; plans set per test
    "VRPMS_STORE_DEADLINE_S": "0.5",
    "VRPMS_STORE_RETRIES": "1",
    "VRPMS_STORE_BACKOFF_S": "0.01",
    "VRPMS_CB_FAILURES": "3",
    "VRPMS_CB_RESET_S": "0.3",
}


@pytest.fixture(scope="module")
def server():
    saved = {k: os.environ.get(k) for k in ENV}
    os.environ.update(ENV)
    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@pytest.fixture(autouse=True)
def seeded():
    mem.reset()
    reset_faults()
    reset_resilience()
    os.environ["VRPMS_STORE"] = "faulty:"
    rng = np.random.default_rng(23)
    pts = rng.uniform(0, 100, size=(N, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(KEY, [{"id": i, "demand": 2 if i else 0}
                             for i in range(N)])
    mem.seed_durations(KEY, d.tolist())
    yield
    reset_faults()
    reset_resilience()


def post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def body(**over):
    b = {
        "solutionName": "chaos",
        "solutionDescription": "t",
        "locationsKey": KEY,
        "durationsKey": KEY,
        "capacities": [2 * N] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": 200,
        "populationSize": 8,
    }
    b.update(over)
    return b


def assert_valid_vrp(msg):
    visited = sorted(c for v in msg["vehicles"] for c in v["tour"][1:-1])
    assert visited == list(range(1, N)), msg


def warm_cache(base):
    """One healthy solve: warms the resilient read-through cache for
    the locations/durations rows this module uses."""
    status, resp = post(base, "/api/vrp/sa", body())
    assert status == 200, resp
    assert "degraded" not in resp["message"]
    return resp


def poll_until(base, job_id, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


class TestDegradedServing:
    def test_sync_solve_survives_store_down(self, server):
        warm_cache(server)
        os.environ["VRPMS_STORE"] = "faulty:down"
        status, resp = post(server, "/api/vrp/sa", body(seed=2))
        assert status == 200, resp
        msg = resp["message"]
        assert msg.get("degraded") is True
        assert_valid_vrp(msg)

    def test_async_job_survives_store_down(self, server):
        warm_cache(server)
        os.environ["VRPMS_STORE"] = "faulty:down"
        status, resp = post(server, "/api/jobs",
                            dict(body(seed=3), problem="vrp", algorithm="sa"))
        assert status == 202, resp
        # job records spooled to the journal are visible to the poll
        # (degraded read-your-writes) even though the store is down
        job = poll_until(server, resp["jobId"])
        assert job["status"] == "done", job
        assert job["message"].get("degraded") is True
        assert_valid_vrp(job["message"])
        # the poll response itself discloses it was served by fallback
        status, poll = get(server, f"/api/jobs/{resp['jobId']}")
        assert status == 200 and poll.get("degraded") is True, poll

    def test_ready_tracks_degradation_and_recovery(self, server):
        status, resp = get(server, "/api/ready")
        assert status == 200 and resp["status"] == "ok", resp
        warm_cache(server)
        os.environ["VRPMS_STORE"] = "faulty:down"
        status, resp = post(server, "/api/vrp/sa", body(seed=4))
        assert status == 200 and resp["message"].get("degraded"), resp
        status, resp = get(server, "/api/ready")
        assert status == 200, resp
        assert resp["status"] == "degraded"
        assert resp["circuits"].get("faulty") in ("open", "half-open")
        # heal the backend; past the reset window the next request is
        # the half-open probe, recovery closes the circuit and replays
        os.environ["VRPMS_STORE"] = "faulty:"
        time.sleep(0.35)
        status, resp = post(server, "/api/vrp/sa", body(seed=5))
        assert status == 200, resp
        assert "degraded" not in resp["message"]
        status, resp = get(server, "/api/ready")
        assert status == 200 and resp["status"] == "ok", resp

    def test_journal_replays_job_records_after_recovery(self, server):
        warm_cache(server)
        os.environ["VRPMS_STORE"] = "faulty:down"
        status, resp = post(server, "/api/jobs",
                            dict(body(seed=6), problem="vrp", algorithm="sa"))
        assert status == 202, resp
        job_id = resp["jobId"]
        job = poll_until(server, job_id)
        assert job["status"] == "done"
        assert mem._tables["jobs"] == {}  # nothing hit the real store
        os.environ["VRPMS_STORE"] = "faulty:"
        time.sleep(0.35)
        status, resp = post(server, "/api/vrp/sa", body(seed=7))  # probe
        assert status == 200, resp
        # the spooled queued/running/done records replay in order on a
        # background thread: the real store ends up with the terminal
        # record
        deadline = time.monotonic() + 5.0
        while job_id not in mem._tables["jobs"] and time.monotonic() < deadline:
            time.sleep(0.02)
        assert job_id in mem._tables["jobs"]
        assert mem._tables["jobs"][job_id]["record"]["status"] == "done"

    def test_hung_store_bounded_by_deadline(self, server):
        warm_cache(server)
        # every read hangs 5s; the 0.5s per-call deadline + no retries
        # must keep the whole request far under the raw hang cost
        os.environ["VRPMS_STORE_RETRIES"] = "0"
        os.environ["VRPMS_STORE"] = "faulty:hang=5;ops=reads"
        try:
            t0 = time.monotonic()
            status, resp = post(server, "/api/vrp/sa", body(seed=8))
            elapsed = time.monotonic() - t0
        finally:
            os.environ["VRPMS_STORE_RETRIES"] = ENV["VRPMS_STORE_RETRIES"]
        assert status == 200, resp
        assert resp["message"].get("degraded") is True
        assert_valid_vrp(resp["message"])
        assert elapsed < 4.0, f"request blocked {elapsed:.1f}s on a hung store"

    def test_ready_down_after_drain_until_rebuild(self, server):
        warm_cache(server)  # ensures a scheduler exists to drain
        jobs_mod.shutdown_scheduler()
        status, resp = get(server, "/api/ready")
        assert status == 503, resp
        assert resp["status"] == "down" and resp["success"] is False
        # the next solve lazily rebuilds the scheduler -> ready again
        status, resp = post(server, "/api/vrp/sa", body(seed=11))
        assert status == 200, resp
        status, resp = get(server, "/api/ready")
        assert status == 200 and resp["status"] == "ok", resp

    def test_cached_instances_still_solve_when_store_down(self, server):
        # ISSUE 6: a cache-store outage degrades to SOLVING, never to
        # failing — previously-cached instances lose their fast path
        # (`cacheHit: false`) but 100% of requests are served
        warm = warm_cache(server)
        # healthy: the repeat is an exact hit
        status, resp = post(server, "/api/vrp/sa", body())
        assert status == 200 and resp["message"]["cacheHit"] is True, resp
        os.environ["VRPMS_STORE"] = "faulty:down"
        for _ in range(3):
            status, resp = post(server, "/api/vrp/sa", body())
            assert status == 200, resp
            msg = resp["message"]
            # the cache lookup failed fast under the breaker: the solve
            # ran for real and the response says so honestly
            assert msg["cacheHit"] is False
            assert msg.get("degraded") is True
            assert_valid_vrp(msg)
            assert msg["durationSum"] == pytest.approx(
                warm["message"]["durationSum"]
            )

    def test_metrics_expose_resilience_series(self, server):
        warm_cache(server)
        os.environ["VRPMS_STORE"] = "faulty:down"
        post(server, "/api/vrp/sa", body(seed=9))
        with urllib.request.urlopen(server + "/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert 'vrpms_store_circuit_state{kind="faulty"} 2' in text
        assert "vrpms_store_fallbacks_total" in text
        assert "vrpms_sched_worker_restarts_total" in text
        assert "vrpms_jobs_failed_total" in text


def _metric_value(base, name: str) -> float:
    with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            if line.split("{", 1)[0] == name.split("{", 1)[0] and (
                "{" not in name or name.split("{", 1)[1].rstrip("}")
                in line
            ):
                try:
                    return float(line.rsplit(" ", 1)[1])
                except ValueError:
                    continue
    return 0.0


class TestCheckpointChaos:
    """ISSUE 15 satellite: a faulty-store plan active DURING a
    decomposed giant solve — the request still serves, and checkpoint
    write failures only increment vrpms_ckpt_total{dropped} (fail-open:
    a checkpoint store outage never fails, or even slows, a solve)."""

    GIANT_ENV = {
        "VRPMS_TIERS": "n=8,16,32;v=1,2,4,8;t=1",
        "VRPMS_SCHED_MAX_BATCH": "1",
        "VRPMS_CKPT_MS": "1",
    }

    def test_ckpt_write_failures_only_drop_never_fail(self, server):
        saved = {k: os.environ.get(k) for k in self.GIANT_ENV}
        os.environ.update(self.GIANT_ENV)
        try:
            from vrpms_tpu.io.synth import synth_clustered_coords

            n = 61
            coords, demands = synth_clustered_coords(n, 4, seed=5)
            d = np.linalg.norm(
                coords[:, None] - coords[None, :], axis=-1
            )
            mem.seed_locations(
                "chaos_giant",
                [
                    {"id": i, "demand": float(demands[i]) if i else 0}
                    for i in range(n)
                ],
            )
            mem.seed_durations("chaos_giant", d.tolist())
            cap = float(np.ceil(demands.sum() * 1.3 / 6))
            content = dict(
                body(seed=13),
                problem="vrp",
                algorithm="sa",
                locationsKey="chaos_giant",
                durationsKey="chaos_giant",
                capacities=[cap] * 6,
                startTimes=[0.0] * 6,
                iterationCount=2_000_000,
                populationSize=16,
                timeLimit=10.0,
            )
            dropped0 = _metric_value(
                server, 'vrpms_ckpt_total{outcome="dropped"}'
            )
            # submit while healthy (the dataset reads + queued-record
            # persist succeed), then break WRITES mid-solve: every
            # per-shard checkpoint write now fails. The poll surface
            # would serve a stale pre-terminal record during the
            # outage (writes are what is broken), so the live job —
            # same process — is the truth the "still serves" claim is
            # checked against.
            status, resp = post(server, "/api/jobs", content)
            assert status == 202, resp
            job_obj = jobs_mod.get_live_job(resp["jobId"])
            assert job_obj is not None
            os.environ["VRPMS_STORE"] = "faulty:fail=100000;ops=writes"
            assert job_obj.wait(timeout=120), "solve never finished"
            assert job_obj.status == "done", job_obj.errors
            msg = job_obj.result
            visited = sorted(
                c for v in msg["vehicles"] for c in v["tour"][1:-1]
            )
            assert visited == list(range(1, n)), msg
            assert "decomposition" in msg
            dropped1 = _metric_value(
                server, 'vrpms_ckpt_total{outcome="dropped"}'
            )
            assert dropped1 > dropped0, (
                "checkpoint write failures must be accounted as dropped"
            )
        finally:
            os.environ["VRPMS_STORE"] = "faulty:"
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
